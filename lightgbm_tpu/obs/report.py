"""Consolidated, comparable run reports.

One ``run_report.json`` per training (or serving) run — the single
artifact that answers "what did this run do, and did it regress vs that
one?" without JSONL archaeology: dispatch/compile counters with their
per-iteration derivations, every ``megastep_evicted`` feature and
``degrade`` reason that fired, the device-time cost ledger (obs/cost),
measured collective traffic, per-device memory watermarks (incl. the
``bytes_reserved``/fragmentation series where the backend reports
them), checkpoint/recovery activity and profile windows.  Schema-
versioned so ``scripts/run_diff.py`` can refuse apples-to-oranges
comparisons, and rank-0 aggregates a compact per-rank section under
multi-process (riding the finalize allgather — zero new collectives).

Produced at finalize when ``run_report_out=<path>`` is set, and on
demand from ``GET /report`` on the metrics exporter; ``bench.py``
attaches it to trajectory records so the bench history carries the full
attribution, not just headline numbers.
"""
from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional

#: bump on any structural change; run_diff refuses mismatched majors
SCHEMA = "lightgbm_tpu.run_report/1"

#: counters whose per-iteration derivation is deterministic for a fixed
#: config — the strict half of run_diff (borrowed from bench_compare's
#: deterministic-counter discipline: no wall-clock noise, tight
#: threshold, zero-to-nonzero always flags)
DETERMINISTIC_KEYS = (
    "derived.dispatches_per_iter",
    "derived.drains_per_iter",
    "cost.flops_per_iter",
    "cost.hlo_bytes_per_iter",
    "cost.achieved_fraction",
    "hist.bytes_per_iter",
    "counters.iterations",
    # roofline plane (obs/kernelstats.py): the fraction of measured
    # anchor dispatches that joined an analytic cost signature.  A
    # DROP means a signature stopped joining (renamed, lost its cost
    # entry) — flagged decrease-only below; rising coverage is fine.
    "roofline.join_coverage",
)

#: deterministic keys where only a DECREASE regresses (more is better,
#: and a baseline below 1.0 must not flag the fix that raised it)
_DECREASE_ONLY = ("roofline.join_coverage",)


def _g(d: Dict[str, Any], dotted: str) -> Any:
    cur: Any = d
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def build_report(snapshot: Dict[str, Any], *,
                 run_id: str = "", rank: int = 0, world_size: int = 1,
                 evicted: Optional[List[str]] = None,
                 cost_entries: Optional[List[Dict[str, Any]]] = None,
                 roofline: Optional[Dict[str, Any]] = None,
                 extra: Optional[Dict[str, Any]] = None,
                 ranks: Optional[List[Dict[str, Any]]] = None
                 ) -> Dict[str, Any]:
    """Registry snapshot (Telemetry.snapshot schema) -> report dict.

    Bounded by construction: counters/gauges/timings come over whole,
    events are consolidated into per-name counts plus the small
    record families the report exists to surface (cost ledger, profile
    windows, recovery) — never the raw 512-entry ring."""
    counters = dict(snapshot.get("counters", {}))
    gauges = dict(snapshot.get("gauges", {}))
    events = snapshot.get("events", []) or []
    iters = float(counters.get("iterations", 0))

    def per_iter(key: str) -> Optional[float]:
        if iters <= 0:
            return None
        return round(float(counters.get(key, 0)) / iters, 6)

    degrade = {k[len("degrade."):]: int(v) for k, v in counters.items()
               if k.startswith("degrade.")}
    by_name: Dict[str, int] = {}
    cost_records: List[Dict[str, Any]] = []
    profile_windows: List[Dict[str, Any]] = []
    recoveries: List[Dict[str, Any]] = []
    drift_alerts: List[Dict[str, Any]] = []
    for ev in events:
        name = str(ev.get("event", "?"))
        by_name[name] = by_name.get(name, 0) + 1
        if name in ("drift_alert", "mapper_drift", "drift_unavailable"):
            drift_alerts.append({k: v for k, v in ev.items()
                                 if k not in ("ts", "rank")})
        if name == "cost_ledger":
            cost_records.append({k: v for k, v in ev.items()
                                 if k not in ("ts", "rank", "event")})
        elif name == "profile_window":
            profile_windows.append({k: v for k, v in ev.items()
                                    if k not in ("ts", "rank", "event")})
        elif name in ("recovery", "rank_divergence", "straggler"):
            recoveries.append({k: v for k, v in ev.items()
                               if k not in ("ts",)})
        elif name == "megastep_evicted":
            feat = str(ev.get("feature", "?"))
            evicted = list(evicted or [])
            if feat not in evicted:
                evicted.append(feat)
    mem = {}
    for k, v in gauges.items():
        if k.startswith("mem."):
            dev, _, stat = k[len("mem."):].partition(".")
            mem.setdefault(dev, {})[stat] = v
    cost = {
        "flops_per_iter": gauges.get("cost.flops_per_iter"),
        "hlo_bytes_per_iter": gauges.get("cost.hlo_bytes_per_iter"),
        "achieved_fraction": gauges.get("cost.achieved_fraction"),
        "executables": list(cost_entries or []),
        "records": cost_records[-32:],
    }
    hist = {k[len("hist."):]: v for k, v in gauges.items()
            if k.startswith("hist.")}
    # SLO plane: alert transitions live in the findings ring (they are
    # finding events, so they survive the whole run even after the
    # general event ring evicts them).  "active" is the last state seen
    # per objective — run_diff treats a newly-active id as a regression.
    slo_transitions: List[Dict[str, Any]] = []
    last_state: Dict[str, str] = {}
    for ev in snapshot.get("findings", []) or []:
        if str(ev.get("event")) != "alert":
            continue
        slo_transitions.append({k: v for k, v in ev.items()
                                if k not in ("ts", "rank", "event")})
        last_state[str(ev.get("objective", "?"))] = str(ev.get("state"))
    alerts = {
        "fired": int(counters.get("slo.alerts_fired", 0)),
        "resolved": int(counters.get("slo.alerts_resolved", 0)),
        "incidents": int(counters.get("slo.incidents", 0)),
        "ticks": int(counters.get("slo.ticks", 0)),
        "active": sorted(o for o, s in last_state.items()
                         if s == "firing"),
        "transitions": slo_transitions[-32:],
    }
    # drift & lineage plane: PSI gauges + the alert/mapper-drift record
    # families, so run_diff flags a new drift alert exactly like a new
    # eviction reason (docs/Observability.md §13)
    drift = {
        "gauges": {k[len("drift."):]: v for k, v in gauges.items()
                   if k.startswith("drift.")},
        "model_age_s": {k[len("serve.model_age_s."):]: v
                        for k, v in gauges.items()
                        if k.startswith("serve.model_age_s.")},
        "alerts": drift_alerts[-32:],
        "alert_count": int(counters.get("drift.alerts", 0)),
        "evaluations": int(counters.get("drift.evaluations", 0)),
        "unavailable": int(counters.get("drift.unavailable", 0)),
    }
    report: Dict[str, Any] = {
        "schema": SCHEMA,
        "generated_ts": round(time.time(), 3),
        "run_id": str(run_id),
        "rank": int(rank),
        "world_size": int(world_size),
        "counters": counters,
        "gauges": gauges,
        "timings": {k: dict(v)
                    for k, v in snapshot.get("timings", {}).items()},
        "derived": {
            "iterations": int(iters),
            "dispatches_per_iter": per_iter("train.dispatches"),
            "drains_per_iter": per_iter("train.drains"),
            "compile_executables": int(counters.get(
                "compile.executables", 0)),
        },
        "reasons": {
            "megastep_evicted": sorted(evicted or []),
            "degrade": degrade,
        },
        "cost": cost,
        "hist": hist,
        "drift": drift,
        "alerts": alerts,
        "collectives": {
            "count": counters.get("collectives.count", 0),
            "bytes": counters.get("collectives.bytes", 0),
            "bytes_per_iter": per_iter("collectives.bytes"),
        },
        "memory": mem,
        "checkpoints": {
            "written": int(counters.get("ckpt.written", 0)),
            "recoveries": recoveries[-32:],
        },
        "profile_windows": profile_windows[-32:],
        "events": {"by_name": by_name},
    }
    if roofline:
        # roofline plane (obs/kernelstats.py): the last parsed profile
        # window's measured view, bounded to the top executables and
        # kernels — run_diff diffs per-executable measured device time
        # from here the way it diffs deterministic counters
        report["roofline"] = {
            "join_coverage": roofline.get("join_coverage"),
            "joined_executables": roofline.get("joined_executables"),
            "anchor_dispatches": roofline.get("anchor_dispatches"),
            "total_device_time_us": roofline.get("total_device_time_us"),
            "unattributed_time_us": roofline.get("unattributed_time_us"),
            "trace_files": roofline.get("trace_files"),
            "trace_bytes": roofline.get("trace_bytes"),
            "parse_errors": roofline.get("parse_errors"),
            "executables": list(roofline.get("executables", []))[:16],
            "kernels": list(roofline.get("kernels", []))[:8],
        }
    if extra:
        report.update(extra)
    if ranks is not None:
        report["ranks"] = ranks
    return report


def rank_section(snapshot: Dict[str, Any], rank: int,
                 evicted: Optional[List[str]] = None) -> Dict[str, Any]:
    """The compact per-rank payload rank 0 aggregates under
    ``report["ranks"]`` — counters + the deterministic gauges, small
    enough to ride the existing finalize allgather."""
    counters = dict(snapshot.get("counters", {}))
    gauges = snapshot.get("gauges", {})
    return {
        "rank": int(rank),
        "counters": counters,
        "gauges": {k: v for k, v in gauges.items()
                   if k.startswith(("cost.", "hist.", "mem.",
                                    "screening."))},
        "evicted": sorted(evicted or []),
    }


def write_report(path: str, report: Dict[str, Any]) -> None:
    """Atomic write (write-then-rename) of the JSON artifact plus a
    rendered ``<path>.md`` markdown sibling."""
    from ..resilience.atomicio import atomic_write_text
    atomic_write_text(path, json.dumps(report, indent=1, sort_keys=True,
                                       default=str) + "\n")
    try:
        atomic_write_text(path + ".md", render_markdown(report))
    except Exception:      # the JSON artifact is the contract
        pass


def load_report(path: str) -> Dict[str, Any]:
    with open(path) as fh:
        rep = json.load(fh)
    if not isinstance(rep, dict) or not str(
            rep.get("schema", "")).startswith("lightgbm_tpu.run_report/"):
        raise ValueError(f"{path} is not a lightgbm_tpu run report")
    return rep


def _fmt(v: Any) -> str:
    if isinstance(v, float):
        if v == 0:
            return "0"
        if abs(v) >= 1e5 or abs(v) < 1e-3:
            return f"{v:.3e}"
        return f"{v:.4g}"
    return str(v)


def render_markdown(report: Dict[str, Any]) -> str:
    d = report.get("derived", {})
    lines = [
        f"# Run report `{report.get('run_id', '?')}`",
        "",
        f"- schema: `{report.get('schema')}`  rank "
        f"{report.get('rank', 0)}/{report.get('world_size', 1)}",
        f"- iterations: {d.get('iterations')}   dispatches/iter: "
        f"{_fmt(d.get('dispatches_per_iter'))}   drains/iter: "
        f"{_fmt(d.get('drains_per_iter'))}   fresh executables: "
        f"{d.get('compile_executables')}",
    ]
    cost = report.get("cost", {})
    if cost.get("flops_per_iter") is not None:
        lines += ["", "## Cost ledger",
                  f"- flops/iter: {_fmt(cost.get('flops_per_iter'))}   "
                  f"hlo bytes/iter: "
                  f"{_fmt(cost.get('hlo_bytes_per_iter'))}   "
                  f"analytic hist fraction: "
                  f"{_fmt(cost.get('achieved_fraction'))}"]
        for ent in cost.get("executables", [])[:16]:
            lines.append(
                f"  - `{ent.get('signature')}` ({ent.get('kind')}, "
                f"x{ent.get('scale')}): flops {_fmt(ent.get('flops'))}, "
                f"bytes {_fmt(ent.get('hlo_bytes'))}, operands "
                f"{_fmt(ent.get('operand_bytes'))}")
    reasons = report.get("reasons", {})
    if reasons.get("megastep_evicted") or reasons.get("degrade"):
        lines += ["", "## Evictions & degradations"]
        for feat in reasons.get("megastep_evicted", []):
            lines.append(f"- megastep_evicted: `{feat}`")
        for r, n in sorted(reasons.get("degrade", {}).items()):
            lines.append(f"- degrade `{r}`: {n}")
    coll = report.get("collectives", {})
    if coll.get("count"):
        lines += ["", "## Collectives",
                  f"- {int(coll['count'])} ops, "
                  f"{_fmt(float(coll.get('bytes', 0)))} bytes "
                  f"({_fmt(coll.get('bytes_per_iter'))}/iter)"]
    mem = report.get("memory", {})
    if mem:
        lines += ["", "## Memory watermarks"]
        for dev in sorted(mem):
            ent = mem[dev]
            lines.append(
                "- " + dev + ": " + "  ".join(
                    f"{k}={_fmt(v)}" for k, v in sorted(ent.items())))
    ck = report.get("checkpoints", {})
    if ck.get("written") or ck.get("recoveries"):
        lines += ["", "## Resilience",
                  f"- checkpoints written: {ck.get('written', 0)}, "
                  f"recovery/divergence events: "
                  f"{len(ck.get('recoveries', []))}"]
    dr = report.get("drift", {})
    if dr.get("alert_count") or dr.get("gauges") or dr.get("unavailable"):
        lines += ["", "## Drift",
                  f"- alerts: {dr.get('alert_count', 0)}   evaluations: "
                  f"{dr.get('evaluations', 0)}   psi_max: "
                  f"{_fmt(dr.get('gauges', {}).get('psi_max', 0))}   "
                  f"unavailable: {dr.get('unavailable', 0)}"]
        for a in dr.get("alerts", [])[:8]:
            lines.append("- " + "  ".join(f"{k}={_fmt(v)}"
                                          for k, v in sorted(a.items())))
    al = report.get("alerts", {})
    if al.get("fired") or al.get("active") or al.get("ticks"):
        lines += ["", "## SLO alerts",
                  f"- fired: {al.get('fired', 0)}   resolved: "
                  f"{al.get('resolved', 0)}   incidents: "
                  f"{al.get('incidents', 0)}   ticks: "
                  f"{al.get('ticks', 0)}   active: "
                  f"{al.get('active', []) or 'none'}"]
        for t in al.get("transitions", [])[:8]:
            lines.append("- " + "  ".join(f"{k}={_fmt(v)}"
                                          for k, v in sorted(t.items())))
    roof = report.get("roofline", {})
    if roof:
        lines += ["", "## Roofline (measured)",
                  f"- join coverage: {_fmt(roof.get('join_coverage'))}   "
                  f"joined executables: {roof.get('joined_executables')} "
                  f"  anchor dispatches: {roof.get('anchor_dispatches')}"
                  f"   device time: "
                  f"{_fmt(roof.get('total_device_time_us'))} us"]
        for ex in roof.get("executables", [])[:8]:
            extra = ""
            if ex.get("achieved_flops_per_s") is not None:
                extra = (f", {_fmt(ex['achieved_flops_per_s'])} flop/s"
                         f", {_fmt(ex.get('achieved_bytes_per_s'))} B/s")
            lines.append(
                f"  - `{ex.get('signature') or ex.get('kind')}`: "
                f"{_fmt(ex.get('device_time_us_per_dispatch'))} us/disp "
                f"x{ex.get('dispatches')}, measured fraction "
                f"{_fmt(ex.get('measured_fraction'))}{extra}")
        for k in roof.get("kernels", [])[:5]:
            lines.append(f"  - kernel `{k.get('name')}`: "
                         f"{_fmt(k.get('time_us'))} us "
                         f"(x{k.get('count')})")
    pw = report.get("profile_windows", [])
    if pw:
        lines += ["", "## Profile windows"]
        for w in pw:
            lines.append("- " + "  ".join(f"{k}={_fmt(v)}"
                                          for k, v in sorted(w.items())))
    ranks = report.get("ranks")
    if ranks:
        lines += ["", "## Per-rank"]
        for sec in ranks:
            c = sec.get("counters", {})
            lines.append(
                f"- rank {sec.get('rank')}: iterations "
                f"{int(c.get('iterations', 0))}, dispatches "
                f"{int(c.get('train.dispatches', 0))}, evicted "
                f"{sec.get('evicted', [])}")
    lines += ["", "## Events", ""]
    for name, n in sorted(report.get("events", {}).get("by_name", {})
                          .items(), key=lambda kv: -kv[1]):
        lines.append(f"- {name}: {n}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------- diff
def compare_reports(prev: Dict[str, Any], cur: Dict[str, Any],
                    threshold: float = 0.15,
                    det_threshold: float = 0.05,
                    fail_on_timing: bool = False) -> Dict[str, Any]:
    """Two reports -> comparison with bench_compare's deterministic-
    counter strictness: the DETERMINISTIC_KEYS get a tight threshold
    (they carry no wall-clock noise), zero-to-nonzero always flags, a
    NEW eviction/degradation reason always flags, and wall timings diff
    per-call under the loose timing threshold.  Schema majors must
    match.

    Timing entries are flagged in ``timings`` either way, but join the
    hard ``regressions`` list only under ``fail_on_timing``: identical
    runs must compare clean BY CONSTRUCTION, and per-call wall timings
    between two identical runs routinely swing past any usable
    threshold on scheduler noise alone (a 15-20%% section swing under a
    loaded CI box is weather, not regression).  The deterministic
    counters are the gate; timings are the narrative."""
    rep: Dict[str, Any] = {"status": "ok",
                           "prev_run": prev.get("run_id"),
                           "cur_run": cur.get("run_id"),
                           "deterministic": {}, "timings": [],
                           "regressions": [], "new_reasons": []}
    ps, cs = str(prev.get("schema", "")), str(cur.get("schema", ""))
    if ps != cs:
        rep["status"] = "schema_mismatch"
        rep["prev_schema"], rep["cur_schema"] = ps, cs
        return rep

    for key in DETERMINISTIC_KEYS:
        p, c = _g(prev, key), _g(cur, key)
        p_num = isinstance(p, (int, float))
        c_num = isinstance(c, (int, float))
        if not p_num and not c_num:
            continue          # neither run carries it: not comparable
        if p_num and not c_num:
            # the baseline measured this counter and the candidate
            # LOST it (e.g. every cost analysis failed, so the gauges
            # never appeared) — silently skipping here would let the
            # gate pass while the very counters it guards vanished
            ent = {"name": key, "prev": round(float(p), 6),
                   "cur": None, "ratio": None, "regressed": True,
                   "lost": True}
        elif not p_num:
            # new counter the baseline predates: informational only
            ent = {"name": key, "prev": None,
                   "cur": round(float(c), 6), "ratio": None,
                   "regressed": False, "new": True}
        elif p <= 0:
            ent = {"name": key, "prev": float(p), "cur": float(c),
                   "ratio": None if c > 0 else 1.0, "regressed": c > 0}
        elif c == 0:
            # nonzero -> zero is the counter disappearing in place
            # (a real run with iterations > 0 cannot dispatch zero
            # times, and a ledger that read zero stopped measuring)
            ent = {"name": key, "prev": round(float(p), 6),
                   "cur": 0.0, "ratio": 0.0, "regressed": True,
                   "lost": True}
        else:
            ratio = float(c) / float(p)
            ent = {"name": key, "prev": round(float(p), 6),
                   "cur": round(float(c), 6), "ratio": round(ratio, 6),
                   "regressed": ratio > 1.0 + det_threshold}
            # achieved_fraction regresses in EITHER direction: the
            # analytic model drifting off the HLO truth is the finding
            if key.endswith("achieved_fraction") \
                    and ratio < 1.0 - det_threshold:
                ent["regressed"] = True
            if key in _DECREASE_ONLY:
                ent["regressed"] = ratio < 1.0 - det_threshold
        rep["deterministic"][key] = ent
        if ent["regressed"]:
            rep["regressions"].append(ent)

    prev_r = set(_g(prev, "reasons.megastep_evicted") or [])
    cur_r = set(_g(cur, "reasons.megastep_evicted") or [])
    prev_d = set((_g(prev, "reasons.degrade") or {}).keys())
    cur_d = set((_g(cur, "reasons.degrade") or {}).keys())
    for reason in sorted(cur_r - prev_r):
        ent = {"name": f"megastep_evicted:{reason}", "prev": 0.0,
               "cur": 1.0, "ratio": None, "regressed": True}
        rep["new_reasons"].append(ent)
        rep["regressions"].append(ent)
    for reason in sorted(cur_d - prev_d):
        ent = {"name": f"degrade:{reason}", "prev": 0.0, "cur": 1.0,
               "ratio": None, "regressed": True}
        rep["new_reasons"].append(ent)
        rep["regressions"].append(ent)

    # a NEW drift alert flags exactly like a new eviction reason: the
    # candidate run's serving traffic diverged from the training
    # distribution where the baseline's did not
    def _alert_keys(r: Dict[str, Any]) -> set:
        keys = set()
        for a in (_g(r, "drift.alerts") or []):
            if a.get("event") == "drift_alert":
                keys.add(f"{a.get('model_id', '?')}"
                         f":f{a.get('worst_feature', -1)}")
        return keys
    for key in sorted(_alert_keys(cur) - _alert_keys(prev)):
        ent = {"name": f"drift_alert:{key}", "prev": 0.0, "cur": 1.0,
               "ratio": None, "regressed": True}
        rep["new_reasons"].append(ent)
        rep["regressions"].append(ent)

    # SLO plane: an alert OBJECTIVE that fired in the candidate but not
    # in the baseline is a regression — baseline-clean vs
    # candidate-firing always flags, no threshold.  Resolved-by-run-end
    # alerts count too (the fire happened); only objectives the
    # baseline also fired are considered steady-state.
    def _slo_fired(r: Dict[str, Any]) -> set:
        return {str(t.get("objective", "?"))
                for t in (_g(r, "alerts.transitions") or [])
                if t.get("state") == "firing"}
    for oid in sorted(_slo_fired(cur) - _slo_fired(prev)):
        ent = {"name": f"slo_alert:{oid}", "prev": 0.0, "cur": 1.0,
               "ratio": None, "regressed": True}
        rep["new_reasons"].append(ent)
        rep["regressions"].append(ent)

    # roofline plane: MEASURED per-executable device time per dispatch,
    # joined across the two reports by signature.  Diffs under the
    # loose wall-clock threshold (measured time carries scheduler
    # noise) but joins the hard regressions list — unlike section
    # timings, these are per-dispatch device times from the profiler,
    # the exact quantity the item-5 autotuner optimizes, and a slip
    # past the loose threshold is the regression this plane exists to
    # catch.
    def _roof_execs(r: Dict[str, Any]) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for ex in (_g(r, "roofline.executables") or []):
            sig = ex.get("signature") or ex.get("kind")
            per = ex.get("device_time_us_per_dispatch")
            if sig and isinstance(per, (int, float)) and per > 0:
                out[str(sig)] = float(per)
        return out
    pr_ex, cu_ex = _roof_execs(prev), _roof_execs(cur)
    rep["roofline"] = []
    for sig in sorted(set(pr_ex) & set(cu_ex)):
        ratio = cu_ex[sig] / pr_ex[sig]
        ent = {"name": f"roofline:{sig}", "prev": round(pr_ex[sig], 3),
               "cur": round(cu_ex[sig], 3), "ratio": round(ratio, 4),
               "regressed": ratio > 1.0 + threshold}
        rep["roofline"].append(ent)
        if ent["regressed"]:
            rep["regressions"].append(ent)

    pt, ct = prev.get("timings", {}) or {}, cur.get("timings", {}) or {}
    # only run-time duration families diff as timings: compile.* is
    # build time (swings on compilation-cache hits, not run perf) and
    # observe() families that aren't seconds (batch.split_gain_mean)
    # have no slower/faster meaning
    _TIMED = ("section.", "megastep.", "collective.", "serve.")
    for name in sorted(set(pt) & set(ct)):
        if not name.startswith(_TIMED):
            continue
        try:
            p = float(pt[name]["total"]) / max(1, int(pt[name]["count"]))
            c = float(ct[name]["total"]) / max(1, int(ct[name]["count"]))
        except (KeyError, TypeError, ValueError):
            continue
        if max(p, c) < 0.005 or p <= 0:
            continue
        ratio = c / p
        ent = {"name": name, "prev": round(p, 6), "cur": round(c, 6),
               "ratio": round(ratio, 4),
               "regressed": ratio > 1.0 + threshold}
        rep["timings"].append(ent)
        if ent["regressed"] and fail_on_timing:
            rep["regressions"].append(ent)
    return rep
