"""Cross-rank training-health auditing.

An SPMD run that silently diverges (one rank's model drifts from the
others') or straggles (one rank's sections run far slower, stalling every
collective) leaves no evidence until the final model is wrong — the
failure class the multi-chip deployment must survive (ROADMAP north
star; the reference's socket layer had the same blind spot, SURVEY §2.8).
Every ``health_check_period`` iterations the auditor:

1. hashes the rank-local model state — leaf values + split parameters of
   every materialized tree (under the SPMD contract all ranks grow
   identical trees, so the digests must agree bit-for-bit);
2. allgathers ``{hash, section times}`` across ranks (one small
   host-plane collective via :func:`registry.allgather_json` — every
   rank must reach the check at the same iteration, which the shared
   config guarantees);
3. emits a ``health_check`` event, a ``rank_divergence`` event when the
   hashes differ, and per-section ``straggler`` events + skew gauges
   when the max/median section-time ratio exceeds
   ``health_skew_threshold``.

Fault injection for tests: set ``LIGHTGBM_TPU_HEALTH_FAULT_RANK=<r>`` to
salt rank r's digest — the two-process driver test forces a divergence
without mistraining anything.
"""
from __future__ import annotations

import hashlib
import os
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..utils import log

FAULT_RANK_ENV = "LIGHTGBM_TPU_HEALTH_FAULT_RANK"


def model_state_hash(models, rank: int = 0) -> str:
    """SHA-256 over every tree's leaf values and split parameters
    (feature, bin + real threshold, decision type) in model order.
    Deterministic across ranks when — and only when — the ranks hold the
    same model.

    Deliberately a FULL re-hash per call, not an incremental chain over
    newly appended trees: boosting modes mutate already-materialized
    trees in place (DART normalization, RF renewal, rollback pops), and
    an incremental digest would be blind to exactly the divergence class
    the auditor exists to catch. The full pass is tobytes over small
    arrays — milliseconds even at thousands of trees."""
    h = hashlib.sha256()
    for t in models:
        for arr, dt in ((t.leaf_value, np.float64),
                        (t.split_feature, np.int32),
                        (t.threshold, np.float64),
                        (t.threshold_bin, np.int32),
                        (t.decision_type, np.int32)):
            h.update(np.ascontiguousarray(
                np.asarray(arr, dtype=dt)).tobytes())
    fault = os.environ.get(FAULT_RANK_ENV, "")
    if fault:
        try:
            if int(fault) == int(rank):
                h.update(b"injected-fault")
        except ValueError:
            pass
    return h.hexdigest()


class HealthAuditor:
    """Periodic cross-rank consistency + straggler checks.

    Owned by the GBDT driver (one per booster, like the Telemetry
    registry it reports into); ``check`` must be called from the
    synchronous path on EVERY rank at the same iteration — the driver
    guarantees that by gating on ``(it + 1) % period`` of the shared
    config. Under the multi-chip megastep (round 12) the audit moves to
    DRAIN boundaries instead of evicting the fast path: every rank
    drains at the same iteration (SPMD), the model list is already
    host-synced there, and the hash allgather pairs with the drain's
    one sync — section times are empty on that path, so the straggler
    skew check reads only drain wall times.
    """

    def __init__(self, telemetry, period: int,
                 skew_threshold: float = 2.0, resync_fn=None,
                 auto_resync: bool = True, checkpoint_fn=None,
                 straggler_checkpoint: bool = False):
        self.telemetry = telemetry
        self.period = max(0, int(period))
        self.skew_threshold = float(skew_threshold)
        # recovery wiring (resilience/recovery.py): on divergence,
        # re-sync the diverged rank from rank 0 instead of just logging;
        # on stragglers, optionally force a checkpoint-now so the
        # launcher's restart point stays fresh while a rank limps
        self.resync_fn = resync_fn
        self.auto_resync = bool(auto_resync)
        self.checkpoint_fn = checkpoint_fn
        self.straggler_checkpoint = bool(straggler_checkpoint)
        self._resync_disabled = False

    def due(self, it: int) -> bool:
        return self.period > 0 and (int(it) + 1) % self.period == 0

    def check(self, it: int, models,
              sections: Optional[Dict[str, float]] = None) -> bool:
        """Run one audit round; returns True when every rank agrees.
        SPMD: contains a host-plane allgather — all ranks, same point."""
        tel = self.telemetry
        from .registry import allgather_json
        wall0 = tel.wall_now()
        t0 = time.perf_counter()
        # a rank-local failure (hashing, payload building) must NOT skip
        # the allgather: every rank entered this check, and a rank that
        # bails early leaves its peers' collective pairing with the next
        # iteration's host allgather — so degrade to a sentinel payload
        # that still participates (the hash mismatch then reports it)
        try:
            local: Dict[str, Any] = {
                "rank": tel.rank,
                "hash": model_state_hash(models, rank=tel.rank),
                "sections": {k: float(v)
                             for k, v in (sections or {}).items()},
                # piggybacked counter snapshot: rank 0's OpenMetrics
                # exporter (obs/export.py) serves the fleet view off
                # this payload, so live cross-rank metrics cost ZERO
                # collectives beyond the audit that already runs
                "counters": tel.counters_snapshot(),
            }
        except Exception as e:
            local = {"rank": tel.rank,
                     "hash": f"error:{type(e).__name__}",
                     "sections": {}, "counters": {}}
        per_rank: List[Dict[str, Any]] = allgather_json(local)
        dt = time.perf_counter() - t0
        if tel.rank == 0:
            # only rank 0's exporter serves the fleet view — storing
            # the copies on every rank would be pure lock contention
            tel.set_fleet_counters(
                [{"rank": r.get("rank"),
                  "counters": r.get("counters", {})}
                 for r in per_rank])
        ok = len({r["hash"] for r in per_rank}) == 1
        tel.inc("health.checks")
        tel.event("health_check", iteration=it, ok=ok,
                  ranks=len(per_rank), models=len(models))
        tel.span("health_check", wall0, dt, track="health", iteration=it)
        if not ok:
            # every rank emits into its own stream (separate JSONL files)
            # so the evidence survives whichever rank is inspected
            tel.inc("health.rank_divergence")
            tel.event("rank_divergence", iteration=it,
                      hashes={str(r["rank"]): r["hash"][:16]
                              for r in per_rank})
            if self.auto_resync and self.resync_fn is not None \
                    and not self._resync_disabled:
                # SPMD: the resync contains its own host allgathers and
                # runs on EVERY rank of this same audit round; any
                # exception propagates to the driver's health handler
                # (multi-process re-raises there — a one-sided bail
                # would desync the collective schedule)
                repaired = bool(self.resync_fn(it, per_rank))
                if repaired:
                    ok = True
                else:
                    # a repair that does not converge (persistent
                    # corruption source, salted digest) must not thrash
                    # a broadcast + replay every period
                    self._resync_disabled = True
                    log.warning("divergence resync did not converge at "
                                "iteration %d; auto-resync disabled for "
                                "the rest of the run", it)
        straggled = False
        names = sorted({n for r in per_rank for n in r["sections"]})
        for name in names:
            times = [float(r["sections"].get(name, 0.0)) for r in per_rank]
            med = float(np.median(times))
            if med <= 0.0:
                continue
            skew = max(times) / med
            tel.gauge("health.skew." + name, skew)
            if len(per_rank) > 1 and skew >= self.skew_threshold:
                slowest = int(per_rank[int(np.argmax(times))]["rank"])
                straggled = True
                tel.inc("health.straggler")
                tel.event("straggler", iteration=it, section=name,
                          skew=round(skew, 3), slowest_rank=slowest,
                          max_seconds=round(max(times), 9),
                          median_seconds=round(med, 9))
        if straggled and self.straggler_checkpoint \
                and self.checkpoint_fn is not None:
            # a straggling rank often precedes a dead one — refresh the
            # restart point now so the launcher's lost work stays small.
            # Checkpoint capture is collective-free, so the SPMD
            # schedule is unaffected (every rank straggles or none: the
            # verdict comes from the shared allgathered payload)
            tel.event("recovery", action="checkpoint_now", iteration=it)
            try:
                self.checkpoint_fn(it)
            except Exception as e:
                log.warning("straggler checkpoint-now failed: %s", e)
        return ok
