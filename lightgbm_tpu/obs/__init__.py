"""Structured training telemetry.

The reference's only introspection is the compile-time TIMETAG section
timer (ref: include/LightGBM/utils/common.h:978); SURVEY §5 calls the
profiling gap out explicitly, and PROFILE.md documents why ad-hoc
wall-clock timing through the remote TPU tunnel cannot be trusted.  This
package is the permanent, low-overhead replacement:

- :class:`Telemetry` (registry.py) — thread-safe registry of counters,
  gauges and per-section timing distributions, plus a structured event
  stream (degradations with reasons, compile events, per-iteration
  records) that can sink to a JSONL file;
- :class:`JsonlSink` (events.py) — the rank-aware JSONL writer behind
  ``telemetry_out=<path>``;
- jaxmon.py — ``jax.monitoring`` bridge (XLA compile events) and device
  memory stats;
- trace.py — Perfetto/Chrome-trace exporter behind ``trace_out=<path>``
  (one track per rank, spans for sections/collectives/compiles);
- :class:`HealthAuditor` (health.py) — periodic cross-rank model-hash +
  straggler auditing behind ``health_check_period``.

Every recording method is a no-op behind a single attribute check while
the registry is disabled, so instrumentation stays in the hot driver
paths permanently, like the reference's TIMETAG sections.
"""
from .events import JsonlSink
from .health import HealthAuditor, model_state_hash
from .jaxmon import device_memory_stats
from .registry import Telemetry, allgather_json
from .trace import chrome_trace_events, write_trace

__all__ = ["Telemetry", "JsonlSink", "device_memory_stats",
           "allgather_json", "HealthAuditor", "model_state_hash",
           "chrome_trace_events", "write_trace"]
