"""Structured training telemetry.

The reference's only introspection is the compile-time TIMETAG section
timer (ref: include/LightGBM/utils/common.h:978); SURVEY §5 calls the
profiling gap out explicitly, and PROFILE.md documents why ad-hoc
wall-clock timing through the remote TPU tunnel cannot be trusted.  This
package is the permanent, low-overhead replacement:

- :class:`Telemetry` (registry.py) — thread-safe registry of counters,
  gauges and per-section timing distributions, plus a structured event
  stream (degradations with reasons, compile events, per-iteration
  records) that can sink to a JSONL file;
- :class:`JsonlSink` (events.py) — the rank-aware JSONL writer behind
  ``telemetry_out=<path>``;
- jaxmon.py — ``jax.monitoring`` bridge (XLA compile events) and device
  memory stats;
- trace.py — Perfetto/Chrome-trace exporter behind ``trace_out=<path>``
  (one track per rank, spans for sections/collectives/compiles);
- :class:`HealthAuditor` (health.py) — periodic cross-rank model-hash +
  straggler auditing behind ``health_check_period``;
- :class:`MetricsExporter` (export.py) — live OpenMetrics/Prometheus
  HTTP endpoint over the registry behind ``metrics_port=<p>`` (per-rank
  ports under multi-process; rank 0 appends the fleet counter view);
- reqtrace.py — request-scoped serving traces: a ``trace_id`` minted at
  ``PredictionService.submit()`` rides through the micro-batcher and
  engine dispatch into one ``serve_access`` JSONL record and one
  Perfetto span per request;
- :class:`ProfileControl` (export.py) — the on-demand profiling handoff
  behind ``POST /profile?iters=N``: the exporter arms it, the driver
  opens/closes a bounded ``jax.profiler`` window at its next drain
  boundary;
- :class:`CostLedger` (cost.py) — device-time cost ledger: per fresh
  executable signature ``cost_analysis()`` joined with measured wall
  times, collective payloads and the analytic histogram byte model into
  ``cost.*`` gauges and per-batch ``cost_ledger`` records;
- report.py — the schema-versioned consolidated run report
  (``run_report_out=<path>`` / ``GET /report``) that
  ``scripts/run_diff.py`` compares with deterministic-counter
  strictness;
- drift.py — the drift & lineage plane: training-data profiles
  (embedded in model artifacts + checkpoints), PSI/JS divergence, the
  serving-side :class:`DriftMonitor` and the provenance record chained
  through rollovers (docs/Observability.md §13);
- :class:`SloEngine` (slo.py) — the SLO plane: declarative objectives
  (built-in catalog + ``slo_config=<path>``) evaluated on a host-side
  ticker with multi-window burn-rate alerting, ``alert`` events,
  fleet/liveness watchdogs and bounded incident artifacts
  (docs/Observability.md §14).

Every recording method is a no-op behind a single attribute check while
the registry is disabled, so instrumentation stays in the hot driver
paths permanently, like the reference's TIMETAG sections.
"""
from .cost import CostLedger
from .drift import (DriftMonitor, build_profile, build_provenance,
                    canonical_json, js_divergence, profile_digest, psi)
from .events import JsonlSink
from .export import MetricsExporter, ProfileControl, render_openmetrics
from .health import HealthAuditor, model_state_hash
from .jaxmon import device_memory_stats, memory_watermarks
from .registry import Telemetry, allgather_json
from .report import (build_report, compare_reports, load_report,
                     render_markdown, write_report)
from .slo import BUILTIN_OBJECTIVES, SloEngine, SloSpec
from .trace import chrome_trace_events, write_trace

__all__ = ["Telemetry", "JsonlSink", "device_memory_stats",
           "memory_watermarks", "allgather_json", "HealthAuditor",
           "model_state_hash", "chrome_trace_events", "write_trace",
           "MetricsExporter", "render_openmetrics", "ProfileControl",
           "CostLedger", "build_report", "compare_reports",
           "load_report", "render_markdown", "write_report",
           "DriftMonitor", "build_profile", "build_provenance",
           "canonical_json", "js_divergence", "profile_digest", "psi",
           "SloEngine", "SloSpec", "BUILTIN_OBJECTIVES"]
