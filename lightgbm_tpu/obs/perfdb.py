"""Persistent, shape-keyed performance database of measured samples.

The roofline plane (obs/kernelstats.py) turns a profile window into
measured per-executable device times — but a single window is one
sample on one run.  The item-5 autotuner (and every hardware A/B queued
for the chip tunnel's return) needs those samples to ACCUMULATE across
runs into a durable, queryable history instead of one-off JSON blobs.
That history is this file format:

- **append-only JSONL** at ``perf_db=<path>`` — each line one sample,
  serialized into a single ``os.write`` to an ``O_APPEND`` descriptor,
  so concurrent writers (two bench runs, a training job and an
  ablation sweep) interleave whole lines, never torn ones;
- **schema-versioned** — every row carries ``schema``; ``load()``
  skips rows from a different major (and malformed lines) with a
  count, so a format bump never crashes an old reader;
- **shape-keyed** — rows are keyed by ``key_id``, a digest of
  (signature, kind, shape class, backend, quant bits, packed layout,
  world size): the tuple that determines which measured samples are
  comparable.  Same model shape + same backend + same layout knobs →
  same key → the samples form a distribution the autotuner (and
  ``scripts/run_diff.py --perf-db``) can consult at trace time.

Writers: the profile-window close hook in boosting/gbdt.py,
``bench.py`` and ``scripts/ablate_hist.py``.  Readers:
``scripts/perfdb_query.py`` and ``scripts/run_diff.py``.
docs/Observability.md §15 documents the row schema.
"""
from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Any, Dict, List, Optional

SCHEMA = "lightgbm_tpu.perfdb/1"

#: the comparability tuple — two samples share a key iff all of these
#: match (docs/Observability.md §15)
KEY_FIELDS = ("signature", "kind", "shape_class", "backend",
              "quant_bits", "packed_layout", "world_size")


def make_key(signature: str, kind: str, shape_class: str, backend: str,
             quant_bits: int = 0, packed_layout: bool = False,
             world_size: int = 1) -> Dict[str, Any]:
    """Canonical key dict (KEY_FIELDS order) with its ``key_id``
    digest attached."""
    key = {
        "signature": str(signature), "kind": str(kind),
        "shape_class": str(shape_class), "backend": str(backend),
        "quant_bits": int(quant_bits),
        "packed_layout": bool(packed_layout),
        "world_size": int(world_size),
    }
    canon = json.dumps([key[f] for f in KEY_FIELDS],
                       separators=(",", ":"))
    key["key_id"] = hashlib.sha1(canon.encode()).hexdigest()[:16]
    return key


def sample(key: Dict[str, Any], *, dispatches: int,
           device_time_us_per_dispatch: float,
           measured_fraction: Optional[float] = None,
           achieved_flops_per_s: Optional[float] = None,
           achieved_bytes_per_s: Optional[float] = None,
           source: str = "", run_id: str = "",
           extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """One measured row.  ``key`` comes from ``make_key``; measurement
    fields come from a joined roofline executable."""
    row: Dict[str, Any] = {
        "schema": SCHEMA,
        "key_id": key.get("key_id", ""),
        "key": {f: key.get(f) for f in KEY_FIELDS},
        "dispatches": int(dispatches),
        "device_time_us_per_dispatch": round(
            float(device_time_us_per_dispatch), 3),
        "source": str(source), "run_id": str(run_id),
        "ts": round(time.time(), 3),
    }
    if measured_fraction is not None:
        row["measured_fraction"] = round(float(measured_fraction), 6)
    if achieved_flops_per_s is not None:
        row["achieved_flops_per_s"] = float(achieved_flops_per_s)
    if achieved_bytes_per_s is not None:
        row["achieved_bytes_per_s"] = float(achieved_bytes_per_s)
    if extra:
        row.update(extra)
    return row


def samples_from_roofline(roofline: Dict[str, Any], *, shape_class: str,
                          backend: str, quant_bits: int = 0,
                          packed_layout: bool = False,
                          world_size: int = 1, source: str = "",
                          run_id: str = "") -> List[Dict[str, Any]]:
    """Every JOINED executable of a roofline record (kernelstats
    ``join_cost`` output) with non-zero measured device time -> one
    perfdb row.  Unjoined anchors have no signature to key on and are
    skipped (they already show up as join_coverage < 1.0)."""
    rows: List[Dict[str, Any]] = []
    for ex in roofline.get("executables", []) or []:
        if not ex.get("joined") or not ex.get("signature"):
            continue
        per_disp = ex.get("device_time_us_per_dispatch")
        if not isinstance(per_disp, (int, float)) or per_disp <= 0:
            continue
        key = make_key(ex["signature"], ex.get("kind", "?"),
                       shape_class, backend, quant_bits=quant_bits,
                       packed_layout=packed_layout,
                       world_size=world_size)
        extra = {}
        if ex.get("timing_source"):
            extra["timing_source"] = str(ex["timing_source"])
        rows.append(sample(
            key, dispatches=int(ex.get("dispatches", 0)),
            device_time_us_per_dispatch=float(per_disp),
            measured_fraction=ex.get("measured_fraction"),
            achieved_flops_per_s=ex.get("achieved_flops_per_s"),
            achieved_bytes_per_s=ex.get("achieved_bytes_per_s"),
            source=source, run_id=run_id, extra=extra))
    return rows


class PerfDB:
    """One perf database file.  Stateless beyond the path — every
    ``append`` opens, writes once and closes, so the handle never
    outlives a training run or pins a deleted file."""

    def __init__(self, path: str):
        self.path = str(path)

    # ---------------------------------------------------------- write
    def append(self, rows: List[Dict[str, Any]]) -> int:
        """Atomically append rows (one buffered ``os.write`` to an
        ``O_APPEND`` fd — concurrent appenders interleave whole lines).
        Returns the number of rows written; never raises (a perf
        database must never be the reason training dies)."""
        rows = [r for r in rows or [] if isinstance(r, dict)]
        if not rows:
            return 0
        try:
            buf = "".join(
                json.dumps(r, sort_keys=True, default=str) + "\n"
                for r in rows).encode("utf-8")
            parent = os.path.dirname(os.path.abspath(self.path))
            os.makedirs(parent, exist_ok=True)
            fd = os.open(self.path,
                         os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
            try:
                os.write(fd, buf)
            finally:
                os.close(fd)
            return len(rows)
        except (OSError, TypeError, ValueError):
            return 0

    # ----------------------------------------------------------- read
    def load(self) -> Dict[str, Any]:
        """Read every well-formed same-major row.  Malformed lines and
        foreign-schema rows are counted in ``skipped``, never raised —
        an interrupted writer or a future format must not brick the
        reader."""
        rows: List[Dict[str, Any]] = []
        skipped = 0
        major = SCHEMA.rsplit("/", 1)[0]
        try:
            with open(self.path) as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        row = json.loads(line)
                    except ValueError:
                        skipped += 1
                        continue
                    if not isinstance(row, dict) or not str(
                            row.get("schema", "")).startswith(
                                major + "/"):
                        skipped += 1
                        continue
                    rows.append(row)
        except OSError:
            pass
        return {"rows": rows, "skipped": skipped}

    def query(self, rows: Optional[List[Dict[str, Any]]] = None,
              **filters: Any) -> List[Dict[str, Any]]:
        """Filter rows by key fields (``signature`` matches on the
        full string OR its pre-``[`` base) and/or ``key_id`` /
        ``source``."""
        if rows is None:
            rows = self.load()["rows"]
        out = []
        for row in rows:
            key = row.get("key", {}) or {}
            ok = True
            for f, want in filters.items():
                if want in (None, ""):
                    continue
                if f in ("key_id", "source", "run_id"):
                    have = row.get(f)
                elif f == "signature":
                    have = key.get(f)
                    if have != want and str(have or "").split(
                            "[", 1)[0] != want:
                        ok = False
                        break
                    continue
                else:
                    have = key.get(f)
                if str(have) != str(want):
                    ok = False
                    break
            if ok:
                out.append(row)
        return out


def summarize(rows: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Group rows by key_id -> per-key summaries (sample count,
    mean/min/max/last measured device time per dispatch, best achieved
    rates), sorted by sample count then mean time — the
    ``perfdb_query.py`` view and run_diff's baseline source."""
    by_key: Dict[str, List[Dict[str, Any]]] = {}
    for row in rows:
        by_key.setdefault(str(row.get("key_id", "?")), []).append(row)
    out: List[Dict[str, Any]] = []
    for key_id, group in by_key.items():
        times = [float(r["device_time_us_per_dispatch"]) for r in group
                 if isinstance(r.get("device_time_us_per_dispatch"),
                               (int, float))]
        ent: Dict[str, Any] = {
            "key_id": key_id,
            "key": dict(group[-1].get("key", {}) or {}),
            "samples": len(group),
            "sources": sorted({str(r.get("source", "?"))
                               for r in group}),
        }
        if times:
            ent["device_time_us_per_dispatch"] = {
                "mean": round(sum(times) / len(times), 3),
                "min": round(min(times), 3),
                "max": round(max(times), 3),
                "last": round(times[-1], 3),
            }
        flops = [float(r["achieved_flops_per_s"]) for r in group
                 if isinstance(r.get("achieved_flops_per_s"),
                               (int, float))]
        if flops:
            ent["achieved_flops_per_s_best"] = max(flops)
        byts = [float(r["achieved_bytes_per_s"]) for r in group
                if isinstance(r.get("achieved_bytes_per_s"),
                              (int, float))]
        if byts:
            ent["achieved_bytes_per_s_best"] = max(byts)
        out.append(ent)
    out.sort(key=lambda e: (-e["samples"], e.get(
        "device_time_us_per_dispatch", {}).get("mean", 0.0)))
    return out
