"""JSONL event sink for the telemetry registry.

One JSON object per line.  Schema (docs/Observability.md):

- every record carries ``ts`` (unix seconds), ``rank`` (jax process
  index) and ``event`` (name);
- iteration records (``event == "iteration"``) add ``iter`` plus the
  per-iteration payload (``sections``, ``collectives``, ``compile``,
  ``num_leaves``, optionally ``memory``);
- other events carry their attributes as flat extra keys.

Multi-process runs write one file per rank: rank 0 owns the configured
path, rank r writes ``<path>.rank<r>`` (a shared file over NFS would
interleave partial lines).

Lifecycle: the FIRST open of a path in this process truncates it (a
fresh run starts a fresh stream); any later re-open — a
``reset_parameter(telemetry_out=...)`` re-enable after a close, or a
second booster pointed at the same file — appends, so an established
stream is never clobbered mid-process.
"""
from __future__ import annotations

import atexit
import json
import threading
from typing import Any, Dict

# paths this process has already opened: re-opens append (see module
# docstring) instead of truncating the earlier records
_OPENED_PATHS = set()


def _json_default(o: Any):
    """Last-resort coercion so numpy scalars / device arrays in event
    attributes cannot kill the sink."""
    for cast in (int, float):
        try:
            return cast(o)
        except (TypeError, ValueError):
            continue
    return str(o)


class JsonlSink:
    """Line-buffered JSONL writer (one flush per record — telemetry
    records are per-iteration scale, not per-op scale)."""

    def __init__(self, path: str, rank: int = 0):
        # the path as configured, BEFORE rank suffixing — Telemetry.enable
        # compares against it to decide whether a re-enable is the same
        # sink or a genuine re-target
        self.requested_path = path
        if rank:
            path = f"{path}.rank{rank}"
        self.path = path
        self._lock = threading.Lock()
        mode = "a" if path in _OPENED_PATHS else "w"
        self._fh = open(path, mode, buffering=1)
        _OPENED_PATHS.add(path)
        atexit.register(self.close)

    def write(self, record: Dict[str, Any]) -> None:
        line = json.dumps(record, separators=(",", ":"),
                          default=_json_default)
        with self._lock:
            if self._fh is not None:
                self._fh.write(line + "\n")

    def flush(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.flush()

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
