# coding: utf-8
"""Declarative SLO plane: objectives, burn-rate alerting, incident capture.

The :class:`SloEngine` closes the observability loop in-process.  Every
earlier plane (counters/gauges/dists, health audits, the exporter, the
cost ledger, drift monitors, per-lane fleet stats) is passive — something
external has to scrape or tail it to notice a problem.  The engine instead
evaluates a catalog of declarative objectives against live
``Telemetry`` snapshots on a host-side daemon ticker:

- each objective is an :class:`SloSpec` (id, signal kind, target,
  comparison, severity, fast/slow windows, hysteresis);
- each tick appends a ``(ts, measured, breach)`` sample to the
  objective's ring buffer and recomputes multi-window burn rates (the
  fraction of breaching samples inside the fast and slow windows);
- transitions use consecutive-breach hysteresis mirroring the
  AdmissionController's flap-proofing: ``hysteresis`` breaching ticks in
  a row (with both burn rates past ``burn_threshold``) fire the alert,
  ``resolve_hysteresis`` clean ticks in a row resolve it — a single
  outlier sample never pages;
- transitions emit structured ``alert`` events (state firing/resolved,
  objective id, measured vs target, burn rates, window) which land in
  the findings ring, the JSONL sink, and — via the ``slo.*`` counter
  namespace — the ``lgbm_slo_*`` Prometheus series;
- a firing alert captures a bounded incident artifact
  ``<telemetry_out>.incident.<id>.json`` reusing the crash
  flight-recorder payload (recent event/finding rings, counters,
  gauges) plus per-device memory + fragmentation and a caller-supplied
  context snapshot (per-lane serving stats, training iteration).

The evaluator is host-flag-only and dispatch-neutral by construction: it
reads host-side telemetry snapshots and never touches device arrays, so
an armed ticker adds zero dispatches (counter-asserted in bench + CI
exactly like the profile control).
"""
from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils import log

__all__ = ["SloSpec", "SloEngine", "BUILTIN_OBJECTIVES", "INCIDENT_SCHEMA"]

INCIDENT_SCHEMA = "lightgbm_tpu.incident/1"

# Samples kept per objective; windows select a suffix of this ring.
_SAMPLE_RING = 512
# Alert transitions kept for /alerts and the run report.
_HISTORY_RING = 64
# Incident artifacts are bounded per engine so a flapping objective
# cannot fill a disk.
_MAX_INCIDENTS = 8

_SEVERITIES = ("page", "ticket")
_COMPARISONS = ("above", "below")


class SloSpec:
    """One declarative objective.

    ``kind`` selects the signal extractor (see ``SloEngine._measure``);
    ``target`` is the threshold; ``comparison`` says which side of it is
    a breach (``"above"``: measured > target breaches).  ``hysteresis``
    consecutive breaching ticks fire, ``resolve_hysteresis`` clean ticks
    resolve.  ``plane`` gates which engines evaluate the objective
    (``"serve"``, ``"train"`` or ``"any"``).
    """

    __slots__ = ("id", "kind", "target", "comparison", "severity",
                 "hysteresis", "resolve_hysteresis", "fast_window_s",
                 "slow_window_s", "burn_threshold", "plane", "enabled",
                 "description")

    def __init__(self, id, kind, target, comparison="above",
                 severity="ticket", hysteresis=3, resolve_hysteresis=None,
                 fast_window_s=60.0, slow_window_s=600.0,
                 burn_threshold=0.5, plane="any", enabled=True,
                 description=""):
        if comparison not in _COMPARISONS:
            raise ValueError(f"slo comparison must be one of {_COMPARISONS}")
        if severity not in _SEVERITIES:
            raise ValueError(f"slo severity must be one of {_SEVERITIES}")
        self.id = str(id)
        self.kind = str(kind)
        self.target = float(target)
        self.comparison = comparison
        self.severity = severity
        self.hysteresis = max(1, int(hysteresis))
        self.resolve_hysteresis = max(1, int(
            self.hysteresis if resolve_hysteresis is None
            else resolve_hysteresis))
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.burn_threshold = float(burn_threshold)
        self.plane = str(plane)
        self.enabled = bool(enabled)
        self.description = str(description)

    def breaches(self, measured: float) -> bool:
        if self.comparison == "above":
            return measured > self.target
        return measured < self.target

    def to_dict(self) -> Dict[str, Any]:
        return {k: getattr(self, k) for k in self.__slots__}


def _spec(**kw) -> SloSpec:
    return SloSpec(**kw)


# Built-in catalog.  Targets are deliberately conservative: a healthy
# run (bench --micro / --serve clean legs) must produce zero alerts.
# Objectives whose feed is absent simply skip the tick (measured=None).
BUILTIN_OBJECTIVES: Tuple[SloSpec, ...] = (
    _spec(id="serve.latency_p99", kind="latency_p99", target=250.0,
          comparison="above", severity="page", hysteresis=2, plane="serve",
          description="serve.latency_ms p99 (ms) vs target"),
    _spec(id="serve.shed_ratio", kind="shed_ratio", target=0.05,
          comparison="above", severity="page", hysteresis=3, plane="serve",
          description="(shed+rejected)/offered request ratio per tick"),
    _spec(id="serve.lane_liveness", kind="lane_liveness", target=30.0,
          comparison="above", severity="page", hysteresis=2, plane="serve",
          description="seconds a lane queue has been non-empty with no "
                      "dispatch progress"),
    _spec(id="serve.spill_imbalance", kind="spill_ratio", target=0.25,
          comparison="above", severity="ticket", hysteresis=3, plane="serve",
          description="cross-lane spills per offered request"),
    _spec(id="serve.worker_liveness", kind="worker_wedged", target=0.0,
          comparison="above", severity="page", hysteresis=1, plane="serve",
          description="wedged (non-exiting) lane worker threads"),
    _spec(id="serve.shadow_divergence", kind="shadow_divergence",
          target=1e-3, comparison="above", severity="ticket", hysteresis=3,
          plane="serve",
          description="max |candidate - live| during rollover shadow scoring"),
    _spec(id="serve.model_age", kind="model_age", target=86400.0,
          comparison="above", severity="ticket", hysteresis=2, plane="serve",
          description="seconds since the freshest resident model was loaded"),
    _spec(id="serve.drift_score", kind="drift_ceiling", target=0.5,
          comparison="above", severity="ticket", hysteresis=3, plane="any",
          description="drift monitor PSI ceiling (drift.psi_max gauge)"),
    _spec(id="train.liveness", kind="train_liveness", target=600.0,
          comparison="above", severity="page", hysteresis=2, plane="train",
          description="seconds since the training loop last advanced "
                      "(drain-granularity heartbeat)"),
    _spec(id="train.iteration_rate", kind="iteration_rate", target=0.0,
          comparison="below", severity="ticket", hysteresis=3, plane="train",
          description="iterations/s floor; default 0 disables — set a "
                      "positive target via slo_config to arm"),
    _spec(id="train.straggler_skew", kind="straggler_skew", target=5.0,
          comparison="above", severity="ticket", hysteresis=3, plane="train",
          description="max cross-rank section skew ratio (health.skew.*)"),
    _spec(id="train.checkpoint_age", kind="checkpoint_age", target=3600.0,
          comparison="above", severity="ticket", hysteresis=2, plane="train",
          description="seconds since the last successful checkpoint write"),
    _spec(id="ingest.prefetch_starvation", kind="prefetch_starvation",
          target=0.5, comparison="above", severity="ticket", hysteresis=3,
          plane="train",
          description="fraction of wall time the host blocked on prefetch "
                      "transfer slots"),
    _spec(id="obs.scrape_staleness", kind="scrape_staleness", target=900.0,
          comparison="above", severity="ticket", hysteresis=2, plane="any",
          description="seconds since the exporter last served /metrics "
                      "(only once it has been scraped at all)"),
)

_BUILTIN_KINDS = frozenset(s.kind for s in BUILTIN_OBJECTIVES)


def load_slo_config(path: str) -> List[Dict[str, Any]]:
    """Parse a ``slo_config`` JSON file into raw objective dicts.

    Accepts either ``{"objectives": [...]}`` or a bare list.  Raises
    ``ValueError`` on malformed structure so callers can surface a
    config error instead of silently running without objectives.
    """
    with open(path, "r") as fh:
        raw = json.load(fh)
    if isinstance(raw, dict):
        raw = raw.get("objectives", [])
    if not isinstance(raw, list):
        raise ValueError("slo_config must be a list or {'objectives': [...]}")
    out = []
    for entry in raw:
        if not isinstance(entry, dict) or "id" not in entry:
            raise ValueError("each slo_config objective needs an 'id'")
        out.append(dict(entry))
    return out


class _ObjectiveState:
    __slots__ = ("spec", "samples", "over", "under", "firing", "alert_seq",
                 "fired_ts", "last_measured", "last_burn")

    def __init__(self, spec: SloSpec):
        self.spec = spec
        self.samples = collections.deque(maxlen=_SAMPLE_RING)
        self.over = 0
        self.under = 0
        self.firing = False
        self.alert_seq = 0
        self.fired_ts = None
        self.last_measured = None
        self.last_burn = (0.0, 0.0)

    def burn_rates(self, now: float) -> Tuple[float, float]:
        fast_n = fast_b = slow_n = slow_b = 0
        fast_cut = now - self.spec.fast_window_s
        slow_cut = now - self.spec.slow_window_s
        for ts, _m, breach in self.samples:
            if ts >= slow_cut:
                slow_n += 1
                slow_b += breach
                if ts >= fast_cut:
                    fast_n += 1
                    fast_b += breach
        fast = (fast_b / fast_n) if fast_n else 0.0
        slow = (slow_b / slow_n) if slow_n else 0.0
        return fast, slow


class SloEngine:
    """Evaluates declarative SLOs over live telemetry snapshots.

    Host-flag-only: ``step()`` reads ``telemetry.metrics_snapshot()``
    (pure host dicts), updates per-objective rings/streaks, and emits
    events/counters.  It never touches a device array, so arming the
    engine is dispatch-neutral.

    ``source`` selects which catalog planes are active (``"train"`` or
    ``"serve"``; objectives with ``plane="any"`` always run).
    ``context_fn`` is an optional zero-arg callable whose return value
    is embedded in incident artifacts (e.g. per-lane serving stats).
    """

    def __init__(self, telemetry, *, source="train", specs=None,
                 config_path="", tick_period_s=5.0, incident_base="",
                 context_fn: Optional[Callable[[], Any]] = None):
        self.tel = telemetry
        self.source = str(source)
        self.tick_period_s = float(tick_period_s)
        self.incident_base = str(incident_base or "")
        self.context_fn = context_fn
        self._mu = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_tick = 0.0
        self._prev_counters: Dict[str, float] = {}
        self._prev_tick_ts: Optional[float] = None
        self._lane_stall: Dict[str, float] = {}
        self._history: collections.deque = collections.deque(
            maxlen=_HISTORY_RING)
        self._incidents: List[str] = []
        self._fired = 0
        self._resolved = 0
        self._ticks = 0
        self._train_active = False
        self._last_heartbeat = None
        self._last_heartbeat_iter = None
        self._closed = False

        merged = self._build_specs(specs, config_path)
        self._objs: Dict[str, _ObjectiveState] = collections.OrderedDict(
            (s.id, _ObjectiveState(s)) for s in merged)
        self.tel.gauge("slo.objectives", float(len(self._objs)))

    # ------------------------------------------------------------ specs
    def _build_specs(self, specs, config_path) -> List[SloSpec]:
        catalog = collections.OrderedDict(
            (s.id, s) for s in (specs if specs is not None
                                else BUILTIN_OBJECTIVES))
        if config_path:
            try:
                entries = load_slo_config(config_path)
            except Exception as exc:  # malformed file: run the catalog
                log.warning("slo_config %s unreadable: %s", config_path, exc)
                self.tel.event("slo_config_error", path=str(config_path),
                               error=str(exc))
                entries = []
            for entry in entries:
                oid = str(entry.pop("id"))
                base = catalog.get(oid)
                if base is not None:
                    merged = base.to_dict()
                    merged.update(entry)
                elif "kind" in entry:
                    merged = dict(entry, id=oid)
                else:
                    log.warning("slo_config: new objective %r needs a "
                                "'kind'; skipped", oid)
                    self.tel.event("slo_config_error", objective=oid,
                                   error="missing kind")
                    continue
                if merged.get("kind") not in _BUILTIN_KINDS:
                    log.warning("slo_config: objective %r has unknown kind "
                                "%r; skipped", oid, merged.get("kind"))
                    self.tel.event("slo_config_error", objective=oid,
                                   error=f"unknown kind {merged.get('kind')}")
                    continue
                disabled = bool(merged.pop("disabled", False))
                merged.setdefault("id", oid)
                try:
                    spec = SloSpec(**{k: v for k, v in merged.items()
                                      if k in SloSpec.__slots__})
                except Exception as exc:
                    log.warning("slo_config: objective %r invalid: %s",
                                oid, exc)
                    self.tel.event("slo_config_error", objective=oid,
                                   error=str(exc))
                    continue
                spec.enabled = spec.enabled and not disabled
                catalog[oid] = spec
            self.tel.event("slo_config_loaded", path=str(config_path),
                           objectives=len(catalog))
        active = [s for s in catalog.values()
                  if s.enabled and s.plane in ("any", self.source)]
        return active

    # ------------------------------------------------------- lifecycle
    def start(self) -> None:
        """Start the daemon ticker; no-op when tick_period_s <= 0."""
        if self.tick_period_s <= 0 or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="slo-ticker", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.tick_period_s):
            try:
                self.step(force=True)
            except Exception as exc:  # never kill the ticker
                log.warning("slo tick failed: %s", exc)

    def stop(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    close = stop

    # ------------------------------------------------------ train feed
    def note_training_heartbeat(self, iteration=None) -> None:
        """Called by the trainer at drain granularity; arms train.liveness."""
        with self._mu:
            self._train_active = True
            self._last_heartbeat = self.tel.wall_now()
            if iteration is not None:
                self._last_heartbeat_iter = iteration

    def note_training_done(self) -> None:
        """Disarms the training liveness watchdog (clean finalize)."""
        with self._mu:
            self._train_active = False

    # ------------------------------------------------------------ step
    def step(self, now: Optional[float] = None, force: bool = False) -> bool:
        """Evaluate every objective once.  Time-gated unless ``force``.

        ``now`` is injectable for deterministic tests.  Returns True if
        a tick actually ran.
        """
        if self._closed and not force:
            return False
        if now is None:
            now = self.tel.wall_now()
        with self._mu:
            if not force and (now - self._last_tick) < self.tick_period_s:
                return False
            self._last_tick = now
            snap = self.tel.metrics_snapshot()
            counters = snap.get("counters", {}) or {}
            gauges = snap.get("gauges", {}) or {}
            dists = snap.get("dists", {}) or {}
            dt = (now - self._prev_tick_ts) if self._prev_tick_ts else 0.0
            transitions = []
            evaluated = 0
            for st in self._objs.values():
                measured = self._measure(st.spec, counters, gauges, dists,
                                         dt, now)
                if measured is None:
                    continue
                evaluated += 1
                tr = self._observe(st, measured, now)
                if tr is not None:
                    transitions.append(tr)
            self._prev_counters = dict(counters)
            self._prev_tick_ts = now
            self._ticks += 1
            active = sum(1 for st in self._objs.values() if st.firing)
        # Telemetry writes and incident capture outside the engine lock:
        # tel has its own mutex and incident capture does file I/O.
        self.tel.inc("slo.ticks")
        if evaluated:
            self.tel.inc("slo.evaluations", evaluated)
        self.tel.gauge("slo.active_alerts", float(active))
        for tr in transitions:
            self._emit_transition(tr)
        return True

    def _delta(self, counters: Dict[str, float], name: str) -> float:
        return float(counters.get(name, 0.0)) - float(
            self._prev_counters.get(name, 0.0))

    @staticmethod
    def _prefix_gauges(gauges: Dict[str, float], prefix: str):
        return [(k, v) for k, v in gauges.items() if k.startswith(prefix)]

    def _measure(self, spec: SloSpec, counters, gauges, dists, dt, now):
        """Extract the objective's signal; None = feed absent, skip tick."""
        kind = spec.kind
        if kind == "latency_p99":
            d = dists.get("serve.latency_ms")
            if not d or not d.get("count"):
                return None
            return float(d.get("p99", 0.0))
        if kind == "shed_ratio":
            shed = self._delta(counters, "serve.shed") + self._delta(
                counters, "serve.rejected")
            offered = self._delta(counters, "serve.requests") + self._delta(
                counters, "serve.rejected")
            if offered <= 0:
                return None
            return max(0.0, shed) / offered
        if kind == "spill_ratio":
            offered = self._delta(counters, "serve.requests")
            if offered <= 0:
                return None
            return max(0.0, self._delta(counters, "serve.spills")) / offered
        if kind == "worker_wedged":
            if "serve.requests" not in counters:
                return None
            return float(counters.get("serve.worker_wedged", 0.0))
        if kind == "lane_liveness":
            return self._lane_stall_seconds(counters, gauges, now)
        if kind == "shadow_divergence":
            v = gauges.get("serve.shadow_divergence")
            return None if v is None else float(v)
        if kind == "model_age":
            ages = self._prefix_gauges(gauges, "serve.model_age_s.")
            if not ages:
                return None
            return max(float(v) for _k, v in ages)
        if kind == "drift_ceiling":
            v = gauges.get("drift.psi_max")
            return None if v is None else float(v)
        if kind == "train_liveness":
            if not self._train_active or self._last_heartbeat is None:
                return None
            return max(0.0, now - self._last_heartbeat)
        if kind == "iteration_rate":
            if spec.target <= 0 or dt <= 0:
                return None
            it = self._delta(counters, "iterations")
            return it / dt
        if kind == "straggler_skew":
            skews = self._prefix_gauges(gauges, "health.skew.")
            if not skews:
                return None
            return max(float(v) for _k, v in skews)
        if kind == "checkpoint_age":
            ts = gauges.get("ckpt.last_write_ts")
            if ts is None:
                return None
            return max(0.0, now - float(ts))
        if kind == "prefetch_starvation":
            if dt <= 0 or "prefetch.chunks" not in counters:
                return None
            wait_ms = self._delta(counters, "prefetch.host_wait_ms")
            return max(0.0, wait_ms) / (dt * 1000.0)
        if kind == "scrape_staleness":
            ts = gauges.get("export.last_scrape_ts")
            if ts is None:
                return None
            return max(0.0, now - float(ts))
        return None

    def _lane_stall_seconds(self, counters, gauges, now) -> Optional[float]:
        """Max seconds any lane queue has been non-empty without dispatch
        progress.  Lanes are discovered from ``serve.d{i}.queue_depth``
        gauges; single-lane deployments fall back to the aggregates."""
        lanes = []
        for k, v in gauges.items():
            if k.startswith("serve.d") and k.endswith(".queue_depth"):
                lane = k[len("serve."):-len(".queue_depth")]
                lanes.append((lane, float(v),
                              float(counters.get(f"serve.{lane}.dispatches",
                                                 0.0))))
        if not lanes:
            if "serve.queue_depth" not in gauges:
                return None
            lanes = [("all", float(gauges.get("serve.queue_depth", 0.0)),
                      float(counters.get("serve.dispatches", 0.0)))]
        worst = 0.0
        for lane, depth, dispatches in lanes:
            prev = float(self._prev_counters.get(
                f"serve.{lane}.dispatches"
                if lane != "all" else "serve.dispatches", dispatches))
            stalled = depth > 0 and dispatches <= prev \
                and self._prev_tick_ts is not None
            if stalled:
                start = self._lane_stall.setdefault(lane, self._prev_tick_ts)
                worst = max(worst, now - start)
            else:
                self._lane_stall.pop(lane, None)
        return worst

    # ----------------------------------------------------- transitions
    def _observe(self, st: _ObjectiveState, measured: float, now: float):
        spec = st.spec
        breach = spec.breaches(measured)
        st.samples.append((now, float(measured), bool(breach)))
        st.last_measured = float(measured)
        if breach:
            st.over += 1
            st.under = 0
        else:
            st.under += 1
            st.over = 0
        fast, slow = st.burn_rates(now)
        st.last_burn = (fast, slow)
        if not st.firing:
            if (st.over >= spec.hysteresis and fast >= spec.burn_threshold
                    and slow >= spec.burn_threshold):
                st.firing = True
                st.alert_seq += 1
                st.fired_ts = now
                self._fired += 1
                return self._alert_record(st, "firing", measured, fast,
                                          slow, now)
        else:
            if st.under >= spec.resolve_hysteresis:
                st.firing = False
                rec = self._alert_record(st, "resolved", measured, fast,
                                         slow, now)
                rec["duration_s"] = round(now - (st.fired_ts or now), 3)
                st.fired_ts = None
                self._resolved += 1
                return rec
        return None

    def _alert_record(self, st: _ObjectiveState, state: str, measured,
                      fast, slow, now) -> Dict[str, Any]:
        spec = st.spec
        return {
            "state": state,
            "objective": spec.id,
            "alert_id": f"{spec.id}#{st.alert_seq}",
            "severity": spec.severity,
            "kind": spec.kind,
            "measured": round(float(measured), 6),
            "target": spec.target,
            "comparison": spec.comparison,
            "burn_fast": round(fast, 4),
            "burn_slow": round(slow, 4),
            "fast_window_s": spec.fast_window_s,
            "slow_window_s": spec.slow_window_s,
            "ts": now,
        }

    def _emit_transition(self, rec: Dict[str, Any]) -> None:
        self.tel.event("alert", **rec)
        self._history.append(dict(rec))
        if rec["state"] == "firing":
            self.tel.inc("slo.alerts_fired")
            if rec["severity"] == "page":
                self.tel.inc("slo.alerts_page")
            path = self._capture_incident(rec)
            if path:
                rec["incident"] = path
        else:
            self.tel.inc("slo.alerts_resolved")

    # -------------------------------------------------------- incident
    def _capture_incident(self, rec: Dict[str, Any]) -> Optional[str]:
        if not self.incident_base:
            return None
        if len(self._incidents) >= _MAX_INCIDENTS:
            self.tel.inc("slo.incidents_dropped")
            return None
        safe = rec["alert_id"].replace("#", "-").replace("/", "_")
        path = f"{self.incident_base}.incident.{safe}.json"
        try:
            payload = {
                "schema": INCIDENT_SCHEMA,
                "ts": rec["ts"],
                "rank": self.tel.rank,
                "run_id": self.tel.run_id,
                "source": self.source,
                "alert": dict(rec),
                "active_alerts": [s["alert_id"] for s in self.active_alerts()],
                "telemetry": self.tel.crash_payload(),
                "memory": self._memory_snapshot(),
            }
            if self.context_fn is not None:
                try:
                    payload["context"] = self.context_fn()
                except Exception as exc:
                    payload["context"] = {"error": str(exc)}
            from ..resilience.atomicio import atomic_write_text
            atomic_write_text(path, json.dumps(payload, indent=1,
                                               default=str))
        except Exception as exc:
            log.warning("incident capture failed for %s: %s",
                        rec["alert_id"], exc)
            return None
        self._incidents.append(path)
        self.tel.inc("slo.incidents")
        self.tel.event("incident_captured", objective=rec["objective"],
                       alert_id=rec["alert_id"], path=path)
        return path

    @staticmethod
    def _memory_snapshot() -> Dict[str, Any]:
        """Per-device memory + fragmentation; host stats API only."""
        out: Dict[str, Any] = {}
        try:
            from .jaxmon import device_memory_stats, fragmentation
            stats = device_memory_stats()
            for idx, ent in (stats or {}).items():
                entry = dict(ent)
                frag = fragmentation(ent)
                if frag is not None:
                    entry["fragmentation"] = frag
                out[str(idx)] = entry
        except Exception:
            pass
        return out

    # --------------------------------------------------------- queries
    def active_alerts(self) -> List[Dict[str, Any]]:
        out = []
        for st in self._objs.values():
            if not st.firing:
                continue
            out.append({
                "objective": st.spec.id,
                "alert_id": f"{st.spec.id}#{st.alert_seq}",
                "severity": st.spec.severity,
                "since_ts": st.fired_ts,
                "measured": st.last_measured,
                "target": st.spec.target,
                "burn_fast": round(st.last_burn[0], 4),
                "burn_slow": round(st.last_burn[1], 4),
            })
        return out

    def gating_reason(self) -> Optional[str]:
        """Objective id of a firing page-severity alert, else None.

        Used by ``/readyz`` when ``slo_readyz_gating`` is on."""
        for st in self._objs.values():
            if st.firing and st.spec.severity == "page":
                return st.spec.id
        return None

    def alerts_payload(self) -> Dict[str, Any]:
        """The ``GET /alerts`` + run-report ``alerts`` section source."""
        with self._mu:
            objectives = []
            for st in self._objs.values():
                objectives.append({
                    "id": st.spec.id,
                    "kind": st.spec.kind,
                    "target": st.spec.target,
                    "comparison": st.spec.comparison,
                    "severity": st.spec.severity,
                    "plane": st.spec.plane,
                    "firing": st.firing,
                    "last_measured": st.last_measured,
                    "breach_streak": st.over,
                    "burn_fast": round(st.last_burn[0], 4),
                    "burn_slow": round(st.last_burn[1], 4),
                    "samples": len(st.samples),
                })
            return {
                "run_id": self.tel.run_id,
                "rank": self.tel.rank,
                "source": self.source,
                "ticks": self._ticks,
                "fired": self._fired,
                "resolved": self._resolved,
                "active": self.active_alerts(),
                "objectives": objectives,
                "history": list(self._history),
                "incidents": list(self._incidents),
            }
