"""Thread-safe telemetry registry.

One :class:`Telemetry` instance per booster (GBDT driver).  It holds

- **counters** — monotone sums (iterations, collective bytes, degrade
  reasons, compile events);
- **gauges** — last-written values (device memory, bag counts);
- **timings** — per-name duration distributions ``{count, total, min,
  max}`` fed by the driver's per-iteration sections and by compile
  events;
- **events** — a bounded ring of structured records, mirrored to the
  JSONL sink when one is attached (``telemetry_out=<path>``);
- **records** — completed per-iteration records queued for the
  ``record_telemetry`` callback to drain;
- **spans** — wall-clock (start, duration) pairs collected only when the
  trace exporter is on (``trace_out=<path>``), drained by obs.trace into
  a Perfetto/Chrome-trace timeline (one track per rank).

Disabled-path contract: every recording method returns after a single
``self.enabled`` attribute check — no allocation, no locking, no
serialization — so the instrumentation can live in the training loop
permanently (the acceptance bar the ISSUE sets for the disabled path).

Rank handling: every record is tagged with ``jax.process_index()``;
``allgather_json`` is the SPMD helper the driver uses to aggregate
per-rank counter snapshots at rank 0 when emitting the end-of-training
summary.
"""
from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any, Dict, List, Optional

_EVENT_RING = 512       # bounded in-memory event history
_RECORD_RING = 65536    # per-iteration records awaiting a drain
_SPAN_RING = 16384      # trace spans awaiting export (a few per iteration)
_FINDING_RING = 1024    # health/guard findings kept for the whole run
_DIST_RING = 8192       # recent samples per value distribution
_FINDING_EVENTS = frozenset(
    {"anomaly", "rank_divergence", "straggler", "alert"})


class Telemetry:
    """Counters + gauges + timing distributions + structured events."""

    def __init__(self, enabled: bool = False):
        self.enabled = enabled
        # stable per-registry run identity: the exporter stamps it on
        # every metric series so scrapes from successive runs on the
        # same port are distinguishable in a time-series store (the
        # entropy tail keeps two registries born in the same second of
        # the same process distinct)
        self.run_id = (f"{int(time.time()):x}-{os.getpid():x}-"
                       f"{os.urandom(2).hex()}")
        self._lock = threading.RLock()
        # latest per-rank counter snapshots (fed by the health auditor's
        # existing allgather — obs/export.py renders rank 0's fleet view
        # from this, adding zero new collectives)
        self._fleet: List[Dict[str, Any]] = []
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._timings: Dict[str, Dict[str, float]] = {}
        self._events = collections.deque(maxlen=_EVENT_RING)
        self._findings = collections.deque(maxlen=_FINDING_RING)
        self._dists: Dict[str, collections.deque] = {}
        # cumulative [count, sum] per dist name: the ring bounds what
        # the QUANTILES cover, but OpenMetrics summary _count/_sum must
        # be monotone or Prometheus rate()/increase() breaks the moment
        # the ring wraps (count pins at maxlen, sum wobbles on evictions)
        self._dist_totals: Dict[str, List[float]] = {}
        self._records = collections.deque(maxlen=_RECORD_RING)
        self._spans = collections.deque(maxlen=_SPAN_RING)
        self._trace_on = False
        # trace timebase: wall-clock epoch + monotonic offsets, so span
        # timestamps stay comparable ACROSS ranks (shared epoch) yet a
        # mid-run NTP step cannot un-nest spans WITHIN a rank the way
        # raw time.time() starts + perf_counter durations would
        self._perf_epoch = time.time() - time.perf_counter()
        self._sink = None
        self._rank: Optional[int] = None
        # live section nesting (crash flight recorder reads this)
        self._section_stack: List[str] = []
        # per-iteration scratch (begin_iteration .. end_iteration)
        self._cur_iter: Optional[int] = None
        self._cur_iter_wall: Optional[float] = None
        self._cur_sections: Dict[str, float] = {}
        self._cur_collectives: Dict[str, Dict[str, int]] = {}
        self._cur_compile: Dict[str, float] = {}

    # ------------------------------------------------------------ admin
    @property
    def rank(self) -> int:
        if self._rank is None:
            try:
                import jax
                self._rank = int(jax.process_index())
            except Exception:
                self._rank = 0
        return self._rank

    def enable(self, sink_path: Optional[str] = None,
               trace: Optional[bool] = None) -> bool:
        """Turn recording on; ``sink_path`` additionally streams every
        event as a JSONL line (rank-suffixed under multi-process) and
        ``trace`` switches wall-clock span collection for the trace
        exporter on/off (``None`` leaves it as is, so an enable() from a
        path that doesn't know about tracing — e.g. record_telemetry —
        can't silently stop an active collection).  Returns True when a
        NEW sink was attached by this call (re-enabling with the path
        already attached is a no-op, so a
        ``reset_parameter(telemetry_out=...)`` round trip neither
        clobbers nor duplicates the stream; a *different* path closes
        the old sink and opens the new one)."""
        from . import jaxmon
        from .events import JsonlSink
        attached = False
        with self._lock:
            if sink_path:
                old = self._sink
                if old is not None and old.requested_path != sink_path:
                    old.close()
                    self._sink = None
                if self._sink is None:
                    self._sink = JsonlSink(sink_path, rank=self.rank)
                    attached = True
            if trace is not None:
                self._trace_on = bool(trace)
            self.enabled = True
        jaxmon.attach(self)
        return attached

    @property
    def sink_path(self) -> Optional[str]:
        """Path of the attached JSONL sink (rank-suffixed), or None —
        the public view drivers should use instead of ``_sink``."""
        sink = self._sink
        return None if sink is None else sink.path

    def disable(self) -> None:
        from . import jaxmon
        jaxmon.detach(self)
        self.flush()
        self.enabled = False

    def flush(self) -> None:
        sink = self._sink
        if sink is not None:
            sink.flush()

    def close(self) -> None:
        self.disable()
        sink, self._sink = self._sink, None
        if sink is not None:
            sink.close()

    # ------------------------------------------------------- primitives
    def inc(self, name: str, value: float = 1) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + value

    def gauge(self, name: str, value: float) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._gauges[name] = value

    def gauge_max(self, name: str, value: float) -> None:
        """High-watermark gauge: keeps the maximum ever recorded.  For
        series whose contract is a bound (peak live ingest chunks), a
        plain set() from a later, smaller observation would silently
        erase the violation the gauge exists to expose."""
        if not self.enabled:
            return
        with self._lock:
            prev = self._gauges.get(name)
            if prev is None or value > prev:
                self._gauges[name] = value

    def observe(self, name: str, seconds: float) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._observe_locked(name, seconds)

    def _observe_locked(self, name: str, seconds: float) -> None:
        t = self._timings.get(name)
        if t is None:
            t = self._timings[name] = {"count": 0, "total": 0.0,
                                       "min": float("inf"), "max": 0.0}
        t["count"] += 1
        t["total"] += seconds
        t["min"] = min(t["min"], seconds)
        t["max"] = max(t["max"], seconds)

    def dist(self, name: str, value: float) -> None:
        """Value-distribution sample (request latencies, micro-batch
        sizes): kept in a bounded ring per name so the snapshot can
        report real p50/p95/p99 quantiles, which the {count,total,
        min,max} ``observe`` timings cannot.  The ring bounds memory;
        quantiles cover the most recent ``_DIST_RING`` samples."""
        if not self.enabled:
            return
        with self._lock:
            d = self._dists.get(name)
            if d is None:
                d = self._dists[name] = collections.deque(
                    maxlen=_DIST_RING)
                self._dist_totals[name] = [0, 0.0]
            d.append(float(value))
            tot = self._dist_totals[name]
            tot[0] += 1
            tot[1] += float(value)

    @staticmethod
    def _dist_summary(samples, totals=None) -> Dict[str, float]:
        vals = sorted(samples)
        n = len(vals)
        count, total = (totals if totals is not None
                        else (n, float(sum(vals))))
        if n == 0:
            # empty-ring-safe: a dist observed zero samples (or whose
            # ring was drained) must summarize to count/sum only —
            # NEVER NaN quantiles; the exporter renders quantile series
            # only when count > 0
            return {"count": int(count), "sum": float(total)}

        def q(p: float) -> float:
            return vals[min(n - 1, int(p * (n - 1) + 0.5))]

        return {"count": int(count), "sum": float(total),
                "min": vals[0], "max": vals[-1],
                "p50": q(0.50), "p95": q(0.95), "p99": q(0.99)}

    def event(self, name: str, iteration: Optional[int] = None,
              **attrs: Any) -> None:
        """Structured event: ring-buffered, counted, sunk to JSONL."""
        if not self.enabled:
            return
        rec: Dict[str, Any] = {"ts": time.time(), "rank": self.rank,
                               "event": name}
        if iteration is not None:
            rec["iter"] = int(iteration)
        rec.update(attrs)
        with self._lock:
            self._events.append(rec)
            if name in _FINDING_EVENTS:
                # health/guard findings survive in their own ring: the
                # general event ring evicts them within ~500 iterations,
                # but "did anything go wrong" must answer for the whole
                # run (record_telemetry's anomalies list reads this)
                self._findings.append(rec)
            key = "events." + name
            self._counters[key] = self._counters.get(key, 0) + 1
            sink = self._sink
        if sink is not None:
            sink.write(rec)

    def anomaly(self, kind: str, iteration: Optional[int] = None,
                **attrs: Any) -> None:
        """Numerical-guard finding (non-finite gradients, histogram or
        tree outputs, degenerate gain distributions): counted under
        ``anomalies.<kind>`` and emitted as a structured ``anomaly``
        event — the record IS the alarm, not a log string."""
        if not self.enabled:
            return
        self.inc("anomalies." + kind)
        self.event("anomaly", iteration=iteration, kind=kind, **attrs)

    def degrade(self, reason: str, **attrs: Any) -> None:
        """A requested mode/engine fell back: the reason is the record,
        not a log string (the registry's analog of the driver's
        log.warning degradation messages)."""
        if not self.enabled:
            return
        self.inc("degrade." + reason)
        self.event("degrade", reason=reason, **attrs)

    # ------------------------------------------------------ trace spans
    def wall_now(self) -> float:
        """Monotonic 'wall clock' for span starts: the process-start
        wall epoch plus a perf_counter offset.  Every span producer must
        use this (not time.time()) so durations and starts share one
        clock and nesting survives NTP steps."""
        return self._perf_epoch + time.perf_counter()

    def span(self, name: str, wall_start: float, seconds: float,
             track: str = "train", iteration: Optional[int] = None,
             **attrs: Any) -> None:
        """Wall-clock span for the trace exporter (collected only while
        ``trace_out`` turned span collection on; ``seconds == 0`` renders
        as an instant event)."""
        if not (self.enabled and self._trace_on):
            return
        rec: Dict[str, Any] = {"name": name, "ts": float(wall_start),
                               "dur": float(seconds), "rank": self.rank,
                               "track": track}
        if iteration is not None:
            rec["iter"] = int(iteration)
        if attrs:
            rec["args"] = attrs
        with self._lock:
            if len(self._spans) == self._spans.maxlen:
                # ring is full: the append below evicts the oldest span,
                # truncating the front of the exported timeline — count
                # it so trace_written can say so instead of lying
                self._counters["trace.spans_dropped"] = \
                    self._counters.get("trace.spans_dropped", 0) + 1
            self._spans.append(rec)

    def drain_spans(self) -> List[Dict[str, Any]]:
        """Collected trace spans since the last drain (the trace
        exporter's feed; cleared so a second finalize writes nothing)."""
        with self._lock:
            out = list(self._spans)
            self._spans.clear()
        return out

    # ------------------------------------------------- crash bookkeeping
    def push_section(self, name: str) -> None:
        """Driver section entry — the stack is what the crash flight
        recorder dumps as 'where training was' when an exception
        unwinds."""
        if self.enabled:
            self._section_stack.append(name)

    def pop_section(self) -> None:
        if self.enabled and self._section_stack:
            self._section_stack.pop()

    def crash_payload(self) -> Dict[str, Any]:
        """Flight-recorder view: the full event ring (not the JSONL
        tail, which may be lost in a crash), the live section stack and
        the counter/gauge state — everything the registry knows at the
        moment of an exception."""
        with self._lock:
            return {
                "rank": self.rank,
                "section_stack": list(self._section_stack),
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "events": [dict(e) for e in self._events],
                "findings": [dict(e) for e in self._findings],
            }

    # ---------------------------------------------------- per-iteration
    def begin_iteration(self, it: int) -> None:
        if not self.enabled:
            return
        with self._lock:
            # a caught-and-recovered exception leaves its sections on the
            # stack (pop is clean-exit only); a fresh iteration starting
            # means the unwind is over, so the stale entries would only
            # mislead a later crash dump
            self._section_stack.clear()
            self._cur_iter = int(it)
            self._cur_iter_wall = self.wall_now()
            self._cur_sections = {}
            self._cur_collectives = {}
            self._cur_compile = {"count": 0, "secs": 0.0}

    def section(self, name: str, seconds: float,
                wall_start: Optional[float] = None) -> None:
        """Accumulate a named section's duration into the current
        iteration record and the global timing distribution (plus a
        trace span when the caller knows the wall-clock start)."""
        if not self.enabled:
            return
        with self._lock:
            self._cur_sections[name] = (self._cur_sections.get(name, 0.0)
                                        + seconds)
            self._observe_locked("section." + name, seconds)
            it = self._cur_iter
        if wall_start is not None:
            self.span(name, wall_start, seconds, track="train",
                      iteration=it)

    def collective(self, kind: str, count: int, nbytes: int,
                   seconds: Optional[float] = None,
                   wall_start: Optional[float] = None) -> None:
        """Record collective traffic (count + payload bytes) against the
        current iteration (if one is open) and the global counters.
        Real (host-plane) collectives pass their measured ``seconds`` —
        they feed the timing distribution and render as trace spans;
        analytic in-jit estimates pass none and render as instants."""
        if not self.enabled:
            return
        with self._lock:
            if self._cur_iter is not None:
                c = self._cur_collectives.setdefault(
                    kind, {"count": 0, "bytes": 0})
                c["count"] += int(count)
                c["bytes"] += int(nbytes)
            self._counters["collectives.count"] = \
                self._counters.get("collectives.count", 0) + int(count)
            self._counters["collectives.bytes"] = \
                self._counters.get("collectives.bytes", 0) + int(nbytes)
            if seconds is not None:
                self._observe_locked("collective." + kind, seconds)
        if self._trace_on:
            self.span(kind,
                      wall_start if wall_start is not None
                      else self.wall_now(),
                      seconds or 0.0, track="collectives",
                      count=int(count), bytes=int(nbytes))

    def compile_event(self, phase: str, seconds: float,
                      **attrs: Any) -> None:
        """XLA compile phase (fed by obs.jaxmon); attributed to the open
        iteration when one is active.  ``attrs`` carry whatever identity
        jax.monitoring passed along (e.g. ``fun_name`` on newer jax) —
        kept on the counters so the exporter can expose recompile
        rates, not per-phase JSONL spam."""
        if not self.enabled:
            return
        with self._lock:
            self._counters["compile.events"] = \
                self._counters.get("compile.events", 0) + 1
            self._counters["compile.seconds"] = \
                self._counters.get("compile.seconds", 0) + float(seconds)
            self._observe_locked("compile." + phase, seconds)
            if self._cur_iter is not None:
                self._cur_compile["count"] += 1
                self._cur_compile["secs"] += seconds
        if self._trace_on:
            # the monitoring callback fires at phase END; back-date the
            # span so it occupies its real window on the compile track
            now = self.wall_now()
            self.span("compile:" + phase, now - seconds, seconds,
                      track="compile", **attrs)

    def compile_executable(self, signature: str, compile_ms: float,
                           operand_bytes: int, **attrs: Any) -> None:
        """Per-executable compile accounting: one structured event per
        NEW jit signature (megastep chunk, serving bucket) carrying the
        signature, the first-call wall time (trace + XLA compile) and an
        estimate of the operand bytes the executable touches — the
        record the exporter's recompile-rate and HBM-headroom story
        hangs off (compiles are rare; the event volume is bounded by
        the number of distinct signatures)."""
        if not self.enabled:
            return
        self.inc("compile.executables")
        self.inc("compile.operand_bytes", max(0, int(operand_bytes)))
        self.event("compile_executable", signature=str(signature),
                   compile_ms=round(float(compile_ms), 3),
                   operand_bytes=int(operand_bytes), **attrs)

    # ----------------------------------------------------- fleet counters
    def set_fleet_counters(self, per_rank: List[Dict[str, Any]]) -> None:
        """Store the newest per-rank counter snapshots (each entry
        ``{"rank": r, "counters": {...}}``) — fed by the health
        auditor's existing allgather so the metrics exporter's rank-0
        fleet view costs zero additional collectives."""
        with self._lock:
            self._fleet = [dict(e) for e in per_rank
                           if isinstance(e, dict)]

    def fleet_counters(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(e) for e in self._fleet]

    def end_iteration(self, it: int, **attrs: Any) -> Dict[str, Any]:
        """Close the iteration: emit its record (sections, collectives,
        compile activity + caller attrs), queue it for draining and
        return it (the health auditor reads the section times off the
        returned record)."""
        if not self.enabled:
            return {}
        with self._lock:
            sections = {k: round(v, 9)
                        for k, v in self._cur_sections.items()}
            coll = {k: dict(v) for k, v in self._cur_collectives.items()}
            comp = dict(self._cur_compile)
            comp["secs"] = round(comp.get("secs", 0.0), 9)
            wall0 = self._cur_iter_wall
            self._cur_iter = None
            self._cur_iter_wall = None
            self._counters["iterations"] = \
                self._counters.get("iterations", 0) + 1
            rec: Dict[str, Any] = {"ts": time.time(), "rank": self.rank,
                                   "event": "iteration", "iter": int(it),
                                   "sections": sections,
                                   "collectives": coll, "compile": comp}
            rec.update(attrs)
            self._events.append(rec)
            self._records.append(rec)
            sink = self._sink
        if sink is not None:
            sink.write(rec)
        if wall0 is not None:
            # enclosing span on the same track as the section spans, so
            # a trace viewer nests boosting/histogram_split/... inside it
            self.span("iteration", wall0, self.wall_now() - wall0,
                      track="train", iteration=it)
        return rec

    def megastep(self, it0: int, iterations: int, kept: int,
                 sections: Dict[str, float],
                 wall_start: Optional[float] = None,
                 **attrs: Any) -> Dict[str, Any]:
        """Batch-granularity training record: one megastep (or drained
        fast-path batch) covering iterations ``[it0, it0+iterations)``.
        The fast path cannot attribute per-section times without
        synchronizing every phase, so at ``telemetry_granularity=batch``
        wall time is attributed per drained batch instead — ``kept`` is
        how many of the batch's iterations survived the drain (a
        no-more-splits stop discards the tail). Counts toward the
        ``iterations`` counter like ``kept`` end_iteration calls and is
        queued for the record_telemetry callback."""
        if not self.enabled:
            return {}
        secs = {k: round(float(v), 9) for k, v in (sections or {}).items()}
        rec: Dict[str, Any] = {"ts": time.time(), "rank": self.rank,
                               "event": "megastep", "iter": int(it0),
                               "iterations": int(iterations),
                               "kept": int(kept), "sections": secs}
        rec.update(attrs)
        with self._lock:
            self._counters["iterations"] = \
                self._counters.get("iterations", 0) + int(kept)
            self._counters["events.megastep"] = \
                self._counters.get("events.megastep", 0) + 1
            for name, v in secs.items():
                self._observe_locked("section." + name, v)
            self._events.append(rec)
            self._records.append(rec)
            sink = self._sink
        if sink is not None:
            sink.write(rec)
        if wall_start is not None and secs:
            self.span("megastep", wall_start,
                      max(secs.values()), track="train", iteration=it0)
        return rec

    def restore_counters(self, counters: Dict[str, float]) -> None:
        """Seed the counter map from a checkpoint snapshot so a resumed
        run's dashboards continue instead of resetting (resilience/
        state.py). Saved values REPLACE current ones — restore happens
        before training resumes, when the registry is fresh."""
        if not counters:
            return
        with self._lock:
            for key, v in counters.items():
                try:
                    self._counters[str(key)] = float(v)
                except (TypeError, ValueError):
                    continue

    def drain_records(self) -> List[Dict[str, Any]]:
        """Completed iteration records since the last drain (the
        record_telemetry callback's feed)."""
        with self._lock:
            out = list(self._records)
            self._records.clear()
        return out

    # --------------------------------------------------------- snapshot
    def counters_snapshot(self) -> Dict[str, float]:
        """Counters alone — the cheap view the health auditor ships in
        its allgather payload (snapshot() copies the whole event ring,
        which a per-period collective should not)."""
        with self._lock:
            return dict(self._counters)

    def metrics_snapshot(self) -> Dict[str, Any]:
        """Counters/gauges/timings/dists WITHOUT the event rings — the
        exporter's per-scrape view (obs/export.py).  A busy serving
        process holds ~1500 event dicts in its rings; deep-copying them
        under the registry lock on every 15-second Prometheus scrape
        would contend with the batcher's hot-path ``event()`` calls for
        data the exposition never renders.  Dist ``count``/``sum`` are
        CUMULATIVE (monotone — what OpenMetrics summaries require);
        quantiles/min/max cover the bounded recent-sample ring."""
        with self._lock:
            return {
                "enabled": self.enabled,
                "rank": self.rank,
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "timings": {k: dict(v) for k, v in self._timings.items()},
                "dists": {k: self._dist_summary(v, self._dist_totals[k])
                          for k, v in self._dists.items() if v},
            }

    def snapshot(self) -> Dict[str, Any]:
        """Point-in-time dict view: counters, gauges, timing
        distributions and the recent event ring (rank-local; the
        end-of-training summary event carries the rank aggregate)."""
        with self._lock:
            return {
                "enabled": self.enabled,
                "rank": self.rank,
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "timings": {k: dict(v) for k, v in self._timings.items()},
                "dists": {k: self._dist_summary(v, self._dist_totals[k])
                          for k, v in self._dists.items() if v},
                "events": [dict(e) for e in self._events],
                "findings": [dict(e) for e in self._findings],
            }


def allgather_json(obj: Any) -> List[Any]:
    """SPMD allgather of one JSON-serializable value per rank (returns
    ``[obj]`` single-process).  Every rank must call this at the same
    point — the driver only does so from finalize_telemetry, which runs
    on all ranks by the SPMD contract."""
    import json as _json

    import jax
    import numpy as np

    if jax.process_count() <= 1:
        return [obj]
    from jax.experimental import multihost_utils

    from ..resilience.comms import guarded_call
    payload = np.frombuffer(_json.dumps(obj).encode("utf-8"), np.uint8)
    # guarded: with collective_timeout configured, a hung peer degrades
    # to a structured CollectiveError here instead of wedging this rank
    # inside the native allgather forever
    sizes = np.asarray(guarded_call(
        lambda: multihost_utils.process_allgather(
            np.asarray([payload.size], np.int64)),
        what="allgather_json/sizes")).reshape(-1)
    width = int(sizes.max())
    buf = np.zeros(width, np.uint8)
    buf[:payload.size] = payload
    gathered = np.asarray(guarded_call(
        lambda: multihost_utils.process_allgather(buf),
        what="allgather_json/payload")).reshape(sizes.size, width)
    return [_json.loads(bytes(gathered[r, :int(sizes[r])]).decode("utf-8"))
            for r in range(sizes.size)]
