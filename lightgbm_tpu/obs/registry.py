"""Thread-safe telemetry registry.

One :class:`Telemetry` instance per booster (GBDT driver).  It holds

- **counters** — monotone sums (iterations, collective bytes, degrade
  reasons, compile events);
- **gauges** — last-written values (device memory, bag counts);
- **timings** — per-name duration distributions ``{count, total, min,
  max}`` fed by the driver's per-iteration sections and by compile
  events;
- **events** — a bounded ring of structured records, mirrored to the
  JSONL sink when one is attached (``telemetry_out=<path>``);
- **records** — completed per-iteration records queued for the
  ``record_telemetry`` callback to drain.

Disabled-path contract: every recording method returns after a single
``self.enabled`` attribute check — no allocation, no locking, no
serialization — so the instrumentation can live in the training loop
permanently (the acceptance bar the ISSUE sets for the disabled path).

Rank handling: every record is tagged with ``jax.process_index()``;
``allgather_json`` is the SPMD helper the driver uses to aggregate
per-rank counter snapshots at rank 0 when emitting the end-of-training
summary.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, List, Optional

_EVENT_RING = 512       # bounded in-memory event history
_RECORD_RING = 65536    # per-iteration records awaiting a drain


class Telemetry:
    """Counters + gauges + timing distributions + structured events."""

    def __init__(self, enabled: bool = False):
        self.enabled = enabled
        self._lock = threading.RLock()
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._timings: Dict[str, Dict[str, float]] = {}
        self._events = collections.deque(maxlen=_EVENT_RING)
        self._records = collections.deque(maxlen=_RECORD_RING)
        self._sink = None
        self._rank: Optional[int] = None
        # per-iteration scratch (begin_iteration .. end_iteration)
        self._cur_iter: Optional[int] = None
        self._cur_sections: Dict[str, float] = {}
        self._cur_collectives: Dict[str, Dict[str, int]] = {}
        self._cur_compile: Dict[str, float] = {}

    # ------------------------------------------------------------ admin
    @property
    def rank(self) -> int:
        if self._rank is None:
            try:
                import jax
                self._rank = int(jax.process_index())
            except Exception:
                self._rank = 0
        return self._rank

    def enable(self, sink_path: Optional[str] = None) -> None:
        """Turn recording on; ``sink_path`` additionally streams every
        event as a JSONL line (rank-suffixed under multi-process)."""
        from . import jaxmon
        from .events import JsonlSink
        with self._lock:
            if sink_path and self._sink is None:
                self._sink = JsonlSink(sink_path, rank=self.rank)
            self.enabled = True
        jaxmon.attach(self)

    def disable(self) -> None:
        from . import jaxmon
        jaxmon.detach(self)
        self.flush()
        self.enabled = False

    def flush(self) -> None:
        sink = self._sink
        if sink is not None:
            sink.flush()

    def close(self) -> None:
        self.disable()
        sink, self._sink = self._sink, None
        if sink is not None:
            sink.close()

    # ------------------------------------------------------- primitives
    def inc(self, name: str, value: float = 1) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + value

    def gauge(self, name: str, value: float) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._gauges[name] = value

    def observe(self, name: str, seconds: float) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._observe_locked(name, seconds)

    def _observe_locked(self, name: str, seconds: float) -> None:
        t = self._timings.get(name)
        if t is None:
            t = self._timings[name] = {"count": 0, "total": 0.0,
                                       "min": float("inf"), "max": 0.0}
        t["count"] += 1
        t["total"] += seconds
        t["min"] = min(t["min"], seconds)
        t["max"] = max(t["max"], seconds)

    def event(self, name: str, iteration: Optional[int] = None,
              **attrs: Any) -> None:
        """Structured event: ring-buffered, counted, sunk to JSONL."""
        if not self.enabled:
            return
        rec: Dict[str, Any] = {"ts": time.time(), "rank": self.rank,
                               "event": name}
        if iteration is not None:
            rec["iter"] = int(iteration)
        rec.update(attrs)
        with self._lock:
            self._events.append(rec)
            key = "events." + name
            self._counters[key] = self._counters.get(key, 0) + 1
            sink = self._sink
        if sink is not None:
            sink.write(rec)

    def degrade(self, reason: str, **attrs: Any) -> None:
        """A requested mode/engine fell back: the reason is the record,
        not a log string (the registry's analog of the driver's
        log.warning degradation messages)."""
        if not self.enabled:
            return
        self.inc("degrade." + reason)
        self.event("degrade", reason=reason, **attrs)

    # ---------------------------------------------------- per-iteration
    def begin_iteration(self, it: int) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._cur_iter = int(it)
            self._cur_sections = {}
            self._cur_collectives = {}
            self._cur_compile = {"count": 0, "secs": 0.0}

    def section(self, name: str, seconds: float) -> None:
        """Accumulate a named section's duration into the current
        iteration record and the global timing distribution."""
        if not self.enabled:
            return
        with self._lock:
            self._cur_sections[name] = (self._cur_sections.get(name, 0.0)
                                        + seconds)
            self._observe_locked("section." + name, seconds)

    def collective(self, kind: str, count: int, nbytes: int) -> None:
        """Record collective traffic (count + payload bytes) against the
        current iteration (if one is open) and the global counters."""
        if not self.enabled:
            return
        with self._lock:
            if self._cur_iter is not None:
                c = self._cur_collectives.setdefault(
                    kind, {"count": 0, "bytes": 0})
                c["count"] += int(count)
                c["bytes"] += int(nbytes)
            self._counters["collectives.count"] = \
                self._counters.get("collectives.count", 0) + int(count)
            self._counters["collectives.bytes"] = \
                self._counters.get("collectives.bytes", 0) + int(nbytes)

    def compile_event(self, phase: str, seconds: float) -> None:
        """XLA compile phase (fed by obs.jaxmon); attributed to the open
        iteration when one is active."""
        if not self.enabled:
            return
        with self._lock:
            self._counters["compile.events"] = \
                self._counters.get("compile.events", 0) + 1
            self._observe_locked("compile." + phase, seconds)
            if self._cur_iter is not None:
                self._cur_compile["count"] += 1
                self._cur_compile["secs"] += seconds

    def end_iteration(self, it: int, **attrs: Any) -> None:
        """Close the iteration: emit its record (sections, collectives,
        compile activity + caller attrs) and queue it for draining."""
        if not self.enabled:
            return
        with self._lock:
            sections = {k: round(v, 9)
                        for k, v in self._cur_sections.items()}
            coll = {k: dict(v) for k, v in self._cur_collectives.items()}
            comp = dict(self._cur_compile)
            comp["secs"] = round(comp.get("secs", 0.0), 9)
            self._cur_iter = None
            self._counters["iterations"] = \
                self._counters.get("iterations", 0) + 1
            rec: Dict[str, Any] = {"ts": time.time(), "rank": self.rank,
                                   "event": "iteration", "iter": int(it),
                                   "sections": sections,
                                   "collectives": coll, "compile": comp}
            rec.update(attrs)
            self._events.append(rec)
            self._records.append(rec)
            sink = self._sink
        if sink is not None:
            sink.write(rec)

    def drain_records(self) -> List[Dict[str, Any]]:
        """Completed iteration records since the last drain (the
        record_telemetry callback's feed)."""
        with self._lock:
            out = list(self._records)
            self._records.clear()
        return out

    # --------------------------------------------------------- snapshot
    def snapshot(self) -> Dict[str, Any]:
        """Point-in-time dict view: counters, gauges, timing
        distributions and the recent event ring (rank-local; the
        end-of-training summary event carries the rank aggregate)."""
        with self._lock:
            return {
                "enabled": self.enabled,
                "rank": self.rank,
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "timings": {k: dict(v) for k, v in self._timings.items()},
                "events": [dict(e) for e in self._events],
            }


def allgather_json(obj: Any) -> List[Any]:
    """SPMD allgather of one JSON-serializable value per rank (returns
    ``[obj]`` single-process).  Every rank must call this at the same
    point — the driver only does so from finalize_telemetry, which runs
    on all ranks by the SPMD contract."""
    import json as _json

    import jax
    import numpy as np

    if jax.process_count() <= 1:
        return [obj]
    from jax.experimental import multihost_utils
    payload = np.frombuffer(_json.dumps(obj).encode("utf-8"), np.uint8)
    sizes = np.asarray(multihost_utils.process_allgather(
        np.asarray([payload.size], np.int64))).reshape(-1)
    width = int(sizes.max())
    buf = np.zeros(width, np.uint8)
    buf[:payload.size] = payload
    gathered = np.asarray(multihost_utils.process_allgather(buf)) \
        .reshape(sizes.size, width)
    return [_json.loads(bytes(gathered[r, :int(sizes[r])]).decode("utf-8"))
            for r in range(sizes.size)]
