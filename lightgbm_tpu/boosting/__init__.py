"""Boosting variants factory (ref: src/boosting/boosting.cpp:36
Boosting::CreateBoosting)."""
from __future__ import annotations

from ..config import Config
from ..utils import log
from .gbdt import DART, GBDT, GOSS, RF


def create_boosting(config: Config):
    name = config.boosting
    if name in ("gbdt", "gbrt"):
        return GBDT()
    if name == "dart":
        return DART()
    if name == "goss":
        return GOSS()
    if name in ("rf", "random_forest"):
        return RF()
    log.fatal("Unknown boosting type %s", name)
