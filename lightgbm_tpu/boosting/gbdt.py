"""GBDT training driver + DART / GOSS / RF variants.

TPU-native analog of the reference boosting layer (ref: src/boosting/gbdt.cpp,
dart.hpp, goss.hpp, rf.hpp).  Orchestration (per-iteration bookkeeping, model
list, bagging index logic, early stopping) runs on host; all O(num_data) math
— gradients, histograms, tree growth, score updates — runs jit-compiled on
device.  Semantics follow gbdt.cpp:371 TrainOneIter:

    boost-from-average -> gradients -> bagging -> per-class tree train ->
    renew leaf outputs -> shrinkage -> score update -> (bias on first iter)
"""
from __future__ import annotations

import contextlib
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..dataset import TpuDataset
from ..models.learner import FeatureMeta, grow_tree_depthwise, grow_tree_leafwise
from ..models.tree import HostTree, TreeArrays
from ..obs import Telemetry
from ..ops.predict import add_tree_score
from ..ops.split import SplitParams, calculate_leaf_output
from ..utils import log
from ..parallel.mesh import donate_argnums as _donate
from ..parallel.mesh import shard_map as _shard_map
from ..utils.timer import global_timer as timer
from ..utils import random as ref_random

K_EPSILON = 1e-15


@jax.jit
def _count_nonfinite(grad, hess):
    """NaN/Inf element counts for the numerical guards (one fused
    reduction; on sharded inputs the replicated scalars come back to
    every rank, so the guard works unchanged under multi-process)."""
    return (jnp.sum(~jnp.isfinite(grad)), jnp.sum(~jnp.isfinite(hess)))


class _SecHandle:
    """Late-bound sync target for a timed section: the arrays to block
    on are produced INSIDE the section body (``with self._sec(..) as s:
    ...; s.sync(tree)``), so the handle carries them to section exit —
    the honest-attribution idiom timer.section(sync=...) can't express
    for values that don't exist yet."""

    __slots__ = ("_sync",)

    def __init__(self):
        self._sync = None

    def sync(self, arrays) -> None:
        self._sync = arrays


class _NullSecHandle:
    """Disabled-path handle: sync() must NOT store its argument — a
    module-level global retaining the last score matrix would pin its
    device buffer for the process lifetime."""

    __slots__ = ()

    def sync(self, arrays) -> None:
        pass


# shared no-op handle: zero per-section allocation when telemetry and
# the TIMETAG timer are both off
_NULL_SEC = _NullSecHandle()


def feature_meta_from_dataset(ds: TpuDataset) -> FeatureMeta:
    default_bins = np.array([ds.mappers[j].default_bin for j in
                             ds.used_features], np.int32)
    if ds.monotone_constraints is not None:
        mono = ds.monotone_constraints[ds.used_features].astype(np.int32)
    else:
        mono = np.zeros(ds.num_features, np.int32)
    return FeatureMeta(
        num_bin=jnp.asarray(ds.num_bin_per_feat),
        missing_type=jnp.asarray(ds.missing_types),
        default_bin=jnp.asarray(default_bins),
        monotone=jnp.asarray(mono),
        # already per-USED-feature (unlike monotone_constraints, which the
        # user supplies per original column)
        is_cat=jnp.asarray(ds.is_categorical))


def split_params_from_config(config: Config) -> SplitParams:
    return SplitParams(
        lambda_l1=float(config.lambda_l1),
        lambda_l2=float(config.lambda_l2),
        max_delta_step=float(config.max_delta_step),
        min_data_in_leaf=int(config.min_data_in_leaf),
        min_sum_hessian_in_leaf=float(config.min_sum_hessian_in_leaf),
        min_gain_to_split=float(config.min_gain_to_split),
        path_smooth=float(config.path_smooth),
        monotone_penalty=float(config.monotone_penalty),
        max_cat_to_onehot=int(config.max_cat_to_onehot),
        max_cat_threshold=int(config.max_cat_threshold),
        cat_l2=float(config.cat_l2),
        cat_smooth=float(config.cat_smooth),
        min_data_per_group=int(config.min_data_per_group),
        cegb_tradeoff=float(config.cegb_tradeoff),
        cegb_penalty_split=float(config.cegb_penalty_split))


class _DeviceTree:
    """Per-model device arrays for score updates/re-routing (DART)."""

    __slots__ = ("leaf_value", "split_feature", "threshold_bin",
                 "default_left", "left_child", "right_child", "max_depth",
                 "num_leaves", "cat_flag", "cat_mask")

    def __init__(self, host_tree: HostTree, inner_feature: np.ndarray,
                 cat_flag: np.ndarray = None, cat_mask: np.ndarray = None):
        self.num_leaves = host_tree.num_leaves
        self.max_depth = (int(host_tree.leaf_depth.max())
                          if getattr(host_tree, "leaf_depth", None) is not None
                          and len(host_tree.leaf_depth) else
                          max(1, host_tree.num_leaves - 1))
        self.leaf_value = jnp.asarray(host_tree.leaf_value, jnp.float32)
        self.split_feature = jnp.asarray(inner_feature, jnp.int32)
        self.threshold_bin = jnp.asarray(host_tree.threshold_bin, jnp.int32)
        self.default_left = jnp.asarray(
            (host_tree.decision_type & 2).astype(bool))
        self.left_child = jnp.asarray(host_tree.left_child, jnp.int32)
        self.right_child = jnp.asarray(host_tree.right_child, jnp.int32)
        # binned-space categorical decisions for on-device valid routing
        if cat_flag is not None and np.any(cat_flag):
            self.cat_flag = jnp.asarray(cat_flag.astype(bool))
            self.cat_mask = jnp.asarray(cat_mask.astype(bool))
        else:
            self.cat_flag = None
            self.cat_mask = None


def _round_up_pow2(n: int) -> int:
    return 1 << max(1, (n - 1).bit_length())


def _screening_mask_fn(ema: jax.Array, explore, F: int,
                       keep_k: int) -> jax.Array:
    """EMA-FS screening mask [F_oh]: keep the top ``keep_k`` REAL
    features by gain EMA (ties kept), or everything on an exploration
    round.  Pure/traced — shared by the sync driver's cached mask and
    the fast paths' in-scan mask so the two cannot drift.  A dataset
    whose features were all pre-filtered (F == 0 — e.g. a
    min_data_in_leaf past the row count) has nothing to screen."""
    if F <= 0 or keep_k >= F:
        return jnp.ones(ema.shape, bool)
    kth = jnp.sort(ema[:F])[F - keep_k]
    return (ema >= kth) | explore


def _tree_gain_vec(split_feature: jax.Array, split_gain: jax.Array,
                   F_oh: int) -> jax.Array:
    """Realized per-feature split gains of one iteration's trees
    ([k, L-1] or [L-1] node arrays) — what feeds the gain EMA.  The
    frontier grower materializes split_gain per node; unused nodes
    carry feature -1 / gain 0 and contribute nothing."""
    sf = split_feature.reshape(-1)
    sg = split_gain.reshape(-1).astype(jnp.float32)
    ok = (sf >= 0) & jnp.isfinite(sg) & (sg > 0)
    return jnp.zeros((F_oh,), jnp.float32) \
        .at[jnp.clip(sf, 0, F_oh - 1)].add(jnp.where(ok, sg, 0.0))


class GBDT:
    """Gradient Boosting Decision Tree driver (ref: src/boosting/gbdt.h:35)."""

    name = "gbdt"

    def __init__(self):
        self.config: Optional[Config] = None
        self.train_data: Optional[TpuDataset] = None
        self.objective = None
        self.models: List[HostTree] = []
        self.device_trees: List[_DeviceTree] = []
        self.iter = 0
        self.num_init_iteration = 0
        self.average_output = False
        self._last_cat = None  # host cat arrays from the latest _to_host_tree
        # async pipeline state (see _train_one_iter_fast): device trees not
        # yet materialised as HostTrees, scores checkpoint for stop rollback.
        # Entries are (stacked TreeArrays, [init_scores per iteration],
        # batch, metrics) — batch > 1 for megastep entries ([B, k, ...]
        # arrays); metrics is the scan's [B, n_slots] on-device eval
        # matrix when a drain-replay consumer is armed, else None.
        self._pending: List[Tuple] = []
        self._pending_iters = 0
        self._fast_step_fn = None
        self._fast_ok_cache = None
        self._stopped_early = False
        # multi-iteration megastep state (see _train_one_megastep): armed
        # only by driver loops that tolerate train_one_iter advancing
        # more than one iteration per call
        self._megastep_armed = False
        self._megastep_fns: Dict[int, object] = {}
        self._megastep_fm: Dict[int, object] = {}
        # on-device eval inside the megastep (metric/traced.py): the
        # drain-replay consumer a driver loop registered via
        # arm_megastep(eval_consumer=...), the traced eval plan built by
        # megastep_eval_precheck, its cached operand pytree, the
        # device-resident early-stop carry (best metric / best round /
        # stopped flag / stop iteration threaded through the scan), and
        # the host-side "early stop confirmed at drain" latch
        self._eval_consumer = None
        self._traced_plan = None
        self._plan_ops = None
        self._es_spec = None
        self._es_carry = None
        self._es_finished = False
        # megastep_evicted dedup: one structured event per distinct
        # eviction reason, not one per iteration
        self._evict_reported = set()
        # batch-granularity telemetry window: wall/perf stamps of the
        # first dispatch since the last drain, and how many of the
        # pending iterations came from fused megastep chunks
        self._batch_t0 = None
        self._batch_w0 = None
        self._batch_fused = 0
        # fused-epilogue state (see _use_epilogue)
        self._epi_ok_cache = None
        self._epi_fns = None
        self._epi_carry = None
        self._epi_ops = None
        # histogram-plane cuts (ROADMAP item 4): quantized gradient
        # histograms, adaptive per-feature bins, EMA-FS gain screening
        self.quant_bits = 0
        self.use_adaptive_bins = False
        self.use_screening = False
        self.fused_packed = None
        self._gain_ema_dev = None      # [F_oh] f32 gain EMA (screening)
        self._iter_gain_acc = None     # sync driver: per-iteration gains
        self._screen_mask_cache = None
        self._hist_stats = None
        # distribution axis (ref: tree_learner.cpp:17-49 factory matrix)
        self.parallel_mode = "serial"
        self.mesh = None
        self.n_shards = 1
        self.axis_name = None
        self._par_fns: Dict[str, object] = {}
        # measured in-trace collective profiles (ops/collectives.py):
        # (count, bytes) recorded from the traced static shapes at the
        # first call of each fresh grower jit — per fused iteration
        # (fast step / megastep scan body, k trees) and per sync-driver
        # grow call (one tree)
        self._coll_per_iter = None
        self._coll_per_grow = None
        # telemetry registry (obs/): disabled by default — every record
        # call is a single attribute check until telemetry_out or
        # record_telemetry enables it
        self.telemetry = Telemetry()
        self._health = None
        self._metrics = None           # live OpenMetrics exporter
        self._mem_watermarks = True
        self._tel_gran = "batch"
        self._trace_out = ""
        self._trace_written = False
        self._prof_dir = ""
        self._prof_start = 0
        self._prof_n = -1
        self._prof_active = False
        self._prof_done = False
        # on-demand profiling control plane (POST /profile on the
        # metrics exporter): the armed-request handoff and the open
        # window's bookkeeping ({dir, it0, iters}); windows open/close
        # only at drain boundaries / iteration edges, so an armed-but-
        # idle endpoint is dispatch-neutral by construction
        self._profile_ctl = None
        self._ctl_window = None
        self._ctl_no_open = False
        # SLO plane (obs/slo.py): declarative objectives evaluated on a
        # host-side ticker plus at the same drain-boundary sync points
        # the profile control polls — dispatch-neutral by the same
        # construction
        self._slo = None
        # device-time cost ledger (obs/cost.py): fresh executable
        # signatures queue here at dispatch, analyses run at drains
        self._cost = None
        self._run_report_out = ""
        # resilience (resilience/): async checkpoint manager, cadence
        # bookkeeping, the engine's extra-state hook (callback closures'
        # early-stop state rides the checkpoint), fault registry
        self._ckpt = None
        self._ckpt_period = 0
        self._last_ckpt_iter = 0
        self._ckpt_busy = False
        self._ckpt_extra = None
        self._faults = None

    # ------------------------------------------------------------------
    def init(self, config: Config, train_data: TpuDataset, objective,
             training_metrics: Sequence = ()) -> None:
        self.config = config
        self.train_data = train_data
        self.objective = objective
        from ..utils.platform import apply_compilation_cache
        apply_compilation_cache(config)   # before the first trace
        self._setup_telemetry(config)
        self._setup_resilience(config)
        self.training_metrics = list(training_metrics)
        self.num_data = train_data.num_data
        self.num_tree_per_iteration = (objective.num_model_per_iteration
                                       if objective is not None else
                                       max(1, int(config.num_class)))
        self.shrinkage_rate = float(config.learning_rate)
        self.max_leaves = max(2, int(config.num_leaves))
        # static padded bin count shared by all jit instances
        self.max_bins = int(train_data.max_num_bin)
        self.params = split_params_from_config(config)
        self.meta = feature_meta_from_dataset(train_data)
        self.has_cat = bool(np.any(train_data.is_categorical))
        self.use_mono_bounds = bool(np.any(np.asarray(self.meta.monotone)
                                           != 0))
        self._setup_cegb(config)
        self._setup_forced_splits(config, train_data)
        self._setup_bundles(config, train_data)
        # NOTE: computed before _setup_engine so the frontier-v1 fallback
        # sees them
        ic = config.interaction_constraints
        bynode = float(config.feature_fraction_bynode)
        self.use_node_masks = bool(ic) or (0.0 < bynode < 1.0)
        self.node_masks = None
        if self.use_node_masks:
            from ..models.learner import make_node_mask_cfg
            # constraints are in REAL feature indices; map to inner
            inner_ic = []
            for g in (ic or []):
                gi = [train_data.inner_feature_index(int(f)) for f in g]
                inner_ic.append([f for f in gi if f >= 0])
            self.node_masks = make_node_mask_cfg(
                train_data.num_features, inner_ic, bynode,
                int(config.feature_fraction_seed) + 12345)
        # lazy: the parallel XLA path holds a SHARDED copy (bins_par) and
        # only rollback/stop-subtract/DART replay need this replicated one
        self._bins_dev = None
        # the fused/Pallas paths are the TPU throughput modes; leafwise is
        # the exact reference-parity mode (and the CPU default)
        self.on_tpu = jax.default_backend() == "tpu"
        self._setup_parallel(config)
        self._setup_engine(config)

        md = self._mp_metadata if self.mp is not None else train_data.metadata
        k, n = self.num_tree_per_iteration, self.num_data
        self.has_init_score = md.init_score is not None
        from jax.sharding import PartitionSpec as P
        if self.has_init_score:
            init = np.asarray(md.init_score, np.float64)
            if init.size == n * k:
                scores = init.reshape(k, n, order="C")
            else:
                scores = np.tile(init.reshape(1, n), (k, 1))
            self.scores = (self.mp.shard_full(scores.astype(np.float32),
                                              P(None, self.axis_name))
                           if self.mp is not None
                           else jnp.asarray(scores, jnp.float32))
        elif self.mp is not None:
            self.scores = self.mp.zeros_sharded((k, n),
                                                P(None, self.axis_name))
        else:
            self.scores = jnp.zeros((k, n), jnp.float32)

        self.valid_data: List[TpuDataset] = []
        self.valid_bins: List = []
        self.valid_scores: List = []
        self.valid_metrics: List[List] = []
        self.valid_names: List[str] = []

        self.class_need_train = [
            objective.class_need_train(i) if objective is not None else True
            for i in range(self.num_tree_per_iteration)]

        # bagging state (ref: gbdt.cpp:686-758 ResetBaggingConfig)
        # reference-parity streams (ref: utils/random.h LCG; gbdt.cpp:804
        # per-block bagging generators; col_sampler.hpp:26 by-tree stream)
        self.bag_streams = ref_random.BlockBaggingStreams(
            int(config.bagging_seed), n)
        self._bag_round_cache = None
        self.bag_rng = np.random.RandomState(config.bagging_seed)  # GOSS
        self.feat_rng = ref_random.Random(int(config.feature_fraction_seed))
        self.balanced_bagging = False
        self.is_bagging = False
        if config.bagging_freq > 0:
            if config.bagging_fraction < 1.0:
                self.is_bagging = True
            elif (self.objective is not None
                  and self.objective.name == "binary"
                  and (config.pos_bagging_fraction < 1.0
                       or config.neg_bagging_fraction < 1.0)):
                self.is_bagging = True
                self.balanced_bagging = True
        self.bag_weight = self._bag_ones()  # 1=in bag (mp: 0 on pad rows)
        self.bag_cnt = n

        self.best_score: Dict[Tuple[int, str], float] = {}
        self.best_iter: Dict[Tuple[int, str], int] = {}
        self.early_stopping_round = int(config.early_stopping_round)
        self.es_first_metric_only = bool(config.first_metric_only)



    @property
    def bins_dev(self):
        if self._bins_dev is None:
            self._bins_dev = self._dataset_bins_to_device(self.train_data)
        return self._bins_dev

    def _dataset_bins_to_device(self, ds):
        """Host->device transfer of a dataset's bin matrix.  Streamed /
        mmap-cached datasets (ingest/) go through the double-buffered
        chunk prefetcher — the next chunk's host read (page faults on a
        cache mmap) overlaps the in-flight copy, at most two chunks
        live host-side, and the counters/watermarks land in telemetry —
        instead of faulting the whole artifact into RAM for one giant
        ``jnp.asarray``.  The result is elementwise-identical either
        way (prefetch is a transfer schedule, not a data transform)."""
        if getattr(ds, "streamed", False) \
                and bool(getattr(self.config, "ingest_prefetch", True)):
            from ..ingest.prefetch import stream_to_device
            tel = self.telemetry
            out = stream_to_device(
                ds.bins, int(self.config.ingest_chunk_rows), tel=tel)
            if tel.enabled and getattr(self, "_mem_watermarks", False):
                # the prefetch assembly is where a streamed dataset's
                # HBM residency materializes — watermark it like the
                # drain boundary
                from ..obs.jaxmon import memory_watermarks
                memory_watermarks(tel, where="prefetch")
            return out
        return jnp.asarray(ds.bins)

    def _publish_ingest(self, ds) -> None:
        """Fold a dataset's ingest counters (chunked parse/bin stats,
        cache hit, max-live-chunks watermark) into the telemetry
        registry — ingest runs before the booster owns a registry, so
        the stats ride the dataset and land here exactly once."""
        stats = getattr(ds, "ingest_stats", None)
        if not stats or getattr(ds, "_ingest_published", False) \
                or not self.telemetry.enabled:
            return
        from ..ingest.prefetch import publish_ingest_stats
        publish_ingest_stats(self.telemetry, stats)
        ds._ingest_published = True

    # ------------------------------------------------------------------
    def _setup_telemetry(self, config: Config) -> None:
        """Telemetry registry + profiler window from the config (re-run
        by reset_config so reset_parameter can turn either on). Runs
        FIRST in init so mode/engine degradation events route through
        the registry."""
        tel = self.telemetry
        out = str(getattr(config, "telemetry_out", "") or "")
        self._trace_out = str(getattr(config, "trace_out", "") or "")
        period = int(getattr(config, "health_check_period", 0) or 0)
        metrics_port = int(getattr(config, "metrics_port", 0) or 0)
        self._mem_watermarks = bool(getattr(config, "memory_watermarks",
                                            True))
        self._run_report_out = str(getattr(config, "run_report_out", "")
                                   or "")
        if out or self._trace_out or period > 0 or metrics_port > 0 \
                or self._run_report_out:
            # enable() attaches the sink even when the registry is
            # already on sink-less (record_telemetry first, then
            # reset_parameter(telemetry_out=...) must still get a file);
            # it reports whether THIS call attached a new sink, so the
            # enablement event fires once per stream, and trace_out /
            # health_check_period enable the registry sink-less
            newly_attached = tel.enable(sink_path=out or None,
                                        trace=bool(self._trace_out))
            if newly_attached:
                tel.event("telemetry_enabled", sink=out)
        elif tel.enabled:
            # every observability key cleared on an already-enabled
            # registry (reset_parameter round trip): span collection
            # must stop too, or each section keeps paying the append
            # with no exporter left to drain it
            tel.enable(trace=False)
        # live OpenMetrics endpoint (obs/export.py): one exporter per
        # booster at metrics_port + rank; a config reset that keeps the
        # same port keeps the running server (re-binding would drop a
        # scraper mid-run), any other change stops the old one first.
        # The exporter outlives finalize_telemetry deliberately — "live"
        # means scrapeable for as long as the process holds the booster.
        want_port = metrics_port + tel.rank if metrics_port > 0 else 0
        if self._metrics is not None and (
                want_port <= 0
                or self._metrics.requested_port != want_port):
            self._metrics.stop()
            self._metrics = None
        if want_port > 0 and self._metrics is None:
            from ..obs.export import MetricsExporter, ProfileControl
            if self._profile_ctl is None:
                self._profile_ctl = ProfileControl()
                # overlap refusal extends to the config-keyed window: a
                # pending/active profile_dir trace owns the profiler
                self._profile_ctl.conflict_check = (
                    lambda: "config:profile_dir window pending"
                    if (self._prof_active
                        or (self._prof_dir and not self._prof_done))
                    else None)
            self._metrics = MetricsExporter(
                tel, want_port, profile_control=self._profile_ctl,
                report_fn=self.build_run_report,
                roofline_fn=lambda: getattr(self, "_roofline_last",
                                            None))
            if self._metrics.start() < 0:
                # total bind failure (not the in-use fallback): drop
                # the dead exporter so a later reset_parameter round
                # trip RETRIES the bind instead of matching
                # requested_port against a server that never existed
                self._metrics = None
        self._health = None
        if period > 0:
            from ..obs.health import HealthAuditor
            self._health = HealthAuditor(
                tel, period,
                float(getattr(config, "health_skew_threshold", 2.0)),
                resync_fn=self._health_resync,
                auto_resync=bool(getattr(config, "health_auto_resync",
                                         True)),
                checkpoint_fn=lambda it: self.maybe_checkpoint(force=True),
                straggler_checkpoint=bool(getattr(
                    config, "health_checkpoint_on_straggler", False)))
        self._prof_dir = str(getattr(config, "profile_dir", "") or "")
        self._prof_start = max(
            0, int(getattr(config, "profile_start_iteration", 0)))
        self._prof_n = int(getattr(config, "profile_num_iterations", -1))
        gran = str(getattr(config, "telemetry_granularity", "batch")
                   or "batch")
        if gran not in ("batch", "iteration", "section"):
            log.warning("unknown telemetry_granularity=%s; using batch",
                        gran)
            gran = "batch"
        self._tel_gran = gran
        # device-time cost ledger: one per registry lifetime (keeps the
        # analyzed-signature dedup across reset_parameter round trips);
        # mode changes re-derive it
        cost_mode = str(getattr(config, "cost_ledger", "hlo") or "hlo")
        if not tel.enabled or cost_mode == "off":
            self._cost = None
        elif self._cost is None or self._cost.mode != cost_mode:
            from ..obs.cost import CostLedger
            self._cost = CostLedger(tel, cost_mode)
        # roofline plane (obs/kernelstats.py): measured samples from
        # every closed profile window accumulate in the shape-keyed
        # perf database when perf_db is set (obs/perfdb.py)
        self._perf_db_path = str(getattr(config, "perf_db", "") or "")
        # SLO plane (obs/slo.py): one engine per registry lifetime,
        # rebuilt when a reset_config changes the arming keys.  The
        # engine only reads host-side snapshots — arming it is
        # dispatch-neutral exactly like the profile control.
        slo_cfg = str(getattr(config, "slo_config", "") or "")
        slo_on = bool(getattr(config, "slo_enabled", False)) or bool(slo_cfg)
        if self._slo is not None:
            self._slo.stop()
            self._slo = None
        if slo_on and tel.enabled:
            from ..obs.slo import SloEngine
            self._slo = SloEngine(
                tel, source="train", config_path=slo_cfg,
                tick_period_s=float(getattr(config, "slo_tick_period_s",
                                            5.0)),
                incident_base=out,
                context_fn=self._slo_context)
            self._slo.start()
        if self._metrics is not None:
            self._metrics.alerts_fn = (self._slo.alerts_payload
                                       if self._slo is not None else None)
        # streamed/cached datasets carry their ingest counters from
        # before the registry existed; fold them in now (init and any
        # reset_config that turns telemetry on)
        if getattr(self, "train_data", None) is not None:
            self._publish_ingest(self.train_data)
            for vd in getattr(self, "valid_data", []) or []:
                self._publish_ingest(vd)

    def _slo_context(self):
        """Incident-artifact context: where training stood when the
        alert fired (host attribute reads only)."""
        return {
            "iteration": int(getattr(self, "iter", 0)),
            "models": len(getattr(self, "models", []) or []),
            "last_checkpoint_iter": int(self._last_ckpt_iter),
        }

    def _slo_step(self) -> None:
        """Heartbeat + time-gated SLO evaluation at the drain-boundary
        sync points the driver already owns (same contract as
        _profile_ctl_step: host flags only, no dispatch)."""
        slo = self._slo
        if slo is None:
            return
        slo.note_training_heartbeat(self.iter)
        slo.step()

    def _tel_granularity(self) -> str:
        """Effective time-attribution granularity. trace_out (spans come
        from synced sections) and the health auditor (needs the sync
        driver's per-iteration records) imply 'section' regardless of the
        configured value — EXCEPT under the multi-chip megastep, where
        the health audit moves to drain boundaries (_health_at_drain)
        instead of evicting the one configuration that needs dispatch
        amortization most."""
        if self._trace_out:
            return "section"
        if self._health is not None and not self._health_at_drain():
            return "section"
        return self._tel_gran

    def _health_at_drain(self) -> bool:
        """Multi-process fused runs audit at drain boundaries: the model
        list and score carries are host-synced there already, so the
        hash allgather costs zero extra dispatches and the megastep
        keeps its 1-dispatch-per-chunk contract (section times are not
        collected on the fast path, so the straggler skew check reads
        empty sections — drain wall times still land in the batch
        record). The sync drivers (XLA growers, non-batch granularity)
        keep the per-iteration audit with real section times."""
        return (getattr(self, "mp", None) is not None
                and getattr(self, "use_fused", False)
                and bool(getattr(self.config, "tpu_mp_megastep", True))
                and self._tel_gran == "batch")

    @contextlib.contextmanager
    def _sec(self, name: str):
        """Dual-sink timed section: one measurement feeds both the
        TIMETAG global timer (as GBDT::<name>) and the telemetry
        registry's per-iteration record. Yields a handle whose
        ``sync(arrays)`` blocks before the section closes, attributing
        asynchronous device work honestly (the timer.section(sync=...)
        idiom, late-bound). No-op when both sinks are off."""
        tel = self.telemetry
        timing = timer.enabled
        if not (tel.enabled or timing):
            yield _NULL_SEC
            return
        h = _SecHandle()
        tel.push_section(name)   # crash flight recorder's "where"
        w0 = tel.wall_now()
        t0 = time.perf_counter()
        # everything below the yield runs on CLEAN exit only: an
        # exception must leave the section on the stack so the crash
        # flight recorder can dump where training was (a finally-pop
        # would erase the evidence during unwind)
        yield h
        if h._sync is not None:
            jax.block_until_ready(h._sync)
        dt = time.perf_counter() - t0
        tel.pop_section()
        if timing:
            timer.add("GBDT::" + name, dt)
        if tel.enabled:
            tel.section(name, dt, wall_start=w0)

    def _profiler_step(self) -> None:
        """Open/close the jax.profiler trace window at iteration edges
        (profile_dir + profile_start_iteration + profile_num_iterations:
        a TensorBoard/Perfetto trace of iterations K..K+n is one config
        key away)."""
        self._profile_ctl_step()
        self._slo_step()
        if self._prof_done or not self._prof_dir \
                or self._ctl_window is not None:
            return
        it = self.iter
        if not self._prof_active:
            if it >= self._prof_start:
                try:
                    jax.block_until_ready(self.scores)
                    jax.profiler.start_trace(self._prof_dir)
                except Exception as e:
                    log.warning("profiler trace failed to start: %s", e)
                    self._prof_done = True
                    return
                self._prof_active = True
                self.telemetry.event("profiler_trace_start", iteration=it,
                                     log_dir=self._prof_dir)
        elif 0 <= self._prof_n <= it - self._prof_start:
            self._profiler_stop()

    def _profiler_stop(self) -> None:
        if not getattr(self, "_prof_active", False):
            return
        try:
            jax.block_until_ready(self.scores)
            jax.profiler.stop_trace()
        except Exception as e:
            log.warning("profiler trace failed to stop: %s", e)
        self._prof_active = False
        self._prof_done = True
        self.telemetry.event("profiler_trace_stop", iteration=self.iter,
                             log_dir=self._prof_dir)
        self._roofline_capture(self._prof_dir)

    # ------------------------------------------- on-demand profile windows
    def _profile_ctl_step(self) -> None:
        """Advance the on-demand profiling state machine (POST /profile
        on the metrics exporter) at the driver's existing sync points:
        megastep drain boundaries (_drain_body tail) and iteration
        edges (_profiler_step).  An open window closes at the first
        boundary >= ``iters`` iterations after it opened; an armed
        request opens only when no device work is pending and no
        config-keyed window owns the profiler.  Everything here is host
        flag-reads and (rarely) jax.profiler start/stop — zero device
        dispatches, which is the neutrality contract the bench gates."""
        ctl = self._profile_ctl
        if ctl is None:
            return
        win = self._ctl_window
        if win is not None:
            if self.iter - win["it0"] >= win["iters"]:
                self._close_ctl_window()
            return
        if self._prof_active or self._pending \
                or getattr(self, "_ctl_no_open", False):
            # a config window owns the profiler, dispatches are in
            # flight (mid-pipeline edge), or finalize is running (no
            # later boundary would ever stop a window opened now):
            # wait for an honest boundary
            return
        req = ctl.take()
        if req is None:
            return
        if not req.get("dir"):
            # default trace dir minted only now, when the window really
            # opens — an armed-but-never-fired request leaks nothing
            import tempfile
            req["dir"] = tempfile.mkdtemp(prefix="lgbm_profile_")
        try:
            jax.profiler.start_trace(req["dir"])
        except Exception as e:
            log.warning("on-demand profiler window failed to start: %s",
                        e)
            self.telemetry.event("profile_window", state="failed",
                                 iteration=self.iter, dir=req["dir"],
                                 error=str(e)[:200])
            ctl.done()
            return
        self._ctl_window = {"dir": req["dir"], "it0": self.iter,
                            "iters": int(req["iters"])}
        self.telemetry.event("profile_window", state="open",
                             iteration=self.iter, dir=req["dir"],
                             iters=int(req["iters"]))

    def _close_ctl_window(self, state: str = "closed") -> None:
        win, self._ctl_window = self._ctl_window, None
        if win is None:
            return
        try:
            jax.profiler.stop_trace()
        except Exception as e:
            log.warning("on-demand profiler window failed to stop: %s",
                        e)
            state = "failed"
        self.telemetry.event("profile_window", state=state,
                             iteration=self.iter, dir=win["dir"],
                             iters=win["iters"],
                             covered=self.iter - win["it0"])
        self._roofline_capture(win["dir"])
        if self._profile_ctl is not None:
            self._profile_ctl.done()

    # --------------------------------------------------- roofline plane
    def _shape_class(self) -> str:
        """Perfdb shape key: rows bucketed to the next power of two
        (padding-invariant across minor row-count jitter), feature
        count and bin budget — what determines which measured samples
        are comparable (obs/perfdb.py)."""
        rows = max(1, int(getattr(self, "num_data", 0) or 1))
        rows_p2 = 1 << (rows - 1).bit_length()
        feats = int(getattr(getattr(self, "train_data", None),
                            "num_features", 0) or 0)
        max_bin = int(getattr(self.config, "max_bin", 0) or 0)
        return f"r{rows_p2}.f{feats}.b{max_bin}"

    def _roofline_capture(self, trace_dir: str) -> None:
        """Post-window measurement hook, both window flavors
        (profile_dir config window and POST /profile): record the trace
        dir size/count gauges (an empty or truncated capture must be
        observable, not silently parsed to zero kernels), parse the
        Chrome trace via obs/kernelstats.py, join it to the cost
        ledger's analytic entries, publish the roofline gauges + one
        ``roofline`` event, and append measured samples to the perf
        database when ``perf_db`` is set.  Pure host work at a point
        the profiler already synced — zero device dispatches — and
        exception-proof: measurement must never kill training."""
        tel = self.telemetry
        if not trace_dir or not tel.enabled:
            return
        try:
            from ..obs import kernelstats
            n_files, n_bytes = kernelstats.dir_stats(trace_dir)
            tel.gauge("profile.trace_files", float(n_files))
            tel.gauge("profile.trace_bytes", float(n_bytes))
            if self._cost is not None:
                self._cost.flush()   # analyses queued since last drain
            compile_evs = [e for e in tel.snapshot().get("events", [])
                           if e.get("event") == "compile_executable"]
            roof = kernelstats.roofline_from_dir(
                trace_dir,
                cost_entries=(self._cost.entries()
                              if self._cost is not None else None),
                compile_entries=compile_evs)
            tel.gauge("roofline.join_coverage",
                      float(roof["join_coverage"]))
            tel.gauge("roofline.joined_executables",
                      float(roof["joined_executables"]))
            tel.gauge("roofline.anchor_dispatches",
                      float(roof["anchor_dispatches"]))
            # measured occupancy of the training executable's host
            # span — the measured complement to the analytic
            # cost.achieved_fraction gauge
            fracs = [r["measured_fraction"]
                     for r in roof["executables"]
                     if r["kind"] in ("megastep", "fast_step")
                     and isinstance(r.get("measured_fraction"),
                                    (int, float))]
            if fracs:
                tel.gauge("cost.measured_fraction", max(fracs))
            top = roof["kernels"][0] if roof["kernels"] else None
            tel.event(
                "roofline", iteration=self.iter, dir=trace_dir,
                join_coverage=roof["join_coverage"],
                joined_executables=roof["joined_executables"],
                anchor_dispatches=roof["anchor_dispatches"],
                total_device_time_us=roof["total_device_time_us"],
                measured_fraction=(max(fracs) if fracs else None),
                top_kernel=(top["name"] if top else None),
                top_kernel_us=(top["time_us"] if top else None),
                trace_files=roof["trace_files"],
                trace_bytes=roof["trace_bytes"],
                parse_errors=roof["parse_errors"])
            self._roofline_last = roof
            if self._perf_db_path:
                from ..obs import perfdb
                try:
                    import jax as _jax
                    backend = _jax.default_backend()
                    world = int(_jax.process_count())
                except Exception:
                    backend, world = "unknown", 1
                # packed hist layout = the feature-bin axis was padded
                # to a lane multiple (hist.fb_padded gauge > hist.fb)
                hs = getattr(self, "_hist_stats", None) or {}
                packed = bool(hs.get("fb_padded", 0) > hs.get("fb", 0))
                rows = perfdb.samples_from_roofline(
                    roof, shape_class=self._shape_class(),
                    backend=backend,
                    quant_bits=int(getattr(self, "quant_bits", 0) or 0),
                    packed_layout=packed,
                    world_size=world, source="profile_window",
                    run_id=tel.run_id)
                n = perfdb.PerfDB(self._perf_db_path).append(rows)
                tel.inc("perfdb.samples_written", n)
                tel.event("perfdb_append", path=self._perf_db_path,
                          samples=n)
        except Exception as e:   # measurement must never kill training
            log.warning("roofline capture of %s failed: %s",
                        trace_dir, e)
            tel.event("roofline", dir=trace_dir, error=str(e)[:200])

    def finalize_telemetry(self) -> None:
        """End-of-training hook: stop an open profiler trace, emit the
        summary event (per-rank counters aggregated at rank 0 under
        multi-process — SPMD: every rank calls this at the same point),
        write the consolidated run report (run_report_out), flush the
        JSONL sink."""
        # no NEW on-demand window may open past this point: the tail
        # drain below runs _profile_ctl_step at its boundary, and a
        # request taken there would open a trace with no later boundary
        # to stop it (busy forever, leaked profiler session)
        self._ctl_no_open = True
        try:
            self._finalize_telemetry_body()
        finally:
            # a kept booster can resume training (update loop after
            # engine.train finalized) — windows must re-arm then
            self._ctl_no_open = False

    def _finalize_telemetry_body(self) -> None:
        self._profiler_stop()
        if self._ckpt is not None:
            # join the in-flight write: a checkpoint enqueued at the
            # last drain must commit before the process can exit
            try:
                self._ckpt.wait()
            except Exception as e:
                log.warning("checkpoint writer drain failed: %s", e)
        tel = self.telemetry
        if not tel.enabled:
            self._close_ctl_window("closed_at_finalize")
            return
        self.drain_pending()
        if self._slo is not None:
            # one forced final evaluation so even a sub-tick-period run
            # gets a non-vacuous slo.ticks count, then disarm the
            # training-liveness watchdog (clean finalize is not a stall)
            # and the ticker thread
            self._slo.note_training_heartbeat(self.iter)
            self._slo.step(force=True)
            self._slo.note_training_done()
            self._slo.stop()
        # the tail drain may have closed an elapsed window at its
        # boundary; anything still open ends here, after the last
        # iterations it covered are drained
        self._close_ctl_window("closed_at_finalize")
        if self._cost is not None:
            self._cost.flush()   # analyses queued since the last drain
        snap = tel.snapshot()
        rank_sections = None
        if getattr(self, "mp", None) is not None:
            from ..obs import allgather_json
            from ..obs import report as report_mod
            # ONE allgather carries both the summary counters and the
            # compact per-rank report section (zero new collectives —
            # the payload just grew)
            per_rank = allgather_json({
                "rank": snap["rank"], "counters": snap["counters"],
                "report_section": report_mod.rank_section(
                    snap, snap["rank"],
                    evicted=self._evicted_snapshot())})
            rank_sections = [p.get("report_section") for p in per_rank
                             if isinstance(p.get("report_section"), dict)]
            if tel.rank == 0:
                tel.event("summary", iteration=self.iter,
                          counters=snap["counters"],
                          timings=snap["timings"],
                          ranks=[{k: p.get(k)
                                  for k in ("rank", "counters")}
                                 for p in per_rank])
        else:
            tel.event("summary", iteration=self.iter,
                      counters=snap["counters"],
                      timings=snap["timings"])
        self._write_run_report(snap, rank_sections)
        self._export_trace()
        tel.flush()

    # -------------------------------------------------------- run report
    def _evicted_snapshot(self):
        """Race-tolerant copy of the eviction-reason set: GET /report is
        served from the exporter's HTTP threads WHILE training mutates
        `_evict_reported`, and iterating a set across a concurrent add
        raises RuntimeError in CPython.  The set only ever grows (a few
        entries per run), so a short retry converges immediately."""
        for _ in range(8):
            try:
                return sorted(self._evict_reported)
            except RuntimeError:
                continue
        return []

    def build_run_report(self, snapshot=None, rank_sections=None):
        """Consolidated run report (obs/report.py) from the LIVE
        registry — the exporter's GET /report source and the
        run_report_out artifact builder."""
        from ..obs import report as report_mod
        tel = self.telemetry
        try:
            import jax as _jax
            world = int(_jax.process_count())
        except Exception:
            world = 1
        extra = None
        prov = getattr(self, "provenance", None)
        if prov is not None:
            # lineage section: the training run's provenance record
            # (run_id, source fingerprint, parent checkpoint, profile
            # digest) — the training end of the rollover chain
            extra = {"lineage": {"training": dict(prov)}}
        return report_mod.build_report(
            snapshot if snapshot is not None else tel.snapshot(),
            run_id=tel.run_id, rank=tel.rank, world_size=world,
            evicted=self._evicted_snapshot(),
            cost_entries=self._cost.entries() if self._cost else None,
            roofline=getattr(self, "_roofline_last", None),
            extra=extra, ranks=rank_sections)

    def _write_run_report(self, snap, rank_sections) -> None:
        """Write run_report.json (+ .md) at finalize.  Multi-process:
        rank 0 writes the aggregated report (per-rank sections rode the
        finalize allgather); other ranks write nothing — one artifact
        per run, like the merged trace."""
        out = self._run_report_out
        if not out or self.telemetry.rank != 0:
            return
        from ..obs import report as report_mod
        try:
            report = self.build_run_report(snap, rank_sections)
            report_mod.write_report(out, report)
        except Exception as e:   # the report must never kill finalize
            log.warning("run report write to %s failed: %s", out, e)
            return
        self.telemetry.event("run_report_written", path=out,
                             schema=report_mod.SCHEMA)
        log.info("run report written to %s", out)

    def _export_trace(self) -> None:
        """Write the Chrome-trace timeline (trace_out): drain this
        rank's spans, allgather them under multi-process (SPMD — every
        rank reaches finalize), and let rank 0 write the merged file
        with one track per rank."""
        tel = self.telemetry
        if not self._trace_out or self._trace_written:
            return
        self._trace_written = True
        # each rank ships its dropped-span count with its spans: a ring
        # overflow on ANY rank truncates that rank's track, so rank 0's
        # local counter alone cannot vouch for the merged file
        local = {"spans": tel.drain_spans(),
                 "dropped": int(tel.snapshot()["counters"].get(
                     "trace.spans_dropped", 0))}
        if getattr(self, "mp", None) is not None:
            from ..obs import allgather_json
            payloads = allgather_json(local)
        else:
            payloads = [local]
        if tel.rank != 0:
            return
        from ..obs import trace as trace_mod
        per_rank = [p["spans"] for p in payloads]
        try:
            trace_mod.write_trace(self._trace_out, per_rank)
        except Exception as e:
            log.warning("trace export to %s failed: %s",
                        self._trace_out, e)
            return
        dropped = sum(int(p.get("dropped", 0)) for p in payloads)
        tel.event("trace_written", path=self._trace_out,
                  spans=sum(len(s) for s in per_rank), dropped=dropped)
        if dropped:
            log.warning("trace span ring overflowed: %d spans were "
                        "evicted across ranks, %s starts mid-run",
                        dropped, self._trace_out)
        log.info("Chrome trace written to %s", self._trace_out)

    def dump_crash(self, exc: BaseException) -> Optional[str]:
        """Crash flight recorder: on an exception unwinding out of the
        train loop, dump the telemetry event ring, the live section
        stack, the counter/gauge state and a config snapshot to
        ``<telemetry_out>.crash.json`` (rank-suffixed like the JSONL
        sink) so a dead run leaves evidence, not just a traceback.
        Returns the path written, or None (recorder off / no
        telemetry_out). Must never raise — it runs on the unwind path."""
        tel = self.telemetry
        out = (str(getattr(self.config, "telemetry_out", "") or "")
               if self.config is not None else "")
        if not tel.enabled or not out:
            return None
        import json as _json
        import traceback as _tb
        # rank-suffixed BEFORE the extension (concurrent multi-rank
        # crashes each get their own dump; rank 0 keeps the bare path
        # the single-process tooling watches)
        path = (out + ".crash.json" if not tel.rank
                else out + f".crash.rank{tel.rank}.json")
        try:
            payload = {
                "ts": time.time(),
                "rank": tel.rank,
                "iteration": int(self.iter),
                "exception": {
                    "type": type(exc).__name__,
                    "message": str(exc)[:4000],
                    "traceback": _tb.format_exception(
                        type(exc), exc, exc.__traceback__, limit=50),
                },
                "config": self.config.to_dict(),
                "telemetry": tel.crash_payload(),
                # the resume hint: the newest checkpoint THIS rank
                # committed (None = no checkpointing / nothing written
                # yet) — the first thing an operator needs from a dump
                "checkpoint": (self._ckpt.last_written
                               if self._ckpt is not None else None),
            }
            tel.flush()
            with open(path, "w") as fh:
                _json.dump(payload, fh, indent=1, default=str)
        except Exception as dump_err:
            log.warning("crash flight recorder failed: %s", dump_err)
            return None
        log.warning("training crashed (%s); flight record written to %s",
                    type(exc).__name__, path)
        return path

    # ------------------------------------------------------------------
    # Resilience: async checkpoints + resume + auditor auto-recovery
    # (resilience/; docs/Reliability.md). Checkpoint capture happens at
    # host consistency boundaries only (drain boundaries on the fast
    # path, iteration edges on the sync driver) so the 0.125-dispatch
    # megastep contract is untouched — the bench guard asserts
    # dispatches_per_iter is identical with checkpointing on.
    def _setup_resilience(self, config: Config) -> None:
        from ..resilience import comms
        from ..resilience.checkpoint import CheckpointManager
        from ..resilience.faults import registry_from_env
        comms.set_collective_policy(
            float(getattr(config, "collective_timeout", 0.0) or 0.0),
            int(getattr(config, "collective_retries", 2)))
        self._faults = registry_from_env()
        self._ckpt_period = int(getattr(config, "checkpoint_period", 0)
                                or 0)
        root = str(getattr(config, "checkpoint_dir", "") or "")
        if not root:
            if self._ckpt_period > 0:
                log.warning("checkpoint_period=%d set without "
                            "checkpoint_dir; checkpointing is off",
                            self._ckpt_period)
            if self._ckpt is not None:
                # reset_parameter dropped checkpoint_dir: drain + stop
                # the writer instead of orphaning its thread
                try:
                    self._ckpt.close()
                except Exception as e:
                    log.warning("checkpoint writer shutdown failed: %s",
                                e)
            self._ckpt = None
            return
        if self._ckpt_period <= 0 and not bool(getattr(
                config, "health_checkpoint_on_straggler", False)):
            # dir without period writes nothing on its own (only the
            # auditor's checkpoint-now would) — say so, mirroring the
            # inverse misconfiguration's warning above
            log.warning("checkpoint_dir=%s set without "
                        "checkpoint_period; no periodic checkpoints "
                        "will be written", root)
        if self._ckpt is not None and self._ckpt.root == root:
            return   # reset_parameter round trip: keep the writer
        if self._ckpt is not None:
            # checkpoint_dir changed on a reset: drain + stop the old
            # writer so its in-flight checkpoint commits and its thread
            # does not leak (one parked thread per reset otherwise)
            try:
                self._ckpt.close()
            except Exception as e:
                log.warning("old checkpoint writer shutdown failed: %s", e)
        tel = self.telemetry
        self._ckpt = CheckpointManager(
            root, rank=tel.rank, world=jax.process_count(),
            keep=int(getattr(config, "checkpoint_keep", 2)),
            telemetry=tel)

    def set_checkpoint_extra(self, provider) -> None:
        """Engine hook: a callable returning JSON-able state to ride the
        checkpoint (callback closures' early-stop lists, the last eval
        list) so a resumed engine loop continues bit-identically."""
        self._ckpt_extra = provider

    def maybe_checkpoint(self, force: bool = False) -> bool:
        """Capture + enqueue a checkpoint when one is due. Called at
        drain boundaries (_drain_body), after each sync-driver iteration
        (engine.train / _train_loop_body) and by the auditor's
        checkpoint-now action (force=True). Collective-free; a capture
        or write failure degrades to telemetry, never kills training."""
        if self._ckpt is None or self._ckpt_busy:
            return False
        if self._stopped_early or self._es_finished:
            return False
        if self.iter <= self._last_ckpt_iter:
            return False
        if not force and (self._ckpt_period <= 0
                          or self.iter - self._last_ckpt_iter
                          < self._ckpt_period):
            return False
        self._ckpt_busy = True
        try:
            # no-op when called from inside _drain_body (pending already
            # taken); drains first otherwise so the snapshot covers a
            # settled model list + score carries
            self.drain_pending()
            from ..resilience import state as rstate
            payload, arrays = rstate.capture(self)
            self._ckpt.save(self.iter, payload, arrays)
            self._last_ckpt_iter = self.iter
            return True
        except Exception as e:
            log.warning("checkpoint capture at iteration %d failed: %s",
                        self.iter, e)
            if self.telemetry.enabled:
                self.telemetry.inc("ckpt.failed")
                self.telemetry.event("checkpoint_failed",
                                     iteration=self.iter,
                                     error=f"{type(e).__name__}: "
                                           f"{e}"[:500])
            return False
        finally:
            self._ckpt_busy = False

    def _device_tree_for_resume(self, ht: HostTree) -> "_DeviceTree":
        """Device tree for a checkpoint/resync-restored HostTree: the
        model-file rebin path, but with the TRAINING-time threshold_bin
        kept verbatim (the checkpoint stores it) so post-resume replay
        ops route bit-identically to the original run."""
        dt = self._device_tree_from_host(ht)
        tb = np.asarray(ht.threshold_bin)
        if tb.size == max(0, ht.num_leaves - 1) and tb.size:
            dt.threshold_bin = jnp.asarray(tb.astype(np.int32))
        return dt

    def _capture_boosting_extra(self) -> Tuple[Dict, Dict]:
        """Boosting-mode state beyond the base driver's (payload dict,
        npz arrays); DART/GOSS override."""
        return {}, {}

    def _restore_boosting_extra(self, payload: Dict, arrays) -> None:
        pass

    def _health_resync(self, it: int, per_rank) -> bool:
        from ..resilience import recovery
        self.drain_pending()
        return recovery.resync_from_rank0(self, it, per_rank)

    # ------------------------------------------------------------------
    def _setup_bundles(self, config: Config, train_data) -> None:
        """Exclusive feature bundling for the fused and depthwise growers
        (ref: src/io/dataset.cpp FindGroups/FastFeatureBundling). On by
        default like the reference's enable_bundle; engages only when
        bundling actually reduces the column count (dense data is
        unaffected — conflict-free bundles simply don't form)."""
        self.use_bundles = False
        self._replay_bundle = None
        pb = getattr(train_data, "prebundled", None)
        if pb is not None:
            # sparse-built dataset: the bundle matrix IS the storage — the
            # layout arrives from ingestion (TpuDataset.from_sparse), it
            # is not optional and not recomputed here
            if getattr(self, "n_forced", 0) > 0:
                log.fatal("forced splits are not supported on sparse-built "
                          "(prebundled) datasets")
            self._install_bundle_layout(
                train_data, pb,
                np.asarray(train_data.bins),
                np.asarray(train_data.most_freq_bins, np.int32))
            # bundle-aware replay routing for rollback/DART/stop-subtract/
            # valid updates (ops/predict.route_rows_to_leaves decode)
            self._replay_bundle = (
                jnp.asarray(pb.col_of_feat),
                jnp.asarray(pb.offset_of_feat),
                jnp.asarray(np.asarray(train_data.most_freq_bins,
                                       np.int32)))
            return
        if not (bool(config.tpu_enable_bundle)
                and bool(config.enable_bundle)):
            return
        if "tpu_enable_bundle" not in getattr(config, "_user_set", set()):
            # default-on only where it cannot change the grow policy: the
            # fused engine is depth-wise regardless. On the xla engine
            # bundling would force depth-wise growth and silently diverge
            # from the leaf-wise reference default on sparse data, so
            # there it stays opt-in.
            from ..ops.pallas_histogram import HAS_PALLAS
            eng = config.tpu_engine
            on_tpu = jax.default_backend() == "tpu"
            if not (eng == "fused"
                    or (eng == "auto" and on_tpu and HAS_PALLAS)):
                return
        if getattr(self, "n_forced", 0) > 0:
            return  # forced splits route through the leaf-wise grower
        from ..ops.efb import BundleLayout, encode_bundles, find_bundles
        bins_np = np.asarray(train_data.bins)
        mfb = getattr(train_data, "most_freq_bins", None)
        if mfb is None:
            mfb = np.array([train_data.mappers[j].most_freq_bin
                            for j in train_data.used_features], np.int32)
        if jax.process_count() > 1:
            # multi-process: bundle layouts must be IDENTICAL on every
            # rank — conflict masks come from the allgathered binning
            # sample (the reference also bundles from sampled data,
            # dataset_loader.cpp FindGroups over sample_indices); the
            # local rows are then encoded with the shared layout
            sb = getattr(train_data, "mp_sample_bins", None)
            if sb is None:
                log.warning("no shared binning sample retained; skipping "
                            "EFB for this multi-process run")
                self.telemetry.degrade("efb_no_shared_sample")
                return
            masks = [sb[:, k] != mfb[k]
                     for k in range(train_data.num_features)]
            n_for_rate = sb.shape[0]
        else:
            masks = [bins_np[:, k] != mfb[k]
                     for k in range(train_data.num_features)]
            n_for_rate = self.num_data
        nb_all = [int(x) for x in np.asarray(self.meta.num_bin)]
        # reference-parity bundling: tolerated conflicts at the
        # single_val_max_conflict_cnt rate (ref: dataset.cpp:108
        # total/10000). The reference's jagged per-group offsets have no
        # kernel analog here — every bundle column is padded to the
        # widest (the one-hot bin extraction needs a uniform per-column
        # stride) — so the width cap is chosen ADAPTIVELY: start
        # uncapped like the reference, and only tighten when the
        # uniform padding would inflate the stored matrix
        # 32767 = int16 ceiling of the fused kernel's transposed bin
        # matrix (a wider bundle would wrap negative in _init_fused's
        # astype(int16) and zero the one-hot); the reference is uncapped
        # because its jagged storage never widens a column
        for cap in (32767, 8 * self.max_bins, 4 * self.max_bins):
            bundles = find_bundles(masks, n_for_rate,
                                   max_conflict_rate=1e-4,
                                   max_bundle_bins=cap,
                                   num_bin_per_feat=nb_all)
            if len(bundles) >= train_data.num_features:
                return  # nothing to gain
            widths = [1 + sum(nb_all[f] for f in b) for b in bundles]
            padded = len(bundles) * max(widths)
            if padded <= 2 * sum(widths):
                break  # padding waste bounded; keep this layout
        layout = BundleLayout(bundles, nb_all)
        enc = encode_bundles(bins_np, mfb, layout)
        self._install_bundle_layout(train_data, layout, enc,
                                    np.asarray(mfb, np.int32))
        log.info("EFB: %d features bundled into %d columns",
                 train_data.num_features, layout.num_columns)
        self.telemetry.event("efb", features=train_data.num_features,
                             columns=layout.num_columns)

    def _install_bundle_layout(self, train_data, layout, enc_np,
                               mfb_np) -> None:
        """BundleCfg + device bundle matrix from a BundleLayout (shared by
        the dense default-on EFB path and sparse-built prebundled
        datasets)."""
        nb = [int(x) for x in train_data.num_bin_per_feat]
        Bc = max(layout.col_num_bin)
        B = self.max_bins
        F = train_data.num_features
        flat_idx = np.zeros((F, B), np.int32)
        valid = np.zeros((F, B), bool)
        for f in range(F):
            ci = int(layout.col_of_feat[f])
            off = int(layout.offset_of_feat[f])
            for b in range(nb[f]):
                flat_idx[f, b] = ci * Bc + off + b
                valid[f, b] = True
        from ..models.learner import BundleCfg
        # FixHistogram residual lands on each feature's MOST FREQUENT bin
        # (the rows encoded as bundle-default), not the zero-default bin
        self.bundle_cfg = BundleCfg(
            flat_idx=jnp.asarray(flat_idx), valid=jnp.asarray(valid),
            default_bin=jnp.asarray(mfb_np),
            col_of_feat=jnp.asarray(layout.col_of_feat),
            offset_of_feat=jnp.asarray(layout.offset_of_feat))
        enc_small = enc_np.astype(np.uint8 if Bc <= 256 else np.uint16)
        # host copy only where the multi-process placement paths read it
        self.bundle_bins_host = (enc_small if jax.process_count() > 1
                                 else None)
        self.bundle_bins_dev = jnp.asarray(enc_small)
        self.bundle_col_bins = int(Bc)
        self.use_bundles = True

    # ------------------------------------------------------------------
    def _setup_forced_splits(self, config: Config, train_data) -> None:
        """BFS schedule from the forced-splits JSON (ref: gbdt.cpp:72-80
        load + serial_tree_learner.cpp:455 ForceSplits). Leaf numbering
        follows the leaf-wise grower: splitting leaf l keeps l as the left
        child, the right child gets the next fresh id."""
        self.n_forced = 0
        path = str(config.forcedsplits_filename or "")
        if not path:
            return
        import json as _json
        with open(path) as f:
            root = _json.load(f)
        leaves, feats, thrs = [], [], []
        queue = [(root, 0)]
        next_id = 1
        while queue:
            node, leaf = queue.pop(0)
            real_f = int(node["feature"])
            inner = train_data.inner_feature_index(real_f)
            if inner < 0:
                log.warning("forced split on filtered feature %d skipped",
                            real_f)
                continue
            if bool(train_data.is_categorical[inner]):
                log.fatal("forced splits on categorical features are not "
                          "supported (feature %d)", real_f)
            m = train_data.mappers[real_f]
            tbin = int(m.value_to_bin(float(node["threshold"])))
            leaves.append(leaf)
            feats.append(inner)
            thrs.append(tbin)
            right_id = next_id
            next_id += 1
            if "left" in node and node["left"]:
                queue.append((node["left"], leaf))
            if "right" in node and node["right"]:
                queue.append((node["right"], right_id))
        n = min(len(leaves), self.max_leaves - 1)
        self.n_forced = n
        if n:
            self.forced_leaf = jnp.asarray(
                np.asarray(leaves[:n], np.int32))
            self.forced_feat = jnp.asarray(np.asarray(feats[:n], np.int32))
            self.forced_thr = jnp.asarray(np.asarray(thrs[:n], np.int32))
            log.info("Loaded %d forced splits from %s", n, path)

    # ------------------------------------------------------------------
    def _setup_cegb(self, config: Config) -> None:
        """CEGB enablement and per-feature cost arrays (ref:
        cost_effective_gradient_boosting.hpp:26 IsEnable). Re-run by
        reset_config so reset_parameter can change the penalties."""
        train_data = self.train_data
        coupled = list(config.cegb_penalty_feature_coupled or [])
        lazy = list(config.cegb_penalty_feature_lazy or [])
        self.use_cegb = (config.cegb_tradeoff < 1.0
                         or config.cegb_penalty_split > 0.0
                         or bool(coupled) or bool(lazy))
        if not self.use_cegb:
            return
        cp = np.zeros(train_data.num_features, np.float32)
        for real_f, pen in enumerate(coupled):
            inner = train_data.inner_feature_index(real_f)
            if inner >= 0:
                cp[inner] = pen
        self.cegb_coupled = jnp.asarray(cp)
        if not hasattr(self, "cegb_used"):
            self.cegb_used = np.zeros(train_data.num_features, bool)
        # per-(row, feature) lazy penalties (ref:
        # cost_effective_gradient_boosting.hpp:22 — charged per data
        # point in the leaf that has not used the feature on its path
        # yet; the used bitmap persists across the whole boosting run)
        lp = np.zeros(train_data.num_features, np.float32)
        for real_f, pen in enumerate(lazy):
            inner = train_data.inner_feature_index(real_f)
            if inner >= 0:
                lp[inner] = pen
        self.use_cegb_lazy = bool(np.any(lp > 0))
        self.cegb_lazy = jnp.asarray(lp)
        if self.use_cegb_lazy and not hasattr(self, "cegb_used_rf"):
            self.cegb_used_rf = jnp.zeros(
                (train_data.num_data, train_data.num_features), bool)

    # ------------------------------------------------------------------
    def _setup_parallel(self, config: Config) -> None:
        """Distribution axis of the learner factory (ref:
        src/treelearner/tree_learner.cpp:17-49 — the learner_type x
        device_type composition matrix). ``tree_learner=data|voting|
        feature`` makes every tree grow through shard_map over a named
        device mesh so ``lgb.train()`` works unchanged across the chips
        (SURVEY.md north star):

        - data: rows sharded, per-level histogram psum, split decisions
          replicated by construction (ref:
          data_parallel_tree_learner.cpp:126-276);
        - voting: rows sharded, per-level top-k vote caps the exchanged
          histogram columns (ref: voting_parallel_tree_learner.cpp:151-184);
        - feature: columns sharded, zero histogram traffic, per-level
          best-split record merge (ref:
          feature_parallel_tree_learner.cpp:60-77).

        Combinations the distributed growers don't implement degrade to
        data-parallel (still distributed, same trees) with a warning.
        """
        self.parallel_mode = "serial"
        self.mesh = None
        self.n_shards = 1
        self.axis_name = None
        self.mp = None
        self._par_fns = {}
        # external collective functions coordinate the HOST plane only;
        # training a "distributed" model without the jax process runtime
        # up would silently produce rank-local models — fail loudly
        # instead (see parallel/extnet.py module docstring)
        from ..parallel import extnet
        if extnet.is_active() \
                and jax.process_count() < extnet.num_machines():
            log.fatal(
                "LGBM_NetworkInitWithFunctions registered %d machines but "
                "the jax process runtime spans %d process(es); external "
                "function pointers cannot be spliced into XLA's device "
                "collectives — additionally bring up jax.distributed "
                "(parallel.distributed.init_distributed / set_network / "
                "the launcher) so device psums span the machines",
                extnet.num_machines(), jax.process_count())
        if not bool(getattr(config, "is_parallel", False)):
            return
        mode = str(config.tree_learner)
        n_dev = jax.device_count()
        if n_dev < 2:
            log.warning(
                "tree_learner=%s requested but only one device is visible; "
                "training serially (multi-chip needs a TPU slice or "
                "XLA_FLAGS=--xla_force_host_platform_device_count)", mode)
            self.telemetry.degrade("parallel_single_device",
                                   requested=mode, to="serial")
            return
        if getattr(self, "use_cegb_lazy", False):
            log.warning("cegb_penalty_feature_lazy keeps a per-(row, "
                        "feature) bitmap on one device and is not wired "
                        "into the distributed growers; dropping the lazy "
                        "penalties for this parallel run")
            self.telemetry.degrade("cegb_lazy_not_distributed")
            self.use_cegb_lazy = False
        if jax.process_count() > 1 and mode == "feature":
            # feature-parallel replicates rows on every shard; multi-
            # process runs hold one rank-local row shard per process
            log.warning("tree_learner=feature needs row-replicated data; "
                        "multi-process runs shard rows per rank — using "
                        "data-parallel")
            self.telemetry.degrade("feature_parallel_multiproc_rows",
                                   requested="feature", to="data")
            # megastep-taxonomy twin of the degrade event: names the
            # remaining multi-process limitation in the same reason
            # namespace the eviction matrix documents
            self._report_eviction("engine:multiproc_feature_parallel_rows",
                                  to="data")
            mode = "data"
        # feature-parallel composition: the FUSED feature engine keeps
        # the whole replicated layout (global feature indices), so EFB
        # and interaction/bynode constraints compose on it; the sliced
        # XLA feature grower cannot mix local/global indexing — degrade
        # only the combinations that genuinely force the XLA growers
        from ..ops.pallas_histogram import HAS_PALLAS as _HP
        fused_capable = _HP and (str(config.tpu_engine) == "fused"
                                 or (str(config.tpu_engine) == "auto"
                                     and self.on_tpu))
        if mode == "feature" and getattr(self, "use_cegb", False):
            log.warning("CEGB gain accounting is wired into the depthwise "
                        "XLA grower, whose feature-parallel column "
                        "slicing cannot carry the global per-feature "
                        "cost state; using data-parallel")
            self.telemetry.degrade("feature_parallel_cegb",
                                   requested="feature", to="data")
            mode = "data"
        if mode == "feature" and getattr(self, "n_forced", 0):
            log.warning("forced splits run on the leaf-wise grower; "
                        "feature-parallel is depth-wise — using "
                        "data-parallel")
            self.telemetry.degrade("feature_parallel_forced_splits",
                                   requested="feature", to="data")
            mode = "data"
        if mode == "feature" and not fused_capable \
                and (self.use_node_masks
                     or getattr(self, "use_bundles", False)):
            log.warning("the sliced XLA feature-parallel grower does not "
                        "compose with interaction/bynode constraints or "
                        "EFB (local/global feature indexing); set "
                        "tpu_engine=fused (replicated layout) or use "
                        "data-parallel — using data-parallel")
            self.telemetry.degrade("feature_parallel_xla_constraints",
                                   requested="feature", to="data")
            mode = "data"
        from ..parallel.mesh import DATA_AXIS, FEATURE_AXIS, make_mesh
        axis = FEATURE_AXIS if mode == "feature" else DATA_AXIS
        self.mesh = make_mesh(axis_name=axis)
        self.axis_name = axis
        self.n_shards = n_dev
        self.parallel_mode = mode
        n = self.num_data
        # device placement is LAZY (_place_par_data): the fused engine
        # reads only its own sharded fused_bins_T — materialising a second
        # padded copy of the binned matrix would waste O(dataset) HBM on
        # the flagship path
        self._par_placed = False
        self.bins_par = None
        self.bundle_bins_par = None
        if mode in ("data", "voting"):
            self.par_rows = ((n + n_dev - 1) // n_dev) * n_dev
        else:
            # feature mode: rows replicated, columns padded so every shard
            # owns an equal slice; pad features are trivial + masked off
            F = self.train_data.num_features
            self.par_feats = ((F + n_dev - 1) // n_dev) * n_dev
            padF = self.par_feats - F

            def padv(a, fill=0):
                a = np.asarray(a)
                return jnp.asarray(np.pad(a, (0, padF),
                                          constant_values=fill))
            self.par_meta = FeatureMeta(
                num_bin=padv(self.meta.num_bin, 2),
                missing_type=padv(self.meta.missing_type),
                default_bin=padv(self.meta.default_bin),
                monotone=padv(self.meta.monotone),
                is_cat=jnp.asarray(np.pad(
                    np.asarray(self.meta.is_cat), (0, padF))))
        if jax.process_count() > 1:
            self._init_multiproc(config)
        log.info("Using %s-parallel tree learner over %d devices", mode,
                 n_dev)

    def _init_multiproc(self, config: Config) -> None:
        """Joint multi-process training: one global model over per-rank
        row shards (the v5e-pod / DCN analog of the reference's multi-
        machine mode, data_parallel_tree_learner.cpp:126-276 — see
        parallel/multiproc.py for the layout contract)."""
        from ..parallel.multiproc import MultiProcLayout
        if bool(config.linear_tree):
            # REFERENCE PARITY: the reference also refuses this —
            # "Linear tree learner must be serial" (config.cpp:348
            # forces tree_learner=serial + device=cpu under linear_tree)
            log.fatal("linear_tree is serial-only (the reference forces "
                      "tree_learner=serial for linear trees too); not "
                      "supported with multi-process training")
        # DART/GOSS/RF compose since round 5: drop-set and bagging
        # streams are seeded identically on every rank (SPMD control
        # flow), GOSS resampling is rank-local like the reference's
        # (goss.hpp:103 samples each machine's own rows), and score
        # replay routes on the row-sharded global matrix
        # leaf-renewing objectives (L1/quantile/huber/MAPE) compose since
        # round 5: rank-local percentiles averaged over contributing
        # workers — the reference's own distributed semantics
        # (_renew_tree_output_mp; serial_tree_learner.cpp:744-755)
        if getattr(self.train_data, "prebundled", None) is not None:
            log.fatal("sparse-built (prebundled) datasets derive their "
                      "bundle layout from rank-local CSC columns and are "
                      "not supported with multi-process training; dense "
                      "EFB (enable_bundle on dense data) composes — its "
                      "layout comes from the shared binning sample")
        # the fused engine needs per-device row slices aligned to its
        # widest kernel tile (engine resolution happens later, so key on
        # the config request; "auto" resolves to fused only on TPU)
        from ..ops.pallas_histogram import HAS_PALLAS
        wants_fused = (str(config.tpu_engine) == "fused"
                       or (str(config.tpu_engine) == "auto"
                           and jax.default_backend() == "tpu"
                           and HAS_PALLAS))
        self.mp = MultiProcLayout(self.mesh, self.axis_name,
                                  self.train_data.num_data,
                                  row_align=2048 if wants_fused else 1,
                                  telemetry=self.telemetry)
        self.num_data = self.mp.Np
        self.par_rows = self.mp.Np
        self._mp_real_mask = self.mp.real_mask_np()
        self._mp_metadata = self.mp.global_metadata(self.train_data.metadata)
        # objectives/metrics were inited with the rank-local shard; re-init
        # on the global view so label statistics (class counts, averages,
        # metric weights) are global — the reference's GlobalSyncUp* paths.
        # num_data = REAL global rows (statistics), arrays are [Np] padded
        # with zero weight.
        if self.objective is not None:
            self.objective.init(self._mp_metadata, self.mp.total_real)
        for m in self.training_metrics:
            m.init(self._mp_metadata, self.mp.total_real)

    def _place_par_data(self) -> None:
        """Mesh placement of the binned matrix for the XLA parallel
        growers, deferred to first use (the fused engine never needs it)."""
        if self._par_placed:
            return
        from jax.sharding import NamedSharding, PartitionSpec as P
        axis = self.axis_name
        bins_np = np.asarray(self.train_data.bins)
        if self.mp is not None:
            # the one per-rank-DISTINCT operand: rank-local binned rows
            # into their block of the global row-sharded matrix
            if getattr(self, "use_bundles", False):
                self.bundle_bins_par = self.mp.shard_local(
                    np.asarray(self.bundle_bins_host))
            else:
                self.bins_par = self.mp.shard_local(bins_np)
            self._par_placed = True
            return
        if self.parallel_mode in ("data", "voting"):
            pad = self.par_rows - self.num_data
            if getattr(self, "use_bundles", False):
                # the bundled grower only ever reads the bundle matrix
                bb = np.asarray(self.bundle_bins_dev)
                if pad:
                    bb = np.pad(bb, ((0, pad), (0, 0)))
                self.bundle_bins_par = jax.device_put(
                    bb, NamedSharding(self.mesh, P(axis, None)))
            else:
                if pad:
                    bins_np = np.pad(bins_np, ((0, pad), (0, 0)))
                self.bins_par = jax.device_put(
                    bins_np, NamedSharding(self.mesh, P(axis, None)))
        else:
            padF = self.par_feats - self.train_data.num_features
            if padF:
                bins_np = np.pad(bins_np, ((0, 0), (0, padF)))
            self.bins_par = jax.device_put(
                bins_np, NamedSharding(self.mesh, P()))
        self._par_placed = True

    def _get_par_fn(self, kind: str):
        fn = self._par_fns.get(kind)
        if fn is None:
            fn = self._build_par_fn(kind)
            self._par_fns[kind] = fn
        return fn

    def _build_par_fn(self, kind: str):
        """shard_map-wrapped jitted tree growth for the sync path. The
        small per-tree state (meta, params, bundle tables) rides as
        closures — replicated constants; the O(rows) operands are
        explicit sharded arguments."""
        from jax.sharding import PartitionSpec as P
        axis = self.axis_name
        params = self.params
        L, B = self.max_leaves, self.max_bins
        md = int(self.config.max_depth)
        if kind == "fused_sync":
            from ..models.frontier2 import grow_tree_fused
            interp = self.fused_interpret
            use_nm = self.use_node_masks
            mode = self.parallel_mode
            top_k = int(self.config.top_k) if mode == "voting" else 0
            f_oh = self.fused_f_oh
            n_sh = self.n_shards

            quant = self.quant_bits

            def per_shard(bins_T, gh_T, fm_pad, *rest):
                ri = 0
                scales = None
                if quant:
                    scales = rest[0]
                    ri = 1
                nm = rest[ri:]
                fsm = None
                if mode == "feature":
                    # this shard owns an equal contiguous block of the
                    # padded one-hot feature axis (replicated layout,
                    # global indices — merge offset 0)
                    sid = jax.lax.axis_index(axis)
                    Fs = (f_oh + n_sh - 1) // n_sh
                    fi = jnp.arange(f_oh, dtype=jnp.int32)
                    fsm = (fi >= sid * Fs) & (fi < (sid + 1) * Fs)
                return grow_tree_fused(
                    bins_T, gh_T, self.fused_meta, fm_pad, params, L,
                    self.fused_Bp, f_oh, num_rows=0,
                    nch=self.fused_nch, max_depth=md,
                    extra_levels=int(self.config.tpu_extra_levels),
                    has_cat=self.has_cat,
                    use_mono_bounds=self.use_mono_bounds,
                    use_node_masks=use_nm,
                    node_masks=nm[0] if use_nm else None,
                    bundle_cols=self.fused_bundle_cols,
                    bundle_col_bins=self.fused_bundle_col_bins,
                    bundle_cfg=self.fused_bundle_cfg,
                    interpret=interp, psum_axis=axis,
                    mono_mode=getattr(self, "mono_mode", "basic"),
                    parallel_mode=mode, top_k=top_k,
                    feature_shard_mask=fsm,
                    quant_bits=quant, packed=self.fused_packed,
                    mask_onehot=self._mask_onehot(), gh_scales=scales)
            q_specs = (P(),) if quant else ()
            if mode == "feature":
                # rows replicated on every shard; records merge in-jit,
                # every shard emits the identical tree and row_leaf
                in_specs = (P(), P(), P()) + q_specs \
                    + ((P(),) if use_nm else ())
                out_specs = (P(), P())
            else:
                in_specs = (P(None, axis), P(None, axis), P()) + q_specs \
                    + ((P(),) if use_nm else ())
                out_specs = (P(), P(axis))
            # the packed gh block is rebuilt every call — donate it so
            # the sharded operand recycles its per-device buffers
            return jax.jit(_shard_map(
                per_shard, mesh=self.mesh, in_specs=in_specs,
                out_specs=out_specs, check_vma=False),
                donate_argnums=_donate(1))

        if kind == "xla_sync":
            mode = self.parallel_mode
            grow = (grow_tree_leafwise if self.grow_policy == "leafwise"
                    and mode in ("data", "voting")
                    else grow_tree_depthwise)
            hist_impl = self._xla_hist_impl()
            use_nm = self.use_node_masks
            use_cegb = self.use_cegb
            ub = getattr(self, "use_bundles", False)
            # forced splits compose with data- AND voting-parallel since
            # round 5 (the vote exchange always sums the forced features'
            # columns); feature-parallel degraded earlier
            n_forced = (getattr(self, "n_forced", 0)
                        if mode in ("data", "voting") else 0)

            if mode == "feature":
                n_sh = self.n_shards
                Fp = self.par_feats
                Fs = Fp // n_sh

                def per_shard(bins_full, gh, fm_pad):
                    sid = jax.lax.axis_index(axis)
                    f0 = sid * Fs
                    bins_loc = jax.lax.dynamic_slice_in_dim(
                        bins_full, f0, Fs, axis=1)
                    sl = lambda a: jax.lax.dynamic_slice_in_dim(
                        a, f0, Fs, axis=0)
                    meta_loc = FeatureMeta(
                        num_bin=sl(self.par_meta.num_bin),
                        missing_type=sl(self.par_meta.missing_type),
                        default_bin=sl(self.par_meta.default_bin),
                        monotone=sl(self.par_meta.monotone),
                        is_cat=sl(self.par_meta.is_cat))
                    return grow_tree_depthwise(
                        bins_loc, gh, meta_loc, sl(fm_pad), params, L, B,
                        md, hist_impl=hist_impl, psum_axis=axis,
                        has_cat=self.has_cat, parallel_mode="feature",
                        route_bins=bins_full, route_meta=self.par_meta,
                        feature_offset=f0,
                        use_mono_bounds=self.use_mono_bounds)
                return jax.jit(_shard_map(
                    per_shard, mesh=self.mesh, in_specs=(P(), P(), P()),
                    out_specs=(P(), P()), check_vma=False),
                    donate_argnums=_donate(1))

            kw = {"mono_mode": getattr(self, "mono_mode", "basic")}
            if mode == "voting":
                kw.update(parallel_mode="voting",
                          top_k=int(self.config.top_k))
            else:
                kw.update(parallel_mode="data")
            if ub:
                kw.update(use_bundles=True, bundle_cfg=self.bundle_cfg,
                          bundle_col_bins=self.bundle_col_bins)
            if grow is grow_tree_leafwise:
                # leaf-wise accepts parallel_mode/top_k since round 4
                # (voting under best-first growth); forced splits remain
                # data-mode-only
                kw["mono_mode"] = getattr(self, "mono_mode", "basic")
                if n_forced:
                    kw.update(n_forced=n_forced,
                              forced_leaf=self.forced_leaf,
                              forced_feat=self.forced_feat,
                              forced_thr=self.forced_thr)

            def per_shard(bins, gh, fm, *extra):
                i = 0
                nm = None
                if use_nm:
                    nm = extra[i]
                    i += 1
                kw2 = dict(kw)
                if use_cegb:
                    kw2.update(use_cegb=True,
                               cegb_coupled=self.cegb_coupled,
                               cegb_used=extra[i])
                    i += 1
                return grow(bins, gh, self.meta, fm, params, L, B, md,
                            hist_impl=hist_impl, psum_axis=axis,
                            has_cat=self.has_cat,
                            use_mono_bounds=self.use_mono_bounds,
                            use_node_masks=use_nm, node_masks=nm, **kw2)
            in_specs = (P(axis, None), P(axis, None), P()) \
                + ((P(),) if use_nm else ()) \
                + ((P(),) if use_cegb else ())
            return jax.jit(_shard_map(
                per_shard, mesh=self.mesh, in_specs=in_specs,
                out_specs=(P(), P(axis)), check_vma=False),
                donate_argnums=_donate(1))
        raise KeyError(kind)

    @contextlib.contextmanager
    def _maybe_record_collectives(self, fresh: bool):
        """Trace-time collective payload recorder around the FIRST call
        of a fresh grower jit (tracing happens exactly once per jit
        signature, so the recorded static shapes are the program's real
        per-call collective schedule — ops/collectives.py). Yields the
        recorder, or None when there is nothing to measure (serial mode
        or an already-traced function)."""
        if not fresh or self.parallel_mode == "serial":
            yield None
            return
        from ..ops.collectives import CollectiveTrace
        with CollectiveTrace() as rec:
            yield rec

    def _grow_parallel(self, gh, tid: int = 0):
        """Sync-path tree growth through the mesh (driver semantics of
        ref: data_parallel_tree_learner.cpp:126-276 — local histograms,
        global sums, replicated split decisions). ``gh`` is [n, 3]
        (grad*w, hess*w, w); pad rows carry zero weight so they never
        contribute to histograms or counts."""
        n = self.num_data
        fm = self._feature_mask()
        extra = []
        if self.use_node_masks:
            extra.append(self._node_masks_padded() if self.use_fused
                         else self._node_masks_for_iter())
        if self.use_fused:
            from ..ops.fused_level import pack_gh, pack_gh_quant
            pad = self.fused_Rp - n
            g_p = jnp.pad(gh[:, 0], (0, pad))
            h_p = jnp.pad(gh[:, 1], (0, pad))
            w_p = jnp.pad(gh[:, 2], (0, pad))
            qextra = ()
            if self.quant_bits:
                # the max-abs scale reduces over the GLOBAL (sharded)
                # operand, so every shard quantizes on the same grid
                gh_T, scales = pack_gh_quant(
                    g_p, h_p, w_p, self.quant_bits,
                    self._quant_seed(self.iter, tid))
                qextra = (scales,)
            else:
                gh_T = pack_gh(g_p, h_p, w_p, self.fused_nch)
            fm_pad = jnp.zeros((self.fused_f_oh,), bool) \
                .at[:fm.shape[0]].set(fm)
            smask = self._screen_mask_for_iter()
            if smask is not None:
                fm_pad = fm_pad & smask
            fresh = "fused_sync" not in self._par_fns
            fn = self._get_par_fn("fused_sync")
            with self._maybe_record_collectives(fresh) as rec:
                tree, row_leaf = fn(self.fused_bins_T, gh_T, fm_pad,
                                    *qextra, *extra)
            if rec is not None:
                self._coll_per_grow = rec.profile
            self._note_tree_gains(tree)
            return tree, row_leaf[:n]
        if self.use_cegb:
            extra.append(jnp.asarray(self.cegb_used))
        self._place_par_data()
        fresh = "xla_sync" not in self._par_fns
        if self.parallel_mode == "feature":
            Fp = self.par_feats
            fm_pad = jnp.zeros((Fp,), bool).at[:fm.shape[0]].set(fm)
            fn = self._get_par_fn("xla_sync")
            with self._maybe_record_collectives(fresh) as rec:
                tree, row_leaf = fn(self.bins_par, gh, fm_pad, *extra)
            if rec is not None:
                self._coll_per_grow = rec.profile
            return tree, row_leaf
        pad = self.par_rows - n
        gh_p = jnp.pad(gh, ((0, pad), (0, 0)))
        bins = (self.bundle_bins_par if getattr(self, "use_bundles", False)
                else self.bins_par)
        fn = self._get_par_fn("xla_sync")
        with self._maybe_record_collectives(fresh) as rec:
            tree, row_leaf = fn(bins, gh_p, fm, *extra)
        if rec is not None:
            self._coll_per_grow = rec.profile
        return tree, row_leaf[:n]

    # ------------------------------------------------------------------
    def _setup_engine(self, config: Config) -> None:
        """Resolve tpu_engine/grow_policy into the learner flags (called by
        init and again by reset_config so reset_parameter can switch
        engines)."""
        from ..ops.pallas_histogram import HAS_PALLAS
        self._fast_step_fn = None     # engine/params changed: re-derive
        self._fast_ok_cache = None
        self._megastep_fns = {}       # megastep closes over params too
        self._megastep_fm = {}
        self._fast_fm_pads = None
        self._par_fns = {}            # parallel growers close over params
        self._epi_ok_cache = None     # epilogue closes over params too
        self._epi_fns = None
        self._epi_carry = None
        self._epi_fm_pad = None
        self._epi_bag_ones = None
        self._valid_upd_fns = None    # close over shrinkage/depth bound
        self._coll_per_iter = None    # re-measured on the fresh traces
        self._coll_per_grow = None
        engine = config.tpu_engine
        if engine == "auto":
            engine = "fused" if (self.on_tpu and HAS_PALLAS) else "xla"
        # the fused engine composes with every distribution mode since
        # round 5 (ref: tree_learner.cpp:17-49 — the reference
        # instantiates its device learner under data/voting/feature
        # distribution too); only the frontier-v1 engine lacks a
        # multi-chip path
        if getattr(self, "mp", None) is not None \
                and engine not in ("xla", "fused"):
            # the mp row layout was aligned for fused only when the
            # CONFIG requested fused/auto-tpu; a late engine swap to
            # fused would trip the Rp/Np alignment guard
            log.info("multi-process training runs on the XLA or fused "
                     "engines; using xla")
            self.telemetry.degrade("engine_multiproc_needs_xla_or_fused",
                                   requested=config.tpu_engine, to="xla")
            self._report_eviction("engine:multiproc_needs_xla_or_fused",
                                  requested=str(config.tpu_engine))
            engine = "xla"
        if self.parallel_mode in ("voting", "feature") \
                and engine not in ("xla", "fused"):
            log.info("tree_learner=%s runs on the XLA or fused engines",
                     self.parallel_mode)
            self.telemetry.degrade("engine_parallel_needs_xla_or_fused",
                                   requested=config.tpu_engine, to="xla",
                                   mode=self.parallel_mode)
            engine = "xla"
        if self.parallel_mode == "data" and engine == "frontier":
            log.info("the frontier-v1 engine has no multi-chip path; "
                     "using the fused engine")
            self.telemetry.degrade("frontier_no_multichip",
                                   requested="frontier", to="fused")
            engine = "fused"
        # intermediate/advanced monotone modes need the stale-leaf
        # recompute, implemented on the leaf-wise grower (the reference
        # implements them in SerialTreeLearner too,
        # monotone_constraints.hpp:514,856)
        self.mono_mode = "basic"
        if getattr(self, "use_mono_bounds", False):
            method = str(self.config.monotone_constraints_method)
            if method in ("intermediate", "advanced"):
                # round 4: intermediate on ALL growers (leaf-wise inline,
                # depthwise/fused via mono_inter_level_update); advanced
                # (per-segment bound planes) on the leaf-wise grower
                self.mono_mode = method
        if getattr(self, "n_forced", 0) > 0 and engine != "xla":
            log.info("forced splits use the leaf-wise XLA engine")
            self.telemetry.degrade("forced_splits_need_xla",
                                   requested=engine, to="xla")
            engine = "xla"
        if getattr(self, "use_bundles", False) and engine == "frontier":
            log.info("feature bundling is not wired into the frontier-v1 "
                     "engine; using the fused engine")
            self.telemetry.degrade("frontier_no_bundling",
                                   requested="frontier", to="fused")
            engine = "fused"
        if getattr(self, "use_cegb", False) and engine != "xla":
            # CEGB gain deltas are wired into the depthwise XLA grower;
            # must override BEFORE the engine flags are derived
            log.info("cost-effective gradient boosting uses the "
                     "depthwise XLA engine")
            self.telemetry.degrade("cegb_needs_xla", requested=engine,
                                   to="xla")
            engine = "xla"
        self.use_fused = engine == "fused" and HAS_PALLAS
        self.fused_interpret = self.use_fused and not self.on_tpu
        self.use_frontier = (engine == "frontier" and self.on_tpu
                             and HAS_PALLAS
                             and config.tpu_histogram_impl
                             in ("auto", "pallas"))
        needs_v2 = (self.has_cat or getattr(self, "use_mono_bounds", False)
                    or getattr(self, "use_node_masks", False))
        if self.use_frontier and needs_v2:
            log.warning("tpu_engine=frontier supports neither categorical "
                        "features, monotone bounds, nor interaction/bynode "
                        "constraints; using the fused engine")
            self.telemetry.degrade("frontier_missing_features",
                                   requested="frontier", to="fused")
            self.use_frontier = False
            self.use_fused = True
            self.fused_interpret = not self.on_tpu
        default_policy = ("depthwise" if (self.use_fused or self.use_frontier
                                          or getattr(self, "use_cegb", False))
                          else "leafwise")
        self.grow_policy = {"auto": default_policy}.get(config.grow_policy,
                                                        config.grow_policy)
        if self.parallel_mode == "feature" \
                and self.grow_policy != "depthwise":
            # voting composes with leaf-wise growth since round 4 (the
            # reference's voting learner runs best-first too,
            # voting_parallel_tree_learner.cpp:151-184); feature-parallel
            # stays on the depthwise column-slice exchange
            log.warning("tree_learner=feature is implemented on the "
                        "depthwise grower; switching grow_policy")
            self.telemetry.degrade("feature_parallel_needs_depthwise",
                                   to="depthwise")
            self.grow_policy = "depthwise"
        if self.mono_mode == "advanced" and self.grow_policy != "leafwise":
            log.warning("monotone_constraints_method=advanced (segment "
                        "bound planes) runs on the leaf-wise grower; this "
                        "configuration uses intermediate instead")
            self.telemetry.degrade("mono_advanced_needs_leafwise",
                                   to="intermediate")
            self.mono_mode = "intermediate"
        if self.mono_mode in ("intermediate", "advanced") \
                and self.parallel_mode == "feature" and not self.use_fused:
            # the sliced XLA feature grower tracks per-leaf bin regions
            # only for its LOCAL feature slice; cross-leaf adjacency
            # needs every feature's region. The fused feature engine
            # (replicated layout) and voting (validity-masked rescans)
            # compose since round 5.
            log.warning("the intermediate/advanced monotone recompute "
                        "needs full per-feature leaf regions, which the "
                        "sliced feature-parallel grower does not hold; "
                        "this configuration enforces the basic mode "
                        "(tpu_engine=fused composes)")
            self.telemetry.degrade("mono_inter_needs_full_regions",
                                   to="basic")
            self.mono_mode = "basic"
        if getattr(self, "use_cegb", False) \
                and self.grow_policy != "depthwise":
            log.warning("CEGB is implemented on the depthwise grower; "
                        "switching grow_policy")
            self.telemetry.degrade("cegb_needs_depthwise", to="depthwise")
            self.grow_policy = "depthwise"
        if getattr(self, "use_bundles", False) \
                and getattr(self, "n_forced", 0) > 0:
            if getattr(self.train_data, "prebundled", None) is not None:
                # reset_config can reach here after init: the bundle
                # matrix IS the storage — it cannot be switched off
                log.fatal("forced splits are not supported on sparse-"
                          "built (prebundled) datasets")
            log.warning("forced splits disable feature bundling")
            self.telemetry.degrade("forced_splits_disable_efb")
            self.use_bundles = False
        if getattr(self, "n_forced", 0) > 0 \
                and self.grow_policy != "leafwise":
            log.warning("forced splits are implemented on the leaf-wise "
                        "grower; switching grow_policy")
            self.telemetry.degrade("forced_splits_need_leafwise",
                                   to="leafwise")
            self.grow_policy = "leafwise"
        if getattr(self, "n_forced", 0) > 0 \
                and getattr(self, "use_cegb", False):
            log.warning("CEGB penalties are not applied when forced splits "
                        "are enabled (leaf-wise grower); disabling CEGB")
            self.telemetry.degrade("forced_splits_disable_cegb")
            self.use_cegb = False
        if self.grow_policy != "depthwise":
            self.use_fused = self.use_frontier = False
        # ---- histogram-plane cuts (ROADMAP item 4). Each gates
        # independently; all three are fused-engine features — other
        # engines degrade with a structured event and train unchanged.
        qb = int(getattr(config, "tpu_quantized_grad", 0) or 0)
        if qb not in (0, 8, 16):
            log.fatal("tpu_quantized_grad must be 0, 8 or 16; got %s", qb)
        if qb and not self.use_fused:
            log.info("tpu_quantized_grad requires the fused engine; "
                     "training with f32 histograms")
            self.telemetry.degrade("quantized_grad_needs_fused",
                                   requested=qb)
            qb = 0
        self.quant_bits = qb
        adaptive = bool(getattr(config, "tpu_adaptive_bins", False))
        if adaptive and not self.use_fused:
            self.telemetry.degrade("adaptive_bins_needs_fused")
            adaptive = False
        if adaptive and getattr(self, "use_bundles", False):
            # EFB already owns the packed flat axis (bundle columns)
            log.info("tpu_adaptive_bins is subsumed by feature bundling; "
                     "keeping the bundle layout")
            self.telemetry.degrade("adaptive_bins_with_efb")
            adaptive = False
        if adaptive and self.parallel_mode == "voting":
            # the voting exchange slices the flat axis per LOGICAL
            # feature (reshape(F, B)) — incompatible with class packing
            self.telemetry.degrade("adaptive_bins_with_voting")
            adaptive = False
        self.use_adaptive_bins = adaptive
        scr = bool(getattr(config, "tpu_gain_screening", False))
        if scr and not self.use_fused:
            self.telemetry.degrade("gain_screening_needs_fused")
            scr = False
        self.use_screening = scr
        self._screen_mask_cache = None
        self._iter_gain_acc = None
        if self.use_fused:
            if not hasattr(self, "fused_bins_T") \
                    or getattr(self, "_fused_built_mode", None) \
                    != (self.parallel_mode, self.use_adaptive_bins):
                # (re)build: the row padding, mesh placement and packing
                # of the transposed matrix depend on the parallel mode
                # and the adaptive layout
                self._init_fused(self.train_data)
            else:
                from ..ops.fused_level import NCH_FAST, NCH_PRECISE
                self.fused_nch = (NCH_FAST
                                  if config.tpu_hist_precision == "bf16"
                                  else NCH_PRECISE)
            if self.quant_bits:
                # quantized channel layout overrides tpu_hist_precision:
                # 8 -> (g, h, w) int8; 16 -> int8 hi/lo split (5 ch)
                from ..ops.quantize import QNCH
                self.fused_nch = QNCH[self.quant_bits]
            self._publish_hist_gauges()
        elif self.use_frontier and not hasattr(self, "bins_i32_dev"):
            self._init_frontier(self.train_data)

    # ------------------------------------------------------------------
    def _mp_fused_bins_T(self, local_rows_np: np.ndarray, Fp: int,
                         Rp: int, bins_per_col: int) -> jax.Array:
        """Global transposed fused matrix from process-local row blocks
        (the same rank-blocked layout contract as bins_par,
        parallel/multiproc.py). mp.S is fused-aligned so Rp == mp.Np."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        if Rp != self.mp.Np:
            log.fatal("fused multi-process row padding mismatch: Rp=%d "
                      "vs layout Np=%d (mp.S must be 2048-aligned)",
                      Rp, self.mp.Np)
        np_dt = np.int8 if bins_per_col <= 128 else np.int16
        n_cols = local_rows_np.shape[1]
        loc = np.zeros((Fp, self.mp.block), np_dt)
        loc[:n_cols, :self.mp.local_real] = local_rows_np.T.astype(np_dt)
        return jax.make_array_from_process_local_data(
            NamedSharding(self.mesh, P(None, self.axis_name)), loc)

    def _init_fused(self, train_data: TpuDataset) -> None:
        """int8 transposed bin matrix + f_oh-padded metadata for the fused
        route+histogram level kernel (ops/fused_level.py). With EFB the
        matrix holds bundle COLUMNS (kernel layout) while split search
        stays on the logical feature layout."""
        from ..ops.fused_level import NCH_FAST, NCH_PRECISE, feature_layout
        F = train_data.num_features
        F_oh, Bp = feature_layout(F, self.max_bins)
        R = self.num_data
        # adaptive per-feature bin widths (tpu_adaptive_bins): pack each
        # feature's slab at ITS pow2 width instead of the global Bp; the
        # bin matrix rows are permuted into width-class order so the
        # kernel builds each class with one bulk repeat+compare
        self.fused_packed = None
        feat_order = None
        if getattr(self, "use_adaptive_bins", False) \
                and not getattr(self, "use_bundles", False) and F > 0:
            from ..ops.layout import packed_feature_layout
            self.fused_packed = packed_feature_layout(
                np.asarray(train_data.num_bin_per_feat), self.max_bins,
                f_oh=F_oh)
            feat_order = np.asarray(self.fused_packed.feat_order, np.int64)
        # row-sharded modes (data/voting) need kernel-tile-aligned local
        # rows per shard; 2048 = the widest shallow-pass tile
        # (default_tile_rows cap), so shallow levels can actually run at
        # the bigger tile. Multi-process layouts pre-align (mp.S) so
        # Rp == mp.Np already.
        blk = 2048 * (self.n_shards
                      if self.parallel_mode in ("data", "voting") else 1)
        Rp = ((R + blk - 1) // blk) * blk
        if getattr(self, "use_bundles", False):
            n_cols = int(self.bundle_bins_dev.shape[1])
            C_oh, Bc_p = feature_layout(n_cols, self.bundle_col_bins)
            Fp = max(C_oh, 8)
            dtype = jnp.int8 if Bc_p <= 128 else jnp.int16
            if self.mp is not None:
                self.fused_bins_T = self._mp_fused_bins_T(
                    np.asarray(self.bundle_bins_host), Fp, Rp, Bc_p)
            else:
                self.fused_bins_T = (
                    jnp.zeros((Fp, Rp), dtype)
                    .at[:n_cols, :R].set(
                        self.bundle_bins_dev.T.astype(dtype)))
            self.fused_bundle_cols = C_oh
            self.fused_bundle_col_bins = Bc_p
            # decode tables padded to the logical f_oh (padding features:
            # invalid everywhere, residual suppressed by bundle_plane_views)
            from ..models.learner import BundleCfg
            bc = self.bundle_cfg
            # logical plane layout is [f_oh, Bp] (pow2-padded bins like the
            # unbundled fused pool); kernel flat stride is the padded Bc_p
            fi = jnp.zeros((F_oh, Bp), jnp.int32)
            va = jnp.zeros((F_oh, Bp), bool)
            db = jnp.zeros((F_oh,), jnp.int32)
            cof = jnp.full((F_oh,), -1, jnp.int32)
            off = jnp.zeros((F_oh,), jnp.int32)
            col = bc.col_of_feat
            offs = bc.offset_of_feat
            b_i = jnp.arange(Bp, dtype=jnp.int32)[None, :]
            fi = fi.at[:F].set(jnp.minimum(
                col[:, None] * Bc_p + offs[:, None] + b_i,
                C_oh * Bc_p - 1))
            va = va.at[:F, :bc.valid.shape[1]].set(bc.valid)
            db = db.at[:F].set(bc.default_bin)
            cof = cof.at[:F].set(col)
            off = off.at[:F].set(offs)
            self.fused_bundle_cfg = BundleCfg(
                flat_idx=fi, valid=va, default_bin=db, col_of_feat=cof,
                offset_of_feat=off)
        elif self.mp is not None:
            Fp = max(F_oh, 8)
            dtype = jnp.int8 if Bp <= 128 else jnp.int16
            rows_np = np.asarray(self.train_data.bins)
            if feat_order is not None:
                rows_np = rows_np[:, feat_order]
            self.fused_bins_T = self._mp_fused_bins_T(
                rows_np, Fp, Rp, Bp)
            self.fused_bundle_cols = 0
            self.fused_bundle_col_bins = 0
            self.fused_bundle_cfg = None
        else:
            Fp = max(F_oh, 8)
            # int8 covers bins <= 127; larger max_bin needs int16 (a uint8
            # bin index >= 128 would wrap negative in int8 and corrupt the
            # one-hot)
            dtype = jnp.int8 if Bp <= 128 else jnp.int16
            # transpose + pad ON DEVICE from the already-uploaded bin
            # matrix: a second 300+ MB host transpose + host->device
            # transfer through the remote tunnel costs ~10 s at Higgs scale
            src = self.bins_dev.T.astype(dtype)
            if feat_order is not None:
                # width-class permutation of the feature rows (adaptive
                # layout; the logical order is recovered at plane decode)
                src = jnp.take(src, jnp.asarray(feat_order, jnp.int32),
                               axis=0)
            self.fused_bins_T = (
                jnp.zeros((Fp, Rp), dtype)
                .at[:F, :R].set(src))
            self.fused_bundle_cols = 0
            self.fused_bundle_col_bins = 0
            self.fused_bundle_cfg = None
        if self.parallel_mode in ("data", "voting") and self.mp is None:
            # place the transposed matrix row-sharded once, not per call
            from jax.sharding import NamedSharding, PartitionSpec as P
            self.fused_bins_T = jax.device_put(
                self.fused_bins_T,
                NamedSharding(self.mesh, P(None, self.axis_name)))
        elif self.parallel_mode == "feature":
            # feature-parallel replicates rows (zero histogram traffic;
            # per-level record merge instead) — replicate the matrix
            from jax.sharding import NamedSharding, PartitionSpec as P
            self.fused_bins_T = jax.device_put(
                self.fused_bins_T, NamedSharding(self.mesh, P()))
        # the replicated [R, F] copy served only as the transpose source;
        # release it so HBM holds one binned matrix (the property rebuilds
        # it on the rare rollback/stop-subtract/DART replay paths)
        self._bins_dev = None
        self.fused_f_oh = F_oh
        self.fused_Bp = Bp
        self.fused_Rp = Rp
        self._fused_built_mode = (self.parallel_mode,
                                  bool(self.use_adaptive_bins))
        self.fused_nch = (NCH_FAST if self.config.tpu_hist_precision == "bf16"
                          else NCH_PRECISE)
        # the gain EMA is sized to the padded feature axis; keep a live
        # EMA across reset_config (continued training) unless the shape
        # moved
        if self._gain_ema_dev is None \
                or self._gain_ema_dev.shape[0] != F_oh:
            self._gain_ema_dev = jnp.zeros((F_oh,), jnp.float32)
        nb = np.zeros(F_oh, np.int32)
        nb[:F] = np.asarray(self.meta.num_bin)
        mt = np.zeros(F_oh, np.int32)
        mt[:F] = np.asarray(self.meta.missing_type)
        db = np.zeros(F_oh, np.int32)
        db[:F] = np.asarray(self.meta.default_bin)
        mono = np.zeros(F_oh, np.int32)
        mono[:F] = np.asarray(self.meta.monotone)
        ic = np.zeros(F_oh, bool)
        ic[:F] = np.asarray(self.meta.is_cat)
        self.fused_meta = FeatureMeta(jnp.asarray(nb), jnp.asarray(mt),
                                      jnp.asarray(db), jnp.asarray(mono),
                                      jnp.asarray(ic))

    # ------------------------------------------------------------------
    def _init_frontier(self, train_data: TpuDataset) -> None:
        """Feature-padded int32 row-major + transposed bin matrices for the
        Pallas kernel and column-load routing (models/frontier.py)."""
        from ..ops.pallas_histogram import pad_feature_layout
        F = train_data.num_features
        Fp, Bp = pad_feature_layout(F, self.max_bins)
        self.frontier_Fp = Fp
        self.frontier_Bp = Bp
        bins = np.asarray(train_data.bins)
        bins_i32 = np.zeros((self.num_data, Fp), np.int32)
        bins_i32[:, :F] = bins
        self.bins_i32_dev = jnp.asarray(bins_i32)
        self.bins_T_dev = jnp.asarray(bins_i32.T.copy())
        # padded feature meta: pad features are trivial and never selected
        nb = np.full(Fp, 2, np.int32)
        nb[:F] = np.asarray(self.meta.num_bin)
        mt = np.zeros(Fp, np.int32)
        mt[:F] = np.asarray(self.meta.missing_type)
        db = np.zeros(Fp, np.int32)
        db[:F] = np.asarray(self.meta.default_bin)
        mono = np.zeros(Fp, np.int32)
        mono[:F] = np.asarray(self.meta.monotone)
        self.frontier_meta = FeatureMeta(jnp.asarray(nb), jnp.asarray(mt),
                                         jnp.asarray(db), jnp.asarray(mono))

    # ------------------------------------------------------------------
    def add_valid_data(self, valid_data: TpuDataset, name: str,
                       metrics: Sequence) -> None:
        """(ref: gbdt.cpp AddValidDataset)"""
        if getattr(self, "mp", None) is not None \
                and self.early_stopping_round > 0:
            # metrics evaluate on the rank-LOCAL valid shard (the
            # reference's metrics are not distributed-aware either,
            # SURVEY §2.8); divergent stop decisions would desync the
            # ranks' collective schedules and hang the mesh
            log.warning("multi-process early stopping requires IDENTICAL "
                        "validation data on every rank — per-rank valid "
                        "shards may stop ranks at different iterations "
                        "and deadlock the collectives")
        self.drain_pending()          # replay below needs the full model
        self._fast_ok_cache = None    # (valid sets ride the fast path now)
        self._megastep_fns = {}       # valid-set count is baked into the
        self._epi_ok_cache = None     # megastep signature
        self._epi_carry = None
        if self._eval_consumer is not None:
            # the traced eval plan enumerated the old valid-set list; a
            # new set mid-run invalidates it (cannot happen through
            # engine.train, which adds every set before arming)
            log.warning("valid set added while a drain-replay eval "
                        "consumer was armed; disabling on-device eval")
            self.arm_megastep(self._megastep_armed, eval_consumer=None)
        self.valid_data.append(valid_data)
        self._publish_ingest(valid_data)
        self.valid_bins.append(self._dataset_bins_to_device(valid_data))
        k = self.num_tree_per_iteration
        n = valid_data.num_data
        md = valid_data.metadata
        if md is not None and md.init_score is not None:
            init = np.asarray(md.init_score, np.float64)
            if init.size == n * k:
                s = init.reshape(k, n, order="C")
            else:
                s = np.tile(init.reshape(1, n), (k, 1))
            self.valid_scores.append(jnp.asarray(s, jnp.float32))
        else:
            self.valid_scores.append(jnp.zeros((k, n), jnp.float32))
        self.valid_metrics.append(list(metrics))
        self.valid_names.append(name)
        # replay existing model onto the new valid set (continued training)
        for i, dt in enumerate(self.device_trees):
            tree_id = i % self.num_tree_per_iteration
            self.valid_scores[-1] = self._add_tree_to_score(
                self.valid_scores[-1], self.valid_bins[-1], dt, tree_id,
                bundle=self._valid_bundle(len(self.valid_data) - 1))

    # ------------------------------------------------------------------
    def _boost_from_average(self, class_id: int, update_scorer: bool) -> float:
        """(ref: gbdt.cpp:346 BoostFromAverage)"""
        cfg = self.config
        if (self.models or self._pending or self.has_init_score
                or self.objective is None):
            return 0.0
        if not (cfg.boost_from_average or self.train_data.num_features == 0):
            if self.objective.name in ("regression_l1", "quantile", "mape"):
                log.warning("Disabling boost_from_average in %s may cause the "
                            "slow convergence", self.objective.name)
            return 0.0
        init_score = self.objective.boost_from_score(class_id)
        if abs(init_score) > K_EPSILON:
            if update_scorer:
                self.scores = self.scores.at[class_id].add(init_score)
                for vi in range(len(self.valid_scores)):
                    self.valid_scores[vi] = \
                        self.valid_scores[vi].at[class_id].add(init_score)
            log.info("Start training from score %f", init_score)
            return init_score
        return 0.0

    def _boosting_scores(self):
        """Scores used for gradient computation (DART overrides)."""
        return self.scores

    def _get_gradients(self):
        scores = self._boosting_scores()
        grad, hess = self.objective.get_gradients(scores)
        return grad, hess

    # ------------------------------------------------------------------
    def _bag_ones(self):
        """All-rows-in-bag weight vector ([n] f32). Multi-process: the
        real-row mask (pad rows carry zero weight so they never touch
        histograms, counts or leaf sums), sharded over the global mesh."""
        if getattr(self, "mp", None) is not None:
            return self.mp.shard_full(self._mp_real_mask)
        return jnp.ones((self.num_data,), jnp.float32)

    def _bag_mask_for(self, it: int):
        """In-bag mask effective at iteration ``it``. Rounds fire at
        iterations where it % bagging_freq == 0 and are drawn strictly in
        stream order, cached by firing iteration (two most recent kept) —
        the fused-epilogue fast path legitimately asks ONE round ahead
        (the epilogue computes the NEXT iteration's gradients and root
        histogram, so it needs the next round's weights early; the draw
        order, and hence reference parity, is unchanged)."""
        cfg = self.config
        fire = (it // cfg.bagging_freq) * cfg.bagging_freq
        cache = getattr(self, "_bag_round_cache", None)
        if cache is None:
            cache = self._bag_round_cache = {}
        if fire not in cache:
            # requests arrive in nondecreasing firing order, so drawing on
            # first sight preserves the stream sequence (and a fresh
            # stream after reset_config starts over at its first round)
            # reference-parity draws: one float per row per round from the
            # row's 1024-block LCG stream (ref: gbdt.cpp:192
            # BaggingHelper) — the in-bag SET matches the reference
            # bit-for-bit. The float draws are compared against the
            # DOUBLE fraction, matching the reference's float-vs-double
            # promotion (gbdt.cpp:192).
            draws = self.bag_streams.next_floats()
            if self.balanced_bagging:
                label = (self._mp_metadata.label
                         if getattr(self, "mp", None) is not None
                         else self.train_data.metadata.label)
                frac = np.where(label > 0,
                                np.float64(cfg.pos_bagging_fraction),
                                np.float64(cfg.neg_bagging_fraction))
                mask = draws.astype(np.float64) < frac
            else:
                mask = draws.astype(np.float64) < np.float64(
                    cfg.bagging_fraction)
            cache[fire] = mask
            for old in [key for key in cache
                        if key < fire - cfg.bagging_freq]:
                del cache[old]
        return cache[fire]

    def _bagging(self, it: int, grad, hess):
        """Recompute the in-bag weight vector (ref: gbdt.cpp:230 Bagging).
        Returns possibly-modified (grad, hess) (GOSS multiplies)."""
        cfg = self.config
        if not self.is_bagging or cfg.bagging_freq <= 0 \
                or it % cfg.bagging_freq != 0:
            return grad, hess
        mask = self._bag_mask_for(it)
        if getattr(self, "mp", None) is not None:
            m = mask.astype(np.float32) * self._mp_real_mask
            self.bag_cnt = int(m.sum())
            self._bag_weight_host = m    # rank-local renewal reads this
            self.bag_weight = self.mp.shard_full(m)
        else:
            self.bag_cnt = int(mask.sum())
            self.bag_weight = jnp.asarray(mask.astype(np.float32))
        log.debug("Re-bagging, using %d data to train", self.bag_cnt)
        return grad, hess

    def _bag_weight_for_iter(self, it: int):
        """[n] f32 in-bag weights effective at iteration ``it`` (lookahead
        helper for the fused epilogue; does not touch the live
        bag_weight/bag_cnt bookkeeping)."""
        cfg = self.config
        if not self.is_bagging or cfg.bagging_freq <= 0:
            return jnp.ones((self.num_data,), jnp.float32)
        mask = self._bag_mask_for(it)
        return jnp.asarray(mask.astype(np.float32))

    # ------------------------------------------------------------------
    def _make_fused_step(self):
        """One jit-compiled dispatch per tree: bagging fold-in + growth.
        Eager per-op dispatch latency dominates otherwise (each jnp op is a
        separate device round trip on remote-attached TPUs)."""
        if self.use_frontier:
            from ..models.frontier import grow_tree_frontier
            Fp = self.frontier_Fp

            @jax.jit
            def step(grad_row, hess_row, bag_weight, fm_pad):
                gh = jnp.stack([grad_row * bag_weight,
                                hess_row * bag_weight, bag_weight], axis=1)
                return grow_tree_frontier(
                    self.bins_i32_dev, self.bins_T_dev, gh,
                    self.frontier_meta, fm_pad, self.params,
                    self.max_leaves, self.frontier_Bp,
                    int(self.config.max_depth), hist_impl="pallas")
            return step

        grow = (grow_tree_depthwise if self.grow_policy == "depthwise"
                else grow_tree_leafwise)

        @jax.jit
        def step(grad_row, hess_row, bag_weight, fm):
            gh = jnp.stack([grad_row * bag_weight,
                            hess_row * bag_weight, bag_weight], axis=1)
            return grow(self.bins_dev, gh, self.meta, fm, self.params,
                        self.max_leaves, self.max_bins,
                        int(self.config.max_depth),
                        hist_impl=self._xla_hist_impl())
        return step

    def _fused_step(self, grad_row, hess_row):
        if getattr(self, "_fused_step_fn", None) is None:
            self._fused_step_fn = self._make_fused_step()
            self._score_add_fn = self._make_score_add()
        fm = self._feature_mask()
        if self.use_frontier:
            Fp = self.frontier_Fp
            fm = jnp.zeros((Fp,), bool).at[:fm.shape[0]].set(fm)
        return self._fused_step_fn(grad_row, hess_row, self.bag_weight, fm)

    def _make_score_add(self):
        L = self.max_leaves
        if self.use_frontier:
            from ..models.frontier import leaf_value_lookup

            @jax.jit
            def add(scores, tid, leaf_value, row_leaf):
                return scores.at[tid].add(
                    leaf_value_lookup(leaf_value, row_leaf, L))
            return add

        @jax.jit
        def add(scores, tid, leaf_value, row_leaf):
            return scores.at[tid].add(leaf_value[row_leaf])
        return add

    # ------------------------------------------------------------------
    def _grow(self, gh, tid: int = 0):
        if self.parallel_mode != "serial":
            return self._grow_parallel(gh, tid)
        fm = self._feature_mask()
        if self.use_fused:
            from ..models.frontier2 import grow_tree_fused
            from ..ops.fused_level import pack_gh, pack_gh_quant
            n = self.num_data
            pad = self.fused_Rp - n
            g_p = jnp.pad(gh[:, 0], (0, pad))
            h_p = jnp.pad(gh[:, 1], (0, pad))
            w_p = jnp.pad(gh[:, 2], (0, pad))
            scales = None
            if self.quant_bits:
                gh_T, scales = pack_gh_quant(
                    g_p, h_p, w_p, self.quant_bits,
                    self._quant_seed(self.iter, tid))
            else:
                gh_T = pack_gh(g_p, h_p, w_p, self.fused_nch)
            fm_pad = jnp.zeros((self.fused_f_oh,), bool) \
                .at[:fm.shape[0]].set(fm)
            smask = self._screen_mask_for_iter()
            if smask is not None:
                fm_pad = fm_pad & smask
            tree, row_leaf = grow_tree_fused(
                self.fused_bins_T, gh_T, self.fused_meta, fm_pad,
                self.params, self.max_leaves, self.fused_Bp,
                self.fused_f_oh, num_rows=n, nch=self.fused_nch,
                max_depth=int(self.config.max_depth),
                extra_levels=int(self.config.tpu_extra_levels),
                has_cat=self.has_cat,
                use_mono_bounds=self.use_mono_bounds,
                use_node_masks=self.use_node_masks,
                node_masks=self._node_masks_padded(),
                bundle_cols=self.fused_bundle_cols,
                bundle_col_bins=self.fused_bundle_col_bins,
                bundle_cfg=self.fused_bundle_cfg,
                interpret=self.fused_interpret,
                mono_mode=getattr(self, "mono_mode", "basic"),
                quant_bits=self.quant_bits, packed=self.fused_packed,
                mask_onehot=self._mask_onehot(), gh_scales=scales)
            self._note_tree_gains(tree)
            return tree, row_leaf[:n]
        if self.use_frontier:
            from ..models.frontier import grow_tree_frontier
            Fp = self.frontier_Fp
            fm_pad = jnp.zeros((Fp,), bool).at[:fm.shape[0]].set(fm)
            return grow_tree_frontier(
                self.bins_i32_dev, self.bins_T_dev, gh,
                self.frontier_meta, fm_pad, self.params,
                self.max_leaves, self.frontier_Bp,
                int(self.config.max_depth), hist_impl="pallas")
        if self.grow_policy == "depthwise":
            ub = getattr(self, "use_bundles", False)
            lazy = getattr(self, "use_cegb_lazy", False)
            out = grow_tree_depthwise(
                self.bundle_bins_dev if ub else self.bins_dev, gh,
                self.meta, fm, self.params,
                self.max_leaves, self.max_bins,
                int(self.config.max_depth),
                hist_impl=self._xla_hist_impl(), has_cat=self.has_cat,
                use_mono_bounds=self.use_mono_bounds,
                use_node_masks=self.use_node_masks,
                node_masks=self._node_masks_for_iter(),
                use_cegb=self.use_cegb,
                cegb_coupled=(self.cegb_coupled if self.use_cegb else None),
                cegb_used=(jnp.asarray(self.cegb_used)
                           if self.use_cegb else None),
                use_bundles=ub,
                bundle_cfg=self.bundle_cfg if ub else None,
                bundle_col_bins=(self.bundle_col_bins if ub else 0),
                mono_mode=getattr(self, "mono_mode", "basic"),
                use_cegb_lazy=lazy,
                cegb_lazy=self.cegb_lazy if lazy else None,
                cegb_used_rf=self.cegb_used_rf if lazy else None)
            if lazy:
                tree, row_leaf, self.cegb_used_rf = out
                return tree, row_leaf
            return out
        n_forced = getattr(self, "n_forced", 0)
        ub = getattr(self, "use_bundles", False)
        return grow_tree_leafwise(
            self.bundle_bins_dev if ub else self.bins_dev, gh,
            self.meta, fm, self.params,
            self.max_leaves, self.max_bins, int(self.config.max_depth),
            hist_impl=self._xla_hist_impl(), has_cat=self.has_cat,
            use_mono_bounds=self.use_mono_bounds,
            use_node_masks=self.use_node_masks,
            node_masks=self._node_masks_for_iter(),
            n_forced=n_forced,
            forced_leaf=self.forced_leaf if n_forced else None,
            forced_feat=self.forced_feat if n_forced else None,
            forced_thr=self.forced_thr if n_forced else None,
            use_bundles=ub,
            bundle_cfg=self.bundle_cfg if ub else None,
            bundle_col_bins=(self.bundle_col_bins if ub else 0),
            mono_mode=getattr(self, "mono_mode", "basic"))

    def _node_masks_for_iter(self):
        """Per-tree bynode randomness: fold the boosting iteration into the
        sampling key so each tree draws fresh per-node feature subsets."""
        if self.node_masks is None:
            return None
        import jax.random as jrandom
        return self.node_masks._replace(
            key=jrandom.fold_in(self.node_masks.key, self.iter))

    def _node_masks_padded(self):
        """NodeMaskCfg padded to the fused engine's f_oh feature count,
        with the per-tree key fold."""
        if self.node_masks is None:
            return None
        from ..models.learner import NodeMaskCfg
        nm = self._node_masks_for_iter()
        F_oh = self.fused_f_oh
        F = nm.group_feat.shape[1]
        if F == F_oh:
            return nm
        gf = jnp.zeros((nm.group_feat.shape[0], F_oh), bool) \
            .at[:, :F].set(nm.group_feat)
        gwf = jnp.zeros((F_oh,), jnp.int32).at[:F].set(nm.groups_with_f)
        return NodeMaskCfg(gf, gwf, nm.bynode_k, nm.key)

    def _xla_hist_impl(self) -> str:
        impl = self.config.tpu_histogram_impl
        return "auto" if impl in ("auto", "pallas") else impl

    def _feature_mask(self):
        """Per-tree column sampling (ref: col_sampler.hpp:20)."""
        F = self.train_data.num_features
        frac = float(self.config.feature_fraction)
        mp = getattr(self, "mp", None) is not None
        if frac >= 1.0:
            # mp: host numpy — multi-process jit treats host operands as
            # replicated (every rank computes the identical mask)
            return np.ones(F, bool) if mp else jnp.ones((F,), bool)
        # reference-parity by-tree sampling: one persistent LCG stream,
        # Sample(valid_count, RoundInt(count*fraction)) per tree
        # (ref: col_sampler.hpp:33 GetCnt, :78 ResetByTree)
        k = max(ref_random.round_int(F * frac), min(1, F))
        chosen = self.feat_rng.sample(F, k)
        mask = np.zeros(F, bool)
        mask[chosen] = True
        return mask if mp else jnp.asarray(mask)

    # ---------------------------------------- histogram-plane cuts
    def _mask_onehot(self) -> bool:
        """Screened-out features' one-hot slabs are zeroed in the fused
        kernel (bundle columns interleave logical features, so EFB runs
        keep the full build and screen at the split scan only)."""
        return bool(self.use_screening) \
            and not getattr(self, "fused_bundle_cols", 0)

    def _screening_keep_k(self) -> int:
        F = self.train_data.num_features
        ratio = float(self.config.tpu_screening_keep_ratio)
        return max(1, min(F, int(round(F * ratio))))

    def _screening_explore(self, it: int) -> bool:
        """Exploration rounds keep the full feature set eligible so a
        feature useless early but decisive late re-enters the mask."""
        cfg = self.config
        if it < int(cfg.tpu_screening_warmup):
            return True
        p = int(cfg.tpu_screening_explore_period)
        return p > 0 and it % p == 0

    def _ensure_gain_ema(self):
        F_oh = self.fused_f_oh
        if self._gain_ema_dev is None \
                or self._gain_ema_dev.shape[0] != F_oh:
            self._gain_ema_dev = jnp.zeros((F_oh,), jnp.float32)
        return self._gain_ema_dev

    def _screen_mask_for_iter(self):
        """Sync driver's screening mask (device [F_oh] bool), cached per
        iteration so all k class trees share one mask like the fast
        paths do. None = screening off."""
        if not self.use_screening:
            return None
        cached = self._screen_mask_cache
        if cached is not None and cached[0] == self.iter:
            return cached[1]
        m = _screening_mask_fn(
            self._ensure_gain_ema(),
            jnp.asarray(self._screening_explore(self.iter)),
            self.train_data.num_features, self._screening_keep_k())
        self._screen_mask_cache = (self.iter, m)
        return m

    def _note_tree_gains(self, tree) -> None:
        """Sync driver: accumulate one tree's realized split gains; the
        EMA applies once per iteration (_finish_screen_iter) so the
        update order matches the fast paths' once-per-iteration form."""
        if not self.use_screening:
            return
        g = _tree_gain_vec(tree.split_feature, tree.split_gain,
                           self.fused_f_oh)
        acc = self._iter_gain_acc
        self._iter_gain_acc = g if acc is None else acc + g

    def _finish_screen_iter(self) -> None:
        if not self.use_screening or self._iter_gain_acc is None:
            return
        a = jnp.float32(float(self.config.tpu_screening_ema_alpha))
        self._gain_ema_dev = (a * self._ensure_gain_ema()
                              + (1.0 - a) * self._iter_gain_acc)
        self._iter_gain_acc = None
        self._screen_mask_cache = None

    def _quant_seed(self, it: int, tid: int = 0) -> np.uint32:
        """Stochastic-rounding dither seed: one stream per (iteration,
        class tree), shared by the sync driver / pipelined fast path /
        megastep so all drivers quantize on the same dither streams
        (identical reruns and checkpoint resumes are byte-identical;
        ACROSS drivers ulp-level score differences can still flip
        rounds at the dither threshold — docs/Performance.md
        'Histogram plane')."""
        return np.uint32((it * self.num_tree_per_iteration + tid)
                         & 0xFFFFFFFF)

    def _megastep_aux(self, chunk: int):
        """Per-chunk screening/quantization scan operands: the EMA
        carry, the per-iteration exploration flags, and the per-
        iteration dither seed base (xs)."""
        k = self.num_tree_per_iteration
        ema0 = self._ensure_gain_ema() if self.use_screening else None
        explore_B = None
        if self.use_screening:
            explore_B = jnp.asarray(
                [self._screening_explore(self.iter + b)
                 for b in range(chunk)])
        seeds_B = None
        if self.quant_bits:
            seeds_B = jnp.asarray(
                (np.arange(self.iter, self.iter + chunk,
                           dtype=np.int64) * k) & 0xFFFFFFFF,
                dtype=jnp.uint32)
        return ema0, explore_B, seeds_B

    def _hist_plane_stats(self) -> Dict[str, int]:
        """Deterministic byte model of the histogram plane under the
        CURRENT layout/quantization (ops/layout.hist_plane_bytes): what
        the bench records as hist_bytes_per_iter and the exporter
        scrapes as hist.bytes_per_level."""
        from ..models.frontier2 import level_caps
        from ..ops.fused_level import default_tile_rows, max_slot_cap
        from ..ops.layout import hist_plane_bytes
        kF = self.fused_bundle_cols or self.fused_f_oh
        kB = (self.fused_bundle_col_bins if self.fused_bundle_cols
              else self.fused_Bp)
        fb_padded = kF * kB
        fb = (self.fused_packed.fb if self.fused_packed is not None
              else fb_padded)
        nch = self.fused_nch
        caps = level_caps(self.max_leaves, int(self.config.max_depth),
                          int(self.config.tpu_extra_levels),
                          slot_cap=max_slot_cap(fb_padded, nch))
        sp_max = max([8] + [max(8, c) for c in caps])
        tile = min(self.fused_Rp,
                   default_tile_rows(sp_max, fb_padded, nch,
                                     wide_bins=kB > 256))
        per_level = hist_plane_bytes(fb, nch, sp_max, self.fused_Rp,
                                     tile, self.quant_bits)
        n_levels = len(caps) + 1   # + the root pass
        return {"bytes_per_level": per_level,
                "bytes_per_iter": per_level * n_levels
                * self.num_tree_per_iteration,
                "fb": fb, "fb_padded": fb_padded, "levels": n_levels}

    def _publish_hist_gauges(self) -> None:
        if not self.use_fused:
            return
        try:
            self._hist_stats = st = self._hist_plane_stats()
        except Exception as e:   # a gauge must never kill training
            log.debug("hist plane stats failed: %s", e)
            return
        tel = self.telemetry
        tel.gauge("hist.bytes_per_level", float(st["bytes_per_level"]))
        tel.gauge("hist.bytes_per_iter", float(st["bytes_per_iter"]))
        tel.gauge("hist.quant_bits", float(self.quant_bits))
        tel.gauge("hist.fb", float(st["fb"]))
        tel.gauge("hist.fb_padded", float(st["fb_padded"]))

    # ------------------------------------------------------------------
    def _to_host_tree(self, tree: TreeArrays, shrinkage: float) -> Tuple[
            HostTree, np.ndarray, np.ndarray]:
        """Device TreeArrays -> HostTree with real thresholds.

        Returns (host_tree, inner_split_feature, row_leaf placeholder unused).
        """
        ds = self.train_data
        # single host round trip for the whole tree struct (per-field
        # np.asarray costs one D2H transfer each)
        tree = jax.device_get(tree)
        nl = int(tree.num_leaves)
        ht = HostTree(nl, shrinkage=1.0)
        ni = max(0, nl - 1)
        sf_inner = np.asarray(tree.split_feature)[:ni]
        tb = np.asarray(tree.threshold_bin)[:ni]
        dl = np.asarray(tree.default_left)[:ni]
        ht.split_feature = np.array(
            [ds.real_feature_index(int(f)) if f >= 0 else 0
             for f in sf_inner], np.int32)
        cat_flag = np.asarray(tree.cat_flag)[:ni]
        cat_mask = np.asarray(tree.cat_mask)[:ni]
        thr = np.zeros(ni, np.float64)
        dt = np.zeros(ni, np.int32)
        cat_boundaries = [0]
        cat_threshold: List[int] = []
        for i in range(ni):
            f = int(sf_inner[i])
            if f < 0:
                continue
            m = ds.mappers[ds.real_feature_index(f)]
            if bool(cat_flag[i]):
                # bin-space left set -> category-value bitset
                # (ref: tree.cpp Tree::SplitCategorical cat_boundaries_)
                cats = [int(m.bin_2_categorical[b])
                        for b in np.nonzero(cat_mask[i])[0]
                        if b < len(m.bin_2_categorical)
                        and m.bin_2_categorical[b] >= 0]
                n_words = (max(cats) // 32 + 1) if cats else 1
                words = [0] * n_words
                for c in cats:
                    words[c // 32] |= (1 << (c % 32))
                thr[i] = len(cat_boundaries) - 1  # index into boundaries
                cat_threshold.extend(words)
                cat_boundaries.append(len(cat_threshold))
                dt[i] = HostTree.make_decision_type(
                    True, False, int(m.missing_type))
            else:
                thr[i] = m.bin_to_value(int(tb[i]))
                dt[i] = HostTree.make_decision_type(
                    False, bool(dl[i]), int(m.missing_type))
        if len(cat_boundaries) > 1:
            ht.cat_boundaries = cat_boundaries
            ht.cat_threshold = cat_threshold
        ht.threshold = thr
        ht.threshold_bin = tb.astype(np.int32)
        ht.decision_type = dt
        ht.left_child = np.asarray(tree.left_child)[:ni].astype(np.int32)
        ht.right_child = np.asarray(tree.right_child)[:ni].astype(np.int32)
        ht.split_gain = np.asarray(tree.split_gain)[:ni].astype(np.float64)
        ht.internal_value = np.asarray(
            tree.internal_value)[:ni].astype(np.float64)
        ht.internal_weight = np.asarray(
            tree.internal_weight)[:ni].astype(np.float64)
        ht.internal_count = np.asarray(
            tree.internal_count)[:ni].astype(np.int64)
        ht.leaf_value = np.asarray(tree.leaf_value)[:nl].astype(np.float64)
        ht.leaf_weight = np.asarray(tree.leaf_weight)[:nl].astype(np.float64)
        ht.leaf_count = np.asarray(tree.leaf_count)[:nl].astype(np.int64)
        ht.leaf_depth = np.asarray(tree.leaf_depth)[:nl].astype(np.int32)
        self._last_cat = (cat_flag, cat_mask) if self.has_cat else None
        return ht, sf_inner

    # ------------------------------------------------------------------
    def _renew_tree_output(self, ht: HostTree, row_leaf: np.ndarray,
                           class_id: int) -> None:
        """Leaf renewal for L1-family objectives (ref:
        serial_tree_learner.cpp:717 RenewTreeOutput; in-bag rows only)."""
        obj = self.objective
        if obj is None or not obj.is_renew_tree_output:
            return
        label = self.train_data.metadata.label
        score = np.asarray(self.scores[class_id], np.float64)
        in_bag = np.asarray(self.bag_weight) > 0
        residual = label.astype(np.float64) - score
        # one argsort groups rows by leaf — O(n log n) instead of the
        # O(num_leaves * n) per-leaf scans of round 1 (VERDICT weak #7)
        sel = np.nonzero(in_bag)[0]
        order = sel[np.argsort(row_leaf[sel], kind="stable")]
        leaves_sorted = row_leaf[order]
        starts = np.searchsorted(leaves_sorted,
                                 np.arange(ht.num_leaves + 1))
        for leaf in range(ht.num_leaves):
            rows = order[starts[leaf]:starts[leaf + 1]]
            if len(rows) == 0:
                continue
            new_out = obj.renew_tree_output(ht.leaf_value[leaf],
                                            residual[rows], rows)
            ht.leaf_value[leaf] = new_out

    def _mp_in_bag_local(self) -> np.ndarray:
        """[local_real] bool in-bag mask for THIS rank's rows."""
        mp = self.mp
        bwl = getattr(self, "_bag_weight_local", None)
        if bwl is not None:             # GOSS keeps a rank-local mask
            return bwl[:mp.local_real] > 0
        bw = getattr(self, "_bag_weight_host", None)
        if bw is not None:              # synced-stream bagging: global
            off = mp.process_index * mp.block
            return bw[off:off + mp.local_real] > 0
        return np.ones(mp.local_real, bool)

    def _mp_avg_leaf_renewal(self, ht: HostTree, rl: np.ndarray,
                             residual: np.ndarray, in_bag: np.ndarray
                             ) -> None:
        """Distributed leaf renewal = the AVERAGE of rank-local
        percentile outputs over the workers that have rows in the leaf —
        the reference's own distributed semantics (NOT an exact global
        percentile): serial_tree_learner.cpp:744-755 computes the local
        RenewTreeOutput then GlobalSum(outputs)/GlobalSum(nonzero).
        ``rl``/``residual``/``in_bag`` are rank-local [local_real]."""
        obj = self.objective
        mp = self.mp
        off = mp.process_index * mp.block   # global row base: the
        # objective's weight vector is the allgathered rank-blocked one
        L = ht.num_leaves
        outputs = np.zeros(L, np.float64)
        nonzero = np.zeros(L, np.int64)
        sel = np.nonzero(in_bag)[0]
        order = sel[np.argsort(rl[sel], kind="stable")]
        starts = np.searchsorted(rl[order], np.arange(L + 1))
        for leaf in range(L):
            rows = order[starts[leaf]:starts[leaf + 1]]
            if len(rows) == 0:
                continue
            outputs[leaf] = obj.renew_tree_output(
                ht.leaf_value[leaf], residual[rows], rows + off)
            nonzero[leaf] = 1
        from jax.experimental import multihost_utils
        allg = np.asarray(multihost_utils.process_allgather(
            np.concatenate([outputs, nonzero.astype(np.float64)])))
        allg = allg.reshape(mp.process_count, 2, L)
        tot_out = allg[:, 0, :].sum(axis=0)
        tot_nz = allg[:, 1, :].sum(axis=0)
        renewed = np.where(tot_nz > 0, tot_out / np.maximum(tot_nz, 1),
                           np.asarray(ht.leaf_value[:L], np.float64))
        ht.leaf_value[:L] = renewed

    def _renew_tree_output_mp(self, ht: HostTree, row_leaf, class_id: int
                              ) -> None:
        mp = self.mp
        rl = mp.local_block(row_leaf)[:mp.local_real]
        score = mp.local_block(self.scores, axis=1)[class_id,
                                                    :mp.local_real]
        label = np.asarray(self.train_data.metadata.label, np.float64)
        residual = label - np.asarray(score, np.float64)
        self._mp_avg_leaf_renewal(ht, rl, residual, self._mp_in_bag_local())

    # ------------------------------------------------------------------
    def _fit_linear_leaves(self, ht: HostTree, row_leaf: np.ndarray,
                           grad, hess) -> None:
        """Per-leaf weighted ridge on the raw path features (ref:
        linear_tree_learner.cpp CalculateLinear, Eq 3 of
        arXiv:1802.05640): coeff = -(X^T H X + lambda I)^-1 X^T g with an
        intercept column; the first tree keeps constants only. Rows with
        NaN in the leaf's features are excluded from the fit (they fall
        back to the constant leaf output at predict time)."""
        raw = self.train_data.raw_data
        if raw is None:
            log.warning("linear_tree needs retained raw data; keeping "
                        "constant leaves")
            return
        ht.is_linear = True
        L = ht.num_leaves
        ht.leaf_const = ht.leaf_value.astype(np.float64).copy()
        ht.leaf_features = [[] for _ in range(L)]
        ht.leaf_coeff = [[] for _ in range(L)]
        if len(self.models) < self.num_tree_per_iteration:
            return  # first tree: constants only (ref: is_first_tree)
        g = np.asarray(grad, np.float64)
        h = np.asarray(hess, np.float64)
        in_bag = np.asarray(self.bag_weight) > 0
        lam = float(self.config.linear_lambda)
        paths = ht.branch_features()
        is_cat = self.train_data.is_categorical   # per USED feature
        for leaf in range(L):
            # paths[] carry inner indices; filter on those BEFORE mapping
            # to the real column ids the raw matrix is indexed by
            inner_feats = [f for f in paths[leaf] if not is_cat[f]]
            feats = [self.train_data.real_feature_index(f)
                     for f in inner_feats]
            if not feats:
                continue
            rows = np.nonzero((row_leaf == leaf) & in_bag)[0]
            if len(rows) < len(feats) + 2:
                continue
            Xl = raw[np.ix_(rows, feats)].astype(np.float64)
            ok = ~np.isnan(Xl).any(axis=1)
            rows = rows[ok]
            if len(rows) < len(feats) + 2:
                continue
            Xl = np.concatenate([Xl[ok], np.ones((len(rows), 1))], axis=1)
            hw = h[rows]
            gw = g[rows]
            XtHX = (Xl * hw[:, None]).T @ Xl
            XtHX[np.diag_indices_from(XtHX)] += lam
            Xtg = Xl.T @ gw
            try:
                coef = -np.linalg.solve(XtHX, Xtg)
            except np.linalg.LinAlgError:
                continue
            if not np.isfinite(coef).all():
                continue
            ht.leaf_features[leaf] = [int(f) for f in feats]
            ht.leaf_coeff[leaf] = [float(c) for c in coef[:-1]]
            ht.leaf_const[leaf] = float(coef[-1])

    # ------------------------------------------------------------------
    def _add_tree_to_score(self, score, bins_dev, dt: _DeviceTree,
                           tree_id: int, scale: float = 1.0,
                           bundle=None):
        """``bundle`` must be self._replay_bundle when ``bins_dev`` holds
        EFB bundle columns (sparse-built datasets), None for logical
        bins."""
        if dt.num_leaves <= 1:
            return score.at[tree_id].add(float(dt.leaf_value[0]) * scale)
        steps = _round_up_pow2(dt.max_depth + 1)
        lv = dt.leaf_value * scale if scale != 1.0 else dt.leaf_value
        new_row = add_tree_score(
            score[tree_id], bins_dev, lv, dt.split_feature, dt.threshold_bin,
            dt.default_left, dt.left_child, dt.right_child,
            self.meta.num_bin, self.meta.missing_type, self.meta.default_bin,
            max_steps=steps, cat_flag=dt.cat_flag, cat_mask=dt.cat_mask,
            bundle=bundle)
        return score.at[tree_id].set(new_row)

    def _train_bundle(self):
        """Replay-decode args for the TRAIN bin matrix (None unless the
        dataset is sparse-built)."""
        return getattr(self, "_replay_bundle", None)

    def _train_bins_replay(self):
        """Bin matrix for score add/subtract replay (rollback, DART
        drop/normalize): the replicated copy single-process, the
        row-sharded global matrix under multi-process (per-row routing
        partitions cleanly over the mesh)."""
        if getattr(self, "mp", None) is not None:
            self._place_par_data()
            if self.bins_par is None:
                # bundled mp runs place only the bundle matrix; replay
                # decodes logical bins, so place those on first use
                self.bins_par = self.mp.shard_local(
                    np.asarray(self.train_data.bins))
            return self.bins_par
        return self.bins_dev

    def _valid_bundle(self, vi: int):
        return (self._replay_bundle
                if self.valid_data[vi].prebundled is not None else None)

    # ------------------------------------------------------------------
    # Async pipelined fast path.
    #
    # Through a remote-attached TPU every host synchronisation costs
    # ~25 us-80 ms of round-trip latency; the reference's per-tree host
    # bookkeeping (gbdt.cpp:371 TrainOneIter is all host code) translated
    # naively into 2-3 blocking syncs per tree (int(num_leaves),
    # device_get(tree), score-update data dependency) — ~0.3 s/tree of pure
    # latency at 255 leaves. Instead: ONE jit-compiled step per iteration
    # (gradients -> gh pack -> tree growth -> on-device score update) with
    # NO host read-back; the device TreeArrays are queued and materialised
    # as HostTrees in batches ("drained") only when something actually
    # needs the host model list. Device->host copies are started
    # asynchronously at enqueue time so drains mostly find the data ready.
    _FAST_SYNC_EVERY = 32

    def _fast_path_ok(self) -> bool:
        """Per-tree host work forces the synchronous path: subclass drivers
        (DART drop-sets, GOSS resampling, RF), leaf renewal, linear leaves,
        CEGB feature accounting, forced splits, and per-node mask key
        folding. Valid sets stay on the fast path since round 3: their
        score updates run in-jit from the device TreeArrays
        (_update_valid_from_trees) and eval pulls scalars, not matrices."""
        if self.telemetry.enabled \
                and self._tel_granularity() == "section":
            # per-SECTION attribution blocks on each phase — only the
            # synchronous driver can do that honestly (same reason the
            # reference's TIMETAG is sync). batch/iteration granularity
            # attribute at coarser sync points and keep the fast path
            # (docs/Performance.md). Checked outside the cache so a
            # callback can enable telemetry mid-training.
            self._report_eviction("config:telemetry_granularity=section")
            return False
        if self._fast_ok_cache is None:
            obj = self.objective
            # the row-sharded distribution modes (data, voting) ride the
            # fast path on the FUSED engine since round 12: the
            # shard_map growers compose with the pipelined step and the
            # megastep scan, and multi-process runs (one global mesh
            # over the pod) keep the same trace — the histogram psum /
            # vote exchange already lives inside the jit, so no
            # per-iteration host collective remains (tpu_mp_megastep=
            # false restores the pre-round-12 sync eviction for A/B).
            # feature-parallel stays on the sync driver: its contract is
            # bit-equality with the serial model (replicated rows), and
            # the fast path's f32 leaf-value shrink would break it.
            self._fast_ok_cache = bool(
                type(self) is GBDT
                and bool(self.config.tpu_fast_path)
                and self.use_fused
                and self.parallel_mode in ("serial", "data", "voting")
                and (getattr(self, "mp", None) is None
                     or bool(getattr(self.config, "tpu_mp_megastep",
                                     True)))
                and obj is not None
                and not obj.is_renew_tree_output
                and not bool(self.config.linear_tree)
                and not getattr(self, "use_cegb", False)
                and not getattr(self, "n_forced", 0)
                and not self.use_node_masks
                and all(self.class_need_train))
        if not self._fast_ok_cache and self.telemetry.enabled:
            self._report_eviction(self._fast_path_reason()
                                  or "fast_path:unknown")
        return self._fast_ok_cache

    def _fast_path_reason(self) -> Optional[str]:
        """The SPECIFIC feature evicting training off the pipelined fast
        path, or None when eligible — docs/Performance.md used to tell
        users to guess; the megastep_evicted event names it instead."""
        if self.telemetry.enabled \
                and self._tel_granularity() == "section":
            return "config:telemetry_granularity=section"
        if type(self) is not GBDT:
            return f"boosting:{self.name}"
        if not bool(self.config.tpu_fast_path):
            return "config:tpu_fast_path=false"
        if not self.use_fused:
            if getattr(self, "mp", None) is not None:
                # the XLA growers' sync driver is the only multi-process
                # path off the fused engine (the megastep composes with
                # the shard_map growers through grow_tree_fused only)
                return "engine:multiproc_xla_growers"
            return f"engine:{self.config.tpu_engine}"
        if getattr(self, "mp", None) is not None \
                and not bool(getattr(self.config, "tpu_mp_megastep", True)):
            return "config:tpu_mp_megastep=false"
        if self.parallel_mode not in ("serial", "data", "voting"):
            # feature-parallel: bit-equality with the serial model is its
            # contract (replicated rows) — the fast path's f32 leaf-value
            # shrink would break it, so it stays on the sync driver
            return f"tree_learner:{self.parallel_mode}"
        obj = self.objective
        if obj is None:
            return "fobj"
        if obj.is_renew_tree_output:
            return f"objective_leaf_renewal:{obj.name}"
        if bool(self.config.linear_tree):
            return "config:linear_tree"
        if getattr(self, "use_cegb", False):
            return "config:cegb"
        if getattr(self, "n_forced", 0):
            return "config:forcedsplits_filename"
        if self.use_node_masks:
            return "config:interaction_constraints/feature_fraction_bynode"
        if not all(self.class_need_train):
            return f"objective_class_skip:{obj.name}"
        return None

    def _report_eviction(self, feature: str, **attrs) -> None:
        """Structured `megastep_evicted` telemetry event naming the
        specific evicting feature (callback / feval / fobj / config
        key), emitted once per distinct reason per run."""
        if not self.telemetry.enabled or feature in self._evict_reported:
            return
        self._evict_reported.add(feature)
        self.telemetry.event("megastep_evicted", iteration=self.iter,
                             feature=feature, **attrs)

    def _fast_tree_depth_bound(self) -> int:
        """Static routing-step bound for trees grown by the fused engine:
        depth cannot exceed the number of scheduled level passes."""
        from ..models.frontier2 import level_caps
        from ..ops.fused_level import max_slot_cap
        if self.fused_bundle_cols:
            fb = self.fused_bundle_cols * self.fused_bundle_col_bins
        else:
            fb = self.fused_f_oh * self.fused_Bp
        caps = level_caps(self.max_leaves, int(self.config.max_depth),
                          int(self.config.tpu_extra_levels),
                          slot_cap=max_slot_cap(fb, self.fused_nch))
        return len(caps) + 1

    def _make_valid_apply(self, bundle):
        """Traced valid-score update for one iteration's stacked [k, ...]
        TreeArrays: the ONE body both the per-iteration fast path
        (_update_valid_from_trees jits it per valid set) and the megastep
        scan inline — shared so the two paths cannot drift apart."""
        k = self.num_tree_per_iteration
        shrink = jnp.float32(self.shrinkage_rate)
        steps = self._fast_tree_depth_bound()
        meta = self.meta
        has_cat = self.has_cat

        def apply_trees(vscore, vbins, trees):
            for tid in range(k):
                new_row = add_tree_score(
                    vscore[tid], vbins, trees.leaf_value[tid] * shrink,
                    trees.split_feature[tid], trees.threshold_bin[tid],
                    trees.default_left[tid], trees.left_child[tid],
                    trees.right_child[tid], meta.num_bin,
                    meta.missing_type, meta.default_bin,
                    max_steps=steps,
                    cat_flag=trees.cat_flag[tid] if has_cat else None,
                    cat_mask=trees.cat_mask[tid] if has_cat else None,
                    bundle=bundle)
                # dried class: zero contribution (matches the training
                # score handling)
                new_row = jnp.where(trees.num_leaves[tid] > 1, new_row,
                                    vscore[tid])
                vscore = vscore.at[tid].set(new_row)
            return vscore
        return apply_trees

    def _update_valid_from_trees(self, trees) -> None:
        """In-jit valid-score updates straight from the stacked device
        TreeArrays — no HostTree materialisation, no per-iteration sync
        (ref: gbdt.cpp:493 UpdateScore over valid ScoreUpdaters)."""
        if not self.valid_scores:
            return
        if not getattr(self, "_valid_upd_fns", None):
            self._valid_upd_fns = {}
        for vi in range(len(self.valid_scores)):
            bundled = self.valid_data[vi].prebundled is not None
            if bundled not in self._valid_upd_fns:
                # the old valid-score buffer is dead the moment the
                # update returns — donate it so XLA writes in place
                # instead of allocating a fresh [k, n_valid] f32 every
                # iteration
                self._valid_upd_fns[bundled] = jax.jit(
                    self._make_valid_apply(
                        self._valid_bundle(vi) if bundled else None),
                    donate_argnums=_donate(0))
            self.telemetry.inc("train.dispatches")
            self.valid_scores[vi] = self._valid_upd_fns[bundled](
                self.valid_scores[vi], self.valid_bins[vi], trees)

    def _make_fused_tree_loop(self):
        """Traced per-iteration tree-growing core: gh pack -> fused
        growth -> score delta for each of the k class trees, returning
        the updated scores and the stacked [k, ...] TreeArrays. The ONE
        body the per-iteration fast step and the megastep scan share, so
        the megastep stays bit-identical to the fast path by
        construction."""
        from ..models.frontier2 import grow_tree_fused, tree_score_delta
        from ..ops.fused_level import pack_gh, table_lookup
        k = self.num_tree_per_iteration
        n = self.num_data
        pad = self.fused_Rp - n
        shrink = jnp.float32(self.shrinkage_rate)
        max_depth = int(self.config.max_depth)
        extra = int(self.config.tpu_extra_levels)
        interp = self.fused_interpret

        # distributed modes on the fast path (data/voting — feature
        # keeps the sync driver, its contract is bit-equality with the
        # serial model): the grow + leaf-value lookup run inside a
        # shard_map region (rows sharded, per-level histogram psum /
        # vote exchange inside grow_tree_fused); the [L]-sized tree
        # comes out replicated, the per-row delta row-sharded. Under a
        # multi-process layout the SAME shard_map spans the global
        # ICI/DCN mesh — the collectives cross processes inside the
        # jit, so the megastep scan composes unchanged (ref:
        # data_parallel_tree_learner.cpp:185 reduces the FAST engine's
        # histograms — the flagship kernel stays in play on the pod)
        mode = self.parallel_mode
        par = mode in ("data", "voting")
        quant = self.quant_bits
        screening = self.use_screening
        mask_oh = self._mask_onehot()
        packed = self.fused_packed
        if quant:
            from ..ops.fused_level import pack_gh_quant
        if screening:
            alpha = jnp.float32(float(self.config.tpu_screening_ema_alpha))
            keep_k = self._screening_keep_k()
            F_real = self.train_data.num_features
        F_oh = self.fused_f_oh
        if par:
            from jax.sharding import PartitionSpec as P
            axis = self.axis_name
            top_k = int(self.config.top_k) if mode == "voting" else 0

            def grow_one(bins_T, gh_T, fm_pad, *qrest):
                tree, row_leaf = grow_tree_fused(
                    bins_T, gh_T, self.fused_meta, fm_pad,
                    self.params, self.max_leaves, self.fused_Bp,
                    self.fused_f_oh, num_rows=0, nch=self.fused_nch,
                    max_depth=max_depth, extra_levels=extra,
                    has_cat=self.has_cat,
                    use_mono_bounds=self.use_mono_bounds,
                    bundle_cols=self.fused_bundle_cols,
                    bundle_col_bins=self.fused_bundle_col_bins,
                    bundle_cfg=self.fused_bundle_cfg,
                    interpret=interp, psum_axis=axis,
                    mono_mode=getattr(self, "mono_mode", "basic"),
                    parallel_mode=mode, top_k=top_k,
                    quant_bits=quant, packed=packed,
                    mask_onehot=mask_oh,
                    gh_scales=qrest[0] if quant else None)
                delta = table_lookup(row_leaf[None, :],
                                     tree.leaf_value * shrink,
                                     interpret=interp)[0]
                return tree, delta
            grow_one_sharded = _shard_map(
                grow_one, mesh=self.mesh,
                in_specs=(P(None, axis), P(None, axis), P())
                + ((P(),) if quant else ()),
                out_specs=(P(), P(axis)), check_vma=False)

        def grow_k_trees(bins_T, scores, grad, hess, bag_weight, fm_pads,
                         ema=None, explore=None, seed=None):
            smask = None
            if screening:
                # EMA-FS screening (arxiv 2606.26337): one in-trace
                # top-k mask per iteration over the gain-EMA carry,
                # composed with the feature_fraction masks; exploration
                # rounds keep the mask fully open
                smask = _screening_mask_fn(ema, explore, F_real, keep_k)
            trees = []
            for tid in range(k):
                fm_t = fm_pads[tid] & smask if screening \
                    else fm_pads[tid]
                g_p = jnp.pad(grad[tid] * bag_weight, (0, pad))
                h_p = jnp.pad(hess[tid] * bag_weight, (0, pad))
                w_p = jnp.pad(bag_weight, (0, pad))
                scales = None
                if quant:
                    gh_T, scales = pack_gh_quant(
                        g_p, h_p, w_p, quant,
                        seed + jnp.uint32(tid))
                else:
                    gh_T = pack_gh(g_p, h_p, w_p, self.fused_nch)
                if par:
                    args = (bins_T, gh_T, fm_t) \
                        + ((scales,) if quant else ())
                    tree, delta = grow_one_sharded(*args)
                    # a dried-up class (no split found) contributes
                    # NOTHING: the sync path appends a zero constant tree
                    # for it (gbdt.cpp:421-437 beyond the first
                    # iteration) and keeps boosting the other classes
                    delta = jnp.where(tree.num_leaves > 1, delta[:n], 0.0)
                else:
                    tree, row_leaf = grow_tree_fused(
                        bins_T, gh_T, self.fused_meta, fm_t,
                        self.params, self.max_leaves, self.fused_Bp,
                        self.fused_f_oh, num_rows=n, nch=self.fused_nch,
                        max_depth=max_depth, extra_levels=extra,
                        has_cat=self.has_cat,
                        use_mono_bounds=self.use_mono_bounds,
                        bundle_cols=self.fused_bundle_cols,
                        bundle_col_bins=self.fused_bundle_col_bins,
                        bundle_cfg=self.fused_bundle_cfg,
                        interpret=interp,
                        mono_mode=getattr(self, "mono_mode", "basic"),
                        quant_bits=quant, packed=packed,
                        mask_onehot=mask_oh, gh_scales=scales)
                    delta = tree_score_delta(tree, row_leaf, shrink,
                                             num_rows=n, interpret=interp)
                scores = scores.at[tid].add(delta)
                trees.append(tree)
            stacked = jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *trees)
            if screening:
                # once-per-iteration EMA update from the realized split
                # gains the trees materialize (same order as the sync
                # driver's _finish_screen_iter)
                gvec = _tree_gain_vec(stacked.split_feature,
                                      stacked.split_gain, F_oh)
                ema = alpha * ema + (1.0 - alpha) * gvec
            return scores, stacked, ema
        return grow_k_trees

    def _make_fast_step(self):
        obj = self.objective
        in_jit_grads = (obj is not None
                        and obj.supports_traced_gradients())
        grow_k = self._make_fused_tree_loop()

        # bins_T/gradient operands are ARGUMENTS, not closures: a
        # closed-over device array of O(rows) size would be embedded in
        # the lowered program as a constant (bins alone: 336 MB of HLO at
        # 10.5M rows) and stall remote compilation. Objectives exposing
        # the gradient_operands protocol compute gradients IN-jit (XLA
        # fuses them with the gh pack); others compute eagerly outside.
        # The score matrix is donated: the previous buffer dies at the
        # call, so XLA updates the [k, n] f32 in place instead of
        # round-tripping a fresh allocation through HBM each iteration.
        ext = bool(self.use_screening or self.quant_bits)
        if not ext:
            def step(bins_T, scores, grad_in, hess_in, bag_weight,
                     fm_pads):
                if in_jit_grads:
                    grad, hess = obj.gradients_from(scores, grad_in)
                else:
                    grad, hess = grad_in, hess_in
                scores, stacked, _ = grow_k(bins_T, scores, grad, hess,
                                            bag_weight, fm_pads)
                return scores, stacked
            return jax.jit(step, donate_argnums=_donate(1))

        def step_ext(bins_T, scores, grad_in, hess_in, bag_weight,
                     fm_pads, ema, explore, seed):
            if in_jit_grads:
                grad, hess = obj.gradients_from(scores, grad_in)
            else:
                grad, hess = grad_in, hess_in
            return grow_k(bins_T, scores, grad, hess, bag_weight,
                          fm_pads, ema, explore, seed)
        return jax.jit(step_ext, donate_argnums=_donate(1))

    # ------------------------------------------------------------------
    # Fused boosting epilogue (ops/fused_level.epilogue_pass): the final
    # route + score update + gradients + next ROOT histogram run as ONE
    # streaming kernel, removing two full level passes plus the lookup and
    # gradient streams from every iteration (the host loop being fused:
    # ref gbdt.cpp:371 TrainOneIter's UpdateScore -> GetGradients -> next
    # BeforeTrain). State carried on device between iterations:
    # (padded score row, next root histogram, next packed gh block).
    def _use_epilogue(self) -> bool:
        if self._epi_ok_cache is None:
            spec = (self.objective.epilogue_spec()
                    if self.objective is not None else None)
            # the histogram-plane cuts bypass the fused epilogue: its
            # kernel computes gradients/root histogram on the padded f32
            # layout, and screening's per-tree mask must reach the NEXT
            # tree's root build (docs/Performance.md eligibility matrix)
            self._epi_ok_cache = bool(
                spec is not None
                and bool(self.config.tpu_fused_epilogue)
                and self.num_tree_per_iteration == 1
                and self.parallel_mode == "serial"
                and not self.quant_bits
                and not self.use_adaptive_bins
                and not self.use_screening)
        return self._epi_ok_cache

    def _make_epi_fns(self):
        from ..models.frontier2 import grow_tree_fused
        from ..ops.fused_level import epilogue_pass, pack_gh
        kind, (op0, op1), sig = self.objective.epilogue_spec()
        n = self.num_data
        Rp = self.fused_Rp
        pad = Rp - n
        nch = self.fused_nch
        shrink = jnp.float32(self.shrinkage_rate)
        max_depth = int(self.config.max_depth)
        extra = int(self.config.tpu_extra_levels)
        interp = self.fused_interpret
        kF = self.fused_bundle_cols or self.fused_f_oh
        kB = (self.fused_bundle_col_bins if self.fused_bundle_cols
              else self.fused_Bp)
        # operand rows padded once; zero padding makes padded-row
        # gradients vanish under both closed forms
        self._epi_ops = jnp.zeros((8, Rp), jnp.float32) \
            .at[0, :n].set(op0).at[1, :n].set(op1)

        def in_jit_grads(score_pad, ops_T):
            # the objective's own traced closed form; padded rows carry
            # zero operands and so produce zero gradients under both
            # kinds (the Pallas kernel copy in _epilogue_kernel is the
            # only unavoidable duplicate of these formulas)
            g, h = self.objective.gradients_from(
                score_pad[None, :], (ops_T[0], ops_T[1]))
            return g[0], h[0]

        def grow(bins_T, gh_T, fm_pad, hist0):
            return grow_tree_fused(
                bins_T, gh_T, self.fused_meta, fm_pad, self.params,
                self.max_leaves, self.fused_Bp, self.fused_f_oh,
                num_rows=n, nch=nch, max_depth=max_depth,
                extra_levels=extra, has_cat=self.has_cat,
                use_mono_bounds=self.use_mono_bounds,
                bundle_cols=self.fused_bundle_cols,
                bundle_col_bins=self.fused_bundle_col_bins,
                bundle_cfg=self.fused_bundle_cfg, interpret=interp,
                root_hist=hist0, defer_final_route=True,
                mono_mode=getattr(self, "mono_mode", "basic"))

        def epilogue(bins_T, leafT, W_l, tbl_l, tree, score_pad, ops_T,
                     bag_next):
            lv = jnp.where(tree.num_leaves > 1,
                           tree.leaf_value * shrink, 0.0)
            hist0, score2, ghT = epilogue_pass(
                bins_T, leafT[None, :], W_l, tbl_l, lv,
                score_pad[None, :], ops_T, bag_next[None, :],
                num_bins=kB, f_oh=kF, nch=nch, kind=kind,
                sigmoid=float(sig), interpret=interp)
            return score2[0], hist0, ghT

        def prime(bins_T, score_pad, ops_T, bag_cur, bag_next, fm_pad):
            g, h = in_jit_grads(score_pad, ops_T)
            gh_T = pack_gh(g * bag_cur, h * bag_cur, bag_cur, nch)
            tree, leafT, W_l, tbl_l = grow(bins_T, gh_T, fm_pad, None)
            score2, hist0, ghT = epilogue(bins_T, leafT, W_l, tbl_l, tree,
                                          score_pad, ops_T, bag_next)
            return score2, hist0, ghT, tree

        def cont(bins_T, score_pad, hist0, gh_T, ops_T, bag_next, fm_pad):
            tree, leafT, W_l, tbl_l = grow(bins_T, gh_T, fm_pad, hist0)
            score2, hist0n, ghT_n = epilogue(bins_T, leafT, W_l, tbl_l,
                                             tree, score_pad, ops_T,
                                             bag_next)
            return score2, hist0n, ghT_n, tree
        # the (score, root-hist, packed-gh) carry buffers die at each
        # call — donate them so the iteration carry updates in place
        # (self.scores is a separate sliced buffer, never the donated
        # operand; _epi_ops persists across iterations and is NOT donated)
        return (jax.jit(prime, donate_argnums=_donate(1)),
                jax.jit(cont, donate_argnums=_donate(1, 2, 3)))

    def _epi_iter_body(self):
        n = self.num_data
        Rp = self.fused_Rp
        init_scores = [self._boost_from_average(0, True)]
        self._bagging(self.iter, None, None)   # live bookkeeping, iter t
        if self._epi_fns is None:
            self._epi_fns = self._make_epi_fns()
        prime, cont = self._epi_fns
        F_oh = self.fused_f_oh
        if float(self.config.feature_fraction) >= 1.0:
            # cached: per-iteration eager dispatches cost ~25us-80ms each
            # through a remote-attached chip
            if getattr(self, "_epi_fm_pad", None) is None:
                self._epi_fm_pad = jnp.ones((F_oh,), bool) \
                    .at[self.train_data.num_features:].set(False)
            fm_pad = self._epi_fm_pad
        else:
            fm_pad = jnp.zeros((F_oh,), bool) \
                .at[:self.train_data.num_features].set(self._feature_mask())
        if not self.is_bagging:
            if getattr(self, "_epi_bag_ones", None) is None:
                self._epi_bag_ones = jnp.zeros((Rp,), jnp.float32) \
                    .at[:n].set(1.0)
            bag_next = self._epi_bag_ones
        else:
            bag_next = jnp.pad(self._bag_weight_for_iter(self.iter + 1),
                               (0, Rp - n))
        self.telemetry.inc("train.dispatches")
        if self._epi_carry is None:
            score_pad = jnp.pad(self.scores[0], (0, Rp - n))
            bag_cur = jnp.pad(self.bag_weight, (0, Rp - n))
            out = prime(self.fused_bins_T, score_pad, self._epi_ops,
                        bag_cur, bag_next, fm_pad)
        else:
            score_pad, hist0, gh_T = self._epi_carry
            out = cont(self.fused_bins_T, score_pad, hist0, gh_T,
                       self._epi_ops, bag_next, fm_pad)
        score2, hist0n, ghT_n, tree = out
        self._epi_carry = (score2, hist0n, ghT_n)
        self.scores = score2[None, :n]
        trees = jax.tree_util.tree_map(lambda x: jnp.stack([x]), tree)
        return self._finish_fast_iter(trees, init_scores)

    def _train_one_iter_fast(self) -> bool:
        tel = self.telemetry
        # iteration granularity: the fast path stays (one jit dispatch),
        # but each iteration is synced and timed whole — no per-section
        # split, no eviction to the synchronous driver
        per_iter = tel.enabled and self._tel_granularity() == "iteration"
        it = self.iter
        if per_iter:
            w0 = tel.wall_now()
            t0 = time.perf_counter()
        with timer.section("GBDT::TrainOneIterFast"):
            if self._use_epilogue():
                stop = self._epi_iter_body()
            else:
                stop = self._fast_iter_body()
        if per_iter:
            jax.block_until_ready(self.scores)
            dt = time.perf_counter() - t0
            nl = []
            if self._pending:
                nl = [int(x) for x in
                      np.asarray(self._pending[-1][0].num_leaves)]
            tel.begin_iteration(it)
            tel.section("fast_iteration", dt, wall_start=w0)
            tel.end_iteration(it, num_leaves=nl, engine="fused",
                              mode=self.parallel_mode, pipelined=True)
        if stop is None:    # batch full: drain outside the fast section
            self.drain_pending()
            return self._stopped_early
        return stop

    def _fast_iter_body(self):
        k = self.num_tree_per_iteration
        init_scores = [self._boost_from_average(tid, True)
                       for tid in range(k)]
        operands = (self.objective.gradient_operands()
                    if self.objective is not None
                    and self.objective.supports_traced_gradients()
                    else None)
        if operands is not None:     # gradients traced into the step
            grad_in, hess_in = operands, None
            self._bagging(self.iter, None, None)
        else:
            grad_in, hess_in = self._get_gradients()
            grad_in, hess_in = self._bagging(self.iter, grad_in, hess_in)
        fresh_step = self._fast_step_fn is None
        if fresh_step:
            self._fast_step_fn = self._make_fast_step()
        F_oh = self.fused_f_oh
        if float(self.config.feature_fraction) >= 1.0:
            if getattr(self, "_fast_fm_pads", None) is None:
                self._fast_fm_pads = jnp.ones((k, F_oh), bool).at[
                    :, self.train_data.num_features:].set(False)
            fm_pads = self._fast_fm_pads
        else:
            fm_pads = jnp.stack([
                jnp.zeros((F_oh,), bool).at[:self.train_data.num_features]
                .set(self._feature_mask()) for _ in range(k)])
        self.telemetry.inc("train.dispatches")
        ext = bool(self.use_screening or self.quant_bits)
        t_call0 = time.perf_counter() if fresh_step else 0.0
        with self._maybe_record_collectives(fresh_step) as rec, \
                jax.profiler.StepTraceAnnotation("fast_step",
                                                 step_num=self.iter):
            # the kind-named anchor span the roofline plane
            # (obs/kernelstats.py) attributes fast-step kernels to
            if ext:
                ema = (self._ensure_gain_ema() if self.use_screening
                       else None)
                explore = (jnp.asarray(self._screening_explore(self.iter))
                           if self.use_screening else None)
                seed = (jnp.uint32(self._quant_seed(self.iter))
                        if self.quant_bits else None)
                call_args = (self.fused_bins_T, self.scores, grad_in,
                             hess_in, self.bag_weight, fm_pads, ema,
                             explore, seed)
                self.scores, trees, ema2 = self._fast_step_fn(*call_args)
                if self.use_screening:
                    self._gain_ema_dev = ema2
            else:
                call_args = (self.fused_bins_T, self.scores, grad_in,
                             hess_in, self.bag_weight, fm_pads)
                self.scores, trees = self._fast_step_fn(*call_args)
        if rec is not None:
            self._coll_per_iter = rec.profile
        if fresh_step and self.telemetry.enabled:
            # fast-step compile accounting, same contract as the
            # megastep's: the first call traces + compiles before the
            # async dispatch returns, so its wall is the compile cost;
            # the cost-ledger note defers fn.lower() to the next drain
            op_bytes = sum(int(getattr(a, "nbytes", 0))
                           for a in call_args if a is not None)
            sig = f"fast_step[k={k},ext={ext}]"
            self.telemetry.compile_executable(
                sig, (time.perf_counter() - t_call0) * 1000.0, op_bytes,
                iteration=self.iter)
            if self._cost is not None:
                self._cost.note(self._fast_step_fn, call_args, sig,
                                kind="fast_step", scale=1,
                                operand_bytes=op_bytes,
                                iteration=self.iter)
        return self._finish_fast_iter(trees, init_scores)

    def _finish_fast_iter(self, trees, init_scores):
        """Pipelining tail shared by the fast and epilogue iteration
        bodies: async host copies, in-jit valid updates, pending append,
        batch-drain signalling."""
        for leaf in jax.tree_util.tree_leaves(trees):
            if hasattr(leaf, "copy_to_host_async"):
                leaf.copy_to_host_async()
        self._update_valid_from_trees(trees)
        if not self._pending:
            self._batch_w0 = self.telemetry.wall_now()
            self._batch_t0 = time.perf_counter()
        self._pending.append((trees, [init_scores], 1, None))
        self._pending_iters += 1
        self.iter += 1
        if self._pending_iters >= self._FAST_SYNC_EVERY:
            return None     # signal the wrapper to drain
        return False

    def drain_pending(self) -> None:
        """Materialise queued device trees as HostTrees (ref bookkeeping of
        gbdt.cpp:393-445, deferred). Detects the no-more-splits stop
        condition after the fact: the stopping iteration contributed
        nothing to the scores (dried deltas are zeroed in-jit), and later
        iterations' contributions are subtracted back out of the live
        scores (bin-space routing is training-identical, so each
        subtraction reverses the training add up to f32 rounding)."""
        if not self._pending:
            return
        with timer.section("GBDT::DrainPending"):
            self._drain_body()

    def _drain_body(self) -> None:
        pend, self._pending = self._pending, []
        self._pending_iters = 0
        k = self.num_tree_per_iteration
        self.telemetry.inc("train.drains")
        # one batched fetch for trees, metric rows AND the early-stop
        # latch — the drain is the single host sync point per chunk; a
        # second device_get would be a second blocking round trip
        es_state = (None if (self._eval_consumer is None
                             or self._es_carry is None)
                    else (self._es_carry[2], self._es_carry[3]))
        trees_host, metrics_host, es_host = jax.device_get(
            ([t for t, _, _, _ in pend],
             [m for _, _, _, m in pend if m is not None],
             es_state))
        # flatten megastep entries ([B, k, ...] stacked trees covering B
        # iterations) and per-iteration entries ([k, ...], batch == 1)
        # into one per-iteration sequence of host TreeArrays fields,
        # with the per-iteration [n_slots] metric row alongside (None
        # where the entry carried no on-device eval)
        flat: List[Tuple] = []
        flat_metrics: List = []
        mi = 0
        for (_, init_list, batch, mB), trees_h in zip(pend, trees_host):
            arrays = [np.asarray(a) for a in trees_h]
            if batch == 1 and mB is None:
                # pipelined fast-path entry: [k, ...], no batch axis.
                # A length-1 megastep entry (mB is not None — consumer
                # horizon/bagging tails run chunk-1 scans) still carries
                # the leading [B=1, ...] axis and must unstack below.
                flat.append((arrays, init_list[0]))
            else:
                for b in range(batch):
                    flat.append(([a[b] for a in arrays], init_list[b]))
            if mB is None:
                flat_metrics.extend([None] * batch)
            else:
                rows = np.asarray(metrics_host[mi])
                mi += 1
                flat_metrics.extend(rows[b] for b in range(batch))
        base_iter = self.iter - len(flat)
        # scan-native early stop: the device latch decides the
        # bookkeeping below — iterations past the latch were frozen
        # in-jit (their score deltas masked to zero), so they must be
        # neither appended to the model nor score-subtracted
        es_cut = None
        if es_host is not None and bool(es_host[0]):
            es_cut = int(es_host[1]) - base_iter
        gain_acc: List[np.ndarray] = []
        stop_i = None
        converted = []   # per drained iteration: [(ht, dt, grew)] * k
        for i, (trees_h, init_scores) in enumerate(flat):
            iter_models = []
            dried_first = []   # tids of first-k constant trees
            any_grew = False
            for tid in range(k):
                ta = TreeArrays(*[np.asarray(a)[tid] for a in trees_h])
                if int(ta.num_leaves) <= 1:
                    # dried-up class (the fast step zeroed its delta
                    # in-jit): zero constant tree — except within the
                    # first k models, where the reference stores the init
                    # score in it and adds it to the scorer on top of
                    # BoostFromAverage's update (gbdt.cpp:421-437);
                    # applied after the loop once the iteration is known
                    # to be kept
                    ht = HostTree(1)
                    if stop_i is None \
                            and len(self.models) + len(iter_models) < k:
                        dried_first.append(tid)
                    iter_models.append((ht, None, False))
                    continue
                any_grew = True
                ht, sf_inner = self._to_host_tree(ta, self.shrinkage_rate)
                # numerical guards stay live on the fast path: the host
                # tree is already materialised here, so the non-finite
                # checks cost numpy only (no extra device sync)
                self._guard_tree(base_iter + i, tid, ht, gain_acc)
                ht.apply_shrinkage(self.shrinkage_rate)
                cf, cm = self._last_cat or (None, None)
                dt = _DeviceTree(ht, sf_inner, cat_flag=cf, cat_mask=cm)
                if abs(init_scores[tid]) > K_EPSILON:
                    ht.add_bias(init_scores[tid])
                    dt.leaf_value = jnp.asarray(ht.leaf_value, jnp.float32)
                iter_models.append((ht, dt, True))
            converted.append(iter_models)
            if stop_i is not None:
                continue
            if es_cut is not None and i > es_cut:
                # scan-frozen early-stop tail: score deltas were masked
                # to zero in-jit past the latch, so these trees are
                # neither appended nor subtracted — the drained model
                # ends at the latch iteration bit-identically to the
                # synchronous driver's early-stopped model
                continue
            if not any_grew:
                stop_i = i
                continue
            for tid in dried_first:
                ht = iter_models[tid][0]
                ht.leaf_value[0] = init_scores[tid]
                self.scores = self.scores.at[tid].add(
                    float(init_scores[tid]))
                for vi in range(len(self.valid_scores)):
                    self.valid_scores[vi] = self.valid_scores[vi] \
                        .at[tid].add(float(init_scores[tid]))
            for ht, dt, _ in iter_models:
                if dt is None:
                    dt = _DeviceTree(ht, np.zeros(0, np.int32))
                self.models.append(ht)
                self.device_trees.append(dt)
        if stop_i is not None:
            # the stopping iteration contributed nothing to the scores
            # (every class's delta was zeroed in-jit); iterations after it
            # must be discarded — subtract their contributions from the
            # live scores (bin-space routing is training-identical, so
            # each subtraction reverses the training add up to f32
            # rounding)
            self._epi_carry = None
            scores = self.scores
            # replay bins: the replicated copy single-process, the
            # row-sharded global matrix under multi-process (the
            # rank-local bins_dev cannot route the [k, Np] score carry)
            replay_bins = self._train_bins_replay()
            for conv_i in range(stop_i + 1, len(converted)):
                if es_cut is not None and conv_i > es_cut:
                    continue   # frozen tail: contributed nothing
                iter_models = converted[conv_i]
                for tid, (_, dt, grew) in enumerate(iter_models):
                    if grew:
                        scores = self._add_tree_to_score(
                            scores, replay_bins, dt, tid, scale=-1.0,
                            bundle=self._train_bundle())
                        for vi in range(len(self.valid_scores)):
                            self.valid_scores[vi] = \
                                self._add_tree_to_score(
                                    self.valid_scores[vi],
                                    self.valid_bins[vi], dt, tid,
                                    scale=-1.0,
                                    bundle=self._valid_bundle(vi))
            if not self.models:
                # first-ever iteration stopped outright: the reference
                # keeps one constant tree per class carrying the init
                # score, updating the scorer a second time on top of
                # BoostFromAverage (gbdt.cpp:377,433 — 2x init total;
                # matched bug-for-bug by the synchronous path)
                init_scores = flat[stop_i][1]
                for tid in range(k):
                    ht = HostTree(1)
                    ht.leaf_value[0] = init_scores[tid]
                    scores = scores.at[tid].add(float(init_scores[tid]))
                    for vi in range(len(self.valid_scores)):
                        # the sync path's constant-tree branch updates the
                        # valid scorers too (gbdt.cpp:422-441)
                        self.valid_scores[vi] = self.valid_scores[vi] \
                            .at[tid].add(float(init_scores[tid]))
                    self.models.append(ht)
                    self.device_trees.append(
                        _DeviceTree(ht, np.zeros(0, np.int32)))
            self.scores = scores
            self.iter = base_iter + stop_i
            self._stopped_early = True
            log.warning("Stopped training because there are no more "
                        "leaves that meet the split requirements")
            # structured stop record (the sync path emits the same event
            # inline). `discarded` lets iteration-granularity consumers
            # reconcile: iteration records numbered >= this event's
            # `iter` were rolled back and produced no trees
            self.telemetry.event("stopped_no_splits", iteration=self.iter,
                                 discarded=len(flat) - stop_i)
        self._replay_drained_eval(flat_metrics, base_iter, len(flat),
                                  stop_i, es_cut)
        tel = self.telemetry
        if tel.enabled and flat and self.parallel_mode != "serial":
            # measured in-trace collective traffic of the drained batch:
            # per-iteration (count, bytes) recorded from the scan's /
            # fast step's STATIC traced shapes at compile time
            # (ops/collectives.py) — the traced program runs its full
            # static level schedule for every iteration, frozen or not,
            # so the batch payload is per-iteration x iterations
            meas = getattr(self, "_coll_per_iter", None)
            if meas is not None:
                tel.collective("psum_" + self.parallel_mode,
                               meas[0] * len(flat), meas[1] * len(flat))
        if tel.enabled and flat and self._health is not None \
                and self._health_at_drain():
            # drain-boundary health audit (multi-chip megastep): the
            # model list just settled and every rank drains at the same
            # iteration (SPMD), so the hash allgather pairs here with
            # zero extra device dispatches. One audit per drain window
            # that crossed a period boundary.
            # exceptions propagate: a one-sided bail would desync every
            # later host collective on the mesh (same contract as the
            # sync driver's multi-process handler re-raising)
            period = self._health.period
            if period > 0 and any((base_iter + i + 1) % period == 0
                                  for i in range(len(flat))):
                self._health.check(self.iter - 1, self.models, {})
        if tel.enabled and flat and self._tel_granularity() == "batch":
            # batch-granularity record: one megastep/pipelined batch of
            # `len(flat)` iterations, wall time measured first-dispatch
            # -> drain-complete (the one honest sync point the fast path
            # has). `kept` < iterations means the no-more-splits stop
            # rewound the tail.
            secs = {"batch": (time.perf_counter() - self._batch_t0
                              if self._batch_t0 is not None else 0.0)}
            tel.megastep(base_iter, iterations=len(flat),
                         kept=self.iter - base_iter, sections=secs,
                         wall_start=self._batch_w0, engine="fused",
                         mode=self.parallel_mode,
                         fused_iterations=self._batch_fused,
                         stopped=self._stopped_early)
            if gain_acc:
                gains = np.concatenate(gain_acc)
                if gains.size:
                    tel.observe("batch.split_gain_mean",
                                float(gains.mean()))
        if tel.enabled and flat and self._mem_watermarks:
            # the drain is the fast path's one honest sync point — the
            # allocator's peak over the whole drained batch is settled
            # here, so this is where the HBM watermarks move
            from ..obs.jaxmon import memory_watermarks
            memory_watermarks(tel, where="drain")
        if tel.enabled and flat and self.use_screening \
                and self._gain_ema_dev is not None:
            # screening visibility: how many features the NEXT non-
            # exploration mask keeps (host mirror of _screening_mask_fn
            # over the just-settled EMA; the drain already synced)
            try:
                ema = np.asarray(self._gain_ema_dev)
                F = self.train_data.num_features
                keep_k = self._screening_keep_k()
                kth = np.sort(ema[:F])[F - keep_k]
                tel.gauge("screening.active_features",
                          float(np.sum(ema[:F] >= kth)))
            except Exception as e:   # a gauge must never kill training
                log.debug("screening gauge failed: %s", e)
        if tel.enabled and flat:
            self._publish_hist_gauges()
        if tel.enabled and flat and self._cost is not None:
            # cost-ledger join for the drained batch: the deferred
            # fn.lower() analyses run HERE (host-sync point), then one
            # record marries analytic flops/bytes-per-iter with the
            # batch's measured wall, the measured collective payload
            # and the hist.* analytic plane model
            meas = getattr(self, "_coll_per_iter", None)
            self._cost.ledger_record(
                base_iter, len(flat),
                wall_s=(time.perf_counter() - self._batch_t0
                        if self._batch_t0 is not None else None),
                hist_bytes_per_iter=(self._hist_stats or {}).get(
                    "bytes_per_iter"),
                coll_bytes_per_iter=(float(meas[1]) if meas is not None
                                     else None))
        self._batch_t0 = self._batch_w0 = None
        self._batch_fused = 0
        # drain boundaries are the fast path's natural consistency
        # points: the model list is settled, the score carries just
        # synced, the eval replay ran — checkpoint here captures full
        # training state without any extra device dispatch
        if flat and self._ckpt is not None:
            self.maybe_checkpoint()
        # ... and the on-demand profiling window (POST /profile) opens
        # and closes at exactly these boundaries on the megastep driver,
        # and the SLO watchdogs take their training-liveness heartbeat
        if flat:
            self._profile_ctl_step()
            self._slo_step()

    def _replay_drained_eval(self, flat_metrics, base_iter: int,
                             n_flat: int, stop_i: Optional[int],
                             es_cut: Optional[int]) -> None:
        """Drain-time consumer feed: replay the armed loop's callbacks
        in iteration order against the scan's per-iteration metric rows
        (callback.DrainEvalReplay), then reconcile the scan-native
        early-stop latch with the host replay's verdict. No score fetch
        and no re-predict happen here — only the [B, n_slots] scalars
        already pulled by the drain."""
        consumer = self._eval_consumer
        if consumer is None or n_flat == 0:
            return
        limit = n_flat
        if stop_i is not None:
            # the stopping (dried) iteration still gets its eval and
            # callbacks — the sync loop also evaluates after a finished
            # update; rows past it reflect score contributions the
            # drain just subtracted, so they must not replay
            limit = min(limit, stop_i + 1)
        if es_cut is not None:
            limit = min(limit, es_cut + 1)
        es_j = None
        n_replayed = 0
        for ii in range(limit):
            row = flat_metrics[ii]
            if row is None:
                log.warning("megastep drain: no metric row for iteration "
                            "%d; eval replay truncated", base_iter + ii)
                break
            n_replayed = ii + 1
            if consumer.replay(base_iter + ii, row):
                es_j = ii
                break
        tel = self.telemetry
        if tel.enabled and n_replayed:
            # per-batch eval record (docs/Observability.md §9): which
            # slots were evaluated on device, the last replayed row, and
            # whether a REAL early stop latched inside this batch. The
            # device latch is the discriminator: the callback's
            # final-iteration "did not meet early stopping" raise is
            # normal end-of-training control flow, not a stop.
            tel.event("eval_batch", iteration=base_iter,
                      iterations=n_replayed,
                      slots=[f"{ds}/{name}"
                             for ds, name, _ in consumer.slots],
                      last=[float(v)
                            for v in flat_metrics[n_replayed - 1]],
                      stopped=es_cut is not None)
        if es_cut is not None and stop_i is None:
            if es_j != es_cut:
                # should be unreachable: the device latch and the host
                # replay run the same comparisons on the same f32 values
                log.error("scan early-stop latch (iteration %d) "
                          "disagrees with the callback replay (%s); "
                          "model truncated at the device latch",
                          base_iter + es_cut,
                          "no stop" if es_j is None
                          else f"iteration {base_iter + es_j}")
            # nothing past the latch was appended (frozen tail), so the
            # early stop needs no score arithmetic — just the counter
            self.iter = base_iter + es_cut + 1
            self._es_finished = True
        elif es_j is not None:
            # host-side stop without a device latch: the final-iteration
            # "did not meet early stopping" check, or a stop on the
            # dried no-splits iteration — model and scores are already
            # consistent, only the stop signal needs latching
            self._es_finished = True
        if es_cut is not None and consumer.stop is not None:
            # emitted only on a rounds-based stop (the device latch);
            # the final-iteration EarlyStopException still records
            # best_iteration through consumer.stop but is a completed
            # run, not an early-stopped one
            tel.event("early_stopping", iteration=self.iter,
                      best_iteration=consumer.stop[0])

    # ------------------------------------------------------------------
    # Multi-iteration megastep: up to tpu_megastep_iters boosting
    # iterations chained inside ONE jit via lax.scan over the fused
    # tree-growing step — gradients (traced from the objective's
    # operands), tree growth, training-score and valid-score updates all
    # stay on device; the scan emits stacked TreeArrays [B, k, ...] that
    # drain_pending converts like any other pending batch. At ~25 us per
    # dispatch round trip through the chip tunnel (PROFILE.md), this is
    # the remaining host-side overhead after the round-2 kernel work:
    # the per-iteration fast path still pays >= 1 dispatch per iteration
    # plus per-valid-set updates; the megastep pays ~1 per B iterations.
    def arm_megastep(self, on: bool = True, eval_consumer=None) -> None:
        """Permission from a driver loop that (a) treats train_one_iter
        as 'advance training', not 'advance exactly one iteration', and
        (b) stops when it returns True. Only such loops (engine.train,
        the CLI train loop) may consume multi-iteration megasteps; the
        bare Booster.update contract stays one iteration per call.

        ``eval_consumer`` (callback.DrainEvalReplay) additionally opts
        the loop into ON-DEVICE evaluation: the scan computes every
        configured metric per iteration, and the drain replays the
        loop's callbacks against the stacked metric matrix
        (megastep_eval_precheck must have succeeded first)."""
        if not on and self._eval_consumer is not None:
            # replay any still-queued metric rows before unbinding the
            # consumer — a tail left pending here would drain later with
            # nobody to feed, silently dropping callback invocations.
            # Defensive catch: disarm runs in the engine's `finally`, so
            # a drain failure here must not mask an exception already
            # unwinding through the train loop.
            try:
                self.drain_pending()
            except Exception as e:
                log.warning("drain at consumer disarm failed: %s", e)
        had = self._eval_consumer is not None
        self._megastep_armed = bool(on)
        self._eval_consumer = eval_consumer if on else None
        if (self._eval_consumer is not None) != had:
            # the eval plan is baked into the scan trace; a consumer
            # change invalidates every cached megastep signature
            self._megastep_fns = {}
        if self._eval_consumer is not None:
            if self._traced_plan is None:
                log.fatal("arm_megastep(eval_consumer=...) requires a "
                          "successful megastep_eval_precheck first")
            self._eval_consumer.bind(self._traced_plan.slots)
        else:
            self._traced_plan = None
            self._plan_ops = None
            self._es_spec = None
            self._es_carry = None
            # the drain-replay stop verdict lives on in the consumer
            # (engine.train applies best_iteration from it); the GBDT
            # itself must return to the trainable one-iteration-per-
            # update contract once disarmed, like the synchronous
            # early-stop path does
            self._es_finished = False

    def megastep_eval_precheck(self, include_training: bool,
                               es_spec=None) -> Tuple[bool, Optional[str]]:
        """Decide BEFORE the first iteration whether this run's metrics
        can evaluate on device inside the megastep with callbacks
        replayed at drain. Returns ``(True, None)`` and stores the
        traced plan, or ``(False, reason)`` naming the specific blocker
        (the caller should emit/log it and fall back to the classic
        per-iteration loop).

        ``es_spec`` is ``(stopping_rounds, first_metric_only)`` when an
        early-stopping callback is registered — the scan then carries
        best-metric/rounds-since-best state and freezes training past
        the stopping point so the drained model stays bit-identical to
        the synchronous driver's early-stopped model."""
        if not bool(getattr(self.config, "tpu_traced_eval", True)):
            return False, "config:tpu_traced_eval=false"
        if self._tel_gran != "batch":
            # a replayed record_telemetry can enable the registry
            # mid-run; a non-batch granularity would then evict training
            # with the consumer already committed — reject upfront
            return False, f"config:telemetry_granularity={self._tel_gran}"
        reason = self._fast_path_reason()
        if reason is not None:
            return False, reason
        reason = self._megastep_static_reason()
        if reason is not None:
            return False, reason
        reason = self._mp_valid_agreement_reason()
        if reason is not None:
            return False, reason
        from ..metric.traced import build_plan
        plan, err = build_plan(self, include_training)
        if plan is None:
            return False, err
        self._traced_plan = plan
        self._plan_ops = None
        self._es_spec = es_spec
        self._es_carry = None
        self._es_finished = False
        return True, None

    def _mp_valid_agreement_reason(self) -> Optional[str]:
        """Multi-process on-device eval requires IDENTICAL validation
        data on every rank: the traced metrics read each rank's LOCAL
        valid arrays inside the SPMD program, and divergent values would
        freeze the early-stop latch at different iterations per rank —
        silent model divergence with no collective to catch it. One
        host allgather of a per-rank digest at precheck (not per
        iteration) enforces the contract; None = agreed or not
        applicable. SPMD: every rank runs the same precheck, so the
        collective pairs."""
        if getattr(self, "mp", None) is None or not self.valid_data:
            return None
        import hashlib
        h = hashlib.sha256()
        for vd in self.valid_data:
            h.update(np.ascontiguousarray(
                np.asarray(vd.bins)).tobytes())
            md = vd.metadata
            for arr in ((md.label, md.weight, md.init_score)
                        if md is not None else ()):
                if arr is not None:
                    h.update(np.ascontiguousarray(
                        np.asarray(arr, np.float64)).tobytes())
        digest = np.frombuffer(h.digest(), np.uint8).copy()
        allg = np.asarray(self.mp._allgather(digest)) \
            .reshape(self.mp.process_count, -1)
        if not bool((allg == allg[0]).all()):
            return "engine:multiproc_divergent_valid_data"
        return None

    def _megastep_static_reason(self) -> Optional[str]:
        """Megastep blockers beyond fast-path eligibility that are fixed
        for the run (config keys, objective protocol, profiler window)."""
        obj = self.objective
        if not bool(getattr(self.config, "tpu_megastep", True)):
            return "config:tpu_megastep=false"
        # interpret-mode fused (off-TPU emulation) has no dispatch
        # latency to amortize — the scan would only add compile time —
        # so there the megastep is explicit opt-in (tests, micro bench);
        # on a real chip the default engages it
        if self.fused_interpret and not self.config.was_set("tpu_megastep"):
            return "interpret_mode_without_tpu_megastep_optin"
        if obj is None or not obj.supports_traced_gradients():
            return "objective_untraced_gradients:" + \
                (obj.name if obj is not None else "custom")
        if self.telemetry.enabled \
                and self._tel_granularity() == "iteration":
            return "config:telemetry_granularity=iteration"
        # a bounded/offset jax.profiler window opens and closes at
        # iteration edges _profiler_step only sees once per call —
        # fusing would shift the captured window by up to a chunk
        # (whole-run profiles, start 0 / no bound, are unaffected)
        if self._prof_dir and not self._prof_done \
                and (self._prof_start > 0 or self._prof_n >= 0):
            return "config:profile_start_iteration/profile_num_iterations"
        return None

    def _megastep_ok(self) -> bool:
        if not self._megastep_armed:
            return False
        if not self._fast_path_ok():   # reports its own eviction reason
            return False
        reason = self._megastep_static_reason()
        if reason is None and self._eval_consumer is None:
            # without a drain-replay consumer, per-iteration
            # observability needs per-iteration steps: GBDT-level early
            # stopping evaluates metrics after every iteration, and
            # snapshots fire on iteration numbers. A consumer handles
            # both at drain time.
            if self.early_stopping_round > 0:
                reason = "config:early_stopping_round"
            elif int(getattr(self.config, "snapshot_freq", -1) or -1) > 0:
                reason = "config:snapshot_freq"
        if reason is not None:
            self._report_eviction(reason, stage="megastep")
            return False
        return True

    def _megastep_chunk(self) -> int:
        """Iterations the next megastep may fuse: bounded by
        tpu_megastep_iters, the pipeline drain batch, the
        num_iterations horizon, and the current bagging round's window
        (the in-bag weight vector must be constant inside one jit —
        chunks never cross a re-bagging boundary, so the reference-
        parity LCG draws keep their exact firing order)."""
        if not self._megastep_ok():
            return 0
        chunk = min(int(self.config.tpu_megastep_iters),
                    self._FAST_SYNC_EVERY,
                    int(self.config.num_iterations) - self.iter)
        cfg = self.config
        if self.is_bagging and cfg.bagging_freq > 0:
            next_fire = ((self.iter // cfg.bagging_freq) + 1) \
                * cfg.bagging_freq
            chunk = min(chunk, next_fire - self.iter)
        return chunk

    def _train_one_megastep(self, chunk: int) -> bool:
        tel = self.telemetry
        t0 = time.perf_counter()
        with timer.section("GBDT::TrainMegastep"):
            self._megastep_body(chunk)
        # dispatch (host enqueue) cost of the fused chunk; the batch's
        # wall time is attributed by the drain's batch record
        tel.observe("megastep.dispatch", time.perf_counter() - t0)
        # batch-granularity attribution syncs once per megastep by
        # draining immediately (one sync amortized over `chunk`
        # iterations, which also emits the batch record); a drain-replay
        # consumer drains per chunk too — callbacks (logging, early
        # stopping) replay promptly and a scan-frozen early-stop tail
        # never spans more than one chunk. Without either, the drain
        # keeps its usual pipeline cadence.
        if tel.enabled or self._eval_consumer is not None \
                or self._pending_iters >= self._FAST_SYNC_EVERY:
            self.drain_pending()
        return self._stopped_early or self._es_finished

    def _megastep_body(self, chunk: int) -> None:
        k = self.num_tree_per_iteration
        init0 = [self._boost_from_average(tid, True) for tid in range(k)]
        operands = self.objective.gradient_operands()
        self._bagging(self.iter, None, None)   # chunk-aligned: a round
        # can fire only at the chunk's first iteration
        fn = self._megastep_fns.get(chunk)
        fresh_fn = fn is None
        if fresh_fn:
            fn = self._megastep_fns[chunk] = self._make_megastep(chunk)
        F_oh = self.fused_f_oh
        F = self.train_data.num_features
        if float(self.config.feature_fraction) >= 1.0:
            fm_pads = self._megastep_fm.get(chunk)
            if fm_pads is None:
                fm_pads = self._megastep_fm[chunk] = \
                    jnp.ones((chunk, k, F_oh), bool) \
                    .at[:, :, F:].set(False)
        else:
            # host LCG draws in exactly the per-iteration order
            # (iteration-major, then tree) so column sampling stays
            # reference-parity across the fused chunk
            masks = np.zeros((chunk, k, F_oh), bool)
            for b in range(chunk):
                for tid in range(k):
                    masks[b, tid, :F] = np.asarray(self._feature_mask())
            fm_pads = jnp.asarray(masks)
        self.telemetry.inc("train.dispatches")
        plan = self._traced_plan if self._eval_consumer is not None \
            else None
        metrics_B = None
        # profiler users see the fused chunk as one annotated step
        # (profile_dir / jax.profiler traces); free when no trace is on
        t_call0 = time.perf_counter() if fresh_fn else 0.0
        with jax.profiler.StepTraceAnnotation("megastep",
                                              step_num=self.iter), \
                self._maybe_record_collectives(fresh_fn) as coll_rec:
            ext = bool(self.use_screening or self.quant_bits)
            base_args = (self.fused_bins_T, self.scores,
                         tuple(self.valid_bins),
                         tuple(self.valid_scores),
                         operands, self.bag_weight, fm_pads)
            if plan is None:
                if ext:
                    ema0, explore_B, seeds_B = self._megastep_aux(chunk)
                    call_args = base_args + (ema0, explore_B, seeds_B)
                    scores, vscores, trees_B, ema2 = fn(*call_args)
                    if self.use_screening:
                        self._gain_ema_dev = ema2
                else:
                    call_args = base_args
                    scores, vscores, trees_B = fn(*call_args)
            else:
                if self._plan_ops is None:
                    self._plan_ops = plan.operands()
                if self._es_carry is None:
                    self._es_carry = self._init_es_carry(plan.n_slots)
                iters_B = jnp.arange(self.iter, self.iter + chunk,
                                     dtype=jnp.int32)
                if ext:
                    ema0, explore_B, seeds_B = self._megastep_aux(chunk)
                    call_args = base_args + (iters_B, self._plan_ops,
                                             self._es_carry, ema0,
                                             explore_B, seeds_B)
                    (scores, vscores, self._es_carry, trees_B,
                     metrics_B, ema2) = fn(*call_args)
                    if self.use_screening:
                        self._gain_ema_dev = ema2
                else:
                    call_args = base_args + (iters_B, self._plan_ops,
                                             self._es_carry)
                    (scores, vscores, self._es_carry, trees_B,
                     metrics_B) = fn(*call_args)
        if coll_rec is not None:
            # the scan traces its body ONCE regardless of chunk length,
            # so the recorded totals are the per-iteration schedule
            self._coll_per_iter = coll_rec.profile
        if fresh_fn and self.telemetry.enabled:
            # the first call of a new chunk signature traces + compiles
            # synchronously before the async dispatch returns, so its
            # wall time IS the compile cost; operand bytes estimated
            # from the arrays actually passed (the exporter's
            # recompile-rate / headroom record, obs/export.py)
            op_bytes = sum(
                int(getattr(a, "nbytes", 0)) for a in
                [self.fused_bins_T, self.scores, self.bag_weight,
                 fm_pads, *self.valid_bins, *self.valid_scores])
            sig = f"megastep[chunk={chunk},k={k},eval={plan is not None}]"
            self.telemetry.compile_executable(
                sig, (time.perf_counter() - t_call0) * 1000.0, op_bytes,
                iteration=self.iter)
            if self._cost is not None:
                # queue the fresh signature for the cost ledger: aval
                # capture only here (cheap, donation-safe); the
                # fn.lower() analysis runs at the next drain boundary,
                # off the dispatch path (obs/cost.py)
                self._cost.note(fn, call_args, sig, kind="megastep",
                                scale=chunk, operand_bytes=op_bytes,
                                iteration=self.iter)
        self.scores = scores
        self.valid_scores = list(vscores)
        # the fused-epilogue carry (score_pad, hist0, gh_T) captured
        # score state from before this chunk; a later epilogue iteration
        # must re-prime from the advanced scores, not resume stale state
        self._epi_carry = None
        for leaf in jax.tree_util.tree_leaves(trees_B):
            if hasattr(leaf, "copy_to_host_async"):
                leaf.copy_to_host_async()
        init_list = [init0] + [[0.0] * k for _ in range(chunk - 1)]
        if not self._pending:
            self._batch_w0 = self.telemetry.wall_now()
            self._batch_t0 = time.perf_counter()
        self._pending.append((trees_B, init_list, chunk, metrics_B))
        self._pending_iters += chunk
        self._batch_fused += chunk
        self.iter += chunk

    @staticmethod
    def _init_es_carry(n_slots: int):
        """Fresh scan-native early-stop carry: per-slot best (signed so
        higher is always better), per-slot best round (-1 = no eval
        seen yet, mirroring the callback's best_score_list[i] is None),
        plus the latched stop flag and the latch iteration."""
        return (jnp.full((n_slots,), -jnp.inf, jnp.float32),
                jnp.full((n_slots,), -1, jnp.int32),
                jnp.zeros((), bool),
                jnp.full((), -1, jnp.int32))

    def _make_megastep(self, chunk: int):
        obj = self.objective
        grow_k = self._make_fused_tree_loop()
        valid_appliers = [
            self._make_valid_apply(self._valid_bundle(vi)
                                   if self.valid_data[vi].prebundled
                                   is not None else None)
            for vi in range(len(self.valid_scores))]

        ext = bool(self.use_screening or self.quant_bits)

        def one_iteration(bins_T, scores, vbins, vscores, grad_ops,
                          bag_weight, fm_pads, ema=None, explore=None,
                          seed=None):
            """The SAME traced bodies as the per-iteration fast path —
            _make_fused_tree_loop for growth/score updates and
            _make_valid_apply per valid set — scanned, so the megastep
            is bit-identical to the pipelined path by construction."""
            grad, hess = obj.gradients_from(scores, grad_ops)
            scores, stacked, ema = grow_k(bins_T, scores, grad, hess,
                                          bag_weight, fm_pads, ema,
                                          explore, seed)
            vscores = tuple(
                apply_v(vscore, vb, stacked)
                for apply_v, vscore, vb in zip(valid_appliers, vscores,
                                               vbins))
            return scores, vscores, stacked, ema

        plan = self._traced_plan if self._eval_consumer is not None \
            else None
        if plan is None:
            if not ext:
                def step(bins_T, scores, vbins, vscores, grad_ops,
                         bag_weight, fm_pads_B):
                    def body(carry, fm_pads):
                        scores, vscores = carry
                        scores, vscores, stacked, _ = one_iteration(
                            bins_T, scores, vbins, vscores, grad_ops,
                            bag_weight, fm_pads)
                        return (scores, vscores), stacked
                    (scores, vscores), trees_B = jax.lax.scan(
                        body, (scores, vscores), fm_pads_B)
                    return scores, vscores, trees_B
                # donate the score carry and every valid-score buffer:
                # the scan rewrites them in place across the whole chunk
                return jax.jit(step, donate_argnums=_donate(1, 3))

            def step_ext(bins_T, scores, vbins, vscores, grad_ops,
                         bag_weight, fm_pads_B, ema0, explore_B,
                         seeds_B):
                # the gain EMA rides the scan CARRY (screening feedback
                # within the chunk); exploration flags and dither seeds
                # ride as xs alongside the feature masks
                def body(carry, xs):
                    scores, vscores, ema = carry
                    fm_pads, explore, seed = xs
                    scores, vscores, stacked, ema = one_iteration(
                        bins_T, scores, vbins, vscores, grad_ops,
                        bag_weight, fm_pads, ema, explore, seed)
                    return (scores, vscores, ema), stacked
                (scores, vscores, ema), trees_B = jax.lax.scan(
                    body, (scores, vscores, ema0),
                    (fm_pads_B, explore_B, seeds_B))
                return scores, vscores, trees_B, ema
            return jax.jit(step_ext, donate_argnums=_donate(1, 3))

        # ---- on-device eval variant: the scan additionally computes
        # every configured metric per iteration (traced reductions over
        # the score carries it already holds) and threads the early-stop
        # state; past the stopping point the carries freeze, so the
        # frozen tail's trees contribute NOTHING and the drain discards
        # them without any score arithmetic — the drained model is
        # bit-identical to the synchronous driver's early-stopped one.
        slots = plan.slots
        sign = jnp.asarray([1.0 if bigger else -1.0
                            for (_, _, bigger) in slots], jnp.float32)
        if self._es_spec is not None and slots:
            es_rounds, fmo = self._es_spec
            first_name = slots[0][1]
            # mirrors callback.early_stopping's stop check: training
            # slots never stop, first_metric_only tracks only the first
            # metric's slots (best-state still updates for every slot)
            mask_np = [ds != "training"
                       and (not fmo or name == first_name)
                       for (ds, name, _) in slots]
        else:
            es_rounds, mask_np = (1 << 30), [False] * len(slots)
        es_mask = jnp.asarray(np.asarray(mask_np, bool))
        es_rounds = jnp.int32(es_rounds)

        def es_update(es, mvals, it, active):
            best, bround, stopped, stop_it = es
            signed = mvals * sign
            # first-ever eval always records (bround < 0), like the
            # callback's best_score_list[i]-is-None branch; afterwards a
            # plain signed compare (min_delta != 0 is rejected at
            # precheck — f32-vs-f64 boundary rounding would break the
            # bit-identity contract)
            upd = active & ((bround < 0) | (signed > best))
            best = jnp.where(upd, signed, best)
            bround = jnp.where(upd, it, bround)
            trigger = active & jnp.any(es_mask
                                       & ((it - bround) >= es_rounds))
            stop_it = jnp.where(stopped | ~trigger, stop_it, it)
            return (best, bround, stopped | trigger, stop_it)

        if not ext:
            def step(bins_T, scores, vbins, vscores, grad_ops, bag_weight,
                     fm_pads_B, iters_B, metric_ops, es0):
                def body(carry, xs):
                    scores, vscores, es = carry
                    fm_pads, it = xs
                    active = ~es[2]
                    new_scores, new_vscores, stacked, _ = one_iteration(
                        bins_T, scores, vbins, vscores, grad_ops,
                        bag_weight, fm_pads)
                    # freeze past the stop latch: the tree still comes
                    # out of the scan (static shapes) but contributes
                    # nothing
                    scores = jnp.where(active, new_scores, scores)
                    vscores = tuple(jnp.where(active, nv, v)
                                    for nv, v in zip(new_vscores,
                                                     vscores))
                    mvals = plan.eval_in_scan(scores, vscores, metric_ops)
                    es = es_update(es, mvals, it, active)
                    return (scores, vscores, es), (stacked, mvals)
                (scores, vscores, es), (trees_B, metrics_B) = \
                    jax.lax.scan(body, (scores, vscores, es0),
                                 (fm_pads_B, iters_B))
                return scores, vscores, es, trees_B, metrics_B
            return jax.jit(step, donate_argnums=_donate(1, 3, 9))

        def step_ext(bins_T, scores, vbins, vscores, grad_ops, bag_weight,
                     fm_pads_B, iters_B, metric_ops, es0, ema0,
                     explore_B, seeds_B):
            def body(carry, xs):
                scores, vscores, es, ema = carry
                fm_pads, it, explore, seed = xs
                active = ~es[2]
                (new_scores, new_vscores, stacked,
                 new_ema) = one_iteration(
                    bins_T, scores, vbins, vscores, grad_ops,
                    bag_weight, fm_pads, ema, explore, seed)
                scores = jnp.where(active, new_scores, scores)
                vscores = tuple(jnp.where(active, nv, v)
                                for nv, v in zip(new_vscores, vscores))
                if new_ema is not None:
                    # frozen tail: the latched model stops realizing
                    # gains, so the EMA freezes with it
                    ema = jnp.where(active, new_ema, ema)
                mvals = plan.eval_in_scan(scores, vscores, metric_ops)
                es = es_update(es, mvals, it, active)
                return (scores, vscores, es, ema), (stacked, mvals)
            (scores, vscores, es, ema), (trees_B, metrics_B) = \
                jax.lax.scan(body, (scores, vscores, es0, ema0),
                             (fm_pads_B, iters_B, explore_B, seeds_B))
            return scores, vscores, es, trees_B, metrics_B, ema
        return jax.jit(step_ext, donate_argnums=_donate(1, 3, 9))

    # ------------------------------------------------------------------
    def train_one_iter(self, gradients=None, hessians=None) -> bool:
        """One boosting iteration (ref: gbdt.cpp:371 TrainOneIter) — or,
        when a megastep-armed driver loop permits it, one fused chunk of
        iterations (see arm_megastep). Returns True if training should
        stop."""
        if self._faults:
            from ..resilience import faults as _faults
            _faults.on_training_step(self)   # crash/hang chaos hooks
        self._profiler_step()
        if gradients is None and hessians is None \
                and not self._stopped_early and not self._es_finished:
            if self._megastep_armed \
                    and self.iter >= int(self.config.num_iterations):
                # the armed loop counts calls, not iterations: signal
                # completion once the megastep chunks covered the horizon
                self.drain_pending()
                return True
            chunk = self._megastep_chunk()
            # a drain-replay consumer needs EVERY iteration to flow
            # through the scan (the metrics are computed there), so
            # horizon/bagging tail chunks of one iteration still run as
            # a length-1 megastep instead of the bare fast step
            if chunk >= 2 or (chunk == 1
                              and self._eval_consumer is not None):
                return self._train_one_megastep(chunk)
            if self._eval_consumer is not None:
                # should be unreachable: megastep_eval_precheck vetted
                # every blocker before the consumer was armed. Fail safe
                # by falling back to the classic driver WITHOUT eval
                # replay (the engine loop detects the dropped consumer
                # and resumes inline evaluation).
                log.warning("megastep eval consumer dropped mid-run "
                            "(megastep no longer eligible); falling back "
                            "to per-iteration evaluation")
                self._report_eviction("consumer_dropped_mid_run")
                self.arm_megastep(self._megastep_armed, eval_consumer=None)
            if self._fast_path_ok():
                return self._train_one_iter_fast()
        self.drain_pending()
        if self._stopped_early or self._es_finished:
            return True
        with timer.section("GBDT::TrainOneIter"):
            return self._sync_iter_body(gradients, hessians)

    def _sync_iter_body(self, gradients, hessians) -> bool:
        self._epi_carry = None   # sync iterations mutate scores directly
        k, n = self.num_tree_per_iteration, self.num_data
        tel = self.telemetry
        it = self.iter
        tel.begin_iteration(it)
        init_scores = [0.0] * k
        with self._sec("boosting") as s:
            if gradients is None or hessians is None:
                if self.objective is None:
                    log.fatal("Cannot train without an objective: pass a "
                              "built-in objective or supply gradients via "
                              "Booster.update(fobj=...)")
                for tid in range(k):
                    init_scores[tid] = self._boost_from_average(tid, True)
                grad, hess = self._get_gradients()
            elif getattr(self, "mp", None) is not None:
                # custom gradients are per-ROW data: each rank's fobj
                # returns [k, local_real] for its own shard (the
                # reference's distributed custom objective is rank-local
                # the same way); pad rows carry zero grad/hess and zero
                # bag weight
                mp = self.mp
                gl = np.asarray(gradients, np.float32).reshape(
                    k, mp.local_real)
                hl = np.asarray(hessians, np.float32).reshape(
                    k, mp.local_real)
                pad = mp.block - mp.local_real
                grad = mp.shard_local_cols(np.pad(gl, ((0, 0), (0, pad))))
                hess = mp.shard_local_cols(np.pad(hl, ((0, 0), (0, pad))))
            else:
                # single-process custom gradients: [k, n] host arrays
                # from Booster.__boost
                grad = jnp.asarray(np.asarray(gradients, np.float32)
                                   .reshape(k, n))
                hess = jnp.asarray(np.asarray(hessians, np.float32)
                                   .reshape(k, n))

            grad, hess = self._bagging(self.iter, grad, hess)
            s.sync((grad, hess))
        tel.inc("train.dispatches")   # eager gradient/bagging launch
        self._guard_gradients(it, grad, hess)

        should_continue = False
        nl_per_class = []
        gain_acc: List[np.ndarray] = []
        for tid in range(k):
            if self.class_need_train[tid] and self.train_data.num_features > 0:
                gh = jnp.stack([grad[tid] * self.bag_weight,
                                hess[tid] * self.bag_weight,
                                self.bag_weight], axis=1)
                # histogram build + split eval run fused inside the
                # jitted grower — one section attributes them jointly
                # (profile_dir splits them at the XLA op level)
                with self._sec("histogram_split") as s:
                    tel.inc("train.dispatches")
                    tree, row_leaf = self._grow(gh, tid)
                    s.sync((tree, row_leaf))
                nl = int(tree.num_leaves)
            else:
                nl = 1
            nl_per_class.append(nl)

            if nl > 1:
                should_continue = True
                with self._sec("tree_materialize"):
                    ht, sf_inner = self._to_host_tree(tree,
                                                      self.shrinkage_rate)
                    self._guard_tree(it, tid, ht, gain_acc)
                    if self.use_cegb:
                        for f in sf_inner:
                            if f >= 0:
                                self.cegb_used[int(f)] = True
                    row_leaf_np = None
                    if bool(self.config.linear_tree):
                        row_leaf_np = np.asarray(row_leaf)
                        self._fit_linear_leaves(ht, row_leaf_np, grad[tid],
                                                hess[tid])
                if (self.objective is not None
                        and self.objective.is_renew_tree_output):
                    with self._sec("renew_leaf"):
                        if getattr(self, "mp", None) is not None:
                            self._renew_tree_output_mp(ht, row_leaf, tid)
                        else:
                            row_leaf_np = np.asarray(row_leaf)
                            self._renew_tree_output(ht, row_leaf_np, tid)
                # shrinkage then score update (ref: gbdt.cpp:414-419)
                ht.apply_shrinkage(self.shrinkage_rate)
                with self._sec("score_update") as s:
                    tel.inc("train.dispatches",
                            1 + len(self.valid_scores))
                    if bool(self.config.linear_tree) and ht.is_linear \
                            and self.train_data.raw_data is not None:
                        # linear leaves: per-row outputs on host raw data
                        rl = (row_leaf_np if row_leaf_np is not None
                              else np.asarray(row_leaf))
                        delta_lin = ht._linear_outputs(
                            self.train_data.raw_data, rl)
                        self.scores = self.scores.at[tid].add(
                            jnp.asarray(delta_lin, jnp.float32))
                        dt = _DeviceTree(ht, sf_inner)
                        for vi in range(len(self.valid_scores)):
                            if self.valid_data[vi].raw_data is not None:
                                vp = ht.predict_rows(
                                    self.valid_data[vi].raw_data)
                                self.valid_scores[vi] = \
                                    self.valid_scores[vi].at[tid].add(
                                        jnp.asarray(vp, jnp.float32))
                            else:
                                self.valid_scores[vi] = \
                                    self._add_tree_to_score(
                                        self.valid_scores[vi],
                                        self.valid_bins[vi],
                                        dt, tid,
                                        bundle=self._valid_bundle(vi))
                        if abs(init_scores[tid]) > K_EPSILON:
                            ht.add_bias(init_scores[tid])
                            dt.leaf_value = jnp.asarray(ht.leaf_value,
                                                        jnp.float32)
                        self.models.append(ht)
                        self.device_trees.append(dt)
                        s.sync(self.scores)
                        continue
                    lv_dev = jnp.asarray(ht.leaf_value, jnp.float32)
                    if self.parallel_mode != "serial":
                        # sharded row_leaf: plain sharded gather (the
                        # pallas lookup kernel is not SPMD-partitionable
                        # from outside a shard_map region)
                        delta = lv_dev[row_leaf]
                    elif self.use_fused:
                        # per-row gathers are slow on TPU; streaming lookup
                        from ..ops.fused_level import table_lookup
                        delta = table_lookup(
                            row_leaf[None, :], lv_dev,
                            interpret=self.fused_interpret)[0]
                    elif self.use_frontier:
                        # per-row gathers are slow on TPU; where-chain
                        from ..models.frontier import leaf_value_lookup
                        delta = leaf_value_lookup(lv_dev, row_leaf,
                                                  self.max_leaves)
                    else:
                        delta = lv_dev[row_leaf]
                    self.scores = self.scores.at[tid].add(delta)
                    cf, cm = self._last_cat or (None, None)
                    dt = _DeviceTree(ht, sf_inner, cat_flag=cf, cat_mask=cm)
                    for vi in range(len(self.valid_scores)):
                        self.valid_scores[vi] = self._add_tree_to_score(
                            self.valid_scores[vi], self.valid_bins[vi],
                            dt, tid, bundle=self._valid_bundle(vi))
                    if abs(init_scores[tid]) > K_EPSILON:
                        ht.add_bias(init_scores[tid])
                        dt.leaf_value = jnp.asarray(ht.leaf_value,
                                                    jnp.float32)
                    self.models.append(ht)
                    self.device_trees.append(dt)
                    s.sync(self.scores)
            else:
                # constant tree (ref: gbdt.cpp:422-441)
                ht = HostTree(1)
                if len(self.models) < k:
                    if not self.class_need_train[tid]:
                        output = (self.objective.boost_from_score(tid)
                                  if self.objective is not None else 0.0)
                    else:
                        output = init_scores[tid]
                    ht.leaf_value[0] = output
                    self.scores = self.scores.at[tid].add(output)
                    for vi in range(len(self.valid_scores)):
                        self.valid_scores[vi] = \
                            self.valid_scores[vi].at[tid].add(output)
                self.models.append(ht)
                self.device_trees.append(
                    _DeviceTree(ht, np.zeros(0, np.int32)))

        if not should_continue:
            log.warning("Stopped training because there are no more leaves "
                        "that meet the split requirements")
            tel.event("stopped_no_splits", iteration=it)
            if len(self.models) > k:
                for _ in range(k):
                    self.models.pop()
                    self.device_trees.pop()
            return True
        if self._faults:
            from ..resilience import faults as _faults
            _faults.maybe_diverge(self, it)   # chaos: corrupt this rank
        if tel.enabled:
            rec = self._emit_iteration_record(it, nl_per_class, gain_acc)
            if self._health is not None and self._health.due(it):
                try:
                    self._health.check(it, self.models,
                                       rec.get("sections") or {})
                except Exception as e:
                    # rank-local failures degrade to a sentinel INSIDE
                    # check (so the collective still pairs up); reaching
                    # here means the allgather itself failed. Single
                    # process that is survivable — disable and move on.
                    # Multi-process it is NOT: a one-sided failure (e.g.
                    # a timeout) leaves peers blocked in — or past — the
                    # audit collective, and any rank-local recovery
                    # desynchronizes every later host collective, so
                    # re-raise and let the crash flight recorder dump
                    if getattr(self, "mp", None) is not None:
                        raise
                    self._health = None
                    log.warning("health check failed at iteration %d; "
                                "auditing disabled for the rest of the "
                                "run: %s", it, e)
        self._finish_screen_iter()
        self.iter += 1
        return False

    # ------------------------------------------------ numerical guards
    def _guard_gradients(self, it: int, grad, hess) -> None:
        """NaN/Inf detection on the gradient/hessian tensors (sync path
        only — gated on the registry like the sections; one fused device
        reduction per iteration)."""
        if not self.telemetry.enabled:
            return
        try:
            bad_g, bad_h = _count_nonfinite(grad, hess)
            bad_g, bad_h = int(bad_g), int(bad_h)
        except Exception as e:      # a guard must never kill training
            log.debug("gradient guard failed: %s", e)
            return
        if bad_g or bad_h:
            self.telemetry.anomaly("nonfinite_grad_hess", iteration=it,
                                   grad=bad_g, hess=bad_h)

    def _guard_tree(self, it: int, tid: int, ht: HostTree,
                    gain_acc: List[np.ndarray]) -> None:
        """Post-materialize guards: non-finite leaf values / leaf
        weights (hessian sums — the histogram outputs' downstream image)
        or split gains raise an anomaly event; finite gains accumulate
        for the iteration record's split-gain distribution stats."""
        if not self.telemetry.enabled:
            return
        gains = np.asarray(ht.split_gain, np.float64)
        bad = {"leaf_values": int(np.count_nonzero(
                   ~np.isfinite(np.asarray(ht.leaf_value, np.float64)))),
               "leaf_weights": int(np.count_nonzero(
                   ~np.isfinite(np.asarray(ht.leaf_weight, np.float64)))),
               "gains": int(np.count_nonzero(~np.isfinite(gains)))}
        if any(bad.values()):
            self.telemetry.anomaly("nonfinite_tree", iteration=it,
                                   tree=tid, **bad)
        if gains.size:
            gain_acc.append(gains[np.isfinite(gains)])

    def _emit_iteration_record(self, it: int, nl_per_class: List[int],
                               gain_acc: Optional[List[np.ndarray]] = None
                               ) -> Dict:
        """Close iteration ``it``'s telemetry record: estimated collective
        traffic for the distributed growers (the multiproc host-plane
        allgathers are counted for real by MultiProcLayout), device
        memory, per-class leaf counts, split-gain distribution stats."""
        tel = self.telemetry
        if self.parallel_mode != "serial":
            # MEASURED in-jit psum payloads: (count, bytes) recorded
            # from the grower's traced static shapes at its first call
            # (ops/collectives.py), applied once per dispatched grow —
            # the traced program runs its full static level schedule
            # whether or not a tree dried up. Falls back to the analytic
            # per-learner profile only before any grower has traced
            # (cannot happen on this record path: _grow ran first).
            k = self.num_tree_per_iteration
            n_grown = (sum(1 for t in range(k) if self.class_need_train[t])
                       if self.train_data.num_features > 0 else 0)
            if self._coll_per_grow is not None and n_grown:
                cnt, nbytes = self._coll_per_grow
                tel.collective("psum_" + self.parallel_mode,
                               cnt * n_grown, nbytes * n_grown)
            else:
                from ..parallel import collective_profile
                for nl in nl_per_class:
                    if nl > 1:
                        cnt, nbytes = collective_profile(
                            self.parallel_mode, num_leaves=nl,
                            num_features=self.train_data.num_features,
                            max_bins=self.max_bins,
                            top_k=int(self.config.top_k),
                            leafwise=self.grow_policy == "leafwise")
                        tel.collective("psum_" + self.parallel_mode,
                                       cnt, nbytes)
        extra = {"num_leaves": nl_per_class,
                 "bag_cnt": int(self.bag_cnt),
                 "engine": ("fused" if self.use_fused else
                            "frontier" if self.use_frontier else "xla"),
                 "mode": self.parallel_mode}
        if gain_acc is not None:
            # the key is always present so count == 0 (no finite gains
            # at all — the broken-gradients symptom the docs point
            # monitoring at) is an observable value, not a missing field
            gains = (np.concatenate(gain_acc) if gain_acc
                     else np.empty(0, np.float64))
            sg = {"count": int(gains.size)}
            if gains.size:
                sg.update(min=float(gains.min()), max=float(gains.max()),
                          mean=float(gains.mean()))
            extra["split_gain"] = sg
        if self._mem_watermarks:
            from ..obs.jaxmon import memory_watermarks
            mem = memory_watermarks(tel)   # per-device gauges; None=CPU
            if mem:
                extra["memory"] = {f"d{d}": st for d, st in mem.items()}
                # back-compat headline gauge: the first device's live
                # bytes (docs ≤ §2 schema; dashboards keyed on it keep
                # working while the per-device series ramp up)
                tel.gauge("device.bytes_in_use",
                          mem[min(mem)].get("bytes_in_use", 0))
        return tel.end_iteration(it, **extra)

    # ------------------------------------------------------------------
    def reset_config(self, config: Config) -> None:
        """Re-derive training state from an updated config
        (ref: gbdt.cpp:686-839 ResetConfig/ResetBaggingConfig)."""
        self.drain_pending()
        self.config = config
        self.shrinkage_rate = float(config.learning_rate)
        self.max_leaves = max(2, int(config.num_leaves))
        self.params = split_params_from_config(config)
        self._stopped_early = False   # a relaxed config may split again
        self._es_finished = False
        self._es_carry = None
        self._evict_reported = set()  # reasons may change with the config
        self._setup_telemetry(config)
        self._setup_resilience(config)
        self._setup_cegb(config)
        self._setup_forced_splits(config, self.train_data)
        # mode-compatibility guards must re-fire: a reset can enable CEGB/
        # forced splits under tree_learner=feature|voting, which degrades
        # the mode to data-parallel (the cached shard_map signatures and
        # data placement change with it)
        self._setup_parallel(config)
        self._setup_engine(config)
        n = self.num_data
        self.is_bagging = False
        self.balanced_bagging = False
        if config.bagging_freq > 0:
            if config.bagging_fraction < 1.0:
                self.is_bagging = True
            elif (self.objective is not None
                  and self.objective.name == "binary"
                  and (config.pos_bagging_fraction < 1.0
                       or config.neg_bagging_fraction < 1.0)):
                self.is_bagging = True
                self.balanced_bagging = True
        if not self.is_bagging:
            self.bag_weight = self._bag_ones()
            self.bag_cnt = n
        # the reference recreates its per-block bagging generators on
        # every config reset (gbdt.cpp ResetBaggingConfig)
        self.bag_streams = ref_random.BlockBaggingStreams(
            int(config.bagging_seed), n)
        self._bag_round_cache = None   # round cache follows the streams
        self.early_stopping_round = int(config.early_stopping_round)
        self.es_first_metric_only = bool(config.first_metric_only)

    # ------------------------------------------------------------------
    def rollback_one_iter(self) -> None:
        """(ref: gbdt.cpp:456 RollbackOneIter). Multi-process: the score
        subtraction routes each device tree on the row-sharded global
        bin matrix (bins_par) — per-row routing partitions cleanly over
        the mesh, so the same in-jit replay works rank-sharded."""
        self.drain_pending()
        self._epi_carry = None   # score subtraction invalidates the carry
        # _bag_round_cache is RETAINED: entries are keyed by firing
        # iteration and stay valid, so a rollback within the cache's
        # two-round window replays the exact round it used before —
        # covering the fused epilogue's one-round lookahead (ADVICE r3).
        # Deeper rollbacks fall off the eviction window and draw the
        # next stream round on retrain, which is also what the reference
        # does at ANY depth (gbdt.cpp:456+230 never rewinds the RNG) —
        # so beyond the window we diverge from the unfused engine's
        # replay but not from reference-style stream semantics.
        if self.iter <= 0:
            return
        train_bins = self._train_bins_replay()
        k = self.num_tree_per_iteration
        for tid in range(k):
            idx = len(self.models) - k + tid
            dt = self.device_trees[idx]
            self.scores = self._add_tree_to_score(
                self.scores, train_bins, dt, tid, scale=-1.0,
                bundle=self._train_bundle())
            for vi in range(len(self.valid_scores)):
                self.valid_scores[vi] = self._add_tree_to_score(
                    self.valid_scores[vi], self.valid_bins[vi], dt, tid,
                    scale=-1.0, bundle=self._valid_bundle(vi))
        del self.models[-k:]
        del self.device_trees[-k:]
        self.iter -= 1

    # ------------------------------------------------------------------
    def eval_metrics(self) -> List[Tuple[str, str, float, bool]]:
        """All (dataset_name, metric_name, value, is_higher_better) tuples.

        Metrics with a device formulation evaluate on the live device
        scores and only their SCALARS cross to host (one batched fetch);
        the rest pull the score matrix once per dataset (the reference's
        behavior, gbdt.cpp:519 OutputMetric -> Metric::Eval on host)."""
        out = []
        if self.training_metrics:
            out.extend(self.eval_metric_set("training",
                                            self.training_metrics,
                                            self.scores))
        for vi, metrics in enumerate(self.valid_metrics):
            out.extend(self.eval_metric_set(self.valid_names[vi], metrics,
                                            self.valid_scores[vi]))
        # one batched device->host fetch for every device scalar
        fetched = jax.device_get([v for (_, _, v, _) in out])
        return [(d, n, float(v), b)
                for (d, n, _, b), v in zip(out, fetched)]

    def eval_metric_set(self, ds_name, metrics, score_dev):
        """Shared device-first metric protocol (also used by
        Booster._eval_set): values may be 0-d device arrays — the caller
        batches the host fetch."""
        out = []
        host_score = None
        # one conversion / one host fetch per (eval set, iteration),
        # shared across the set's metrics: the per-metric cache threads
        # through eval_device so e.g. binary_logloss and binary_error
        # sigmoid the score row once, not once each, and host-form
        # metrics reuse one pulled matrix
        dev_cache: Dict = {}
        for m in metrics:
            vals = m.eval_device(score_dev, self.objective, dev_cache)
            if vals is None and getattr(self, "mp", None) is not None:
                # distributed host form (per-query ranking metrics:
                # rank-local sums + allreduce)
                vals = m.eval_mp(score_dev, self.objective, self.mp)
            if vals is None:
                if host_score is None:
                    if not getattr(score_dev, "is_fully_addressable", True):
                        # multi-process sharded scores cannot be pulled to
                        # one host; only device-form metrics apply
                        warned = getattr(self, "_mp_metric_warned", set())
                        if m.names[0] not in warned:
                            log.warning(
                                "metric %s has no device formulation and "
                                "is skipped under multi-process training",
                                m.names[0])
                            warned.add(m.names[0])
                            self._mp_metric_warned = warned
                        continue
                    host_score = np.asarray(score_dev, np.float64)
                vals = m.eval(host_score, self.objective)
            for name, v in zip(m.names, vals):
                out.append((ds_name, name, v, m.is_bigger_better))
        return out

    def output_metric(self, it: int) -> bool:
        """Print metrics and run early stopping (ref: gbdt.cpp:519
        OutputMetric).  Returns True if early stopping fired."""
        results = self.eval_metrics()
        if it % self.config.metric_freq == 0:
            for ds_name, name, v, _ in results:
                log.info("Iteration:%d, %s %s : %g", it, ds_name, name, v)
        if self.early_stopping_round <= 0:
            return False
        stop = False
        first_name = None
        for ds_name, name, v, bigger in results:
            if ds_name == "training":
                continue
            if self.es_first_metric_only:
                # the FIRST metric is tracked on EVERY valid set; later
                # metrics are skipped (ref: gbdt.cpp:560 early-stopping
                # loop over valid sets with first_metric_only)
                if first_name is None:
                    first_name = name
                elif name != first_name:
                    continue
            key = (ds_name, name)
            cmp = v if bigger else -v
            if key not in self.best_score or cmp > self.best_score[key]:
                self.best_score[key] = cmp
                self.best_iter[key] = it
            elif it - self.best_iter[key] >= self.early_stopping_round:
                stop = True
        return stop

    def train(self) -> None:
        """Full training loop (ref: gbdt.cpp:266 Train). Snapshotting lives
        in engine.train (the driver that owns output paths). Any
        exception unwinding out of the loop triggers the crash flight
        recorder (dump_crash) before re-raising."""
        try:
            self._train_loop()
        except BaseException as exc:
            # BaseException: a Ctrl-C on a wedged run must still dump
            self.dump_crash(exc)
            raise
        self.finalize_telemetry()

    def _train_loop(self) -> None:
        # this loop satisfies the megastep contract: it checks the
        # returned `finished` every call and reads iteration counts off
        # self.iter, so train_one_iter may fuse multiple iterations per
        # call (_megastep_ok still bars configs needing per-iteration
        # observation — GBDT-level early stopping, iteration-granularity
        # telemetry, snapshots). Configured metrics keep per-iteration
        # steps: this loop's output_metric runs once per call, and the
        # reference CLI prints every metric_freq iterations — fusing
        # would silently skip 31 of every 32 metric lines.
        self.arm_megastep(not self.training_metrics
                          and not any(self.valid_metrics))
        try:
            self._train_loop_body()
        finally:
            self.arm_megastep(False)

    def _train_loop_body(self) -> None:
        for it in range(self.iter, int(self.config.num_iterations)):
            finished = self.train_one_iter()
            if not finished:
                finished = self.output_metric(self.iter)
                if finished:
                    self.drain_pending()   # the pop below needs host trees
                    best = min(self.best_iter.values()) \
                        if self.best_iter else self.iter
                    log.info("Early stopping at iteration %d, the best "
                             "iteration round is %d", self.iter, best)
                    self.telemetry.event("early_stopping",
                                         iteration=self.iter,
                                         best_iteration=best)
                    # drop trees after the best iteration
                    extra = (self.iter - best) * self.num_tree_per_iteration
                    for _ in range(extra):
                        self.models.pop()
                        self.device_trees.pop()
                    self.iter = best
            if not finished:
                # sync-driver checkpoint cadence (the megastep path
                # checkpoints at its drain boundaries; the period gate
                # makes a second call after a drain a no-op)
                self.maybe_checkpoint()
            if finished:
                break

    # ------------------------------------------------------------------
    @property
    def num_iterations_trained(self) -> int:
        self.drain_pending()
        return len(self.models) // max(1, self.num_tree_per_iteration)

    # ------------------------------------------------------------------
    # ABI lifecycle: adopt pre-trained trees / refit by leaf assignment
    # (ref: gbdt.h:63 MergeFrom, gbdt.cpp:287 RefitTree,
    # gbdt.cpp:686 ResetTrainingData)
    def _device_tree_from_host(self, ht: HostTree) -> _DeviceTree:
        """Re-bin a raw-threshold HostTree (model-file/string loaded)
        against THIS dataset's mappers so it can route on device bins.
        Valid whenever the mappers match the ones the tree was trained
        with — the CheckAlign precondition ResetTrainingData enforces
        (ref: gbdt.cpp:688)."""
        td = self.train_data
        nn = max(0, ht.num_leaves - 1)
        if nn == 0:
            return _DeviceTree(ht, np.zeros(0, np.int32))
        sf_inner = np.zeros(nn, np.int32)
        thr_bin = np.zeros(nn, np.int32)
        cat_flag = np.zeros(nn, bool)
        cat_mask = np.zeros((nn, self.max_bins), bool)
        for i in range(nn):
            f = int(ht.split_feature[i])
            fi = td.inner_feature_index(f)
            if fi < 0:
                log.fatal("tree splits on feature %d which is trivial "
                          "(unused) in the new training data; bin mappers "
                          "do not align", f)
            sf_inner[i] = fi
            mapper = td.mappers[f]
            if int(ht.decision_type[i]) & 1:   # categorical bitset node
                cat_flag[i] = True
                ci = int(ht.threshold[i])      # index into cat_boundaries
                lo = ht.cat_boundaries[ci]
                hi = ht.cat_boundaries[ci + 1]
                for b, cat in enumerate(mapper.bin_2_categorical):
                    if cat < 0:
                        continue
                    word, bit = divmod(int(cat), 32)
                    if word < hi - lo and \
                            (ht.cat_threshold[lo + word] >> bit) & 1:
                        cat_mask[i, b] = True
            else:
                thr_bin[i] = int(mapper.value_to_bin(float(ht.threshold[i])))
        dt = _DeviceTree(ht, sf_inner)
        dt.threshold_bin = jnp.asarray(thr_bin, jnp.int32)
        # loaded trees may lack leaf_depth; device routing truncates at
        # max_depth steps, so compute the true depth from the topology
        depth = np.zeros(nn, np.int32)
        max_d = 1
        for i in range(nn):           # parents precede children
            for c in (int(ht.left_child[i]), int(ht.right_child[i])):
                if c >= 0:
                    depth[c] = depth[i] + 1
            max_d = max(max_d, int(depth[i]) + 1)
        dt.max_depth = max_d
        if np.any(cat_flag):
            dt.cat_flag = jnp.asarray(cat_flag)
            dt.cat_mask = jnp.asarray(cat_mask)
        return dt

    def adopt_init_models(self, host_trees: List[HostTree]) -> None:
        """Install already-trained trees as the init segment: models are
        PREPENDED and scores are NOT replayed — the reference replays only
        post-init iterations on reset (ref: gbdt.cpp:715 loops over iter_,
        offset by num_init_iteration_), and a fresh reset has none."""
        self.drain_pending()
        k = max(1, self.num_tree_per_iteration)
        if len(host_trees) % k:
            log.fatal("cannot adopt %d trees with %d trees per iteration",
                      len(host_trees), k)
        dts = [self._device_tree_from_host(ht) for ht in host_trees]
        self.models[:0] = host_trees
        self.device_trees[:0] = dts
        self.num_init_iteration += len(host_trees) // k

    def refit_by_leaf_preds(self, leaf_preds: np.ndarray) -> None:
        """Refit every tree's leaf values on the current training data
        from a precomputed [num_data, num_models] leaf-assignment matrix
        (ref: gbdt.cpp:287 RefitTree + serial_tree_learner.cpp:212
        FitByExistingTree): scores start at the init score, each
        iteration's gradients are taken at the running scores, leaf
        outputs are the closed-form Newton values blended with
        refit_decay_rate, and the refitted tree's output is added back
        into the scores before the next iteration."""
        self.drain_pending()
        k = max(1, self.num_tree_per_iteration)
        n = int(self.num_data)
        n_models = len(self.models)
        if leaf_preds.shape != (n, n_models):
            log.fatal("leaf_preds shape %s does not match "
                      "[num_data=%d, num_models=%d]",
                      leaf_preds.shape, n, n_models)
        cfg = self.config
        decay = float(cfg.refit_decay_rate)
        md = self.train_data.metadata
        if md.init_score is not None:
            init = np.asarray(md.init_score, np.float64)
            scores = (init.reshape(k, n, order="C") if init.size == n * k
                      else np.tile(init.reshape(1, n), (k, 1)))
        else:
            scores = np.zeros((k, n), np.float64)
        num_iters = n_models // k
        for it in range(num_iters):
            if self.objective is not None:
                g, h = self.objective.get_gradients(
                    jnp.asarray(scores, jnp.float32))
                g = np.asarray(g, np.float64).reshape(k, n)
                h = np.asarray(h, np.float64).reshape(k, n)
            else:
                g = scores - np.asarray(md.label, np.float64)[None, :]
                h = np.ones_like(g)
            for tid in range(k):
                mi = it * k + tid
                ht = self.models[mi]
                L = ht.num_leaves
                lp = leaf_preds[:, mi]
                if int(lp.max(initial=0)) >= L or int(lp.min(initial=0)) < 0:
                    log.fatal("leaf_preds column %d references leaf %d of "
                              "a %d-leaf tree", mi, int(lp.max()), L)
                sum_g = np.bincount(lp, weights=g[tid], minlength=L)
                # kEpsilon floor matches FitByExistingTree's sum_hess init
                sum_h = np.bincount(lp, weights=h[tid], minlength=L) + 1e-15
                out = np.asarray(jax.device_get(calculate_leaf_output(
                    jnp.asarray(sum_g), jnp.asarray(sum_h), self.params)),
                    np.float64)
                new_vals = (decay * np.asarray(ht.leaf_value, np.float64)
                            + (1.0 - decay) * out * float(ht.shrinkage))
                ht.leaf_value[:] = new_vals[:len(ht.leaf_value)]
                dt = self.device_trees[mi]
                dt.leaf_value = jnp.asarray(ht.leaf_value, jnp.float32)
                scores[tid] += new_vals[lp]
        # live device scores must match the refitted model for subsequent
        # training/eval
        self.scores = jnp.asarray(scores, jnp.float32)
        self._epi_carry = None


class DART(GBDT):
    """DART dropout boosting (ref: src/boosting/dart.hpp:23)."""

    name = "dart"

    def init(self, config, train_data, objective, training_metrics=()):
        super().init(config, train_data, objective, training_metrics)
        self.drop_rng = ref_random.Random(int(config.drop_seed))
        self.tree_weight: List[float] = []
        self.sum_weight = 0.0
        self.drop_index: List[int] = []

    def _boosting_scores(self):
        # drop trees then compute gradients on the reduced score
        # (ref: dart.hpp:77-86 GetTrainingScore → DroppingTrees)
        self._dropping_trees()
        return self.scores

    def _dropping_trees(self):
        cfg = self.config
        self.drop_index = []
        is_skip = self.drop_rng.next_float() < cfg.skip_drop
        if not is_skip:
            drop_rate = cfg.drop_rate
            if not cfg.uniform_drop:
                if self.sum_weight > 0:
                    inv_avg = len(self.tree_weight) / self.sum_weight
                    if cfg.max_drop > 0:
                        drop_rate = min(drop_rate,
                                        cfg.max_drop * inv_avg
                                        / self.sum_weight)
                    for i in range(self.iter):
                        if (self.drop_rng.next_float()
                                < drop_rate * self.tree_weight[i] * inv_avg):
                            self.drop_index.append(self.num_init_iteration + i)
                            if len(self.drop_index) >= cfg.max_drop > 0:
                                break
            else:
                if cfg.max_drop > 0 and self.iter > 0:
                    drop_rate = min(drop_rate, cfg.max_drop / self.iter)
                for i in range(self.iter):
                    if self.drop_rng.next_float() < drop_rate:
                        self.drop_index.append(self.num_init_iteration + i)
                        if len(self.drop_index) >= cfg.max_drop > 0:
                            break
        # remove dropped trees from the training score (ref: dart.hpp:131-137)
        k = self.num_tree_per_iteration
        for i in self.drop_index:
            for tid in range(k):
                dt = self.device_trees[i * k + tid]
                self.scores = self._add_tree_to_score(
                    self.scores, self._train_bins_replay(), dt, tid,
                    scale=-1.0, bundle=self._train_bundle())
        nd = len(self.drop_index)
        if not cfg.xgboost_dart_mode:
            self.shrinkage_rate = cfg.learning_rate / (1.0 + nd)
        else:
            self.shrinkage_rate = (cfg.learning_rate if nd == 0 else
                                   cfg.learning_rate
                                   / (cfg.learning_rate + nd))

    def train_one_iter(self, gradients=None, hessians=None) -> bool:
        ret = super().train_one_iter(gradients, hessians)
        if ret:
            return ret
        self._normalize()
        if not self.config.uniform_drop:
            self.tree_weight.append(self.shrinkage_rate)
            self.sum_weight += self.shrinkage_rate
        return False

    def _normalize(self):
        """(ref: dart.hpp:150-199 Normalize)"""
        cfg = self.config
        nd = len(self.drop_index)
        if nd == 0:
            return
        k = self.num_tree_per_iteration
        for i in self.drop_index:
            for tid in range(k):
                idx = i * k + tid
                ht = self.models[idx]
                dt = self.device_trees[idx]
                if not cfg.xgboost_dart_mode:
                    # dropped tree rescaled to k/(k+1) of its old weight
                    ht.apply_shrinkage(nd / (nd + 1.0))
                    # valid score gets -1/(k+1) of old; train gets +k/(k+1)
                    for vi in range(len(self.valid_scores)):
                        self.valid_scores[vi] = self._add_tree_to_score(
                            self.valid_scores[vi], self.valid_bins[vi], dt,
                            tid, scale=-1.0 / (nd + 1.0),
                            bundle=self._valid_bundle(vi))
                    self.scores = self._add_tree_to_score(
                        self.scores, self._train_bins_replay(), dt, tid,
                        scale=nd / (nd + 1.0),
                        bundle=self._train_bundle())
                else:
                    lr = cfg.learning_rate
                    factor = nd / (nd + lr)
                    ht.apply_shrinkage(factor)
                    for vi in range(len(self.valid_scores)):
                        self.valid_scores[vi] = self._add_tree_to_score(
                            self.valid_scores[vi], self.valid_bins[vi], dt,
                            tid, scale=-(1.0 - factor),
                            bundle=self._valid_bundle(vi))
                    self.scores = self._add_tree_to_score(
                        self.scores, self._train_bins_replay(), dt, tid,
                        scale=factor, bundle=self._train_bundle())
                dt.leaf_value = jnp.asarray(ht.leaf_value, jnp.float32)
            if not cfg.uniform_drop:
                j = i - self.num_init_iteration
                if not cfg.xgboost_dart_mode:
                    self.sum_weight -= self.tree_weight[j] / (nd + 1.0)
                    self.tree_weight[j] *= nd / (nd + 1.0)
                else:
                    # (ref: dart.hpp:191-194)
                    lr = cfg.learning_rate
                    self.sum_weight -= self.tree_weight[j] / (nd + lr)
                    self.tree_weight[j] *= nd / (nd + lr)

    def output_metric(self, it):
        # DART never early-stops (ref: dart.hpp:90-93)
        super().output_metric(it)
        return False

    def _capture_boosting_extra(self):
        # drop-set stream position + per-tree weights: the whole DART
        # state beyond the (mutated-in-place, hence checkpointed) models
        payload = {"drop_rng_x": int(self.drop_rng.x),
                   "sum_weight": float(self.sum_weight)}
        return payload, {"dart_tree_weight": np.asarray(self.tree_weight,
                                                       np.float64)}

    def _restore_boosting_extra(self, payload, arrays):
        if "drop_rng_x" in payload:
            self.drop_rng.x = int(payload["drop_rng_x"])
            self.sum_weight = float(payload.get("sum_weight", 0.0))
            self.tree_weight = [float(x)
                                for x in arrays["dart_tree_weight"]]
            self.drop_index = []


class GOSS(GBDT):
    """Gradient-based One-Side Sampling (ref: src/boosting/goss.hpp:25)."""

    name = "goss"

    def init(self, config, train_data, objective, training_metrics=()):
        super().init(config, train_data, objective, training_metrics)
        if config.top_rate + config.other_rate > 1.0:
            log.fatal("top_rate + other_rate cannot be larger than 1.0 in GOSS")
        if config.top_rate <= 0 or config.other_rate <= 0:
            log.fatal("top_rate and other_rate should be positive in GOSS")
        if config.bagging_freq > 0 and config.bagging_fraction != 1.0:
            log.fatal("Cannot use bagging in GOSS")
        log.info("Using GOSS")
        self.is_bagging = False

    def _capture_boosting_extra(self):
        # GOSS resamples every iteration from scores (recomputed on
        # resume) + this MT19937 stream — only the stream needs saving
        kind, keys, pos, has_gauss, cached = self.bag_rng.get_state()
        payload = {"goss_mt": {"pos": int(pos),
                               "has_gauss": int(has_gauss),
                               "cached": float(cached)}}
        return payload, {"goss_mt_keys": np.asarray(keys, np.uint32)}

    def _restore_boosting_extra(self, payload, arrays):
        mt = payload.get("goss_mt")
        if mt:
            self.bag_rng.set_state(
                ("MT19937", np.asarray(arrays["goss_mt_keys"], np.uint32),
                 int(mt["pos"]), int(mt["has_gauss"]),
                 float(mt["cached"])))

    def _bagging(self, it, grad, hess):
        """(ref: goss.hpp:103-159 BaggingHelper/Bagging). Multi-process:
        sampling is rank-LOCAL over this rank's rows, exactly like the
        reference's per-machine GOSS (each machine's BaggingHelper runs
        on its own bag_data_cnt_); thresholds and draws differ per rank
        by design — they only touch rank-local rows, so the SPMD control
        flow stays identical."""
        cfg = self.config
        mp = getattr(self, "mp", None)
        n = self.num_data
        # no subsampling in the first 1/learning_rate iterations
        if it < int(1.0 / cfg.learning_rate):
            self.bag_weight = self._bag_ones()
            self.bag_cnt = mp.total_real if mp is not None else n
            return grad, hess
        # sum over classes of |g*h| (ref: goss.hpp:108-113 accumulates
        # fabs(g*h) per tree-per-iteration model)
        if mp is not None:
            n = mp.local_real
            if n == 0:
                # a rank can legitimately hold zero rows (query-aligned
                # shards); it contributes nothing but must keep the SPMD
                # control flow
                self._bag_weight_local = np.zeros(mp.block, np.float32)
                self.bag_weight = mp.shard_local(self._bag_weight_local)
                from jax.experimental import multihost_utils
                cnts = np.asarray(multihost_utils.process_allgather(
                    np.asarray([0], np.int64)))
                self.bag_cnt = int(cnts.sum())
                mult_dev = mp.shard_local(
                    np.ones(mp.block, np.float32))[None, :]
                return grad * mult_dev, hess * mult_dev
            g_np = np.asarray(jnp.sum(jnp.abs(
                mp.local_block(grad, axis=1)
                * mp.local_block(hess, axis=1)), axis=0))
            g_np = g_np[:n]
        else:
            g_np = np.asarray(jnp.sum(jnp.abs(grad * hess), axis=0))
        top_k = max(1, int(n * cfg.top_rate))
        other_k = max(1, int(n * cfg.other_rate))
        threshold = np.partition(g_np, n - top_k)[n - top_k]
        multiply = (n - top_k) / other_k
        is_top = g_np >= threshold
        rest = ~is_top
        rest_idx = np.nonzero(rest)[0]
        n_rest = len(rest_idx)
        if n_rest > 0:
            take = min(other_k, n_rest)
            sampled = self.bag_rng.choice(rest_idx, size=take, replace=False)
        else:
            sampled = np.zeros(0, np.int64)
        mask = is_top.copy()
        mask[sampled] = True
        mult = np.ones(n, np.float32)
        mult[sampled] = multiply
        if mp is not None:
            pad = mp.block - n
            maskp = np.pad(mask.astype(np.float32), (0, pad))
            multp = np.pad(mult, (0, pad), constant_values=1.0)
            self._bag_weight_local = maskp
            self.bag_weight = mp.shard_local(maskp)
            from jax.experimental import multihost_utils
            cnts = np.asarray(multihost_utils.process_allgather(
                np.asarray([mask.sum()], np.int64)))
            self.bag_cnt = int(cnts.sum())
            mult_dev = mp.shard_local(multp)[None, :]
        else:
            self.bag_cnt = int(mask.sum())
            self.bag_weight = jnp.asarray(mask.astype(np.float32))
            mult_dev = jnp.asarray(mult)[None, :]
        return grad * mult_dev, hess * mult_dev


class RF(GBDT):
    """Random forest mode (ref: src/boosting/rf.hpp:25).

    No shrinkage; gradients always taken at the constant init score; the
    stored prediction is the average over trees."""

    name = "rf"

    def init(self, config, train_data, objective, training_metrics=()):
        if not (config.bagging_freq > 0 and 0.0 < config.bagging_fraction
                < 1.0):
            log.fatal("RF mode requires bagging "
                      "(bagging_freq > 0, bagging_fraction in (0,1))")
        super().init(config, train_data, objective, training_metrics)
        self.shrinkage_rate = 1.0
        self.average_output = True
        if objective is None:
            log.fatal("RF mode do not support custom objective function, "
                      "please use built-in objectives.")
        # gradients fixed at the init score (ref: rf.hpp:82-100 Boosting)
        self.init_scores = [self._rf_init_score(tid)
                            for tid in range(self.num_tree_per_iteration)]
        base_np = np.tile(np.asarray(self.init_scores, np.float32)[:, None],
                          (1, self.num_data))
        if getattr(self, "mp", None) is not None:
            from jax.sharding import PartitionSpec as P
            base = self.mp.shard_full(base_np, P(None, self.axis_name))
        else:
            base = jnp.asarray(base_np)
        self._fixed_grad, self._fixed_hess = objective.get_gradients(base)

    def _rf_init_score(self, tid):
        cfg = self.config
        if self.has_init_score or not cfg.boost_from_average:
            return 0.0
        return self.objective.boost_from_score(tid)

    def _boost_from_average(self, class_id, update_scorer):
        return 0.0

    def _get_gradients(self):
        return self._fixed_grad, self._fixed_hess

    def train_one_iter(self, gradients=None, hessians=None) -> bool:
        self._profiler_step()
        k = self.num_tree_per_iteration
        tel = self.telemetry
        it = self.iter
        tel.begin_iteration(it)
        nl_per_class = []
        with self._sec("boosting") as s:
            grad, hess = (self._get_gradients() if gradients is None
                          else (jnp.asarray(gradients)
                                .reshape(k, self.num_data),
                                jnp.asarray(hessians)
                                .reshape(k, self.num_data)))
            grad, hess = self._bagging(self.iter, grad, hess)
            s.sync((grad, hess))
        should_continue = False
        for tid in range(k):
            gh = jnp.stack([grad[tid] * self.bag_weight,
                            hess[tid] * self.bag_weight,
                            self.bag_weight], axis=1)
            with self._sec("histogram_split") as s:
                tree, row_leaf = self._grow(gh)
                s.sync((tree, row_leaf))
            nl = int(tree.num_leaves)
            nl_per_class.append(nl)
            if nl > 1:
                should_continue = True
                ht, sf_inner = self._to_host_tree(tree, 1.0)
                if (self.objective is not None
                        and self.objective.is_renew_tree_output):
                    if getattr(self, "mp", None) is not None:
                        self._renew_tree_output_rf_mp(ht, row_leaf, tid)
                    else:
                        self._renew_tree_output_rf(ht, np.asarray(row_leaf),
                                                   tid)
                # bias folded into every tree; the averaged score then
                # carries it once (ref: rf.hpp:136-138 AddBias)
                if abs(self.init_scores[tid]) > K_EPSILON:
                    ht.add_bias(self.init_scores[tid])
                lv_dev = jnp.asarray(ht.leaf_value, jnp.float32)
                # scores accumulate the SUM; prediction averages
                self.scores = self.scores.at[tid].add(lv_dev[row_leaf])
                cf, cm = self._last_cat or (None, None)
                dt = _DeviceTree(ht, sf_inner, cat_flag=cf, cat_mask=cm)
                for vi in range(len(self.valid_scores)):
                    self.valid_scores[vi] = self._add_tree_to_score(
                        self.valid_scores[vi], self.valid_bins[vi], dt, tid,
                        bundle=self._valid_bundle(vi))
                self.models.append(ht)
                self.device_trees.append(dt)
            else:
                ht = HostTree(1)
                self.models.append(ht)
                self.device_trees.append(_DeviceTree(ht,
                                                     np.zeros(0, np.int32)))
        if not should_continue:
            log.warning("Stopped training because there are no more leaves "
                        "that meet the split requirements")
            tel.event("stopped_no_splits", iteration=it)
            if len(self.models) > k:
                for _ in range(k):
                    self.models.pop()
                    self.device_trees.pop()
            return True
        if tel.enabled:
            self._emit_iteration_record(it, nl_per_class)
        self.iter += 1
        return False

    def _renew_tree_output_rf(self, ht, row_leaf, tid):
        # residual against the constant init score (ref: rf.hpp:135-139)
        label = self.train_data.metadata.label
        in_bag = np.asarray(self.bag_weight) > 0
        residual = label.astype(np.float64) - self.init_scores[tid]
        for leaf in range(ht.num_leaves):
            rows = np.nonzero((row_leaf == leaf) & in_bag)[0]
            if len(rows):
                ht.leaf_value[leaf] = self.objective.renew_tree_output(
                    ht.leaf_value[leaf], residual[rows], rows)

    def _renew_tree_output_rf_mp(self, ht, row_leaf, tid):
        mp = self.mp
        rl = mp.local_block(row_leaf)[:mp.local_real]
        label = np.asarray(self.train_data.metadata.label, np.float64)
        residual = label - self.init_scores[tid]
        self._mp_avg_leaf_renewal(ht, rl, residual, self._mp_in_bag_local())

    def eval_metrics(self):
        """Metrics see the AVERAGED score in RF mode."""
        it = max(1, self.num_iterations_trained)
        if getattr(self, "mp", None) is not None:
            # sharded scores cannot be pulled to host; divide on device
            # and ride the parent's device-form eval
            saved, saved_v = self.scores, list(self.valid_scores)
            self.scores = self.scores / it
            self.valid_scores = [v / it for v in saved_v]
            try:
                return super().eval_metrics()
            finally:
                self.scores, self.valid_scores = saved, saved_v
        out = []
        if self.training_metrics:
            score = np.asarray(self.scores, np.float64) / it
            for m in self.training_metrics:
                for name, v in zip(m.names, m.eval(score, self.objective)):
                    out.append(("training", name, v, m.is_bigger_better))
        for vi, metrics in enumerate(self.valid_metrics):
            score = np.asarray(self.valid_scores[vi], np.float64) / it
            for m in metrics:
                for name, v in zip(m.names, m.eval(score, self.objective)):
                    out.append((self.valid_names[vi], name, v,
                                m.is_bigger_better))
        return out
