"""Row-sharded bulk scoring over the serve mesh.

The micro-batcher's latency path tops out at one device per dispatch —
right for small online requests, wasteful for the offline/giant-batch
jobs (backfills, batch re-scoring) the fleet could swallow whole.
:class:`BulkScorer` shard_maps the SAME jitted stacked-tree traversal
the serving engine dispatches (models/predictor ``_run_*_body``)
row-wise over a 1-D mesh of the serve devices — the packed tree
tensors ride as replicated read-only operands, the exact shape of the
PR 12 training megastep:

- rows are chunked to ``n_devices × max_shard_rows``, each chunk's
  per-device shard padded up to a power of two (its own compile-cache
  bucket, so a steady bulk stream recompiles nothing);
- per-row math is the identical f32 scan the single-device dispatch
  runs, so ``predict_bulk`` is numerically interchangeable with the
  online path (asserted in tests/test_serve_fleet.py);
- compiles/dispatches count against the engine's process-wide
  signature registry under ``serve.bulk_*`` counters, and every call
  emits one ``serve_bulk`` event (rows, devices, wall, rows/s) — the
  ``fleet:`` summary line's bulk throughput source.

Eligibility: a device-routable engine (``engine.device_ok``); degraded
models fall back to the engine's host walk in the service before a
scorer is ever built.
"""
from __future__ import annotations

import time
from typing import Sequence

import numpy as np

from ..models.predictor import (_round_up_pow2, _run_binned_body,
                                _run_raw_body)
from .engine import _COMPILED_SIGS, _SIG_LOCK

# per-device shard-rows cap (power of two): bounds a single sharded
# dispatch's padded buffer; chunks beyond n_devices × this loop
_MAX_SHARD_ROWS = 1 << 16


class BulkScorer:
    """shard_map'ed scorer for ONE packed model over the serve mesh."""

    def __init__(self, engine, devices: Sequence,
                 telemetry=None, max_shard_rows: int = _MAX_SHARD_ROWS):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..parallel import mesh as mesh_mod
        if engine.pred is None:
            raise ValueError("BulkScorer needs a device-routable engine")
        self.eng = engine
        self.pred = engine.pred
        self.k = engine.k
        self.model_hash = engine.model_hash
        self.tel = telemetry
        self.devices = list(devices)
        self.n_devices = len(self.devices)
        self.max_shard_rows = _round_up_pow2(max(2, int(max_shard_rows)))
        self.mesh = mesh_mod.make_mesh(devices=self.devices)
        self.dispatches = 0
        self.compiles = 0

        ops = self.pred.run_args(engine.lo, engine.hi)
        mask = tuple(a is not None for a in ops)
        # replicate the packed stacks once (read-only operands on every
        # device — the grower-megastep layout); scalar statics ride
        # along un-placed, jit re-stages them
        rep = NamedSharding(self.mesh, P())
        self._present = tuple(
            jax.device_put(a, rep) if hasattr(a, "shape")
            and getattr(a, "ndim", 0) > 0 else a
            for a in ops if a is not None)
        body = _run_binned_body if self.pred.variant == "binned" \
            else _run_raw_body
        k, max_steps = self.k, self.pred.max_steps

        def _shard(enc, *present):
            it = iter(present)
            full = [next(it) if m else None for m in mask]
            return body(enc, *full, k=k, max_steps=max_steps)

        in_specs = (P(mesh_mod.DATA_AXIS, None),) \
            + tuple(P() for _ in self._present)
        out_specs = P(None, mesh_mod.DATA_AXIS)
        self._fn = jax.jit(mesh_mod.shard_map(
            _shard, self.mesh, in_specs, out_specs))
        # deterministic compile accounting: same process-wide registry
        # the online engines count against, "bulk"-prefixed so a bulk
        # bucket never aliases an online one
        self._sig_base = (
            "bulk", self.pred.variant, self.k, self.pred.max_steps,
            self.pred.enc_width, self.pred.enc_dtype,
            tuple(getattr(d, "id", i)
                  for i, d in enumerate(self.devices)),
            tuple((tuple(a.shape), str(a.dtype))
                  if hasattr(a, "shape") else None
                  for a in self._present))

    # ------------------------------------------------------------------
    def predict_raw(self, X) -> np.ndarray:
        """Raw scores [k, n] float64 — one sharded dispatch per
        ``n_devices × shard`` chunk, each device traversing its own
        row shard against the replicated tree stacks."""
        from ..basic import _is_scipy_sparse
        sparse_in = _is_scipy_sparse(X)
        if sparse_in:
            X = X.tocsr()
        n = int(X.shape[0])
        out = np.zeros((self.k, n), np.float64)
        if n == 0:
            return out
        d = self.n_devices
        step = d * self.max_shard_rows
        t_all = time.perf_counter()
        compiles = dispatches = 0
        for c0 in range(0, n, step):
            sl = slice(c0, min(n, c0 + step))
            Xc = X[sl].toarray() if sparse_in else X[sl]
            rows = Xc.shape[0]
            shard = min(self.max_shard_rows,
                        _round_up_pow2(max(2, -(-rows // d))))
            padded = shard * d
            enc = self.pred.encode(np.asarray(Xc))
            if enc.shape[0] < padded:
                pad = np.zeros((padded - enc.shape[0], enc.shape[1]),
                               enc.dtype)
                enc = np.concatenate([enc, pad], axis=0)
            sig = self._sig_base + (shard,)
            with _SIG_LOCK:
                fresh = sig not in _COMPILED_SIGS
            raw = self._fn(enc, *self._present)
            out[:, sl] = np.asarray(raw, np.float64)[:, :rows]
            # register only after the call returned (same rule as the
            # engine: a failed first dispatch must not blind the gates)
            if fresh:
                with _SIG_LOCK:
                    if sig in _COMPILED_SIGS:
                        fresh = False
                    else:
                        _COMPILED_SIGS.add(sig)
            if fresh:
                compiles += 1
            dispatches += 1
        self.dispatches += dispatches
        self.compiles += compiles
        wall = time.perf_counter() - t_all
        if self.tel is not None:
            try:
                self.tel.inc("serve.bulk_dispatches", dispatches)
                self.tel.inc("serve.bulk_rows", n)
                if compiles:
                    self.tel.inc("serve.bulk_compiles", compiles)
                self.tel.event(
                    "serve_bulk", model_id=self.eng.model_id,
                    rows=n, devices=d, dispatches=dispatches,
                    compiles=compiles, wall_ms=round(wall * 1000.0, 3),
                    rows_per_s=round(n / wall, 1) if wall > 0 else 0.0)
            except Exception:
                pass   # monitoring must never fail a prediction
        return out

    def stats(self) -> dict:
        return {"model_hash": self.model_hash[:16],
                "devices": self.n_devices,
                "dispatches": self.dispatches,
                "compiles": self.compiles,
                "max_shard_rows": self.max_shard_rows}
