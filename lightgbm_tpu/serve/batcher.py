"""Micro-batching request queue with admission control and fleet lanes.

Per-request dispatch is what makes naive serving slow: every request
pays a host→device→host round trip.  The batcher coalesces concurrent
requests for the same model into ONE device call — the serving analog
of the training megastep's dispatch amortization:

- ``submit()`` enqueues a request and returns a
  ``concurrent.futures.Future`` immediately (the async form; ``predict``
  on the service is ``submit().result()``);
- a worker thread drains its queue: it takes the oldest request,
  pulls every queued request for the SAME model, and keeps waiting for
  more until either ``max_batch_rows`` rows are assembled or
  ``max_delay_ms`` has passed since the oldest request arrived — the
  classic deadline-coalescing loop;
- the assembled batch is one engine call (≤1 host dispatch per
  micro-batch when the batch fits one bucket), and each requester's
  slice resolves its future.

Fleet mode (``n_lanes > 1``, docs/Serving.md "Serving fleet"): one
LANE — queue + condition + worker thread — per serve device.  A submit
is routed to the least-loaded lane (queued + in-flight rows weighted by
the lane's measured per-row dispatch EWMA; all-idle ties rotate
round-robin so a sequential closed loop still exercises every device),
and the dispatch callback receives the lane index so the service
resolves it against that device's model replica.  Admission caps split
evenly across lanes, and a submit its routed lane would reject SPILLS
to the coldest lane with room before it is shed (``serve.spills``).
Per-lane gauges (``serve.d<i>.queue_depth`` / ``queue_rows``) publish
next to the aggregate ones.  With one lane the dispatch callback keeps
its two-argument form and every pre-fleet contract is unchanged.

Overload hardening (docs/Serving.md "Overload & rollover"):

- **bounded queue** — ``max_queue_rows`` / ``max_queue_requests`` cap
  the backlog; a submit that would overflow raises a structured
  :class:`~.errors.ServeRejected` synchronously, carrying a
  ``retry_after_ms`` hint derived from the measured drain rate.  The
  adaptive controller (admission.py) can lower the effective bound
  below the hard cap via ``shed_watermark_rows``;
- **deadlines** — ``submit(deadline_ms=)`` (or the service-level
  ``default_deadline_ms``) stamps each request; expired requests are
  SHED AT DEQUEUE with :class:`~.errors.ServeDeadlineExceeded` —
  before any device work is spent on them, never after;
- **bounded drain + wedge detection** — ``close(drain_timeout_s=)``
  sheds whatever a timed-out drain leaves with structured
  ``ServeClosed`` errors, and a worker that does not exit (stuck inside
  a device dispatch) is detected: queued AND in-flight futures are
  failed with :class:`~.errors.ServeWorkerWedged` and a
  ``serve_worker_wedged`` event fires instead of silently leaking
  unresolved futures;
- **fault hooks** — every batch consults the ``LIGHTGBM_TPU_FAULTS``
  registry (``serve_slow_dispatch`` / ``serve_dispatch_error`` /
  ``serve_wedge_worker``), the chaos CI's trigger points.

Failures resolve the affected futures with the exception — a poisoned
request cannot wedge the queue.  Telemetry: queue-depth/rows gauges,
refreshed on submit, drain AND shed so a stalled worker's backlog is
visible between drains (+ peak watermarks), batch-size and latency
distributions, ``serve.rejected``/``serve.shed``/``serve.spills``
counters, ``serve_batch`` events.
"""
from __future__ import annotations

import collections
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, List, Optional, Tuple

import numpy as np

from ..obs import reqtrace
from .errors import (ServeClosed, ServeDeadlineExceeded, ServeRejected,
                     ServeWorkerWedged)

# grace after an aborted drain before a worker is declared wedged:
# long enough for a healthy worker to notice the abort flag (it checks
# between batches, and a batch is bounded by max_delay + one dispatch)
_WEDGE_GRACE_S = 5.0
# serve_rejected / serve_spill events are rate-limited (the counters
# are exact; the event ring must not be flooded by an open-loop storm)
_REJECT_EVENT_PERIOD_S = 0.5


class _Request:
    __slots__ = ("model_id", "X", "rows", "cols", "future", "t_submit",
                 "sparse", "trace_id", "w_submit", "deadline")

    def __init__(self, model_id: str, X, rows: int, sparse: bool,
                 wall_now: float, deadline_ms: Optional[float] = None):
        self.model_id = model_id
        self.X = X
        self.rows = rows
        self.cols = int(X.shape[1])
        self.future: Future = Future()
        self.t_submit = time.perf_counter()
        self.sparse = sparse
        # request identity (obs/reqtrace.py): minted HERE, the moment
        # the request exists — every downstream record (serve_access
        # JSONL line, Perfetto serve-track span) quotes it, and the
        # caller reads it back off future.trace_id
        self.trace_id = reqtrace.mint_trace_id()
        self.future.trace_id = self.trace_id
        self.w_submit = wall_now
        # absolute shed deadline on the worker's clock; None = never
        self.deadline = (None if not deadline_ms or deadline_ms <= 0
                         else self.t_submit + float(deadline_ms) / 1000.0)


def _resolve(future: Future, result=None, exc=None) -> None:
    """set_result/set_exception tolerant of a client cancel() racing the
    delivery — an InvalidStateError here would kill a worker thread and
    wedge every future request behind it."""
    try:
        if exc is not None:
            future.set_exception(exc)
        else:
            future.set_result(result)
    except Exception:
        pass   # cancelled between the done() check and delivery


class _Lane:
    """One dispatch queue + worker (one per serve device in fleet
    mode).  The condition shares the batcher's single mutex: routing
    reads every lane's load under one lock; workers only wake for
    their own queue."""

    __slots__ = ("index", "cv", "q", "q_rows", "inflight", "busy_rows",
                 "ewma_ms_per_row", "worker")

    def __init__(self, index: int, mu: threading.Lock):
        self.index = index
        self.cv = threading.Condition(mu)
        self.q: collections.deque = collections.deque()
        self.q_rows = 0
        self.inflight: List[_Request] = []
        self.busy_rows = 0          # rows of the batch being dispatched
        self.ewma_ms_per_row: Optional[float] = None
        self.worker: Optional[threading.Thread] = None


class MicroBatcher:
    """Deadline-coalescing request queue in front of a dispatch fn."""

    def __init__(self, dispatch: Callable[..., np.ndarray],
                 max_batch_rows: int = 8192, max_delay_ms: float = 2.0,
                 telemetry=None, batch_events: bool = True,
                 memory_watermarks: bool = True,
                 max_queue_rows: int = 0, max_queue_requests: int = 0,
                 default_deadline_ms: float = 0.0,
                 n_lanes: int = 1, routing: str = "least_loaded"):
        self._dispatch = dispatch
        self.max_batch_rows = int(max_batch_rows)
        self.max_delay_s = float(max_delay_ms) / 1000.0
        self.tel = telemetry
        self.batch_events = batch_events
        self.memory_watermarks = bool(memory_watermarks)
        # admission control (0 = unbounded, the pre-hardening contract)
        self.max_queue_rows = max(0, int(max_queue_rows or 0))
        self.max_queue_requests = max(0, int(max_queue_requests or 0))
        self.default_deadline_ms = max(0.0, float(default_deadline_ms
                                                  or 0.0))
        # adaptive lever (admission.AdmissionController): a row bound
        # UNDER the hard cap; None = inactive
        self.shed_watermark_rows: Optional[int] = None
        # post-batch hook (the admission controller's step); best-effort
        self.on_batch_done: Optional[Callable[[], None]] = None
        # post-batch cost-ledger flush (obs/cost.py): the service wires
        # this to the resident engines so a fresh bucket signature's
        # deferred HLO analysis runs on the worker AFTER the batch's
        # futures resolved — signature plumbing that keeps the request
        # latency path analysis-free
        self.cost_flush: Optional[Callable[[], None]] = None
        # post-batch drift evaluation (obs/drift.py): the service wires
        # this to the resident engines's monitors — PSI math runs on the
        # worker after the batch resolved, never on the request path
        self.drift_flush: Optional[Callable[[], None]] = None
        self.n_lanes = max(1, int(n_lanes or 1))
        self.routing = str(routing or "least_loaded")
        self._mu = threading.Lock()
        self._lanes = [_Lane(i, self._mu) for i in range(self.n_lanes)]
        self._rr = self.n_lanes - 1   # rotating tie-break cursor
        self._stop = False
        self._abort_drain = False
        self._wedged = False
        self._batch_seq = 0
        # measured drain rate (EWMA over completed batches, all lanes)
        # feeding the retry_after_ms hint on rejections
        self._ewma_batch_ms: Optional[float] = None
        self._ewma_batch_rows: Optional[float] = None
        self._last_reject_event = 0.0
        self._last_spill_event = 0.0
        self._faults = None   # lazy: resilience.faults module
        for lane in self._lanes:
            suffix = f"-d{lane.index}" if self.n_lanes > 1 else ""
            lane.worker = threading.Thread(
                target=self._loop, args=(lane,),
                name=f"lgbm-serve-batcher{suffix}", daemon=True)
            lane.worker.start()

    # ---------------------------------------------------- introspection
    @property
    def _q(self) -> collections.deque:
        """Lane 0's queue (THE queue when ``n_lanes == 1``) — legacy
        single-queue attribute name, kept for callers/tests that
        inspect it."""
        return self._lanes[0].q

    @property
    def _q_rows(self) -> int:
        """Aggregate queued rows across lanes (legacy single-queue
        attribute name, kept for callers/tests that inspect it)."""
        return sum(lane.q_rows for lane in self._lanes)

    @property
    def _inflight(self) -> List[_Request]:
        return [r for lane in self._lanes for r in lane.inflight]

    # ------------------------------------------------------- admission
    def _retry_after_ms(self) -> float:
        """Backlog / measured drain rate — the hint a rejected client
        should wait before resubmitting.  Before any batch completed,
        fall back to twice the coalescing delay."""
        if self._ewma_batch_ms and self._ewma_batch_rows:
            # rows/ms per lane; the fleet drains n_lanes of them
            rate = (self._ewma_batch_rows / self._ewma_batch_ms
                    * self.n_lanes)
            if rate > 0:
                return min(10_000.0, max(1.0, self._q_rows / rate))
        return max(1.0, self.max_delay_s * 2000.0)

    def _lane_caps(self) -> Tuple[int, int, Optional[int]]:
        """Per-lane (row cap, request cap, watermark): the global
        bounds split evenly (ceil) across lanes; 0/None = unbounded."""
        n = self.n_lanes
        cap_rows = -(-self.max_queue_rows // n) \
            if self.max_queue_rows else 0
        cap_reqs = -(-self.max_queue_requests // n) \
            if self.max_queue_requests else 0
        wm = self.shed_watermark_rows
        wm_lane = None if wm is None else max(1, -(-int(wm) // n))
        return cap_rows, cap_reqs, wm_lane

    def _admission_reason(self, lane: _Lane, rows: int) -> Optional[str]:
        """Why this submit must be rejected by ``lane``, or None.
        Caller holds the lock.  A single oversized request against an
        EMPTY lane always admits (it could otherwise never be served;
        the engine chunks it), matching the max_batch_rows
        oversized-single semantics."""
        cap_rows, cap_reqs, wm = self._lane_caps()
        if cap_reqs and len(lane.q) + 1 > cap_reqs:
            return "queue_requests"
        # effective row bound: the hard cap tightened by the adaptive
        # watermark (either may be unset)
        eff = min(cap_rows, wm) if (cap_rows and wm is not None) \
            else (wm if wm is not None else cap_rows)
        if eff and lane.q_rows + rows > eff and (lane.q or rows <= eff):
            return "shed_watermark" \
                if wm is not None and eff != cap_rows else "queue_rows"
        return None

    # --------------------------------------------------------- routing
    def _lane_load(self, lane: _Lane) -> float:
        """Estimated ms of work ahead of a request routed here: queued
        + in-flight rows weighted by the lane's measured per-row
        dispatch EWMA (a neutral weight before any batch completed)."""
        w = lane.ewma_ms_per_row
        if w is None or w <= 0:
            w = 1.0
        return (lane.q_rows + lane.busy_rows) * w

    def _pick_lane(self) -> _Lane:
        """Least-loaded lane; ties (the all-idle closed loop) rotate
        round-robin from the last pick so every device warms and the
        fleet contract is measurable per device.  Caller holds the
        lock."""
        n = self.n_lanes
        if n == 1:
            return self._lanes[0]
        if self.routing == "round_robin":
            self._rr = (self._rr + 1) % n
            return self._lanes[self._rr]
        best, best_load = None, 0.0
        for off in range(n):
            lane = self._lanes[(self._rr + 1 + off) % n]
            load = self._lane_load(lane)
            if best is None or load < best_load:
                best, best_load = lane, load
        self._rr = best.index
        return best

    def _spill_lane(self, rows: int, exclude: int) -> Optional[_Lane]:
        """Coldest OTHER lane that admits ``rows`` — tried before a
        shed.  Caller holds the lock."""
        cands = sorted((lane for lane in self._lanes
                        if lane.index != exclude),
                       key=self._lane_load)
        for lane in cands:
            if self._admission_reason(lane, rows) is None:
                return lane
        return None

    # ------------------------------------------------------------------
    def submit(self, model_id: str, X,
               deadline_ms: Optional[float] = None) -> Future:
        from ..basic import _is_scipy_sparse
        sparse = _is_scipy_sparse(X)
        if not sparse:
            X = np.asarray(X)
            if X.ndim == 1:
                X = X.reshape(1, -1)
            if X.dtype.kind not in "fiub":
                # coerce non-numeric input HERE, synchronously: a bad
                # request must raise in its own submit call, not poison
                # the np.concatenate of a whole coalesced batch
                X = X.astype(np.float64)
        wall = (self.tel.wall_now() if self.tel is not None
                else time.time())
        eff_deadline = (self.default_deadline_ms
                        if deadline_ms is None else float(deadline_ms))
        req = _Request(model_id, X, int(X.shape[0]), sparse, wall,
                       deadline_ms=eff_deadline)
        reject: Optional[ServeRejected] = None
        spilled = False
        with self._mu:
            if self._stop or self._wedged:
                exc = ServeWorkerWedged(
                    "MicroBatcher worker is wedged", model_id=model_id) \
                    if self._wedged else ServeClosed(
                        "MicroBatcher is closed", model_id=model_id)
                req.future.set_exception(exc)
                self._emit_failed(req, type(exc).__name__)
                return req.future
            lane = self._pick_lane()
            reason = self._admission_reason(lane, req.rows)
            if reason is not None and self.n_lanes > 1:
                # admission spill: the coldest lane with room takes the
                # request before admission control sheds it
                alt = self._spill_lane(req.rows, exclude=lane.index)
                if alt is not None:
                    lane, reason, spilled = alt, None, True
            if reason is None:
                lane.q.append(req)
                lane.q_rows += req.rows
                gauges = self._queue_gauges_locked(lane)
                lane.cv.notify()
            else:
                reject = ServeRejected(
                    f"serving queue full ({reason}); retry after "
                    f"~{self._retry_after_ms():.0f} ms",
                    reason=reason,
                    retry_after_ms=self._retry_after_ms(),
                    queue_rows=self._q_rows,
                    queue_requests=sum(len(ln.q) for ln in self._lanes),
                    model_id=model_id)
        if reject is not None:
            # telemetry OUTSIDE the queue lock: a JSONL sink write must
            # never serialize submitters against the workers
            if self.tel is not None:
                self.tel.inc("serve.rejected")
                self.tel.inc("serve.rejected_rows", req.rows)
                now = time.perf_counter()
                if now - self._last_reject_event > _REJECT_EVENT_PERIOD_S:
                    self._last_reject_event = now
                    self._record(lambda: self.tel.event(
                        "serve_rejected", **reject.details()))
            raise reject
        if self.tel is not None:
            self._publish_queue_gauges(gauges, peaks=True)
            self.tel.inc("serve.requests")
            self.tel.inc("serve.rows", req.rows)
            if self.n_lanes > 1:
                self.tel.inc(f"serve.d{lane.index}.requests")
                self.tel.inc(f"serve.d{lane.index}.rows", req.rows)
            if spilled:
                self.tel.inc("serve.spills")
                self.tel.inc(f"serve.d{lane.index}.spills")
                now = time.perf_counter()
                if now - self._last_spill_event > _REJECT_EVENT_PERIOD_S:
                    self._last_spill_event = now
                    self._record(lambda: self.tel.event(
                        "serve_spill", model_id=model_id,
                        rows=req.rows, to_device=lane.index))
        return req.future

    # ---------------------------------------------------------- gauges
    def _queue_gauges_locked(self, lane: Optional[_Lane] = None):
        """Snapshot (aggregate depth, aggregate rows, [(lane, depth,
        rows)]) under the lock; published outside it."""
        agg_d = sum(len(ln.q) for ln in self._lanes)
        agg_r = sum(ln.q_rows for ln in self._lanes)
        per = None
        if self.n_lanes > 1:
            lanes = self._lanes if lane is None else [lane]
            per = [(ln.index, len(ln.q), ln.q_rows) for ln in lanes]
        return agg_d, agg_r, per

    def _publish_queue_gauges(self, gauges, peaks: bool = False) -> None:
        if self.tel is None:
            return
        agg_d, agg_r, per = gauges
        self.tel.gauge("serve.queue_depth", agg_d)
        self.tel.gauge("serve.queue_rows", agg_r)
        if peaks:
            self.tel.gauge_max("serve.queue_peak_requests", agg_d)
            self.tel.gauge_max("serve.queue_peak_rows", agg_r)
        for i, d, r in (per or ()):
            self.tel.gauge(f"serve.d{i}.queue_depth", d)
            self.tel.gauge(f"serve.d{i}.queue_rows", r)

    def _regauge(self, lane: _Lane) -> None:
        """Refresh the queue gauges from a worker (drain/shed paths) —
        best-effort, never on the submit fast path's lock hold."""
        with self._mu:
            gauges = self._queue_gauges_locked(lane)
        self._record(self._publish_queue_gauges, gauges)

    # ------------------------------------------------------- deadlines
    @staticmethod
    def _expired(req: _Request, now: float) -> bool:
        return req.deadline is not None and now >= req.deadline

    def _shed(self, reqs: List[_Request]) -> None:
        """Fail expired requests BEFORE any device work is spent on
        them: structured error, counter, one serve_access record each
        (error="ServeDeadlineExceeded") — shed requests trace too."""
        now = time.perf_counter()
        for r in reqs:
            waited_ms = (now - r.t_submit) * 1000.0
            deadline_ms = 0.0 if r.deadline is None else \
                (r.deadline - r.t_submit) * 1000.0
            _resolve(r.future, exc=ServeDeadlineExceeded(
                f"deadline of {deadline_ms:.1f} ms passed after "
                f"{waited_ms:.1f} ms in queue (shed before dispatch)",
                retry_after_ms=self._retry_after_ms(),
                deadline_ms=round(deadline_ms, 3),
                waited_ms=round(waited_ms, 3),
                model_id=r.model_id, trace_id=r.trace_id))
            if self.tel is not None:
                self.tel.inc("serve.shed")
                self.tel.inc("serve.shed_rows", r.rows)
            self._emit_failed(r, "ServeDeadlineExceeded")

    # ------------------------------------------------------------------
    def _pull_same_model(self, lane: _Lane, model_id: str, cols: int,
                         budget: int
                         ) -> Tuple[List[_Request], List[_Request]]:
        """Remove queued DENSE requests for ``model_id`` with the SAME
        column count (a width mismatch must fail only its own request,
        not its batch neighbors' np.concatenate), up to ``budget`` rows,
        preserving arrival order.  Expired requests of ANY model are
        also removed and returned separately for shedding (emission
        happens outside the lock).  Caller holds the lock."""
        got, expired, keep = [], [], collections.deque()
        now = time.perf_counter()
        while lane.q:
            r = lane.q.popleft()
            if self._expired(r, now):
                lane.q_rows -= r.rows
                expired.append(r)
            elif (r.model_id == model_id and not r.sparse
                    and r.cols == cols and r.rows <= budget):
                # strict budget: a batch never exceeds max_batch_rows,
                # so one micro-batch is one bucketed device dispatch
                # (an oversized SINGLE request still chunks in the
                # engine, but never drags neighbors past the cap)
                lane.q_rows -= r.rows
                got.append(r)
                budget -= r.rows
            else:
                keep.append(r)
        lane.q = keep
        return got, expired

    def _drain_lane_locked(self, lane: _Lane) -> List[_Request]:
        drop = list(lane.q)
        lane.q.clear()
        lane.q_rows = 0
        return drop

    def _loop(self, lane: _Lane) -> None:
        while True:
            drop: Optional[List[_Request]] = None
            with self._mu:
                while not lane.q and not self._stop \
                        and not self._abort_drain:
                    lane.cv.wait()
                if self._abort_drain:
                    drop = self._drain_lane_locked(lane)
                elif not lane.q and self._stop:
                    return
                else:
                    first = lane.q.popleft()
                    lane.q_rows -= first.rows
            if drop is not None:
                # bounded drain expired: shutdown must shed the
                # remaining queue with structured errors, not block
                exc = ServeClosed("MicroBatcher drain timed out; "
                                  "request shed at shutdown",
                                  reason="drain_timeout")
                for r in drop:
                    _resolve(r.future, exc=exc)
                    self._emit_failed(r, "DrainTimeout")
                return
            now = time.perf_counter()
            if self._expired(first, now):
                self._shed([first])
                self._regauge(lane)
                continue
            batch = [first]
            rows = first.rows
            if not first.sparse:
                deadline = first.t_submit + self.max_delay_s
                while rows < self.max_batch_rows:
                    with self._mu:
                        more, expired = self._pull_same_model(
                            lane, first.model_id, first.cols,
                            self.max_batch_rows - rows)
                        if not more and not expired:
                            remaining = deadline - time.perf_counter()
                            if remaining <= 0:
                                break
                            lane.cv.wait(remaining)
                            more, expired = self._pull_same_model(
                                lane, first.model_id, first.cols,
                                self.max_batch_rows - rows)
                    if expired:
                        self._shed(expired)
                    if more:
                        batch.extend(more)
                        rows += sum(r.rows for r in more)
                    elif time.perf_counter() >= deadline:
                        break
            self._run_batch(lane, first.model_id, batch, rows)

    def _emit_failed(self, req: "_Request", error: str) -> None:
        """serve_access for a request that never reached a dispatch
        (submit-after-stop, shed deadline, drain timeout, wedged
        worker) — the exactly-one-record-per-request contract covers
        the failure paths an operator actually debugs."""
        if self.tel is None:
            return

        def _go():
            done_wall = self.tel.wall_now()
            reqtrace.emit_access(
                self.tel, req, {"error": error},
                queue_ms=(time.perf_counter() - req.t_submit) * 1000.0,
                batch_ms=0.0, done_wall=done_wall)
        self._record(_go)

    def _record(self, fn, *args, **kwargs) -> None:
        """Telemetry from a worker thread must be best-effort: a
        failing sink (disk full under telemetry_out) would otherwise
        unwind _loop, kill the lane's worker and wedge every future
        request behind a healthy device."""
        if self.tel is None:
            return
        try:
            fn(*args, **kwargs)
        except Exception:
            pass

    def _fault_hook(self, seq: int) -> None:
        """Serve-plane fault injection (resilience/faults.py): may
        sleep (serve_slow_dispatch), sleep forever (serve_wedge_worker)
        or raise (serve_dispatch_error — resolved into the batch's
        futures like any dispatch failure)."""
        if self._faults is None:
            from ..resilience import faults
            self._faults = faults
        self._faults.on_serve_batch(self.tel, seq)

    def _run_batch(self, lane: _Lane, model_id: str,
                   batch: List[_Request], rows: int) -> None:
        # re-gauge on drain too: submit-only updates would leave an
        # idle service reporting its last (peak) backlog forever
        self._regauge(lane)
        with self._mu:
            self._batch_seq += 1
            seq = self._batch_seq
        lane.inflight = batch
        lane.busy_rows = rows
        t0 = time.perf_counter()
        wait_ms = (t0 - batch[0].t_submit) * 1000.0
        # request-scoped batch context: the engine annotates bucket /
        # dispatch wall / degradation from inside the dispatch without
        # the batcher knowing its internals (obs/reqtrace.py)
        reqtrace.begin_batch(model_id,
                             device=lane.index if self.n_lanes > 1
                             else None)
        try:
            self._fault_hook(seq)
            X = batch[0].X if len(batch) == 1 else np.concatenate(
                [r.X for r in batch], axis=0)
            if self.n_lanes > 1:
                out = self._dispatch(model_id, X, lane.index)
            else:
                out = self._dispatch(model_id, X)
            out = np.asarray(out)
        except Exception as exc:  # resolve, don't wedge
            ctx = reqtrace.end_batch()
            done_wall = (self.tel.wall_now() if self.tel is not None
                         else time.time())
            for r in batch:
                _resolve(r.future, exc=exc)
            lane.inflight = []
            lane.busy_rows = 0

            def _error_telemetry():
                self.tel.inc("serve.batch_errors")
                self.tel.event("serve_batch_error", model_id=model_id,
                               rows=rows, error=type(exc).__name__)
                # the exactly-one-serve_access-per-request contract
                # holds on the failure path too — a request that died
                # must still be traceable by its trace_id
                for r in batch:
                    reqtrace.emit_access(
                        self.tel, r, dict(ctx, error=type(exc).__name__),
                        queue_ms=(t0 - r.t_submit) * 1000.0,
                        batch_ms=(time.perf_counter() - t0) * 1000.0,
                        done_wall=done_wall)
            self._record(_error_telemetry)
            self._record(lambda: self.on_batch_done and
                         self.on_batch_done())
            return
        ctx = reqtrace.end_batch()
        done = time.perf_counter()
        done_wall = (self.tel.wall_now() if self.tel is not None
                     else time.time())
        c0 = 0
        for r in batch:
            _resolve(r.future, result=out[c0:c0 + r.rows])
            c0 += r.rows
        lane.inflight = []
        lane.busy_rows = 0
        batch_ms = (done - t0) * 1000.0
        # drain-rate EWMAs: the global pair feeds the rejection
        # retry_after hint; the per-lane ms/row feeds least-loaded
        # routing (plain attributes: worker-written, submitter-read,
        # GIL-atomic)
        a = 0.2
        self._ewma_batch_ms = batch_ms if self._ewma_batch_ms is None \
            else (1 - a) * self._ewma_batch_ms + a * batch_ms
        self._ewma_batch_rows = float(rows) \
            if self._ewma_batch_rows is None \
            else (1 - a) * self._ewma_batch_rows + a * rows
        ms_per_row = batch_ms / max(1, rows)
        lane.ewma_ms_per_row = ms_per_row \
            if lane.ewma_ms_per_row is None \
            else (1 - a) * lane.ewma_ms_per_row + a * ms_per_row

        def _batch_telemetry():
            self.tel.inc("serve.batches")
            self.tel.dist("serve.batch_rows", rows)
            if self.n_lanes > 1:
                self.tel.inc(f"serve.d{lane.index}.batches")
                self.tel.dist(f"serve.d{lane.index}.batch_ms", batch_ms)
            for r in batch:
                self.tel.dist("serve.latency_ms",
                              (done - r.t_submit) * 1000.0)
                reqtrace.emit_access(
                    self.tel, r, ctx,
                    queue_ms=(t0 - r.t_submit) * 1000.0,
                    batch_ms=batch_ms, done_wall=done_wall)
            if self.batch_events:
                self.tel.event("serve_batch", model_id=model_id,
                               rows=rows, requests=len(batch),
                               wait_ms=round(wait_ms, 3),
                               exec_ms=round(batch_ms, 3),
                               trace_ids=[r.trace_id for r in batch],
                               **({} if self.n_lanes == 1
                                  else {"device": lane.index}))
            if self.memory_watermarks:
                # serving dispatch boundary: the allocator peak just
                # moved (or didn't) — refresh the per-device HBM gauges
                # the exporter serves; cached no-op on stat-less
                # backends
                from ..obs.jaxmon import memory_watermarks
                memory_watermarks(self.tel, where="serve")

        self._record(_batch_telemetry)
        self._record(lambda: self.cost_flush and self.cost_flush())
        self._record(lambda: self.drift_flush and self.drift_flush())
        # adaptive admission: evaluate AFTER the batch's latency samples
        # landed in the dist ring (time-gated inside the controller)
        self._record(lambda: self.on_batch_done and self.on_batch_done())

    # ------------------------------------------------------------------
    def close(self, drain: bool = True,
              drain_timeout_s: Optional[float] = None) -> None:
        """Stop the workers.  ``drain=True`` serves what is already
        queued first, bounded by ``drain_timeout_s`` (default 30 s,
        shared across lanes): when the bound expires, the remaining
        queues are shed with structured ``ServeClosed`` errors instead
        of blocking shutdown indefinitely.  ``drain=False`` fails
        queued requests immediately.  A worker that does not exit even
        after the aborted drain (stuck inside a device dispatch) is
        declared WEDGED: queued + in-flight futures are failed with
        ``ServeWorkerWedged`` and a ``serve_worker_wedged`` event fires
        — never a silent leak of unresolved futures."""
        with self._mu:
            self._stop = True
            dropped: List[_Request] = []
            if not drain:
                for lane in self._lanes:
                    dropped.extend(self._drain_lane_locked(lane))
                for r in dropped:
                    _resolve(r.future,
                             exc=ServeClosed("MicroBatcher closed",
                                             model_id=r.model_id))
            for lane in self._lanes:
                lane.cv.notify_all()
        for r in dropped:
            self._emit_failed(r, "MicroBatcherClosed")
        timeout = 30.0 if drain_timeout_s is None \
            else max(0.0, float(drain_timeout_s))
        # one shared deadline: the drain bound covers the whole fleet,
        # not timeout × n_lanes
        deadline = time.perf_counter() + timeout
        for lane in self._lanes:
            lane.worker.join(
                timeout=max(0.0, deadline - time.perf_counter()))
        if not any(lane.worker.is_alive() for lane in self._lanes):
            return
        # bounded drain expired: tell the workers to stop serving the
        # backlog and shed it (structured errors) on their way out
        with self._mu:
            self._abort_drain = True
            for lane in self._lanes:
                lane.cv.notify_all()
        grace = time.perf_counter() + _WEDGE_GRACE_S
        for lane in self._lanes:
            if lane.worker.is_alive():
                lane.worker.join(
                    timeout=max(0.0, grace - time.perf_counter()))
        if not any(lane.worker.is_alive() for lane in self._lanes):
            return
        # a worker ignored the abort: it is wedged inside a dispatch
        # (hung device, injected serve_wedge_worker).  Fail everything
        # it will never serve — _resolve is race-tolerant, so if the
        # worker ever does come back its own delivery no-ops.
        self._wedged = True
        with self._mu:
            drop = []
            for lane in self._lanes:
                drop.extend(self._drain_lane_locked(lane))
        inflight = self._inflight
        exc = ServeWorkerWedged(
            "serving worker did not exit within the close timeout "
            "(wedged inside a dispatch); queued and in-flight requests "
            "failed", queued=len(drop), inflight=len(inflight))
        for r in drop + inflight:
            _resolve(r.future, exc=exc)
            self._emit_failed(r, "ServeWorkerWedged")
        if self.tel is not None:
            self._record(lambda: self.tel.event(
                "serve_worker_wedged", queued=len(drop),
                inflight=len(inflight),
                drain_timeout_s=timeout))
            self._record(lambda: self.tel.inc("serve.worker_wedged"))
