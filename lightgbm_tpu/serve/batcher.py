"""Micro-batching request queue.

Per-request dispatch is what makes naive serving slow: every request
pays a host→device→host round trip.  The batcher coalesces concurrent
requests for the same model into ONE device call — the serving analog
of the training megastep's dispatch amortization:

- ``submit()`` enqueues a request and returns a
  ``concurrent.futures.Future`` immediately (the async form; ``predict``
  on the service is ``submit().result()``);
- a single worker thread drains the queue: it takes the oldest request,
  pulls every queued request for the SAME model, and keeps waiting for
  more until either ``max_batch_rows`` rows are assembled or
  ``max_delay_ms`` has passed since the oldest request arrived — the
  classic deadline-coalescing loop;
- the assembled batch is one engine call (≤1 host dispatch per
  micro-batch when the batch fits one bucket), and each requester's
  slice resolves its future.

Failures resolve the affected futures with the exception — a poisoned
request cannot wedge the queue.  Telemetry: queue-depth gauge,
batch-size and latency distributions, ``serve_batch`` events.
"""
from __future__ import annotations

import collections
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, List, Optional

import numpy as np

from ..obs import reqtrace


class _Request:
    __slots__ = ("model_id", "X", "rows", "cols", "future", "t_submit",
                 "sparse", "trace_id", "w_submit")

    def __init__(self, model_id: str, X, rows: int, sparse: bool,
                 wall_now: float):
        self.model_id = model_id
        self.X = X
        self.rows = rows
        self.cols = int(X.shape[1])
        self.future: Future = Future()
        self.t_submit = time.perf_counter()
        self.sparse = sparse
        # request identity (obs/reqtrace.py): minted HERE, the moment
        # the request exists — every downstream record (serve_access
        # JSONL line, Perfetto serve-track span) quotes it, and the
        # caller reads it back off future.trace_id
        self.trace_id = reqtrace.mint_trace_id()
        self.future.trace_id = self.trace_id
        self.w_submit = wall_now


def _resolve(future: Future, result=None, exc=None) -> None:
    """set_result/set_exception tolerant of a client cancel() racing the
    delivery — an InvalidStateError here would kill the single worker
    thread and wedge every future request behind it."""
    try:
        if exc is not None:
            future.set_exception(exc)
        else:
            future.set_result(result)
    except Exception:
        pass   # cancelled between the done() check and delivery


class MicroBatcher:
    """Deadline-coalescing request queue in front of a dispatch fn."""

    def __init__(self, dispatch: Callable[[str, Any], np.ndarray],
                 max_batch_rows: int = 8192, max_delay_ms: float = 2.0,
                 telemetry=None, batch_events: bool = True,
                 memory_watermarks: bool = True):
        self._dispatch = dispatch
        self.max_batch_rows = int(max_batch_rows)
        self.max_delay_s = float(max_delay_ms) / 1000.0
        self.tel = telemetry
        self.batch_events = batch_events
        self.memory_watermarks = bool(memory_watermarks)
        self._q: collections.deque = collections.deque()
        self._cv = threading.Condition()
        self._stop = False
        self._worker = threading.Thread(
            target=self._loop, name="lgbm-serve-batcher", daemon=True)
        self._worker.start()

    # ------------------------------------------------------------------
    def submit(self, model_id: str, X) -> Future:
        from ..basic import _is_scipy_sparse
        sparse = _is_scipy_sparse(X)
        if not sparse:
            X = np.asarray(X)
            if X.ndim == 1:
                X = X.reshape(1, -1)
            if X.dtype.kind not in "fiub":
                # coerce non-numeric input HERE, synchronously: a bad
                # request must raise in its own submit call, not poison
                # the np.concatenate of a whole coalesced batch
                X = X.astype(np.float64)
        wall = (self.tel.wall_now() if self.tel is not None
                else time.time())
        req = _Request(model_id, X, int(X.shape[0]), sparse, wall)
        with self._cv:
            if self._stop:
                req.future.set_exception(
                    RuntimeError("MicroBatcher is closed"))
                self._emit_failed(req, "MicroBatcherClosed")
                return req.future
            self._q.append(req)
            depth = len(self._q)
            self._cv.notify()
        if self.tel is not None:
            self.tel.gauge("serve.queue_depth", depth)
            self.tel.inc("serve.requests")
            self.tel.inc("serve.rows", req.rows)
        return req.future

    # ------------------------------------------------------------------
    def _pull_same_model(self, model_id: str, cols: int,
                         budget: int) -> List[_Request]:
        """Remove queued DENSE requests for ``model_id`` with the SAME
        column count (a width mismatch must fail only its own request,
        not its batch neighbors' np.concatenate), up to ``budget`` rows,
        preserving arrival order.  Caller holds the lock."""
        got, keep = [], collections.deque()
        while self._q:
            r = self._q.popleft()
            if (r.model_id == model_id and not r.sparse
                    and r.cols == cols and r.rows <= budget):
                # strict budget: a batch never exceeds max_batch_rows,
                # so one micro-batch is one bucketed device dispatch
                # (an oversized SINGLE request still chunks in the
                # engine, but never drags neighbors past the cap)
                got.append(r)
                budget -= r.rows
            else:
                keep.append(r)
        self._q = keep
        return got

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self._stop:
                    self._cv.wait()
                if not self._q and self._stop:
                    return
                first = self._q.popleft()
            batch = [first]
            rows = first.rows
            if not first.sparse:
                deadline = first.t_submit + self.max_delay_s
                while rows < self.max_batch_rows:
                    with self._cv:
                        more = self._pull_same_model(
                            first.model_id, first.cols,
                            self.max_batch_rows - rows)
                        if not more:
                            remaining = deadline - time.perf_counter()
                            if remaining <= 0:
                                break
                            self._cv.wait(remaining)
                            more = self._pull_same_model(
                                first.model_id, first.cols,
                                self.max_batch_rows - rows)
                    if more:
                        batch.extend(more)
                        rows += sum(r.rows for r in more)
                    elif time.perf_counter() >= deadline:
                        break
            self._run_batch(first.model_id, batch, rows)

    def _emit_failed(self, req: "_Request", error: str) -> None:
        """serve_access for a request that never reached a dispatch
        (submit-after-stop, close(drain=False)) — the exactly-one-
        record-per-request contract covers the failure paths an
        operator actually debugs."""
        if self.tel is None:
            return

        def _go():
            done_wall = self.tel.wall_now()
            reqtrace.emit_access(
                self.tel, req, {"error": error},
                queue_ms=(time.perf_counter() - req.t_submit) * 1000.0,
                batch_ms=0.0, done_wall=done_wall)
        self._record(_go)

    def _record(self, fn, *args, **kwargs) -> None:
        """Telemetry from the worker thread must be best-effort: a
        failing sink (disk full under telemetry_out) would otherwise
        unwind _loop, kill the only worker and wedge every future
        request behind a healthy device."""
        if self.tel is None:
            return
        try:
            fn(*args, **kwargs)
        except Exception:
            pass

    def _run_batch(self, model_id: str, batch: List[_Request],
                   rows: int) -> None:
        # re-gauge on drain too: submit-only updates would leave an
        # idle service reporting its last (peak) backlog forever
        self._record(lambda: self.tel.gauge("serve.queue_depth",
                                            len(self._q)))
        t0 = time.perf_counter()
        wait_ms = (t0 - batch[0].t_submit) * 1000.0
        # request-scoped batch context: the engine annotates bucket /
        # dispatch wall / degradation from inside the dispatch without
        # the batcher knowing its internals (obs/reqtrace.py)
        reqtrace.begin_batch(model_id)
        try:
            X = batch[0].X if len(batch) == 1 else np.concatenate(
                [r.X for r in batch], axis=0)
            out = self._dispatch(model_id, X)
            out = np.asarray(out)
        except Exception as exc:  # resolve, don't wedge
            ctx = reqtrace.end_batch()
            done_wall = (self.tel.wall_now() if self.tel is not None
                         else time.time())
            for r in batch:
                _resolve(r.future, exc=exc)

            def _error_telemetry():
                self.tel.inc("serve.batch_errors")
                self.tel.event("serve_batch_error", model_id=model_id,
                               rows=rows, error=type(exc).__name__)
                # the exactly-one-serve_access-per-request contract
                # holds on the failure path too — a request that died
                # must still be traceable by its trace_id
                for r in batch:
                    reqtrace.emit_access(
                        self.tel, r, dict(ctx, error=type(exc).__name__),
                        queue_ms=(t0 - r.t_submit) * 1000.0,
                        batch_ms=(time.perf_counter() - t0) * 1000.0,
                        done_wall=done_wall)
            self._record(_error_telemetry)
            return
        ctx = reqtrace.end_batch()
        done = time.perf_counter()
        done_wall = (self.tel.wall_now() if self.tel is not None
                     else time.time())
        c0 = 0
        for r in batch:
            _resolve(r.future, result=out[c0:c0 + r.rows])
            c0 += r.rows

        def _batch_telemetry():
            self.tel.inc("serve.batches")
            self.tel.dist("serve.batch_rows", rows)
            batch_ms = (done - t0) * 1000.0
            for r in batch:
                self.tel.dist("serve.latency_ms",
                              (done - r.t_submit) * 1000.0)
                reqtrace.emit_access(
                    self.tel, r, ctx,
                    queue_ms=(t0 - r.t_submit) * 1000.0,
                    batch_ms=batch_ms, done_wall=done_wall)
            if self.batch_events:
                self.tel.event("serve_batch", model_id=model_id,
                               rows=rows, requests=len(batch),
                               wait_ms=round(wait_ms, 3),
                               exec_ms=round(batch_ms, 3),
                               trace_ids=[r.trace_id for r in batch])
            if self.memory_watermarks:
                # serving dispatch boundary: the allocator peak just
                # moved (or didn't) — refresh the per-device HBM gauges
                # the exporter serves; cached no-op on stat-less
                # backends
                from ..obs.jaxmon import memory_watermarks
                memory_watermarks(self.tel, where="serve")

        self._record(_batch_telemetry)

    # ------------------------------------------------------------------
    def close(self, drain: bool = True) -> None:
        """Stop the worker.  ``drain=True`` serves what is already
        queued first; ``drain=False`` fails queued requests."""
        with self._cv:
            self._stop = True
            dropped = []
            if not drain:
                while self._q:
                    r = self._q.popleft()
                    _resolve(r.future,
                             exc=RuntimeError("MicroBatcher closed"))
                    dropped.append(r)
            self._cv.notify_all()
        for r in dropped:
            self._emit_failed(r, "MicroBatcherClosed")
        self._worker.join(timeout=30)
