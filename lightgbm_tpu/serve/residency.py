"""Multi-model device residency under a bytes budget.

One process serves N boosters off one device.  Packed tree tensors are
small relative to training state but not free — a fleet of wide
multiclass models can exceed device memory — so residency is explicit:

- engines build lazily on first use and stay resident;
- every build charges the engine's ``packed_nbytes`` against
  ``budget_bytes``; when the budget would overflow, least-recently-used
  UNPINNED engines are evicted (device tensors dropped; the host
  booster is retained, so a later request simply re-packs — and because
  the jitted runners + compile signatures are process-wide
  (models/predictor.stacked_run_fn, engine._COMPILED_SIGS), a re-pack
  with unchanged shapes recompiles NOTHING);
- ``pin()`` exempts hot models from eviction; a pinned set alone
  exceeding the budget is allowed but flagged with a
  ``serve_budget_exceeded`` event (the operator's signal to raise the
  budget or unpin).

Telemetry: ``serve.evictions`` / ``serve.rebuilds`` counters,
``serve.resident_bytes`` / ``serve.resident_models`` gauges,
``serve_eviction`` events.
"""
from __future__ import annotations

import collections
import threading
from typing import Any, Callable, Dict, List, Optional

from .engine import ServingEngine


class ResidencyManager:
    """LRU cache of :class:`ServingEngine` instances under a budget."""

    def __init__(self, budget_bytes: Optional[int] = None,
                 telemetry=None,
                 engine_factory: Optional[Callable[..., ServingEngine]]
                 = None, **engine_knobs: Any):
        self.budget_bytes = None if budget_bytes is None \
            else int(budget_bytes)
        self.tel = telemetry
        self._factory = engine_factory or ServingEngine
        self._knobs = engine_knobs
        self._boosters: Dict[str, Any] = {}
        self._engines: "collections.OrderedDict[str, ServingEngine]" = \
            collections.OrderedDict()      # LRU: oldest first
        self._pinned = set()
        self._builds: Dict[str, int] = {}
        self._lock = threading.RLock()

    # ------------------------------------------------------------------
    def register(self, model_id: str, booster) -> None:
        with self._lock:
            self._boosters[model_id] = booster

    def model_ids(self) -> List[str]:
        with self._lock:
            return list(self._boosters)

    def has(self, model_id: str) -> bool:
        with self._lock:
            return model_id in self._boosters

    @property
    def resident_bytes(self) -> int:
        with self._lock:
            return sum(e.packed_nbytes for e in self._engines.values())

    # ------------------------------------------------------------------
    def get(self, model_id: str) -> ServingEngine:
        """The engine for ``model_id``, building (or re-building after an
        eviction) on demand and touching LRU recency."""
        with self._lock:
            eng = self._engines.get(model_id)
            if eng is not None:
                self._engines.move_to_end(model_id)
                return eng
            booster = self._boosters.get(model_id)
            if booster is None:
                raise KeyError(f"unknown model_id: {model_id!r}")
            eng = self._factory(booster, model_id=model_id,
                                telemetry=self.tel, **self._knobs)
            self._builds[model_id] = self._builds.get(model_id, 0) + 1
            if self._builds[model_id] > 1 and self.tel is not None:
                self.tel.inc("serve.rebuilds")
            self._engines[model_id] = eng
            self._evict_to_budget(keep=model_id)
            self._update_gauges()
            return eng

    def _evict_to_budget(self, keep: str) -> None:
        if self.budget_bytes is None:
            return
        total = sum(e.packed_nbytes for e in self._engines.values())
        while total > self.budget_bytes:
            victim = next((mid for mid in self._engines
                           if mid != keep and mid not in self._pinned),
                          None)
            if victim is None:
                # nothing evictable left (all pinned / just-built): the
                # overflow is deliberate, but it must be visible
                if self.tel is not None:
                    self.tel.event("serve_budget_exceeded",
                                   resident_bytes=total,
                                   budget_bytes=self.budget_bytes)
                return
            freed = self._engines.pop(victim).packed_nbytes
            total -= freed
            if self.tel is not None:
                self.tel.inc("serve.evictions")
                self.tel.event("serve_eviction", model_id=victim,
                               bytes=freed, resident_bytes=total,
                               budget_bytes=self.budget_bytes)

    def _update_gauges(self) -> None:
        if self.tel is not None:
            self.tel.gauge("serve.resident_models", len(self._engines))
            self.tel.gauge("serve.resident_bytes", self.resident_bytes)

    # ------------------------------------------------------- rollover
    def build_candidate(self, model_id: str, booster) -> ServingEngine:
        """Engine for a rollover candidate, built OUTSIDE the resident
        table and WITHOUT the lock held (packing + warmup are the slow
        part and must not stall live dispatches) — install it with
        :meth:`swap`."""
        return self._factory(booster, model_id=model_id,
                             telemetry=self.tel, **self._knobs)

    def swap(self, model_id: str, booster, engine: ServingEngine
             ) -> Optional[ServingEngine]:
        """Atomically replace ``model_id``'s booster + engine (the
        rollover promotion).  The swap is one dict assignment under the
        residency lock: a dispatch already in flight keeps resolving
        against the OLD engine object it holds, every dispatch that
        dequeues after the swap gets the new one — so each request
        resolves against exactly one consistent model version.  Pin
        state is preserved; returns the old engine (dropped by the
        caller once its event is emitted)."""
        with self._lock:
            if model_id not in self._boosters:
                raise KeyError(f"unknown model_id: {model_id!r}")
            old = self._engines.pop(model_id, None)
            self._boosters[model_id] = booster
            self._engines[model_id] = engine
            self._builds[model_id] = self._builds.get(model_id, 0) + 1
            self._evict_to_budget(keep=model_id)
            self._update_gauges()
            return old

    # ------------------------------------------------------------------
    def pin(self, model_id: str) -> None:
        """Exempt from eviction (and make resident now)."""
        self.get(model_id)
        with self._lock:
            self._pinned.add(model_id)

    def unpin(self, model_id: str) -> None:
        with self._lock:
            self._pinned.discard(model_id)

    def evict(self, model_id: str) -> bool:
        """Explicitly drop a model's device tensors (host booster stays
        registered; the next request re-packs)."""
        with self._lock:
            eng = self._engines.pop(model_id, None)
            self._update_gauges()
            return eng is not None

    def resident(self) -> List[str]:
        with self._lock:
            return list(self._engines)

    def resident_engines(self) -> List["ServingEngine"]:
        """Snapshot of the live engine objects (no LRU touch, no
        rebuild) — the batcher's post-batch cost-flush hook iterates
        this off the request latency path."""
        with self._lock:
            return list(self._engines.values())

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "models": list(self._boosters),
                "resident": list(self._engines),
                "pinned": sorted(self._pinned),
                "resident_bytes": self.resident_bytes,
                "budget_bytes": self.budget_bytes,
                "builds": dict(self._builds),
                "engines": {mid: e.stats()
                            for mid, e in self._engines.items()},
            }
