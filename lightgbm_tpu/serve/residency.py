"""Multi-model device residency under a bytes budget, per device.

One process serves N boosters off the serve fleet.  Packed tree
tensors are small relative to training state but not free — a fleet of
wide multiclass models can exceed device memory — so residency is
explicit:

- engines build lazily on first use and stay resident;
- every build charges the engine's ``packed_nbytes`` against
  ``budget_bytes``; when the budget would overflow, least-recently-used
  UNPINNED engines are evicted (device tensors dropped; the host
  booster is retained, so a later request simply re-packs — and because
  the jitted runners + compile signatures are process-wide
  (models/predictor.stacked_run_fn, engine._COMPILED_SIGS), a re-pack
  with unchanged shapes recompiles NOTHING);
- ``pin()`` exempts hot models from eviction; a pinned set alone
  exceeding the budget is allowed but flagged with a
  ``serve_budget_exceeded`` event (the operator's signal to raise the
  budget or unpin).

Fleet mode (``devices=[...]``): one replica table per serve device.
``get(model_id, device)`` returns that device's replica, building it
from the device-0 replica's host-side packing (one pack per model, N
placements — ``ServingEngine(shared=...)``); LRU recency, eviction and
``budget_bytes`` apply PER DEVICE (the budget is each device's
memory, not the fleet's sum).  ``swap`` installs a full replica set in
one critical section, so a fleet rollover is atomic: no mix of model
versions across devices is ever observable.  With ``devices=None``
everything collapses to the single-device pre-fleet behavior.

Telemetry: ``serve.evictions`` / ``serve.rebuilds`` counters,
``serve.resident_bytes`` / ``serve.resident_models`` gauges (plus
``serve.d<i>.resident_bytes`` / ``resident_models`` in fleet mode),
``serve_eviction`` events.
"""
from __future__ import annotations

import collections
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

from .engine import ServingEngine


class ResidencyManager:
    """LRU cache of :class:`ServingEngine` replicas under a per-device
    budget."""

    def __init__(self, budget_bytes: Optional[int] = None,
                 telemetry=None,
                 engine_factory: Optional[Callable[..., ServingEngine]]
                 = None, devices: Optional[Sequence] = None,
                 **engine_knobs: Any):
        self.budget_bytes = None if budget_bytes is None \
            else int(budget_bytes)
        self.tel = telemetry
        self._factory = engine_factory or ServingEngine
        self._knobs = engine_knobs
        # fleet placement: one replica table per device; devices=None
        # = the legacy single-device manager (engines built without
        # placement kwargs, so custom factories keep working unchanged)
        self.devices = list(devices) if devices else None
        self.n_devices = len(self.devices) if self.devices else 1
        self._boosters: Dict[str, Any] = {}
        self._tables: List[
            "collections.OrderedDict[str, ServingEngine]"] = [
            collections.OrderedDict()      # LRU: oldest first
            for _ in range(self.n_devices)]
        self._pinned = set()
        self._builds: Dict[str, int] = {}
        self._lock = threading.RLock()

    # legacy single-table alias (tests/tools introspect it)
    @property
    def _engines(self) -> "collections.OrderedDict[str, ServingEngine]":
        return self._tables[0]

    # ------------------------------------------------------------------
    def register(self, model_id: str, booster) -> None:
        with self._lock:
            self._boosters[model_id] = booster

    def model_ids(self) -> List[str]:
        with self._lock:
            return list(self._boosters)

    def has(self, model_id: str) -> bool:
        with self._lock:
            return model_id in self._boosters

    @property
    def resident_bytes(self) -> int:
        with self._lock:
            return sum(e.packed_nbytes for t in self._tables
                       for e in t.values())

    def resident_bytes_on(self, device: int) -> int:
        with self._lock:
            return sum(e.packed_nbytes
                       for e in self._tables[device].values())

    # ------------------------------------------------------------------
    def _build_key(self, model_id: str, device: int) -> str:
        return model_id if self.devices is None \
            else f"{model_id}@d{device}"

    def _build_locked(self, model_id: str, device: int) -> ServingEngine:
        booster = self._boosters.get(model_id)
        if booster is None:
            raise KeyError(f"unknown model_id: {model_id!r}")
        kw = dict(self._knobs)
        if self.devices is not None:
            kw["device"] = self.devices[device]
            kw["device_index"] = device
            # reuse an existing replica's host-side packing: one pack
            # per model, N device placements
            for t in self._tables:
                if model_id in t:
                    kw["shared"] = t[model_id]
                    break
        eng = self._factory(booster, model_id=model_id,
                            telemetry=self.tel, **kw)
        bk = self._build_key(model_id, device)
        self._builds[bk] = self._builds.get(bk, 0) + 1
        if self._builds[bk] > 1 and self.tel is not None:
            self.tel.inc("serve.rebuilds")
        return eng

    def get(self, model_id: str, device: int = 0) -> ServingEngine:
        """The engine replica for ``model_id`` on ``device``, building
        (or re-building after an eviction) on demand and touching LRU
        recency."""
        with self._lock:
            table = self._tables[device]
            eng = table.get(model_id)
            if eng is not None:
                table.move_to_end(model_id)
                return eng
            eng = self._build_locked(model_id, device)
            table[model_id] = eng
            self._evict_to_budget(device, keep=model_id)
            self._update_gauges()
            return eng

    def _evict_to_budget(self, device: int, keep: str) -> None:
        if self.budget_bytes is None:
            return
        table = self._tables[device]
        total = sum(e.packed_nbytes for e in table.values())
        while total > self.budget_bytes:
            victim = next((mid for mid in table
                           if mid != keep and mid not in self._pinned),
                          None)
            if victim is None:
                # nothing evictable left (all pinned / just-built): the
                # overflow is deliberate, but it must be visible
                if self.tel is not None:
                    self.tel.event("serve_budget_exceeded",
                                   resident_bytes=total,
                                   budget_bytes=self.budget_bytes,
                                   device=device)
                return
            freed = table.pop(victim).packed_nbytes
            total -= freed
            if self.tel is not None:
                self.tel.inc("serve.evictions")
                self.tel.event("serve_eviction", model_id=victim,
                               bytes=freed, resident_bytes=total,
                               budget_bytes=self.budget_bytes,
                               **({} if self.devices is None
                                  else {"device": device}))

    def _resident_ids(self) -> List[str]:
        seen: "collections.OrderedDict[str, None]" = \
            collections.OrderedDict()
        for t in self._tables:
            for mid in t:
                seen.setdefault(mid)
        return list(seen)

    def _update_gauges(self) -> None:
        if self.tel is None:
            return
        self.tel.gauge("serve.resident_models",
                       len(self._resident_ids()))
        self.tel.gauge("serve.resident_bytes", self.resident_bytes)
        if self.devices is not None:
            for d, t in enumerate(self._tables):
                self.tel.gauge(f"serve.d{d}.resident_models", len(t))
                self.tel.gauge(f"serve.d{d}.resident_bytes",
                               sum(e.packed_nbytes for e in t.values()))

    # ------------------------------------------------------- rollover
    def build_candidate(self, model_id: str, booster
                        ) -> Union[ServingEngine,
                                   Dict[int, ServingEngine]]:
        """Engine(s) for a rollover candidate, built OUTSIDE the
        resident tables and WITHOUT the lock held (packing + warmup are
        the slow part and must not stall live dispatches) — install
        with :meth:`swap`.  Fleet mode returns the full replica set
        ``{device_index: engine}`` (one shared packing); legacy mode a
        single engine."""
        if self.devices is None:
            return self._factory(booster, model_id=model_id,
                                 telemetry=self.tel, **self._knobs)
        base = self._factory(booster, model_id=model_id,
                             telemetry=self.tel,
                             device=self.devices[0], device_index=0,
                             **self._knobs)
        replicas = {0: base}
        for d in range(1, self.n_devices):
            replicas[d] = self._factory(
                booster, model_id=model_id, telemetry=self.tel,
                device=self.devices[d], device_index=d, shared=base,
                **self._knobs)
        return replicas

    def swap(self, model_id: str, booster,
             engine: Union[ServingEngine, Dict[int, ServingEngine]]
             ) -> Optional[ServingEngine]:
        """Atomically replace ``model_id``'s booster + engine replicas
        (the rollover promotion).  The swap is one critical section
        under the residency lock covering EVERY device's table: a
        dispatch already in flight keeps resolving against the OLD
        engine object it holds, every dispatch that dequeues after the
        swap — on any device — gets the new version; no device ever
        serves a different version than its peers.  Pin state is
        preserved; returns the old device-0 engine (dropped by the
        caller once its event is emitted)."""
        replicas = engine if isinstance(engine, dict) else {0: engine}
        with self._lock:
            if model_id not in self._boosters:
                raise KeyError(f"unknown model_id: {model_id!r}")
            self._boosters[model_id] = booster
            old = None
            for d, t in enumerate(self._tables):
                o = t.pop(model_id, None)
                if d == 0:
                    old = o
            for d, eng in replicas.items():
                self._tables[d][model_id] = eng
                bk = self._build_key(model_id, d)
                self._builds[bk] = self._builds.get(bk, 0) + 1
            for d in replicas:
                self._evict_to_budget(d, keep=model_id)
            self._update_gauges()
            return old

    # ------------------------------------------------------------------
    def pin(self, model_id: str) -> None:
        """Exempt from eviction (and make resident now, on every
        device)."""
        for d in range(self.n_devices):
            self.get(model_id, d)
        with self._lock:
            self._pinned.add(model_id)

    def unpin(self, model_id: str) -> None:
        with self._lock:
            self._pinned.discard(model_id)

    def evict(self, model_id: str) -> bool:
        """Explicitly drop a model's device tensors — every replica
        (host booster stays registered; the next request re-packs)."""
        with self._lock:
            hit = False
            for t in self._tables:
                if t.pop(model_id, None) is not None:
                    hit = True
            self._update_gauges()
            return hit

    def resident(self) -> List[str]:
        with self._lock:
            return self._resident_ids()

    def resident_engines(self) -> List["ServingEngine"]:
        """Snapshot of the live engine objects — every replica — (no
        LRU touch, no rebuild); the batcher's post-batch cost/drift
        flush hooks iterate this off the request latency path."""
        with self._lock:
            return [e for t in self._tables for e in t.values()]

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out = {
                "models": list(self._boosters),
                "resident": self._resident_ids(),
                "pinned": sorted(self._pinned),
                "resident_bytes": self.resident_bytes,
                "budget_bytes": self.budget_bytes,
                "builds": dict(self._builds),
                "engines": {mid: e.stats()
                            for mid, e in self._tables[0].items()},
            }
            if self.devices is not None:
                out["devices"] = self.n_devices
                out["per_device"] = [
                    {"device": d, "resident": list(t),
                     "resident_bytes": sum(e.packed_nbytes
                                           for e in t.values())}
                    for d, t in enumerate(self._tables)]
            return out
