"""Adaptive admission control driven by the live latency distribution.

The static queue bounds (``max_queue_rows``/``max_queue_requests``)
protect memory, but the number an operator actually cares about is the
latency SLO — so the controller closes the loop from the live
``serve.latency_ms`` p95/p99 rings the telemetry registry already
maintains (the same rings the OpenMetrics exporter serves) back onto
the batcher's three levers:

- ``max_delay_ms`` — coalescing delay: halved per escalation level, so
  under pressure requests stop waiting for company they do not need;
- the micro-batch row cap (bucket selection) — halved per level, so
  each device call pads into a SMALLER warmed power-of-two bucket and
  bounds the tail latency it adds (never below ``min_batch_rows``, and
  never a fresh compile: every smaller bucket was AOT-compiled by
  ``warmup()``);
- the shed watermark — an admission bound UNDER the hard queue cap:
  above it, new submits are rejected with ``ServeRejected`` so the
  backlog (and therefore queue wait) cannot grow past what the SLO can
  absorb.

Hysteresis so it cannot flap: escalation needs ``hysteresis``
CONSECUTIVE over-target evaluations, recovery needs ``hysteresis``
consecutive evaluations under ``recover_ratio * target`` — the band in
between resets both streaks, holding the current level.  Every level
change emits a structured ``serve_admission`` event and re-gauges
``serve.admission_level`` / ``serve.max_delay_ms`` /
``serve.shed_watermark_rows``.

The controller runs on the batcher's worker thread (the
``on_batch_done`` hook), time-gated to ``interval_s`` — no extra
threads, and an idle service (no batches) is by definition not
overloaded.  Armed by ``PredictionService(target_p99_ms=...)`` (config
key ``serve_target_p99_ms``); the default 0 keeps it off and the
serving plane byte-for-byte on its pre-overload-hardening behavior.
"""
from __future__ import annotations

import time
from typing import Any, Dict, Optional

_MAX_LEVEL = 4


class AdmissionController:
    """p99-driven hysteresis controller over one MicroBatcher."""

    def __init__(self, batcher, telemetry, target_p99_ms: float,
                 interval_s: float = 0.25, hysteresis: int = 3,
                 min_delay_ms: float = 0.25, min_batch_rows: int = 16,
                 recover_ratio: float = 0.7,
                 dist_name: str = "serve.latency_ms"):
        self.batcher = batcher
        self.tel = telemetry
        self.target_p99_ms = float(target_p99_ms)
        self.interval_s = float(interval_s)
        self.hysteresis = max(1, int(hysteresis))
        self.min_delay_s = float(min_delay_ms) / 1000.0
        self.min_batch_rows = max(1, int(min_batch_rows))
        self.recover_ratio = float(recover_ratio)
        self.dist_name = dist_name
        # the healthy-state operating point the levels divide down from
        self.base_delay_s = batcher.max_delay_s
        self.base_batch_rows = batcher.max_batch_rows
        # watermark base: the configured hard cap, or (unbounded queue)
        # a generous multiple of the batch cap — the watermark exists to
        # bound queue WAIT, which an unbounded queue cannot do alone
        self.base_queue_rows = batcher.max_queue_rows or \
            self.base_batch_rows * 16
        self.level = 0
        self._over = 0
        self._under = 0
        self._last_eval = 0.0

    # ------------------------------------------------------------------
    def _p99(self) -> Optional[float]:
        if self.tel is None:
            return None
        d = self.tel.metrics_snapshot().get("dists", {}) \
            .get(self.dist_name)
        return None if not d else float(d.get("p99", 0.0))

    def step(self, now: Optional[float] = None,
             p99_ms: Optional[float] = None, force: bool = False) -> None:
        """One evaluation (batcher ``on_batch_done`` hook).  ``p99_ms``/
        ``force`` exist for deterministic unit tests; production calls
        pass nothing and are time-gated."""
        if self.target_p99_ms <= 0:
            return
        now = time.perf_counter() if now is None else now
        if not force and now - self._last_eval < self.interval_s:
            return
        self._last_eval = now
        p99 = self._p99() if p99_ms is None else float(p99_ms)
        if p99 is None or p99 <= 0:
            return
        if p99 > self.target_p99_ms:
            self._over += 1
            self._under = 0
        elif p99 < self.target_p99_ms * self.recover_ratio:
            self._under += 1
            self._over = 0
        else:
            # dead band: neither escalate nor recover — the hysteresis
            # core; an oscillating p99 around the target holds level
            self._over = self._under = 0
        if self._over >= self.hysteresis and self.level < _MAX_LEVEL:
            self.level += 1
            self._over = 0
            self._apply("shed", p99)
        elif self._under >= self.hysteresis and self.level > 0:
            self.level -= 1
            self._under = 0
            self._apply("recover", p99)

    # ------------------------------------------------------------------
    def _apply(self, direction: str, p99: float) -> None:
        b = self.batcher
        lv = self.level
        b.max_delay_s = max(self.min_delay_s,
                            self.base_delay_s / (2 ** lv))
        b.max_batch_rows = max(self.min_batch_rows,
                               self.base_batch_rows >> lv)
        # no batch-rows floor here: when the configured hard cap is
        # smaller than a micro-batch, a floored watermark would sit
        # above the cap and be clamped inert — shedding down to a
        # below-one-batch backlog is fine (the oversized-single-on-
        # empty-queue exemption keeps requests flowing)
        b.shed_watermark_rows = None if lv == 0 else max(
            1, self.base_queue_rows >> lv)
        if self.tel is not None:
            self.tel.gauge("serve.admission_level", lv)
            self.tel.gauge("serve.max_delay_ms", b.max_delay_s * 1000.0)
            self.tel.gauge("serve.shed_watermark_rows",
                           b.shed_watermark_rows or 0)
            self.tel.event(
                "serve_admission", level=lv, direction=direction,
                p99_ms=round(p99, 3), target_p99_ms=self.target_p99_ms,
                max_delay_ms=round(b.max_delay_s * 1000.0, 3),
                max_batch_rows=int(b.max_batch_rows),
                shed_watermark_rows=b.shed_watermark_rows)

    def stats(self) -> Dict[str, Any]:
        b = self.batcher
        return {"level": self.level,
                "target_p99_ms": self.target_p99_ms,
                "max_delay_ms": b.max_delay_s * 1000.0,
                "max_batch_rows": int(b.max_batch_rows),
                "shed_watermark_rows": b.shed_watermark_rows}
