"""PredictionService: the public serving facade.

``PredictionService`` owns the three layers (engine, micro-batcher,
residency) plus the telemetry registry, and exposes the two-call API the
north star's "millions of users" half needs::

    import lightgbm_tpu as lgb
    svc = lgb.serve.PredictionService(
        {"churn": "churn_model.txt", "rank": rank_booster},
        max_batch_rows=8192, max_delay_ms=2.0,
        device_budget_bytes=256 << 20, telemetry_out="serve.jsonl",
        metrics_port=9200,                # live OpenMetrics endpoint
        trace_out="serve_trace.json",     # per-request Perfetto spans
        max_queue_rows=65536,             # admission control (bounded
        default_deadline_ms=250.0,        #  queue + dequeue shedding)
        target_p99_ms=50.0,               # adaptive controller
        retry_policy=lgb.serve.RetryPolicy())
    svc.warmup()                          # AOT-compile every bucket
    y = svc.predict("churn", X)           # sync (submit + wait + retry)
    fut = svc.submit("rank", X2, deadline_ms=100)   # future form
    svc.rollover("churn", "churn_v2.txt", shadow_requests=100)
    svc.stats()                           # latency p50/p95/p99, counters
    svc.close(drain_timeout_s=10)

Models may be live ``Booster`` objects (binned device routing through
their training BinMappers), model-file paths / model strings (raw
device routing — no training dataset needed), or a resilience
CHECKPOINT directory (``resilience.state.booster_from_checkpoint`` —
the train→serve rollover source).  A model the device path cannot
represent serves through the host walk with a structured
``serve_degradation`` event, never an error.

Overload & rollover (docs/Serving.md):

- admission control / deadlines / adaptive shedding live in the
  micro-batcher (batcher.py) and the controller (admission.py); every
  knob defaults OFF so an un-configured service behaves exactly like
  the pre-hardening one (``dispatches_per_request == 1.0``,
  ``compiles_per_1k_requests == 0`` contracts untouched);
- ``predict`` retries shed/rejected requests under a
  :class:`~.errors.RetryPolicy` (never compute errors);
- :meth:`rollover` hot-swaps a new model version into residency with
  zero dropped requests: pack + warm OFF the serving thread, optional
  shadow scoring of mirrored traffic, then one atomic swap under the
  residency lock — in-flight batches finish on the old engine, every
  later dispatch gets the new one (``serve_rollover`` event with
  old/new model hashes);
- ``/readyz`` on the metrics exporter reports ready only after
  ``warmup()`` and flips unready during the rollover swap window.
"""
from __future__ import annotations

import os
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np

from ..config import param_default
from ..obs import Telemetry, reqtrace
from .admission import AdmissionController
from .batcher import MicroBatcher
from .errors import RetryPolicy
from .residency import ResidencyManager


def _as_booster(spec):
    from ..basic import Booster
    if isinstance(spec, Booster):
        return spec
    if isinstance(spec, (str, os.PathLike)):
        text = str(spec)
        if os.path.isdir(text):
            # a directory is a resilience checkpoint (root or concrete
            # ckpt_<n>): the train→serve rollover source — trees restore
            # f64-binary-exact and hash-verified into a standalone
            # serving booster (raw device routing)
            from ..resilience.state import booster_from_checkpoint
            return booster_from_checkpoint(text)
        if os.path.exists(text):
            return Booster(model_file=text)
        if text.startswith("tree\n") or "\ntree\n" in text[:200]:
            return Booster(model_str=text)
        raise FileNotFoundError(f"model file not found: {text}")
    raise TypeError(f"cannot serve {type(spec).__name__}; expected "
                    "Booster, model-file path, model string or "
                    "checkpoint directory")


class PredictionService:
    """Micro-batched, multi-model, device-resident prediction server."""

    def __init__(self,
                 boosters_or_paths: Union[Dict[str, Any], List[Any], Any],
                 max_batch_rows: int = 8192,
                 max_delay_ms: float = 2.0,
                 min_bucket_rows: int = 64,
                 device_budget_bytes: Optional[int] = None,
                 raw_score: bool = False,
                 num_iteration: Optional[int] = None,
                 telemetry_out: str = "",
                 batch_events: bool = True,
                 metrics_port: int = 0,
                 trace_out: str = "",
                 memory_watermarks: bool = True,
                 max_queue_rows: Optional[int] = None,
                 max_queue_requests: Optional[int] = None,
                 default_deadline_ms: Optional[float] = None,
                 target_p99_ms: Optional[float] = None,
                 retry_policy: Optional[RetryPolicy] = None,
                 cost_ledger: Optional[str] = None,
                 drift_enabled: Optional[bool] = None,
                 drift_psi_threshold: Optional[float] = None,
                 drift_eval_rows: Optional[int] = None,
                 drift_hysteresis: Optional[int] = None,
                 serve_devices: Optional[int] = None,
                 routing: Optional[str] = None,
                 slo_enabled: Optional[bool] = None,
                 slo_config: Optional[str] = None,
                 slo_tick_period_s: Optional[float] = None,
                 slo_readyz_gating: Optional[bool] = None):
        if isinstance(boosters_or_paths, dict):
            specs = dict(boosters_or_paths)
        elif isinstance(boosters_or_paths, (list, tuple)):
            specs = {str(i): s for i, s in enumerate(boosters_or_paths)}
        else:
            specs = {"default": boosters_or_paths}
        if not specs:
            raise ValueError("PredictionService needs at least one model")

        # admission-control knobs default from the config registry (the
        # single source of truth docs/Parameters.md renders); all 0 =
        # off = the pre-overload-hardening serving contract
        if max_queue_rows is None:
            max_queue_rows = param_default("serve_max_queue_rows")
        if max_queue_requests is None:
            max_queue_requests = param_default("serve_max_queue_requests")
        if default_deadline_ms is None:
            default_deadline_ms = param_default("serve_default_deadline_ms")
        if target_p99_ms is None:
            target_p99_ms = param_default("serve_target_p99_ms")
        if cost_ledger is None:
            cost_ledger = param_default("cost_ledger")
        # drift-monitor knobs (obs/drift.py), defaulted from the config
        # registry: ON unless drift_profile=false, degrading
        # structurally on profile-less artifacts
        if drift_enabled is None:
            drift_enabled = param_default("drift_profile")
        if drift_psi_threshold is None:
            drift_psi_threshold = param_default("drift_psi_threshold")
        if drift_eval_rows is None:
            drift_eval_rows = param_default("drift_eval_rows")
        if drift_hysteresis is None:
            drift_hysteresis = param_default("drift_hysteresis")
        # SLO plane knobs (obs/slo.py), defaulted from the config
        # registry; a spec file implies arming
        if slo_enabled is None:
            slo_enabled = param_default("slo_enabled")
        if slo_config is None:
            slo_config = param_default("slo_config")
        if slo_tick_period_s is None:
            slo_tick_period_s = param_default("slo_tick_period_s")
        if slo_readyz_gating is None:
            slo_readyz_gating = param_default("slo_readyz_gating")
        self._slo_gate = bool(slo_readyz_gating)
        self.slo = None          # SloEngine, armed below after wiring
        self.retry_policy = retry_policy

        # serving fleet (docs/Serving.md "Serving fleet"): replicate
        # each hot model's packed tensors onto N local devices, one
        # dispatch lane per device.  0 = all local devices; 1 = the
        # single-device pre-fleet plane (every legacy contract intact).
        if serve_devices is None:
            serve_devices = param_default("serve_devices")
        if routing is None:
            routing = param_default("serve_routing")
        self.routing = str(routing or "least_loaded")
        import jax
        local = list(jax.local_devices())
        nd = int(serve_devices or 0)
        if nd <= 0:
            nd = len(local)
        nd = max(1, min(nd, len(local)))
        self.n_devices = nd
        self.devices = local[:nd] if nd > 1 else None
        # sharded bulk scorers, built lazily per (model, packed hash)
        self._bulk: Dict[str, Any] = {}
        self._bulk_lock = threading.Lock()

        self.raw_score = bool(raw_score)
        self.tel = Telemetry(enabled=True)
        if telemetry_out:
            self.tel.enable(telemetry_out)
        # request-scoped Perfetto spans (serve track): trace_out turns
        # span collection on; close() writes the timeline
        self._trace_out = str(trace_out or "")
        if self._trace_out:
            self.tel.enable(trace=True)
        self._closed = False
        self._warmed = False
        self._rollover_swapping = False
        self._rollover_lock = threading.Lock()
        self._shadow: Dict[str, Dict[str, Any]] = {}
        # live OpenMetrics endpoint over the serving registry
        # (obs/export.py; rank offset matters when a serving process
        # rides inside a multi-rank job).  /readyz consults _readiness.
        self._metrics = None
        if int(metrics_port or 0) > 0:
            from ..obs.export import MetricsExporter
            self._metrics = MetricsExporter(
                self.tel, int(metrics_port) + self.tel.rank,
                ready_check=self._readiness,
                report_fn=self.run_report)
            self._metrics.start()
        self.residency = ResidencyManager(
            budget_bytes=device_budget_bytes, telemetry=self.tel,
            devices=self.devices,
            max_batch_rows=max_batch_rows,
            min_bucket_rows=min_bucket_rows,
            num_iteration=num_iteration,
            cost_ledger=str(cost_ledger or "hlo"),
            drift_enabled=bool(drift_enabled),
            drift_psi_threshold=float(drift_psi_threshold),
            drift_eval_rows=int(drift_eval_rows),
            drift_hysteresis=int(drift_hysteresis))
        # model freshness: birth instant per model_id, reset on rollover
        # promotion -> the model_age_s gauge in the drift flush
        self._model_born: Dict[str, float] = {}
        for mid, spec in specs.items():
            self.residency.register(str(mid), _as_booster(spec))
            self._model_born[str(mid)] = time.time()
        self.batcher = MicroBatcher(
            self._dispatch_batch, max_batch_rows=max_batch_rows,
            max_delay_ms=max_delay_ms, telemetry=self.tel,
            batch_events=batch_events,
            memory_watermarks=memory_watermarks,
            max_queue_rows=int(max_queue_rows or 0),
            max_queue_requests=int(max_queue_requests or 0),
            default_deadline_ms=float(default_deadline_ms or 0.0),
            n_lanes=self.n_devices, routing=self.routing)
        # post-batch cost-ledger flush: fresh bucket signatures'
        # deferred HLO analyses run on the worker thread after the
        # batch's futures resolved (obs/cost.py; engine.flush_cost)
        self.batcher.cost_flush = self._flush_cost
        # post-batch drift evaluation: PSI math + gauge/event export run
        # on the worker thread after the batch's futures resolved
        self.batcher.drift_flush = self._flush_drift
        # adaptive admission: armed only by a nonzero p99 target; runs
        # on the worker thread via the post-batch hook
        self.admission: Optional[AdmissionController] = None
        if float(target_p99_ms or 0.0) > 0:
            self.admission = AdmissionController(
                self.batcher, self.tel, float(target_p99_ms))
            self.batcher.on_batch_done = self.admission.step
        # SLO plane (obs/slo.py): serving-catalog objectives evaluated
        # on the engine's own daemon ticker over this registry.
        # Host-side snapshot reads only — arming adds zero dispatches.
        if bool(slo_enabled) or str(slo_config or ""):
            from ..obs.slo import SloEngine
            self.slo = SloEngine(
                self.tel, source="serve",
                config_path=str(slo_config or ""),
                tick_period_s=float(slo_tick_period_s or 0.0),
                incident_base=str(telemetry_out or ""),
                context_fn=self._slo_context)
            self.slo.start()
            if self._metrics is not None:
                self._metrics.alerts_fn = self.slo.alerts_payload
        self.tel.event("serve_start", models=list(specs),
                       max_batch_rows=int(max_batch_rows),
                       max_delay_ms=float(max_delay_ms),
                       budget_bytes=device_budget_bytes,
                       max_queue_rows=int(max_queue_rows or 0),
                       max_queue_requests=int(max_queue_requests or 0),
                       default_deadline_ms=float(default_deadline_ms
                                                 or 0.0),
                       target_p99_ms=float(target_p99_ms or 0.0),
                       devices=self.n_devices, routing=self.routing)

    # ------------------------------------------------------------------
    @property
    def metrics_url(self) -> Optional[str]:
        """The live OpenMetrics endpoint (None when ``metrics_port``
        was not set)."""
        return None if self._metrics is None else self._metrics.url

    def _readiness(self) -> Tuple[bool, str]:
        """GET /readyz probe: ready only once ``warmup()`` compiled the
        configured buckets, and unready again during a rollover swap
        window / after close — external load balancers drain on 503."""
        if self._closed:
            return False, "closed"
        if getattr(self.batcher, "_wedged", False):
            return False, "worker_wedged"
        if self._rollover_swapping:
            return False, "rollover_swap"
        if not self._warmed:
            return False, "warmup_pending"
        if self._slo_gate and self.slo is not None:
            # opt-in (slo_readyz_gating): a firing PAGE-severity alert
            # drains this replica at the load balancer while it works
            # through the violation — alive, but not routable
            oid = self.slo.gating_reason()
            if oid is not None:
                return False, f"slo_alert:{oid}"
        return True, "ready"

    def _slo_context(self):
        """Incident-artifact context: the full service stats snapshot
        (per-lane queue/dispatch/spill detail included) plus lineage of
        the resident models — host-side reads only."""
        try:
            ctx = {"stats": self.stats()}
        except Exception as e:
            ctx = {"stats_error": repr(e)}
        ctx["models"] = list(self._model_born)
        return ctx

    def _dispatch_batch(self, model_id: str, X,
                        device: int = 0) -> np.ndarray:
        eng = self.residency.get(model_id, device)
        out = eng.predict(X, raw_score=self.raw_score)
        st = self._shadow.get(model_id)
        if st is not None and st["remaining"] > 0:
            self._score_shadow(st, model_id, X, out)
        return out

    def _score_shadow(self, st: Dict[str, Any], model_id: str, X,
                      out: np.ndarray) -> None:
        """Score a rollover candidate on mirrored live traffic and
        report divergence through the request-trace plane.  Runs on the
        worker thread AFTER the live response is computed; a shadow
        failure must never fail live traffic."""
        try:
            reqtrace.begin_shadow()
            try:
                sout = st["engine"].predict(X, raw_score=self.raw_score)
            finally:
                reqtrace.end_shadow()
            div = 0.0
            if np.asarray(out).size:
                div = float(np.max(np.abs(
                    np.asarray(sout, np.float64)
                    - np.asarray(out, np.float64))))
            st["max_divergence"] = max(st["max_divergence"], div)
            st["requests"] += 1
            st["remaining"] -= 1
            reqtrace.annotate(shadow_divergence=round(div, 9))
            # rollover-divergence feed for the SLO plane
            self.tel.gauge("serve.shadow_divergence", div)
            self.tel.event("serve_shadow", model_id=model_id,
                           divergence=round(div, 9),
                           remaining=int(st["remaining"]),
                           candidate_hash=st["engine"].model_hash[:16])
            if st["remaining"] <= 0:
                st["done"].set()
        except Exception as e:
            st["error"] = repr(e)
            st["done"].set()

    # ------------------------------------------------------------------
    def model_ids(self) -> List[str]:
        return self.residency.model_ids()

    def submit(self, model_id: str, X,
               deadline_ms: Optional[float] = None) -> Future:
        """Future form: enqueue and return immediately.  The returned
        future carries ``future.trace_id`` — the request's identity in
        every ``serve_access`` JSONL record and Perfetto serve-track
        span (docs/Serving.md).  ``deadline_ms`` overrides the
        service-level default: a request still queued past its deadline
        is shed before dispatch with ``ServeDeadlineExceeded``.  Raises
        ``ServeRejected`` synchronously when admission control refuses
        the request (bounded queue / shed watermark)."""
        if self._closed:
            raise RuntimeError("PredictionService is closed")
        model_id = str(model_id)
        if not self.residency.has(model_id):
            raise KeyError(f"unknown model_id: {model_id!r}")
        return self.batcher.submit(model_id, X, deadline_ms=deadline_ms)

    def predict(self, model_id: str, X,
                timeout: Optional[float] = None,
                deadline_ms: Optional[float] = None,
                retry: Optional[RetryPolicy] = None) -> np.ndarray:
        """Sync form: ``submit`` + wait for the micro-batched result.
        When a :class:`RetryPolicy` is supplied (or set service-wide),
        shed/rejected requests are resubmitted under capped exponential
        backoff; compute errors surface immediately, never retried."""
        policy = self.retry_policy if retry is None else retry

        def _once():
            return self.submit(model_id, X,
                               deadline_ms=deadline_ms).result(
                                   timeout=timeout)
        if policy is None:
            return _once()
        return policy.call(_once, telemetry=self.tel)

    def predict_bulk(self, model_id: str, X,
                     raw_score: Optional[bool] = None) -> np.ndarray:
        """Offline/giant-batch scoring: shard_map the jitted traversal
        row-wise over the serve mesh (serve/bulk.py) — every device
        traverses its own row shard against replicated tree stacks,
        bypassing the online micro-batch queues entirely.  Numerically
        interchangeable with :meth:`predict` on the same rows (the f32
        tolerance contract).  Falls back to the single-device engine
        path when the fleet has one device or the model serves
        degraded (host walk)."""
        if self._closed:
            raise RuntimeError("PredictionService is closed")
        model_id = str(model_id)
        if not self.residency.has(model_id):
            raise KeyError(f"unknown model_id: {model_id!r}")
        rs = self.raw_score if raw_score is None else bool(raw_score)
        eng = self.residency.get(model_id, 0)
        if self.devices is None or not eng.device_ok:
            return eng.predict(X, raw_score=rs)
        scorer = self._bulk_scorer(model_id, eng)
        raw = scorer.predict_raw(X)
        from ..basic import finalize_raw_predictions
        b = eng.booster
        return finalize_raw_predictions(raw, eng.k, b.objective,
                                        b.average_output,
                                        eng.num_iteration, rs)

    def _bulk_scorer(self, model_id: str, eng):
        """The cached sharded scorer for ``model_id``, rebuilt whenever
        the resident packed state changed (rollover/refresh)."""
        with self._bulk_lock:
            sc = self._bulk.get(model_id)
            if sc is not None and sc.model_hash == eng.model_hash:
                return sc
            from .bulk import BulkScorer
            sc = BulkScorer(eng, self.devices, telemetry=self.tel)
            self._bulk[model_id] = sc
            return sc

    def warmup(self, buckets: Optional[List[int]] = None,
               model_ids: Optional[List[str]] = None) -> Dict[str, Any]:
        """Pack + AOT-compile every model (or ``model_ids``) for every
        bucket size (or ``buckets``): after this, steady-state serving
        does zero XLA compiles — and ``/readyz`` starts reporting
        ready."""
        out = {}
        for mid in (model_ids or self.model_ids()):
            if self.devices is None:
                out[str(mid)] = self.residency.get(str(mid)) \
                    .warmup(buckets)
            else:
                # every replica warms: per-device executables are
                # distinct jit cache entries, so an unwarmed replica
                # would recompile on its first routed request
                out[str(mid)] = [
                    self.residency.get(str(mid), d).warmup(buckets)
                    for d in range(self.n_devices)]
        self._warmed = True
        return out

    def refresh(self, model_id: str) -> None:
        """Re-pack a model whose underlying (live) booster trained
        further since its engine was built — engines pack a snapshot;
        they do not track later updates."""
        self.residency.evict(str(model_id))
        for d in range(self.n_devices):
            self.residency.get(str(model_id), d)

    # ------------------------------------------------------- rollover
    def rollover(self, model_id: str, new_source,
                 warm: bool = True,
                 shadow_requests: int = 0,
                 shadow_timeout_s: float = 30.0,
                 shadow_abort_threshold: Optional[float] = None
                 ) -> Dict[str, Any]:
        """Zero-downtime model rollover: load a candidate from a
        booster / model file / model string / resilience checkpoint
        directory, pack + warm its buckets OFF the serving thread,
        optionally score it on mirrored live traffic (shadow mode),
        then promote it with ONE atomic swap under the residency lock —
        in-flight and queued requests all resolve against a consistent
        version, zero dropped.

        Shadow mode: ``shadow_requests`` mirrored micro-batches are
        scored on the candidate (divergence = max abs difference vs the
        live response, reported per batch through ``serve_shadow``
        events and the ``shadow_divergence`` field of the live
        requests' ``serve_access`` records).  With
        ``shadow_abort_threshold`` set, the rollover is ABORTED —
        old model keeps serving — when the observed divergence exceeds
        it or the shadow could not complete within
        ``shadow_timeout_s``.

        Returns a report: ``promoted``, ``old_hash``/``new_hash``,
        ``shadow`` stats.  Emits a ``serve_rollover`` event carrying
        both hashes on promotion."""
        if self._closed:
            raise RuntimeError("PredictionService is closed")
        model_id = str(model_id)
        if not self.residency.has(model_id):
            raise KeyError(f"unknown model_id: {model_id!r}")
        with self._rollover_lock:
            booster = _as_booster(new_source)
            old_eng = self.residency.get(model_id)
            old_hash = old_eng.model_hash
            # pack + warm on THIS thread: the serving workers keep
            # dispatching against the old engines the whole time.
            # Fleet mode builds + warms the FULL replica set before the
            # swap — the promotion installs every device's replica in
            # one critical section, never a mixed-version fleet.
            cand = self.residency.build_candidate(model_id, booster)
            replicas = cand if isinstance(cand, dict) else {0: cand}
            cand0 = replicas[0]
            if warm:
                for eng in replicas.values():
                    eng.warmup()
            report: Dict[str, Any] = {
                "model_id": model_id, "promoted": False,
                "old_hash": old_hash[:16],
                "new_hash": cand0.model_hash[:16], "shadow": None}
            if isinstance(new_source, (str, os.PathLike)):
                source_kind = "checkpoint" \
                    if os.path.isdir(str(new_source)) else "file"
            else:
                source_kind = type(new_source).__name__
            if int(shadow_requests) > 0:
                st = {"engine": cand0, "remaining": int(shadow_requests),
                      "requests": 0, "max_divergence": 0.0,
                      "done": threading.Event()}
                self._shadow[model_id] = st
                completed = st["done"].wait(float(shadow_timeout_s))
                self._shadow.pop(model_id, None)
                shadow_rep = {
                    "requests": int(st["requests"]),
                    "max_divergence": float(st["max_divergence"]),
                    "completed": bool(completed and "error" not in st)}
                if "error" in st:
                    shadow_rep["error"] = st["error"]
                report["shadow"] = shadow_rep
                if shadow_abort_threshold is not None and (
                        not shadow_rep["completed"]
                        or shadow_rep["max_divergence"]
                        > float(shadow_abort_threshold)):
                    self.tel.inc("serve.rollover_aborts")
                    self.tel.event(
                        "serve_rollover_aborted", model_id=model_id,
                        old_hash=old_hash[:16],
                        new_hash=cand0.model_hash[:16],
                        **{f"shadow_{k}": v
                           for k, v in shadow_rep.items()})
                    return report
            # the swap window: /readyz flips unready so external load
            # balancers drain; the swap itself is one dict assignment
            self._rollover_swapping = True
            try:
                self.residency.swap(model_id, booster, cand)
            finally:
                self._rollover_swapping = False
            with self._bulk_lock:
                # the packed state changed: the sharded bulk scorer
                # rebuilds from the new replica on its next call
                self._bulk.pop(model_id, None)
            self.tel.inc("serve.rollovers")
            # lineage chain: the incumbent's provenance becomes the
            # candidate's serving parent — training run_id -> checkpoint
            # -> rollover is one reconstructible chain in the event log
            old_b = None
            try:
                old_b = getattr(old_eng, "booster", None)
            except Exception:
                pass
            old_prov = getattr(old_b, "provenance", None) or {}
            new_prov = getattr(booster, "provenance", None) or {}
            self.tel.event("serve_rollover", model_id=model_id,
                           old_hash=old_hash[:16],
                           new_hash=cand0.model_hash[:16],
                           source=source_kind,
                           warmed=bool(warm),
                           devices=len(replicas),
                           shadow=report["shadow"],
                           old_run_id=str(old_prov.get("run_id", "")),
                           new_run_id=str(new_prov.get("run_id", "")),
                           new_parent_checkpoint=str(
                               new_prov.get("parent_checkpoint", ""))[:16],
                           new_profile_digest=str(
                               new_prov.get("profile_digest", ""))[:16])
            self._model_born[model_id] = time.time()
            self.tel.gauge(f"serve.model_age_s.{model_id}", 0.0)
            report["promoted"] = True
            return report

    def pin(self, model_id: str) -> None:
        self.residency.pin(str(model_id))

    def unpin(self, model_id: str) -> None:
        self.residency.unpin(str(model_id))

    # ------------------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        """Operator view: request/batch/dispatch/compile counters, the
        latency and batch-size distributions (p50/p95/p99), admission
        and residency state.  ``dispatches_per_request`` and
        ``compiles_per_1k_requests`` are the two deterministic numbers
        ``bench.py --serve`` gates on."""
        snap = self.tel.snapshot()
        c = snap.get("counters", {})
        requests = int(c.get("serve.requests", 0))
        out: Dict[str, Any] = {
            "requests": requests,
            "rows": int(c.get("serve.rows", 0)),
            "batches": int(c.get("serve.batches", 0)),
            "dispatches": int(c.get("serve.dispatches", 0)),
            "compiles": int(c.get("serve.compiles", 0)),
            "warmup_dispatches": int(c.get("serve.warmup_dispatches", 0)),
            "warmup_compiles": int(c.get("serve.warmup_compiles", 0)),
            "evictions": int(c.get("serve.evictions", 0)),
            "rebuilds": int(c.get("serve.rebuilds", 0)),
            "degradations": int(c.get("serve.degradations", 0)),
            "host_rows": int(c.get("serve.host_rows", 0)),
            "rejected": int(c.get("serve.rejected", 0)),
            "shed": int(c.get("serve.shed", 0)),
            "retries": int(c.get("serve.retries", 0)),
            "rollovers": int(c.get("serve.rollovers", 0)),
            "queue_depth": snap.get("gauges", {}).get(
                "serve.queue_depth", 0),
            "queue_peak_requests": snap.get("gauges", {}).get(
                "serve.queue_peak_requests", 0),
            "latency_ms": snap.get("dists", {}).get(
                "serve.latency_ms"),
            "batch_rows": snap.get("dists", {}).get("serve.batch_rows"),
            "residency": self.residency.stats(),
        }
        if self.admission is not None:
            out["admission"] = self.admission.stats()
        g = snap.get("gauges", {})
        out["drift"] = {
            "alerts": int(c.get("drift.alerts", 0)),
            "evaluations": int(c.get("drift.evaluations", 0)),
            "unavailable": int(c.get("drift.unavailable", 0)),
            "psi_max": float(g.get("drift.psi_max", 0.0)),
            "score_psi": float(g.get("drift.score_psi", 0.0)),
        }
        if requests > 0:
            # steady-state rates: warmup's deliberate dispatches/compiles
            # must not read as a bucketing or recompile regression
            out["dispatches_per_request"] = round(
                max(0, out["dispatches"] - out["warmup_dispatches"])
                / requests, 6)
            out["compiles_per_1k_requests"] = round(
                max(0, out["compiles"] - out["warmup_compiles"])
                * 1000.0 / requests, 6)
        if self.devices is not None:
            # fleet view: the per-device deterministic contract
            # (dispatches_per_request == 1.0, compiles_per_1k == 0 on
            # EVERY device that took traffic) the serve-fleet CI gates
            per = []
            for i in range(self.n_devices):
                d_req = int(c.get(f"serve.d{i}.requests", 0))
                d_disp = int(c.get(f"serve.d{i}.dispatches", 0))
                d_comp = int(c.get(f"serve.d{i}.compiles", 0))
                d_wd = int(c.get(f"serve.d{i}.warmup_dispatches", 0))
                d_wc = int(c.get(f"serve.d{i}.warmup_compiles", 0))
                ent: Dict[str, Any] = {
                    "device": i, "requests": d_req,
                    "rows": int(c.get(f"serve.d{i}.rows", 0)),
                    "batches": int(c.get(f"serve.d{i}.batches", 0)),
                    "dispatches": d_disp, "compiles": d_comp,
                    "warmup_dispatches": d_wd, "warmup_compiles": d_wc,
                    "spills": int(c.get(f"serve.d{i}.spills", 0)),
                    "queue_depth": snap.get("gauges", {}).get(
                        f"serve.d{i}.queue_depth", 0)}
                if d_req > 0:
                    ent["dispatches_per_request"] = round(
                        max(0, d_disp - d_wd) / d_req, 6)
                    ent["compiles_per_1k_requests"] = round(
                        max(0, d_comp - d_wc) * 1000.0 / d_req, 6)
                per.append(ent)
            out["fleet"] = {
                "devices": self.n_devices,
                "routing": self.routing,
                "routed_devices": sum(1 for e in per
                                      if e["requests"] > 0),
                "spills": int(c.get("serve.spills", 0)),
                "bulk_rows": int(c.get("serve.bulk_rows", 0)),
                "bulk_dispatches": int(
                    c.get("serve.bulk_dispatches", 0)),
                "bulk_compiles": int(c.get("serve.bulk_compiles", 0)),
                "per_device": per}
        return out

    def _flush_cost(self) -> None:
        """Batcher post-batch hook: run every resident engine's queued
        cost analyses (obs/cost.py) off the request latency path.  Must
        never raise into the worker."""
        try:
            for eng in self.residency.resident_engines():
                eng.flush_cost()
        except Exception:
            pass

    def _flush_drift(self) -> None:
        """Batcher post-batch hook: evaluate every resident engine's
        drift monitor (rate-limited inside the monitor by
        drift_eval_rows) and export gauges/events.  Host-side numpy
        only — the serving dispatch counters are untouched.  Must never
        raise into the worker."""
        try:
            now = time.time()
            seen_monitors = set()
            for eng in self.residency.resident_engines():
                age = now - self._model_born.get(eng.model_id, now)
                self.tel.gauge(f"serve.model_age_s.{eng.model_id}",
                               round(age, 3))
                # fleet replicas share one monitor per model — evaluate
                # it once per flush, not once per device
                if eng.drift is None or id(eng.drift) in seen_monitors:
                    continue
                seen_monitors.add(id(eng.drift))
                res = eng.drift.evaluate()
                if res is None:
                    continue
                worst_feat, worst_psi = -1, 0.0
                for fi, v in res["psi"].items():
                    self.tel.gauge(f"drift.psi.f{fi}", round(v, 6))
                    if v >= worst_psi:
                        worst_feat, worst_psi = int(fi), float(v)
                self.tel.gauge("drift.score_psi",
                               round(res["score_psi"], 6))
                self.tel.gauge("drift.psi_max", round(res["psi_max"], 6))
                self.tel.inc("drift.evaluations")
                self.tel.event("drift", model_id=eng.model_id,
                               psi_max=round(res["psi_max"], 6),
                               score_psi=round(res["score_psi"], 6),
                               rows=int(res["rows"]),
                               model_age_s=round(age, 3))
                if res["alert"]:
                    self.tel.inc("drift.alerts")
                    self.tel.event(
                        "drift_alert", model_id=eng.model_id,
                        psi_max=round(res["psi_max"], 6),
                        worst_feature=worst_feat,
                        worst_psi=round(worst_psi, 6),
                        score_psi=round(res["score_psi"], 6),
                        threshold=eng.drift.psi_threshold,
                        rows=int(res["rows"]))
        except Exception:
            pass

    def lineage(self) -> Dict[str, Any]:
        """Per-model provenance chain — the run report's / ``/snapshot``'s
        ``lineage`` section: each model's embedded provenance record
        (None for pre-plane artifacts) plus its birth time and current
        age."""
        now = time.time()
        out: Dict[str, Any] = {}
        for mid in self.residency.model_ids():
            prov = None
            try:
                booster = self.residency._boosters.get(mid)
                prov = getattr(booster, "provenance", None)
            except Exception:
                pass
            born = self._model_born.get(mid)
            out[mid] = {"provenance": prov,
                        "born_ts": round(born, 3) if born else None,
                        "model_age_s": round(now - born, 3) if born
                        else None}
        return out

    def run_report(self) -> Dict[str, Any]:
        """Consolidated run report over the serving registry — the
        exporter's ``GET /report`` source, same schema as training's
        ``run_report_out`` artifact with the serving stats attached."""
        from ..obs import report as report_mod
        return report_mod.build_report(
            self.tel.snapshot(), run_id=self.tel.run_id,
            rank=self.tel.rank,
            extra={"serve": self.stats(), "lineage": self.lineage()})

    # ------------------------------------------------------------------
    def close(self, drain: bool = True,
              drain_timeout_s: Optional[float] = None) -> None:
        """Stop the worker (serving queued requests first when
        ``drain``, bounded by ``drain_timeout_s`` — under overload the
        remaining queue is shed with structured errors rather than
        blocking shutdown indefinitely), emit the final ``serve_stats``
        event and flush."""
        if self._closed:
            return
        self._closed = True
        if self.slo is not None:
            # final forced evaluation (a resolved-by-shutdown alert
            # still records its cycle), then stop the ticker
            try:
                self.slo.step(force=True)
            except Exception:
                pass
            self.slo.stop()
        self.batcher.close(drain=drain, drain_timeout_s=drain_timeout_s)
        final = self.stats()
        final.pop("residency", None)
        final.pop("admission", None)
        self.tel.event("serve_stats", **final)
        if self._trace_out:
            from ..obs import trace as trace_mod
            from ..utils import log
            try:
                trace_mod.write_trace(self._trace_out,
                                      [self.tel.drain_spans()])
                log.info("serving trace written to %s", self._trace_out)
            except Exception as e:   # close() must not raise over a dump
                log.warning("serving trace export to %s failed: %s",
                            self._trace_out, e)
        if self._metrics is not None:
            self._metrics.stop()
            self._metrics = None
        self.tel.close()

    def __enter__(self) -> "PredictionService":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
