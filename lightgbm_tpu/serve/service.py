"""PredictionService: the public serving facade.

``PredictionService`` owns the three layers (engine, micro-batcher,
residency) plus the telemetry registry, and exposes the two-call API the
north star's "millions of users" half needs::

    import lightgbm_tpu as lgb
    svc = lgb.serve.PredictionService(
        {"churn": "churn_model.txt", "rank": rank_booster},
        max_batch_rows=8192, max_delay_ms=2.0,
        device_budget_bytes=256 << 20, telemetry_out="serve.jsonl",
        metrics_port=9200,                # live OpenMetrics endpoint
        trace_out="serve_trace.json")     # per-request Perfetto spans
    svc.warmup()                          # AOT-compile every bucket
    y = svc.predict("churn", X)           # sync (submit + wait)
    fut = svc.submit("rank", X2)          # future form (.trace_id set)
    svc.stats()                           # latency p50/p95/p99, counters
    svc.close()

Models may be live ``Booster`` objects (binned device routing through
their training BinMappers) or model-file paths / model strings (raw
device routing — no training dataset needed).  A model the device path
cannot represent serves through the host walk with a structured
``serve_degradation`` event, never an error.
"""
from __future__ import annotations

import os
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Union

import numpy as np

from ..obs import Telemetry
from .batcher import MicroBatcher
from .residency import ResidencyManager


def _as_booster(spec):
    from ..basic import Booster
    if isinstance(spec, Booster):
        return spec
    if isinstance(spec, (str, os.PathLike)):
        text = str(spec)
        if os.path.exists(text):
            return Booster(model_file=text)
        if text.startswith("tree\n") or "\ntree\n" in text[:200]:
            return Booster(model_str=text)
        raise FileNotFoundError(f"model file not found: {text}")
    raise TypeError(f"cannot serve {type(spec).__name__}; expected "
                    "Booster, model-file path or model string")


class PredictionService:
    """Micro-batched, multi-model, device-resident prediction server."""

    def __init__(self,
                 boosters_or_paths: Union[Dict[str, Any], List[Any], Any],
                 max_batch_rows: int = 8192,
                 max_delay_ms: float = 2.0,
                 min_bucket_rows: int = 64,
                 device_budget_bytes: Optional[int] = None,
                 raw_score: bool = False,
                 num_iteration: Optional[int] = None,
                 telemetry_out: str = "",
                 batch_events: bool = True,
                 metrics_port: int = 0,
                 trace_out: str = "",
                 memory_watermarks: bool = True):
        if isinstance(boosters_or_paths, dict):
            specs = dict(boosters_or_paths)
        elif isinstance(boosters_or_paths, (list, tuple)):
            specs = {str(i): s for i, s in enumerate(boosters_or_paths)}
        else:
            specs = {"default": boosters_or_paths}
        if not specs:
            raise ValueError("PredictionService needs at least one model")

        self.raw_score = bool(raw_score)
        self.tel = Telemetry(enabled=True)
        if telemetry_out:
            self.tel.enable(telemetry_out)
        # request-scoped Perfetto spans (serve track): trace_out turns
        # span collection on; close() writes the timeline
        self._trace_out = str(trace_out or "")
        if self._trace_out:
            self.tel.enable(trace=True)
        # live OpenMetrics endpoint over the serving registry
        # (obs/export.py; rank offset matters when a serving process
        # rides inside a multi-rank job)
        self._metrics = None
        if int(metrics_port or 0) > 0:
            from ..obs.export import MetricsExporter
            self._metrics = MetricsExporter(
                self.tel, int(metrics_port) + self.tel.rank)
            self._metrics.start()
        self.residency = ResidencyManager(
            budget_bytes=device_budget_bytes, telemetry=self.tel,
            max_batch_rows=max_batch_rows,
            min_bucket_rows=min_bucket_rows,
            num_iteration=num_iteration)
        for mid, spec in specs.items():
            self.residency.register(str(mid), _as_booster(spec))
        self.batcher = MicroBatcher(
            self._dispatch_batch, max_batch_rows=max_batch_rows,
            max_delay_ms=max_delay_ms, telemetry=self.tel,
            batch_events=batch_events,
            memory_watermarks=memory_watermarks)
        self._closed = False
        self.tel.event("serve_start", models=list(specs),
                       max_batch_rows=int(max_batch_rows),
                       max_delay_ms=float(max_delay_ms),
                       budget_bytes=device_budget_bytes)

    # ------------------------------------------------------------------
    @property
    def metrics_url(self) -> Optional[str]:
        """The live OpenMetrics endpoint (None when ``metrics_port``
        was not set)."""
        return None if self._metrics is None else self._metrics.url

    def _dispatch_batch(self, model_id: str, X) -> np.ndarray:
        return self.residency.get(model_id).predict(
            X, raw_score=self.raw_score)

    # ------------------------------------------------------------------
    def model_ids(self) -> List[str]:
        return self.residency.model_ids()

    def submit(self, model_id: str, X) -> Future:
        """Future form: enqueue and return immediately.  The returned
        future carries ``future.trace_id`` — the request's identity in
        every ``serve_access`` JSONL record and Perfetto serve-track
        span (docs/Serving.md)."""
        if self._closed:
            raise RuntimeError("PredictionService is closed")
        model_id = str(model_id)
        if not self.residency.has(model_id):
            raise KeyError(f"unknown model_id: {model_id!r}")
        return self.batcher.submit(model_id, X)

    def predict(self, model_id: str, X,
                timeout: Optional[float] = None) -> np.ndarray:
        """Sync form: ``submit`` + wait for the micro-batched result."""
        return self.submit(model_id, X).result(timeout=timeout)

    def warmup(self, buckets: Optional[List[int]] = None,
               model_ids: Optional[List[str]] = None) -> Dict[str, Any]:
        """Pack + AOT-compile every model (or ``model_ids``) for every
        bucket size (or ``buckets``): after this, steady-state serving
        does zero XLA compiles."""
        out = {}
        for mid in (model_ids or self.model_ids()):
            out[str(mid)] = self.residency.get(str(mid)).warmup(buckets)
        return out

    def refresh(self, model_id: str) -> None:
        """Re-pack a model whose underlying (live) booster trained
        further since its engine was built — engines pack a snapshot;
        they do not track later updates."""
        self.residency.evict(str(model_id))
        self.residency.get(str(model_id))

    def pin(self, model_id: str) -> None:
        self.residency.pin(str(model_id))

    def unpin(self, model_id: str) -> None:
        self.residency.unpin(str(model_id))

    # ------------------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        """Operator view: request/batch/dispatch/compile counters, the
        latency and batch-size distributions (p50/p95/p99) and residency
        state.  ``dispatches_per_request`` and
        ``compiles_per_1k_requests`` are the two deterministic numbers
        ``bench.py --serve`` gates on."""
        snap = self.tel.snapshot()
        c = snap.get("counters", {})
        requests = int(c.get("serve.requests", 0))
        out: Dict[str, Any] = {
            "requests": requests,
            "rows": int(c.get("serve.rows", 0)),
            "batches": int(c.get("serve.batches", 0)),
            "dispatches": int(c.get("serve.dispatches", 0)),
            "compiles": int(c.get("serve.compiles", 0)),
            "warmup_dispatches": int(c.get("serve.warmup_dispatches", 0)),
            "warmup_compiles": int(c.get("serve.warmup_compiles", 0)),
            "evictions": int(c.get("serve.evictions", 0)),
            "rebuilds": int(c.get("serve.rebuilds", 0)),
            "degradations": int(c.get("serve.degradations", 0)),
            "host_rows": int(c.get("serve.host_rows", 0)),
            "queue_depth": snap.get("gauges", {}).get(
                "serve.queue_depth", 0),
            "latency_ms": snap.get("dists", {}).get(
                "serve.latency_ms"),
            "batch_rows": snap.get("dists", {}).get("serve.batch_rows"),
            "residency": self.residency.stats(),
        }
        if requests > 0:
            # steady-state rates: warmup's deliberate dispatches/compiles
            # must not read as a bucketing or recompile regression
            out["dispatches_per_request"] = round(
                max(0, out["dispatches"] - out["warmup_dispatches"])
                / requests, 6)
            out["compiles_per_1k_requests"] = round(
                max(0, out["compiles"] - out["warmup_compiles"])
                * 1000.0 / requests, 6)
        return out

    # ------------------------------------------------------------------
    def close(self, drain: bool = True) -> None:
        """Stop the worker (serving queued requests first when
        ``drain``), emit the final ``serve_stats`` event and flush."""
        if self._closed:
            return
        self._closed = True
        self.batcher.close(drain=drain)
        final = self.stats()
        final.pop("residency", None)
        self.tel.event("serve_stats", **final)
        if self._trace_out:
            from ..obs import trace as trace_mod
            from ..utils import log
            try:
                trace_mod.write_trace(self._trace_out,
                                      [self.tel.drain_spans()])
                log.info("serving trace written to %s", self._trace_out)
            except Exception as e:   # close() must not raise over a dump
                log.warning("serving trace export to %s failed: %s",
                            self._trace_out, e)
        if self._metrics is not None:
            self._metrics.stop()
            self._metrics = None
        self.tel.close()

    def __enter__(self) -> "PredictionService":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
