"""Serving engine: one booster packed once, dispatched many times.

Wraps the stacked-tree device predictors (models/predictor.py) with the
serving-side machinery the training-time batch path never needed:

- **row-count bucketing** — request rows are padded up to a power-of-two
  bucket in ``[min_bucket_rows, max_batch_rows]`` and the result sliced
  back, so after :meth:`ServingEngine.warmup` EVERY request size hits
  the XLA compile cache (zero recompiles on the serving path — the
  per-chunk-shape recompile of the old ``Booster.predict`` device path
  is the exact failure this buys out);
- **deterministic counters** — compiles are counted against a
  process-wide signature registry (variant + static config + operand
  shapes, the same key XLA's jit cache uses), dispatches per device
  call; ``bench.py --serve`` gates on both;
- **graceful degradation** — a booster the device path cannot represent
  (linear trees, categorical vocabulary past the raw-variant cap) serves
  through the host walk instead, with a structured ``serve_degradation``
  event carrying the packer's reason.

File-loaded boosters (no training BinMappers) pack through
:class:`RawDevicePredictor` — raw-value thresholds pre-rounded so
float32-representable inputs route bit-identically to the host walk.
"""
from __future__ import annotations

import hashlib
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..models.predictor import (DevicePredictor, RawDevicePredictor,
                                _round_up_pow2)
from ..obs import reqtrace

# process-wide registry of dispatched jit signatures: the deterministic
# model of XLA's compile cache the serve counters are asserted against.
# Module scope (not per engine) because the jitted runners are module
# scope too — a second model with identical packed shapes, or a rebuilt
# engine after an LRU eviction, reuses the compiled program.
_COMPILED_SIGS = set()
_SIG_LOCK = threading.Lock()


def _is_sparse(X) -> bool:
    from ..basic import _is_scipy_sparse
    return _is_scipy_sparse(X)


def _commit(a, device):
    """Place one packed operand on ``device`` (committed), skipping the
    copy when the buffer already lives there — identity is what the
    byte accounting below keys on, so an alias must stay an alias."""
    if a is None or not hasattr(a, "devices"):
        return a           # python/np scalar operand: jit re-stages it
    try:
        devs = a.devices()
        if len(devs) == 1 and next(iter(devs)) == device:
            return a
    except Exception:
        pass
    import jax
    return jax.device_put(a, device)


class ServingEngine:
    """Device-resident predictor for ONE booster state."""

    def __init__(self, booster, model_id: str = "default",
                 telemetry=None, max_batch_rows: int = 8192,
                 min_bucket_rows: int = 64,
                 start_iteration: int = 0,
                 num_iteration: Optional[int] = None,
                 cost_ledger: str = "hlo",
                 drift_enabled: bool = True,
                 drift_psi_threshold: float = 0.2,
                 drift_eval_rows: int = 512,
                 drift_hysteresis: int = 2,
                 device=None, device_index: int = 0,
                 shared: Optional["ServingEngine"] = None):
        self.booster = booster
        self.model_id = model_id
        self.tel = telemetry
        # fleet placement: ``device`` commits this engine's operand
        # copies (and every dispatch) to ONE local device; ``shared``
        # points at the base replica whose host-side packing this one
        # reuses — one pack per model, N device placements.  Both None
        # = the single-device pre-fleet engine, byte-for-byte.
        self.device = device
        self.device_index = int(device_index)
        self._dtag = None if device is None else f"d{self.device_index}"
        self._owns_pred = shared is None
        booster._drain()
        if shared is not None:
            self.model_hash = shared.model_hash
        else:
            # version identity: every response is attributable to
            # exactly one packed model state (serve_access
            # model_version field, the serve_rollover old/new hashes).
            # rank=-1 skips the health fault salt — this must describe
            # the REAL state.
            from ..obs.health import model_state_hash
            self.model_hash = model_state_hash(booster.models, rank=-1)
        self.k = max(1, booster.num_tree_per_iteration)
        total_iter = len(booster.models) // self.k
        if num_iteration is None:
            num_iteration = (booster.best_iteration
                             if booster.best_iteration > 0 else -1)
        if num_iteration <= 0:
            num_iteration = total_iter - start_iteration
        num_iteration = max(0, min(num_iteration,
                                   total_iter - start_iteration))
        self.lo = start_iteration * self.k
        self.hi = (start_iteration + num_iteration) * self.k
        self.num_iteration = num_iteration

        self.max_bucket = _round_up_pow2(max(2, int(max_batch_rows)))
        self.min_bucket = min(_round_up_pow2(max(2, int(min_bucket_rows))),
                              self.max_bucket)

        self.dispatches = 0
        self.compiles = 0
        self.host_rows = 0
        self._lock = threading.Lock()
        # device-time cost ledger (obs/cost.py): fresh bucket signatures
        # queue a cost analysis at dispatch; the batcher's post-batch
        # hook (flush_cost) runs them OFF the request latency path,
        # warmup flushes inline (cold path anyway).  Mode follows the
        # cost_ledger config key like training's ledger does.
        self._cost = None
        # fleet: post-batch flushes run on EVERY lane worker while other
        # lanes keep dispatching — note/flush serialize on this lock
        self._cost_lock = threading.Lock()
        if telemetry is not None and cost_ledger != "off":
            from ..obs.cost import CostLedger
            self._cost = CostLedger(telemetry, cost_ledger)

        if shared is not None:
            self.variant = shared.variant
            self.pred = shared.pred
            self.device_ok = self.pred is not None and num_iteration > 0
            self.degraded_reason = "" if self.device_ok else \
                (shared.degraded_reason or "no_trees")
        else:
            ts = getattr(booster, "train_set", None)
            if ts is not None and getattr(ts, "_inner", None) is not None:
                self.variant = "binned"
                self.pred = DevicePredictor(booster.models, ts._inner,
                                            self.k)
            else:
                self.variant = "raw"
                self.pred = RawDevicePredictor(
                    booster.models, booster.max_feature_idx + 1, self.k)
            self.device_ok = bool(self.pred.ok) and num_iteration > 0
            self.degraded_reason = "" if self.device_ok else \
                (self.pred.reason or "no_trees")
        if not self.device_ok:
            self.pred = None
            self._resident_nbytes = 0
            if shared is None:
                self._event("serve_degradation", model_id=model_id,
                            reason=self.degraded_reason)
                self._inc("serve.degradations")
        else:
            # [lo, hi) is fixed for the engine's lifetime: slice the
            # packed operands ONCE (per-dispatch re-slicing would be
            # ~10 eager device ops per micro-batch — the exact overhead
            # this engine exists to amortize) and derive the signature
            # base the per-bucket compile-cache key extends
            ops = self.pred.run_args(self.lo, self.hi)
            if device is not None:
                ops = tuple(_commit(a, device) for a in ops)
            self._operands = ops
            # honest byte accounting (audited against live device
            # buffers in tests/test_serve_fleet.py): the base packing
            # is charged once, to the engine that owns it; operand
            # buffers that are NOT the packed arrays themselves
            # (sub-range slices, replica copies on another device) are
            # charged on top.  The old estimate summed pred.packed
            # regardless, missing the duplicate-slice / replica bytes.
            packed_ids = {id(x) for x in self.pred._packed
                          if x is not None}
            extra = sum(int(a.nbytes) for a in self._operands
                        if a is not None and hasattr(a, "devices")
                        and id(a) not in packed_ids)
            self._resident_nbytes = extra + (
                self.pred.packed_nbytes if self._owns_pred else 0)
            self._sig_base = (
                self.pred.variant, self.k, self.pred.max_steps,
                # the encoded-rows operand's width/dtype fork compiled
                # programs too — tree-stack shapes alone are not enough
                self.pred.enc_width, self.pred.enc_dtype,
                # committed placements fork executables per device —
                # the registry must model that or the per-replica
                # warmup compiles would read as cache hits
                None if device is None else getattr(
                    device, "id", self.device_index),
                tuple(None if a is None or not hasattr(a, "shape")
                      else (tuple(a.shape), str(getattr(a, "dtype", "")))
                      for a in self._operands))
        self._event("serve_model_loaded", model_id=model_id,
                    variant=self.variant, device=self.device_ok,
                    trees=self.hi - self.lo,
                    bytes=self.packed_nbytes,
                    **({} if self._dtag is None
                       else {"device_index": self.device_index}))

        # drift monitor (obs/drift.py): fed host-side from batches this
        # engine already encoded/predicted — zero extra device
        # dispatches.  A pre-plane artifact (no embedded profile)
        # degrades structurally: one drift_unavailable event, never an
        # exception.
        self.drift = None
        self._warming = False
        profile = getattr(booster, "data_profile", None)
        if shared is not None:
            # replicas share ONE monitor (it locks internally): drift
            # is a per-model signal, not a per-device one
            self.drift = shared.drift
        elif drift_enabled:
            if profile:
                from ..obs.drift import DriftMonitor
                self.drift = DriftMonitor(
                    profile, psi_threshold=drift_psi_threshold,
                    eval_rows=drift_eval_rows,
                    hysteresis=drift_hysteresis)
            else:
                self._event("drift_unavailable", model_id=model_id,
                            reason="no_embedded_profile")
                self._inc("drift.unavailable")

    # ------------------------------------------------------- telemetry
    def _inc(self, name: str, v: float = 1) -> None:
        if self.tel is not None:
            self.tel.inc(name, v)

    def _event(self, name: str, **attrs: Any) -> None:
        if self.tel is not None:
            self.tel.event(name, **attrs)

    # ------------------------------------------------------------------
    @property
    def packed_nbytes(self) -> int:
        """Device bytes THIS engine keeps alive (base packing if it
        owns it + any slice/replica operand copies) — the residency
        manager's per-device accounting unit."""
        return 0 if self.pred is None else self._resident_nbytes

    def buckets(self) -> List[int]:
        """All power-of-two bucket sizes this engine pads into."""
        out, b = [], self.min_bucket
        while b < self.max_bucket:
            out.append(b)
            b <<= 1
        out.append(self.max_bucket)
        return out

    def bucket_for(self, rows: int) -> int:
        return min(self.max_bucket,
                   max(self.min_bucket, _round_up_pow2(max(2, rows))))

    def _signature(self, bucket: int):
        """Cache key of one bucketed dispatch — mirrors what XLA keys its
        jit cache on: runner identity + static args + operand
        shapes/dtypes (tree-stack dims, feature width, cat mask)."""
        return self._sig_base + (bucket,)

    # ------------------------------------------------------------------
    def warmup(self, buckets: Optional[List[int]] = None) -> Dict[str, Any]:
        """AOT-compile the bucketed traversal for every ``buckets`` size
        (default: all of :meth:`buckets`) by dispatching a zero batch
        and blocking on the result.  After warmup, any request stream
        whose per-chunk row counts pad into the warmed buckets incurs
        zero recompiles."""
        import jax
        if not self.device_ok:
            return {"warmed": [], "compiles": 0, "degraded": True}
        compiles_before, dispatches_before = self.compiles, self.dispatches
        warmed = []
        # warmup feeds synthetic zero rows — keep them out of the drift
        # histograms (the monitor watches real traffic only)
        self._warming = True
        try:
            for b in sorted(set(buckets or self.buckets())):
                b = self.bucket_for(b)
                if b in warmed:
                    continue
                enc = self._encode_pad(np.zeros(
                    (1, self.booster.max_feature_idx + 1), np.float32), b)
                jax.block_until_ready(self._dispatch(enc, b))
                warmed.append(b)
        finally:
            self._warming = False
        n = self.compiles - compiles_before
        # warmup is the cold path: run the queued cost analyses inline
        # so steady-state traffic starts with the ledger settled
        self.flush_cost()
        # warmup activity is accounted separately so steady-state rates
        # (dispatches_per_request, compiles_per_1k_requests) can be
        # computed off the lifetime counters without warmup skew
        nd = self.dispatches - dispatches_before
        self._inc("serve.warmup_compiles", n)
        self._inc("serve.warmup_dispatches", nd)
        if self._dtag:
            # per-device warmup accounting: the fleet's per-device
            # steady-state rates subtract these, same as the aggregate
            self._inc(f"serve.{self._dtag}.warmup_compiles", n)
            self._inc(f"serve.{self._dtag}.warmup_dispatches", nd)
        self._event("serve_warmup", model_id=self.model_id,
                    buckets=warmed, compiles=n,
                    **({} if self._dtag is None
                       else {"device_index": self.device_index}))
        return {"warmed": warmed, "compiles": n, "degraded": False}

    def _encode_pad(self, Xc: np.ndarray, bucket: int) -> np.ndarray:
        enc = self.pred.encode(Xc)
        if enc.shape[0] < bucket:
            pad = np.zeros((bucket - enc.shape[0], enc.shape[1]),
                           enc.dtype)
            enc = np.concatenate([enc, pad], axis=0)
        return enc

    def _dispatch(self, enc: np.ndarray, bucket: int):
        import jax
        import jax.numpy as jnp

        from ..models.predictor import stacked_run_fn
        sig = self._signature(bucket)
        with _SIG_LOCK:
            fresh = sig not in _COMPILED_SIGS
        t0 = time.perf_counter() if fresh else 0.0
        # committed request buffer: the computation follows the replica's
        # device, not the process default
        enc_dev = jnp.asarray(enc) if self.device is None \
            else jax.device_put(enc, self.device)
        # kind-named anchor span for the roofline plane
        # (obs/kernelstats.py): a profile window over serving attributes
        # predictor kernels to this bucket's dispatch
        with jax.profiler.TraceAnnotation("serve_bucket"):
            out = stacked_run_fn(self.pred.variant)(
                enc_dev, *self._operands, k=self.k,
                max_steps=self.pred.max_steps)
        # register only AFTER the call returns: a failed first dispatch
        # (transient device error) must not mark the signature compiled,
        # or the successful retry's real compile would count as a cache
        # hit and the zero-recompile gates would go blind to it
        if fresh:
            compile_ms = (time.perf_counter() - t0) * 1000.0
            with _SIG_LOCK:
                if sig in _COMPILED_SIGS:
                    fresh = False      # another thread won the compile
                else:
                    _COMPILED_SIGS.add(sig)
            if fresh:
                with self._lock:
                    self.compiles += 1
                self._inc("serve.compiles")
                if self._dtag:
                    self._inc(f"serve.{self._dtag}.compiles")
                reqtrace.annotate(compiles=1)
                # per-executable compile record: the jit cache key,
                # the first-call wall (trace + XLA compile — the call
                # blocks through compilation before dispatching async)
                # and the bytes the executable's operands pin on device
                sig_hash = hashlib.sha1(
                    repr(sig).encode()).hexdigest()[:12]
                op_bytes = self.packed_nbytes + int(enc.nbytes)
                self._event("serve_compile", model_id=self.model_id,
                            bucket=bucket, variant=self.pred.variant,
                            signature=sig_hash,
                            compile_ms=round(compile_ms, 3),
                            operand_bytes=op_bytes)
                sig_str = (f"serve[{self.pred.variant},bucket={bucket},"
                           f"sig={sig_hash}]")
                if self.tel is not None:
                    self.tel.compile_executable(
                        sig_str, compile_ms, op_bytes,
                        model_id=self.model_id)
                if self._cost is not None:
                    # avals only (shape/dtype) — the np buffer itself
                    # never reaches the ledger, donation-safe
                    with self._cost_lock:
                        self._cost.note(
                            stacked_run_fn(self.pred.variant),
                            (enc,) + tuple(self._operands),
                            sig_str, kind="serve_bucket", scale=bucket,
                            kwargs={"k": self.k,
                                    "max_steps": self.pred.max_steps},
                            operand_bytes=op_bytes,
                            model_id=self.model_id, bucket=bucket)
        with self._lock:
            self.dispatches += 1
        self._inc("serve.dispatches")
        if self._dtag:
            self._inc(f"serve.{self._dtag}.dispatches")
        reqtrace.annotate(dispatches=1, bucket=bucket)
        return out

    # ------------------------------------------------------------------
    def predict_raw(self, X) -> np.ndarray:
        """Raw scores [k, n] float64 over trees [lo, hi)."""
        if not self.device_ok:
            return self._host_predict_raw(X)
        reqtrace.annotate(model_version=self.model_hash[:16])
        sparse_in = _is_sparse(X)
        if sparse_in:
            X = X.tocsr()
        n = X.shape[0]
        out = np.zeros((self.k, n), np.float64)
        for c0 in range(0, n, self.max_bucket):
            sl = slice(c0, min(n, c0 + self.max_bucket))
            Xc = X[sl].toarray() if sparse_in else X[sl]
            rows = Xc.shape[0]
            bucket = self.bucket_for(rows)
            t0 = time.perf_counter()
            enc = self._encode_pad(Xc, bucket)
            raw = self._dispatch(enc, bucket)
            # np.asarray blocks on the device result, so this window is
            # the honest dispatch+execute wall the serve_access record
            # reports per request (summed across an oversized request's
            # chunks)
            out[:, sl] = np.asarray(raw, np.float64)[:, :rows]
            disp_ms = (time.perf_counter() - t0) * 1000.0
            reqtrace.annotate(dispatch_ms=disp_ms)
            if self._dtag and self.tel is not None:
                self.tel.dist(f"serve.{self._dtag}.dispatch_ms", disp_ms)
            self._drift_accumulate(enc[:rows], Xc, out[:, sl])
        return out

    def _drift_accumulate(self, enc, Xc, scores) -> None:
        """Feed the drift monitor from a batch that was ALREADY encoded
        and predicted — pure host numpy, zero device work (the serving
        dispatch/recompile contracts are counter-asserted over this)."""
        drift = self.drift
        if drift is None or self._warming:
            return
        try:
            if enc is not None and self.variant == "binned":
                # binned encode output: int bin indices in used-feature
                # order — exactly the profile's histogram layout
                drift.accumulate(enc)
            elif Xc is not None:
                drift.accumulate_raw(np.asarray(Xc, np.float64))
            drift.accumulate_scores(scores)
        except Exception:
            pass  # monitoring must never fail a prediction

    def _host_predict_raw(self, X) -> np.ndarray:
        """Degraded path: the exact float64 host walk (basic.py
        host_walk_raw — the one shared implementation, with its bounded
        per-chunk sparse densify)."""
        from ..basic import host_walk_raw
        t0 = time.perf_counter()
        reqtrace.annotate(model_version=self.model_hash[:16])
        out = host_walk_raw(self.booster.models, X, self.lo, self.hi,
                            self.k)
        n = X.shape[0]
        with self._lock:
            self.host_rows += n
        self._inc("serve.host_rows", n)
        if not _is_sparse(X):
            self._drift_accumulate(None, X, out)
        reqtrace.annotate(degraded=True,
                          dispatch_ms=(time.perf_counter() - t0) * 1000.0)
        return out

    # ------------------------------------------------------------------
    def predict(self, X, raw_score: bool = False) -> np.ndarray:
        """Final predictions, same output contract as
        ``Booster.predict`` — the tail is basic.finalize_raw_predictions,
        shared with the Booster so the two cannot drift."""
        from ..basic import finalize_raw_predictions
        if not _is_sparse(X) and not isinstance(X, np.ndarray):
            X = np.asarray(X, np.float64)
        if getattr(X, "ndim", 2) == 1:
            X = np.asarray(X).reshape(1, -1)
        b = self.booster
        raw = self.predict_raw(X)
        return finalize_raw_predictions(raw, self.k, b.objective,
                                        b.average_output,
                                        self.num_iteration, raw_score)

    # ------------------------------------------------------------------
    def flush_cost(self) -> None:
        """Run queued cost analyses and refresh the ``cost.serve.*``
        per-row gauges.  Called from warmup and from the batcher's
        post-batch hook — never from inside a request's dispatch."""
        cost = self._cost
        if cost is None or not cost.has_pending:
            return
        # best-effort under contention: another lane worker mid-flush
        # keeps the pending entries for the next post-batch hook
        if not self._cost_lock.acquire(blocking=False):
            return
        try:
            cost.flush()
        finally:
            self._cost_lock.release()
        ent = cost.entry("serve_bucket")
        if ent is not None and self.tel is not None and ent["scale"] > 0:
            self.tel.gauge("cost.serve.flops_per_row",
                           ent["flops"] / ent["scale"])
            self.tel.gauge("cost.serve.hlo_bytes_per_row",
                           ent["hlo_bytes"] / ent["scale"])

    # ------------------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out = {"model_id": self.model_id, "variant": self.variant,
                   "model_hash": self.model_hash[:16],
                   "device": self.device_ok,
                   "degraded_reason": self.degraded_reason,
                   "trees": self.hi - self.lo,
                   "packed_bytes": self.packed_nbytes,
                   "compiles": self.compiles,
                   "dispatches": self.dispatches,
                   "host_rows": self.host_rows,
                   "buckets": self.buckets()}
            if self._dtag is not None:
                out["device_index"] = self.device_index
        if self.drift is not None:
            out["drift"] = {
                "alerts": self.drift.alerts,
                "evaluations": self.drift.evaluations,
                "psi_max": round(float(
                    self.drift.last.get("psi_max", 0.0)), 6),
                "score_psi": round(float(
                    self.drift.last.get("score_psi", 0.0)), 6)}
        return out
