"""Device-resident prediction serving.

The training half of the north star got fast (megastep, donated
buffers); this package is the serving half: trees packed ONCE into the
device-resident stacked tensors ``models/predictor.py`` builds, jitted
traversal with power-of-two row-count bucketing (any request size after
warmup hits the XLA cache — zero recompiles), request micro-batching
with deadline coalescing, and multi-model residency under a bytes
budget.  The shape of the win follows XGBoost's device-resident
predictor (arxiv 1806.11248): keep the model on the accelerator and
amortize dispatch over batched requests.

Layers (docs/Serving.md):

- :class:`ServingEngine` (engine.py) — one packed model: bucketed,
  donated, warmup-compiled device traversal with deterministic
  compile/dispatch counters and graceful degradation to the host walk;
- :class:`MicroBatcher` (batcher.py) — thread-safe request queue with
  ``max_batch_rows`` / ``max_delay_ms`` deadline coalescing, one device
  call per drained micro-batch, future-based responses;
- :class:`ResidencyManager` (residency.py) — N models sharing the
  serve devices under a per-device bytes budget with LRU eviction and
  pin/unpin;
- :class:`BulkScorer` (bulk.py) — row-sharded offline scoring: the
  jitted traversal shard_mapped over the serve mesh with the packed
  stacks as replicated read-only operands
  (``PredictionService.predict_bulk``);
- :class:`PredictionService` (service.py) — the public facade:
  ``PredictionService(boosters_or_paths).predict(model_id, X)``.

Serving fleet (docs/Serving.md "Serving fleet"): with
``serve_devices > 1`` each hot model's packed tensors replicate onto N
local devices, each with its own dispatch lane (queue + worker); the
micro-batcher routes micro-batches to the least-loaded replica, spills
to the coldest lane before shedding, and keeps the per-device
deterministic contract — exactly 1.0 dispatches/request, 0
steady-state recompiles — that ``bench.py --serve`` gates per device.
Rollover swaps all replicas atomically.

Overload hardening (docs/Serving.md "Overload & rollover"): bounded
queues with structured :class:`ServeRejected` admission refusals,
per-request deadlines shed at dequeue (:class:`ServeDeadlineExceeded`),
an adaptive p99-driven :class:`AdmissionController`, client
:class:`RetryPolicy` (shed/reject only, never compute errors),
zero-downtime ``PredictionService.rollover`` with optional shadow
scoring, and wedged-worker detection (:class:`ServeWorkerWedged`).
"""
from .admission import AdmissionController
from .batcher import MicroBatcher
from .bulk import BulkScorer
from .engine import ServingEngine
from .errors import (RetryPolicy, ServeClosed, ServeDeadlineExceeded,
                     ServeError, ServeRejected, ServeWorkerWedged)
from .residency import ResidencyManager
from .service import PredictionService

__all__ = ["PredictionService", "ServingEngine", "MicroBatcher",
           "ResidencyManager", "BulkScorer", "AdmissionController",
           "RetryPolicy", "ServeError", "ServeRejected",
           "ServeDeadlineExceeded", "ServeClosed", "ServeWorkerWedged"]
