"""Structured serving errors + the client retry policy.

Under open-loop overload a serving queue without admission control
grows without bound and p99 diverges; the fix is *structured rejection*
— a refused or shed request must carry machine-readable fields (reason,
retry-after hint, queue state) a client-side policy can act on, not a
bare string.  Every error below extends ``RuntimeError`` so existing
``except RuntimeError`` call sites keep working.

Taxonomy (docs/Serving.md "Overload & rollover"):

- :class:`ServeRejected` — admission refusal AT SUBMIT: the bounded
  queue (``max_queue_rows`` / ``max_queue_requests``, or the adaptive
  controller's shed watermark) is full.  Raised synchronously from
  ``submit()``; carries ``retry_after_ms`` (backlog / measured drain
  rate).  Retryable.
- :class:`ServeDeadlineExceeded` — the request's deadline passed while
  it waited in the queue; it is shed AT DEQUEUE, before any device work
  is spent on it.  Retryable (the service shed it unserved).
- :class:`ServeClosed` — submit after ``close()``, or a queued request
  failed by a bounded drain (``close(drain_timeout_s=)``).  Not
  retryable: the service is going away.
- :class:`ServeWorkerWedged` — the worker thread did not exit within
  the close timeout (stuck inside a device dispatch); queued and
  in-flight futures are failed with this instead of leaking unresolved.
  Not retryable.

Compute errors (a poisoned request, a device failure inside the
dispatch) are deliberately NOT in this hierarchy: they resolve the
affected futures with the original exception, and :class:`RetryPolicy`
never retries them — retrying a deterministic failure only doubles the
damage, mirroring ``resilience/comms.guarded_call``'s
transport-retries-only semantics.
"""
from __future__ import annotations

import time
from typing import Any, Dict, Optional, Tuple


class ServeError(RuntimeError):
    """Base of the structured serving errors; ``details()`` returns the
    machine-readable fields as a plain dict (what the telemetry event
    carries)."""

    def __init__(self, message: str, **fields: Any):
        super().__init__(message)
        self.fields = dict(fields)

    def details(self) -> Dict[str, Any]:
        return {"error": type(self).__name__,
                "message": str(self), **self.fields}


class ServeRejected(ServeError):
    """Admission control refused the request at submit time.

    Fields: ``reason`` (``queue_rows`` / ``queue_requests`` /
    ``shed_watermark``), ``retry_after_ms`` (estimated backlog drain
    time), ``queue_rows``, ``queue_requests``, ``model_id``."""

    def __init__(self, message: str, reason: str = "",
                 retry_after_ms: float = 0.0, **fields: Any):
        super().__init__(message, reason=reason,
                         retry_after_ms=round(float(retry_after_ms), 3),
                         **fields)
        self.reason = reason
        self.retry_after_ms = float(retry_after_ms)


class ServeDeadlineExceeded(ServeError):
    """The request's deadline expired while it was still queued; it was
    shed before dispatch (no device work spent).

    Fields: ``deadline_ms``, ``waited_ms``, ``model_id``,
    ``trace_id``."""

    def __init__(self, message: str, retry_after_ms: float = 0.0,
                 **fields: Any):
        super().__init__(message,
                         retry_after_ms=round(float(retry_after_ms), 3),
                         **fields)
        self.retry_after_ms = float(retry_after_ms)


class ServeClosed(ServeError):
    """The service/batcher is closed (or a bounded drain gave up on the
    remaining queue)."""


class ServeWorkerWedged(ServeError):
    """The batcher worker did not exit within the close timeout —
    wedged inside a dispatch.  Queued + in-flight futures are failed
    with this so nothing leaks unresolved."""


#: errors a retry can reasonably help with: the service refused or shed
#: the request WITHOUT doing its work.  Everything else (compute
#: errors, closed service, wedged worker) must surface immediately.
RETRYABLE = (ServeRejected, ServeDeadlineExceeded)


class RetryPolicy:
    """Capped-exponential-backoff retry for ``PredictionService.predict``.

    Retries ONLY on shed/reject (:data:`RETRYABLE`) — never on compute
    errors — with ``backoff = base * multiplier**attempt`` capped at
    ``max_backoff_ms``, and honors a larger server-provided
    ``retry_after_ms`` hint when one rides the error.  The serving
    analog of ``resilience/comms.guarded_call``: bounded attempts,
    transient-only, the last failure re-raises untouched.

    ``max_elapsed_s`` additionally bounds the total time spent
    (attempts + sleeps): a client with its own deadline should not
    out-wait it retrying.
    """

    def __init__(self, max_attempts: int = 4,
                 base_backoff_ms: float = 5.0,
                 backoff_multiplier: float = 2.0,
                 max_backoff_ms: float = 2000.0,
                 max_elapsed_s: Optional[float] = None):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = int(max_attempts)
        self.base_backoff_ms = float(base_backoff_ms)
        self.backoff_multiplier = float(backoff_multiplier)
        self.max_backoff_ms = float(max_backoff_ms)
        self.max_elapsed_s = (None if max_elapsed_s is None
                              else float(max_elapsed_s))

    # ------------------------------------------------------------------
    def backoff_ms(self, attempt: int,
                   exc: Optional[BaseException] = None) -> float:
        """Sleep before retry number ``attempt`` (0-based): capped
        exponential, never shorter than the service's own
        ``retry_after_ms`` hint (the server knows its backlog better
        than the client's curve does)."""
        b = min(self.max_backoff_ms,
                self.base_backoff_ms * self.backoff_multiplier ** attempt)
        hint = float(getattr(exc, "retry_after_ms", 0.0) or 0.0)
        return max(b, min(hint, self.max_backoff_ms))

    def should_retry(self, exc: BaseException, attempt: int) -> bool:
        return isinstance(exc, RETRYABLE) \
            and attempt + 1 < self.max_attempts

    # ------------------------------------------------------------------
    def call(self, fn, telemetry=None) -> Any:
        """Run ``fn()`` under the policy; returns its result or raises
        the final error.  Telemetry: ``serve.retries`` per retry,
        ``serve.retry_exhausted`` when attempts run out."""
        t0 = time.perf_counter()
        attempt = 0
        while True:
            try:
                return fn()
            except RETRYABLE as exc:
                elapsed = time.perf_counter() - t0
                delay_s = self.backoff_ms(attempt, exc) / 1000.0
                budget_ok = self.max_elapsed_s is None or \
                    (elapsed + delay_s) < self.max_elapsed_s
                if not (self.should_retry(exc, attempt) and budget_ok):
                    if telemetry is not None:
                        telemetry.inc("serve.retry_exhausted")
                    raise
                if telemetry is not None:
                    telemetry.inc("serve.retries")
                time.sleep(delay_s)
                attempt += 1

    def stats(self) -> Tuple[int, float, float]:
        return (self.max_attempts, self.base_backoff_ms,
                self.max_backoff_ms)
