"""Training callbacks.

Behavioral analog of ref: python-package/lightgbm/callback.py (log_evaluation
:65, record_evaluation :96, reset_parameter :147, early_stopping :187).

Drain-replay protocol (docs/Observability.md §9): the megastep fuses
whole boosting iterations into one jit and computes the built-in
metrics ON DEVICE inside the scan, so per-iteration callbacks cannot
run inline — instead the drain replays them in iteration order against
an :class:`EvalResultView` built from the stacked metric matrix.  A
callback is replayable when the factory marked it with
``_megastep_replay`` (our own ``log_evaluation``, ``record_evaluation``,
``early_stopping``, ``record_telemetry`` are); an unmarked callback
evicts training to the classic per-iteration loop with a structured
``megastep_evicted`` telemetry event naming it.
"""
from __future__ import annotations

import collections
from typing import Any, Callable, Dict, List, Optional, Union

from .utils import log


class EarlyStopException(Exception):
    """(ref: callback.py:14)"""

    def __init__(self, best_iteration: int, best_score):
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


CallbackEnv = collections.namedtuple(
    "CallbackEnv",
    ["model", "params", "iteration", "begin_iteration", "end_iteration",
     "evaluation_result_list"])


def log_evaluation(period: int = 1, show_stdv: bool = True):
    """(ref: callback.py:65)"""
    def _callback(env: CallbackEnv) -> None:
        if period > 0 and env.evaluation_result_list \
                and (env.iteration + 1) % period == 0:
            result = "\t".join(
                f"{name}'s {metric}: {value:g}"
                for name, metric, value, _ in env.evaluation_result_list)
            log.info("[%d]\t%s", env.iteration + 1, result)
    _callback.order = 10
    _callback._megastep_replay = "log_evaluation"
    return _callback


def record_evaluation(eval_result: Dict[str, Dict[str, List[float]]]):
    """(ref: callback.py:96)"""
    if not isinstance(eval_result, dict):
        raise TypeError("eval_result should be a dictionary")

    def _init(env: CallbackEnv) -> None:
        eval_result.clear()
        for name, metric, _, _ in env.evaluation_result_list:
            eval_result.setdefault(name, collections.OrderedDict()) \
                .setdefault(metric, [])

    def _callback(env: CallbackEnv) -> None:
        if not eval_result:
            _init(env)
        for name, metric, value, _ in env.evaluation_result_list:
            eval_result[name][metric].append(value)
    _callback.order = 20
    _callback._megastep_replay = "record_evaluation"

    # checkpoint/resume hooks (resilience/state.py): the recorded curve
    # continues across a resume instead of restarting at the boundary
    def _cb_state():
        return {name: {m: [float(v) for v in vals]
                       for m, vals in metrics.items()}
                for name, metrics in eval_result.items()}

    def _cb_restore(st, env) -> None:
        eval_result.clear()
        for name, metrics in (st or {}).items():
            eval_result[name] = collections.OrderedDict(
                (m, list(vals)) for m, vals in metrics.items())
    _callback._cb_state = _cb_state
    _callback._cb_restore = _cb_restore
    return _callback


def record_telemetry(telemetry_result: Dict[str, Any]):
    """Record per-iteration telemetry into ``telemetry_result``
    (symmetric with :func:`record_evaluation`, but fed by the obs
    registry instead of the eval loop).

    Enables the booster's telemetry registry before the first iteration
    runs (so iteration 0 is covered), then drains completed training
    records into ``telemetry_result["iterations"]`` as training
    progresses; at the end of ``engine.train`` the finalize hook drains
    the tail and stores the registry snapshot (counters, gauges, timing
    distributions, recent events) under ``telemetry_result["summary"]``.

    Record shape follows ``telemetry_granularity`` (docs/Observability.md):
    at the default ``batch`` a fast-path run yields one ``megastep``
    record per drained batch (covering up to 32 iterations; the
    synchronous driver — engine ``xla``, DART/GOSS/RF, custom ``fobj``,
    ... — still yields per-iteration ``iteration`` records); set
    ``telemetry_granularity=iteration`` or ``section`` for one record
    per iteration with whole-iteration or per-section times.
    """
    if not isinstance(telemetry_result, dict):
        raise TypeError("telemetry_result should be a dictionary")

    def _registry(env):
        # plain Booster only: CVBooster proxies attribute access, so read
        # the instance dict (sub-boosters each own a registry)
        gb = env.model.__dict__.get("_gbdt")
        return None if gb is None else gb.telemetry

    def _drain(tel) -> None:
        recs = tel.drain_records()
        if recs:
            telemetry_result.setdefault("iterations", []).extend(recs)

    def _callback(env: CallbackEnv) -> None:
        tel = _registry(env)
        if tel is None:
            return
        if not tel.enabled:
            tel.enable()
        _drain(tel)
    _callback.before_iteration = True
    _callback.order = 5
    # replayable: the registry drain is order-insensitive, and enabling
    # telemetry at the default batch granularity keeps the fast path
    _callback._megastep_replay = "record_telemetry"

    def _finalize(env: CallbackEnv) -> None:
        tel = _registry(env)
        if tel is None:
            return
        _drain(tel)
        snap = tel.snapshot()
        telemetry_result["summary"] = snap
        # surface the health/guard findings (anomaly events, rank
        # divergence, stragglers) as a first-class list — callers
        # checking run health should not have to sift the event ring.
        # The registry keeps findings in their own ring, so early ones
        # survive long runs that evict them from the general event ring;
        # the key is always present (empty list == healthy run)
        telemetry_result["anomalies"] = snap.get("findings", [])
    _callback.finalize = _finalize
    return _callback


def reset_parameter(**kwargs: Union[list, Callable[[int], Any]]):
    """Reset parameters on schedule, e.g.
    ``reset_parameter(learning_rate=lambda i: 0.1 * 0.99 ** i)``
    (ref: callback.py:147)."""
    def _callback(env: CallbackEnv) -> None:
        it = env.iteration - env.begin_iteration
        n_rounds = env.end_iteration - env.begin_iteration
        updates = {}
        for name, schedule in kwargs.items():
            if isinstance(schedule, list):
                if len(schedule) != n_rounds:
                    raise ValueError(
                        f"the schedule list for {name!r} needs one entry "
                        f"per boosting round ({n_rounds})")
                target = schedule[it]
            else:
                target = schedule(it)
            if env.params.get(name) != target:
                updates[name] = target
        if updates:
            env.model.reset_parameter(updates)
            env.params.update(updates)
    _callback.before_iteration = True
    _callback.order = 10
    return _callback


def early_stopping(stopping_rounds: int, first_metric_only: bool = False,
                   verbose: bool = True, min_delta: Union[float, list] = 0.0):
    """(ref: callback.py:187)"""
    best_score: List[float] = []
    best_iter: List[int] = []
    best_score_list: List[list] = []
    cmp_op: List[Callable] = []
    enabled = [True]
    first_metric = [""]

    def _init(env: CallbackEnv) -> None:
        enabled[0] = not any(
            env.params.get(alias, "") == "dart"
            for alias in ("boosting", "boosting_type", "boost"))
        if not enabled[0]:
            log.warning("Early stopping is not available in dart mode")
            return
        if not env.evaluation_result_list:
            raise ValueError(
                "For early stopping, at least one dataset and eval metric is "
                "required for evaluation")
        if stopping_rounds <= 0:
            raise ValueError("stopping_rounds should be greater than zero.")
        if verbose:
            log.info("Training until validation scores don't improve for %d "
                     "rounds", stopping_rounds)

        # min_delta broadcast: a scalar applies everywhere; a list gives
        # one threshold per metric, tiled across datasets
        n_metrics = len({m[1] for m in env.evaluation_result_list})
        n_datasets = len(env.evaluation_result_list) // max(1, n_metrics)
        n_slots = n_datasets * n_metrics
        if isinstance(min_delta, list):
            if any(t < 0 for t in min_delta):
                raise ValueError("early stopping min_delta entries must "
                                 "be >= 0")
            if len(min_delta) == 0:
                deltas = [0.0] * n_slots
            elif len(min_delta) == 1:
                deltas = list(min_delta) * n_slots
            elif len(min_delta) == n_metrics:
                if first_metric_only and verbose:
                    log.info("Using only %s for early stopping",
                             min_delta[0])
                deltas = list(min_delta) * n_datasets
            else:
                raise ValueError("min_delta takes a scalar, a 1-element "
                                 "list, or one value per metric")
        else:
            if min_delta < 0:
                raise ValueError("early stopping min_delta must be >= 0")
            deltas = [min_delta] * n_slots

        first_metric[0] = env.evaluation_result_list[0][1].split(" ")[-1]
        for eval_ret, delta in zip(env.evaluation_result_list, deltas):
            best_iter.append(0)
            best_score_list.append(None)
            if eval_ret[3]:  # is_higher_better
                best_score.append(float("-inf"))
                cmp_op.append(
                    lambda new, best, d=delta: new > best + d)
            else:
                best_score.append(float("inf"))
                cmp_op.append(
                    lambda new, best, d=delta: new < best - d)

    def _final_iteration_check(env, eval_name_splitted, i):
        if env.iteration == env.end_iteration - 1:
            if verbose:
                best = "\t".join(
                    f"{n}'s {m}: {v:g}" for n, m, v, _ in best_score_list[i])
                log.info("Did not meet early stopping. Best iteration is:"
                         "\n[%d]\t%s", best_iter[i] + 1, best)
                if first_metric_only:
                    log.info("Evaluated only: %s", eval_name_splitted[-1])
            raise EarlyStopException(best_iter[i], best_score_list[i])

    def _callback(env: CallbackEnv) -> None:
        if not cmp_op:
            _init(env)
        if not enabled[0]:
            return
        for i, (name, metric, value, _) in \
                enumerate(env.evaluation_result_list):
            if best_score_list[i] is None or cmp_op[i](value, best_score[i]):
                best_score[i] = value
                best_iter[i] = env.iteration
                best_score_list[i] = env.evaluation_result_list
            eval_name_splitted = metric.split(" ")
            if first_metric_only and first_metric[0] != eval_name_splitted[-1]:
                continue
            if name == "training":
                _final_iteration_check(env, eval_name_splitted, i)
                continue
            if env.iteration - best_iter[i] >= stopping_rounds:
                if verbose:
                    best = "\t".join(
                        f"{n}'s {m}: {v:g}"
                        for n, m, v, _ in best_score_list[i])
                    log.info("Early stopping, best iteration is:\n[%d]\t%s",
                             best_iter[i] + 1, best)
                    if first_metric_only:
                        log.info("Evaluated only: %s",
                                 eval_name_splitted[-1])
                raise EarlyStopException(best_iter[i], best_score_list[i])
            _final_iteration_check(env, eval_name_splitted, i)
    _callback.order = 30
    _callback._megastep_replay = "early_stopping"
    # the scan-native early-stop tracker mirrors this callback's state
    # machine on device; it needs the spec the closure was built with
    _callback._es_spec = (int(stopping_rounds), bool(first_metric_only),
                          min_delta)

    # checkpoint/resume hooks (resilience/state.py): the closure's best
    # lists ARE the early-stop state — restoring them is what keeps a
    # resumed run's stopping decision bit-identical to an uninterrupted
    # one (the megastep's device carry is synthesized from this state)
    def _cb_state():
        return {
            "inited": bool(cmp_op),
            "enabled": bool(enabled[0]),
            "first_metric": first_metric[0],
            "seen": [e is not None for e in best_score_list],
            "best_score": [float(s) if e is not None else 0.0
                           for s, e in zip(best_score, best_score_list)],
            "best_iter": [int(i) for i in best_iter],
            "best_score_list": [
                None if e is None
                else [[n, m, float(v), bool(b)] for n, m, v, b in e]
                for e in best_score_list],
        }

    def _cb_restore(st, env) -> None:
        if not st or not st.get("inited"):
            return
        if not cmp_op:
            # _init builds the per-slot comparators from a representative
            # evaluation list (the checkpoint carries the last one)
            _init(env)
        if len(best_iter) != len(st["best_iter"]):
            raise ValueError(
                f"early-stopping slots changed across resume "
                f"({len(st['best_iter'])} saved, {len(best_iter)} now)")
        enabled[0] = bool(st.get("enabled", True))
        first_metric[0] = st.get("first_metric", first_metric[0])
        for i in range(len(best_iter)):
            best_iter[i] = int(st["best_iter"][i])
            if st["seen"][i]:
                best_score[i] = float(st["best_score"][i])
                lst = st["best_score_list"][i]
                best_score_list[i] = ([tuple(t) for t in lst]
                                      if lst is not None else None)
    _callback._cb_state = _cb_state
    _callback._cb_restore = _cb_restore
    return _callback


# ---------------------------------------------------------------------------
# Drain-replay protocol (megastep on-device eval; boosting/gbdt.py
# _drain_body is the producer, engine.train the owner).
# ---------------------------------------------------------------------------
class EvalResultView(list):
    """One iteration's ``evaluation_result_list`` reconstructed from the
    megastep's device-computed metric vector: a plain list of
    ``(dataset_name, metric_name, value, is_higher_better)`` tuples in
    the exact order the synchronous engine loop would have produced —
    no score fetch, no re-predict; only the per-iteration scalars ever
    crossed from the device."""

    __slots__ = ()

    @classmethod
    def from_values(cls, slots, values) -> "EvalResultView":
        return cls((ds, name, float(v), bigger)
                   for (ds, name, bigger), v in zip(slots, values))


def drain_replay_blocker(callbacks: List) -> Optional[str]:
    """None when every callback is drain-replayable, else the specific
    feature that evicts the megastep (named in the ``megastep_evicted``
    telemetry event)."""
    n_es = 0
    for cb in callbacks:
        kind = getattr(cb, "_megastep_replay", None)
        if kind is None:
            name = getattr(cb, "__qualname__",
                           getattr(cb, "__name__",
                                   type(cb).__name__))
            return f"callback:{name}"
        if kind == "early_stopping":
            n_es += 1
            _, _, delta = cb._es_spec
            deltas = delta if isinstance(delta, list) else [delta]
            if any(float(d) != 0.0 for d in deltas):
                # a nonzero min_delta compares best + delta in host f64;
                # the scan's f32 compare could diverge on the boundary,
                # breaking the drained model's bit-identity contract
                return "callback:early_stopping(min_delta)"
            if n_es > 1:
                return "callback:early_stopping(duplicate)"
    return None


def find_es_spec(callbacks: List):
    """(stopping_rounds, first_metric_only) of the early_stopping
    callback, or None when none is registered."""
    for cb in callbacks:
        if getattr(cb, "_megastep_replay", None) == "early_stopping":
            rounds, fmo, _ = cb._es_spec
            return (rounds, fmo)
    return None


class DrainEvalReplay:
    """Drain-time consumer for the megastep's per-iteration metric rows.

    ``boosting.GBDT._drain_body`` calls :meth:`replay` once per kept
    iteration, in order; this object rebuilds the iteration's
    evaluation list, runs the registered callbacks against it (and
    writes the engine-level snapshots on their schedule), and converts
    an :class:`EarlyStopException` into recorded state the engine loop
    applies — the exception must not unwind through ``Booster.update``.
    """

    def __init__(self, booster, params: Dict[str, Any],
                 callbacks_before: List, callbacks_after: List,
                 end_iteration: int, snapshot_freq: int = -1,
                 snapshot_base: str = "", include_training: bool = False):
        self.booster = booster
        self.params = params
        self.callbacks_before = list(callbacks_before)
        self.callbacks_after = list(callbacks_after)
        self.end_iteration = int(end_iteration)
        self.snapshot_freq = int(snapshot_freq)
        self.snapshot_base = snapshot_base
        self.include_training = bool(include_training)
        self.es_spec = find_es_spec(self.callbacks_after)
        self.slots: List = []          # bound by GBDT.arm_megastep
        self.stop = None               # (best_iteration, best_score_list)
        self.last_eval: List = []

    def bind(self, slots) -> None:
        self.slots = list(slots)

    def _env(self, iteration: int, results) -> CallbackEnv:
        return CallbackEnv(model=self.booster, params=self.params,
                           iteration=iteration, begin_iteration=0,
                           end_iteration=self.end_iteration,
                           evaluation_result_list=results)

    def replay(self, iteration: int, values) -> bool:
        """Replay one drained iteration; returns True when an early
        stop fired (training must rewind to ``iteration`` and stop)."""
        for cb in self.callbacks_before:
            cb(self._env(iteration, None))
        if self.snapshot_freq > 0 \
                and (iteration + 1) % self.snapshot_freq == 0:
            # the synchronous loop snapshots with num_iteration=-1 right
            # after iteration's update; at drain time the model already
            # holds later trees, so slice to the same content instead
            self.booster.save_model(
                f"{self.snapshot_base}.snapshot_iter_{iteration + 1}",
                num_iteration=iteration + 1)
        view = EvalResultView.from_values(self.slots, values)
        try:
            for cb in self.callbacks_after:
                cb(self._env(iteration, view))
        except EarlyStopException as es:
            self.stop = (es.best_iteration, es.best_score)
            return True
        self.last_eval = view
        return False
