"""User-facing Dataset and Booster.

Behavioral analog of ref: python-package/lightgbm/basic.py (Dataset :1122,
Booster :2512).  There is no ctypes/C-API hop: the "library" is the in-process
TPU runtime, so `_safe_call`/handle plumbing collapses away while the public
surface (lazy construction, reference-aligned binning, update/eval/predict,
model IO, continued training) is preserved.
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Union

import numpy as np

from .boosting import create_boosting
from .config import Config
from .dataset import TpuDataset
from .io import model_io
from .metric import create_metric, default_metric_for_objective
from .models.tree import HostTree
from .objective import create_objective, create_objective_from_string
from .utils import log
from .utils.log import LightGBMError

__all__ = ["Dataset", "Booster", "Sequence"]

# host-walk sparse fallback densifies in bounded row chunks (a tall CSR
# predict must be a loop, not a whole-matrix todense)
_HOST_SPARSE_CHUNK_ROWS = 65_536


class Sequence:
    """Generic chunked data-access interface for dataset construction
    (ref: basic.py:605 Sequence ABC): implement ``__len__``,
    ``__getitem__`` for slices, and optionally ``batch_size``. The matrix
    is assembled in ``batch_size`` slices (the source never has to hand
    over one giant array; the assembled matrix itself is in RAM — the
    binned representation is what training keeps).

    A list of Sequences concatenates row-wise (multi-file datasets)."""

    batch_size = 4096

    def __len__(self) -> int:  # pragma: no cover - interface
        raise NotImplementedError

    def __getitem__(self, idx):  # pragma: no cover - interface
        raise NotImplementedError


def _materialize_sequences(seqs) -> np.ndarray:
    """Assemble a row-major float64 matrix from Sequence chunks (float64
    so binning matches the equivalent ndarray input exactly)."""
    if isinstance(seqs, Sequence):
        seqs = [seqs]
    chunks = []
    for seq in seqs:
        n = len(seq)
        bs = int(getattr(seq, "batch_size", None) or 4096)
        for lo in range(0, n, bs):
            chunks.append(np.asarray(seq[lo:min(n, lo + bs)], np.float64))
    if not chunks:
        raise ValueError("Sequence dataset has 0 rows")
    return np.concatenate(chunks, axis=0)


def host_walk_raw(models, X, lo: int, hi: int, k: int) -> np.ndarray:
    """Exact float64 host tree walk over trees [lo, hi): raw scores
    [k, n].  The ONE implementation of the host fallback — Booster
    ``_predict_raw`` and the serving engine's degraded path both route
    here, so the densify-in-bounded-chunks behavior (a tall sparse
    predict must be a loop, not a whole-matrix todense) cannot
    diverge."""
    n = X.shape[0]
    raw = np.zeros((k, n), np.float64)
    if _is_scipy_sparse(X):
        X = X.tocsr()
        step = _HOST_SPARSE_CHUNK_ROWS
        for c0 in range(0, n, step):
            sl = slice(c0, min(n, c0 + step))
            Xc = np.asarray(X[sl].todense(), np.float64)
            for i, t in enumerate(models[lo:hi]):
                raw[(lo + i) % k, sl] += t.predict_rows(Xc)
        return raw
    X = np.asarray(X, np.float64)
    for i, t in enumerate(models[lo:hi]):
        raw[(lo + i) % k] += t.predict_rows(X)
    return raw


def finalize_raw_predictions(raw: np.ndarray, k: int, objective,
                             average_output: bool, num_iteration: int,
                             raw_score: bool) -> np.ndarray:
    """Raw [k, n] scores -> the user-facing prediction array: RF score
    averaging, objective output transform, multiclass transpose.  The
    ONE implementation of the output contract — ``Booster.predict`` and
    the serving engine both end here, so serving results cannot drift
    from the Booster's."""
    if average_output and num_iteration > 0:
        raw = raw / num_iteration
    if not raw_score and objective is not None:
        if k > 1:
            return objective.convert_output(raw.T)
        return np.asarray(objective.convert_output(raw[0]))
    return raw[0] if k == 1 else raw.T


def pred_trees_stale(pred, booster) -> bool:
    # a monotonically-bumped version survives rollback+update swaps where
    # both the length and (recycled) id of the tail tree can repeat
    return getattr(pred, "model_version", -1) != booster._model_version


def _mappers_match(ref_inner, inner) -> bool:
    """Do two constructed datasets bin identically?  Identical mapper
    list objects short-circuit (streamed-with-reference builds, a
    dataset referencing itself); otherwise compare the full mapper
    digests.  The ONE alignment predicate both cache-acceptance paths
    (explicit .bin refusal, auto-sidecar miss) share."""
    if ref_inner.mappers is inner.mappers:
        return True
    from .binning import mappers_digest
    return mappers_digest(ref_inner.mappers) == mappers_digest(
        inner.mappers)


def _cohort_votes(flag: bool):
    """Allgather a boolean vote -> (any_true, all_true).  Cache hit/miss
    decisions must be cohort-consistent under multi-process loading:
    the rebuild path enters the binning-sample allgather, so a split
    vote (one rank's shard valid, another's missing/corrupt) would
    leave the hitting ranks outside a collective their peers are
    blocked in — every rank sees the split and can act on it."""
    import jax
    if jax.process_count() <= 1:
        return flag, flag
    from jax.experimental import multihost_utils
    votes = np.asarray(multihost_utils.process_allgather(
        np.array([1 if flag else 0], np.int32)))
    return bool(votes.max() == 1), bool(votes.min() == 1)


def _cohort_all_agree(flag: bool) -> bool:
    return _cohort_votes(flag)[1]


def _is_scipy_sparse(data) -> bool:
    try:
        import scipy.sparse as sp
    except ImportError:  # pragma: no cover
        return False
    return sp.issparse(data)


def _to_2d_numpy(data) -> np.ndarray:
    if _is_scipy_sparse(data):
        # chunk-free densify is only acceptable at prediction-batch sizes;
        # Dataset construction routes sparse input to from_sparse instead
        return np.asarray(data.todense(), np.float64)
    if hasattr(data, "values") and not isinstance(data, np.ndarray):
        data = data.values  # pandas
    arr = np.asarray(data)
    if arr.ndim == 1:
        arr = arr.reshape(-1, 1)
    if arr.dtype == object:
        arr = arr.astype(np.float64)
    return arr


class Dataset:
    """Training dataset with lazy construction
    (ref: basic.py:1122 Dataset)."""

    def __init__(self, data, label=None, reference: Optional["Dataset"] = None,
                 weight=None, group=None, init_score=None,
                 feature_name="auto", categorical_feature="auto",
                 params: Optional[Dict[str, Any]] = None,
                 free_raw_data: bool = True):
        self.data = data
        self.label = label
        self.reference = reference
        self.weight = weight
        self.group = group
        self.init_score = init_score
        self.feature_name = feature_name
        self.categorical_feature = categorical_feature
        self.params = dict(params) if params else {}
        self.free_raw_data = free_raw_data
        self._inner: Optional[TpuDataset] = None
        self.used_indices: Optional[np.ndarray] = None
        self._predictor = None

    # ------------------------------------------------------------------
    def construct(self) -> "Dataset":
        """(ref: basic.py Dataset.construct / _lazy_init)"""
        if self._inner is not None:
            return self
        cfg = Config(self.params)
        if isinstance(self.data, Sequence) or (
                isinstance(self.data, list) and self.data
                and all(isinstance(x, Sequence) for x in self.data)):
            # chunked out-of-core assembly (ref: Sequence streaming push)
            self.data = _materialize_sequences(self.data)
        pending_cache = None
        if isinstance(self.data, (str, os.PathLike)):
            if self._construct_from_file(cfg):
                return self
            pending_cache = self._pending_cache_write
            self._pending_cache_write = None
        is_sparse = _is_scipy_sparse(self.data)
        data = self.data if is_sparse else _to_2d_numpy(self.data)
        cats, feature_names = self._resolve_cats_names(self.data)
        ref_inner = None
        if self.reference is not None:
            ref_inner = self.reference.construct()._inner
        if is_sparse:
            # CSR/CSC ingestion without densifying (ref: c_api.cpp:398-520
            # DatasetCreateFromCSR/CSC; storage answer: ingestion-time EFB,
            # see TpuDataset.from_sparse)
            if cats:
                raise LightGBMError(
                    "categorical features are not supported for sparse "
                    "input yet; densify those columns")
            if bool(cfg.linear_tree):
                raise LightGBMError(
                    "linear_tree needs retained raw data and is not "
                    "supported for sparse input")
            self._inner = TpuDataset.from_sparse(
                data, cfg, feature_names=feature_names,
                reference=ref_inner)
        else:
            self._inner = TpuDataset.from_data(
                data, cfg, categorical_feature=cats,
                feature_names=feature_names, reference=ref_inner)
        if not is_sparse and bool(cfg.linear_tree):
            # linear leaves fit ridge models on RAW feature values
            # (ref: dataset raw-data retention for linear_tree)
            self._inner.raw_data = np.asarray(data, np.float32)
        if self.label is not None:
            self._inner.metadata.set_label(np.asarray(self.label))
        if self.weight is not None:
            self._inner.metadata.set_weight(np.asarray(self.weight))
        if self.group is not None:
            self._inner.metadata.set_group(np.asarray(self.group))
        if self.init_score is not None:
            self._inner.metadata.set_init_score(np.asarray(self.init_score))
        if self.free_raw_data:
            # keep raw features for prediction-time use only if small
            pass
        if pending_cache is not None:
            self._write_sidecar_cache(*pending_cache)
        return self

    # ------------------------------------------------------------------
    def _apply_explicit_metadata(self) -> None:
        """Explicitly-passed metadata overrides a cache/stream-loaded
        copy (the reference's LoadFromBinFile + SetField sequence
        behaves the same way); absent overrides adopt the loaded
        values onto the facade attributes."""
        # a cache used as validation data must share its reference's
        # bin mappers (it was built with reference= at save time, or it
        # is the train cache itself) — anything else would route eval
        # rows through foreign bins silently (the reference's
        # CheckAlign contract)
        if self.reference is not None:
            if not _mappers_match(self.reference.construct()._inner,
                                  self._inner):
                raise LightGBMError(
                    "cached dataset was binned with different mappers "
                    "than its reference dataset; rebuild the cache from "
                    "text with reference= the training data")
        elif getattr(self._inner, "reference_binned", False):
            # a validation cache carries ANOTHER dataset's mappers —
            # training on it standalone would bin against foreign
            # boundaries silently
            raise LightGBMError(
                "this dataset cache was binned against a reference "
                "(validation) dataset; pass reference= the training "
                "data, or rebuild the cache from text standalone")
        # the cache round-trips the binning-defining params (like the
        # reference's .bin): a booster built on the reloaded dataset
        # resolves the SAME values the original build used — explicit
        # user params still win
        for k, v in (getattr(self._inner, "dataset_params", None)
                     or {}).items():
            self.params.setdefault(k, v)
        md = self._inner.metadata
        if self.label is not None:
            md.set_label(np.asarray(self.label))
        elif md is not None:
            self.label = md.label
        if self.weight is not None:
            md.set_weight(np.asarray(self.weight))
        elif md.weight is not None:
            self.weight = md.weight
        if self.group is not None:
            md.set_group(np.asarray(self.group, np.int64))
        if self.init_score is not None:
            md.set_init_score(np.asarray(self.init_score))
        elif md.init_score is not None:
            self.init_score = md.init_score

    def _resolve_cats_names(self, columns_source=None):
        """(categorical index list, feature names or None) — the ONE
        resolution of user feature names / string categoricals, shared
        by the generic construct tail (``columns_source`` supplies
        pandas column names) and the streamed file build (which needs
        them BEFORE mapper construction)."""
        feature_names = None
        if self.feature_name != "auto" and self.feature_name is not None:
            feature_names = list(self.feature_name)
        elif columns_source is not None \
                and hasattr(columns_source, "columns"):
            feature_names = [str(c) for c in columns_source.columns]
        cats = []
        if self.categorical_feature != "auto" \
                and self.categorical_feature is not None:
            for c in self.categorical_feature:
                if isinstance(c, str):
                    if feature_names and c in feature_names:
                        cats.append(feature_names.index(c))
                else:
                    cats.append(int(c))
        return cats, feature_names

    def _construct_from_file(self, cfg) -> bool:
        """File-based construction routing (ref:
        DatasetLoader::LoadFromFile / LoadFromBinFile).  Returns True
        when ``_inner`` is fully built (binary-cache hit or streamed
        chunked ingest); False to fall through to the monolithic tail
        with ``self.data`` holding the parsed shard.  Multi-process:
        each rank reads its contiguous row slice unless pre_partition
        says the file already IS this rank's partition
        (ref: dataset_loader.cpp:203 + config.h pre_partition)."""
        import jax as _jax

        from .ingest.cache import (CACHE_MAGIC, CacheError,
                                   cache_shard_path, load_dataset_cache,
                                   read_manifest, source_fingerprint)
        from .ingest.pipeline import (dataset_params_digest,
                                      ingest_text_streamed,
                                      streaming_eligible)
        self._pending_cache_write = None
        path = str(self.data)
        rank, nm = 0, 1
        if _jax.process_count() > 1 and not bool(cfg.pre_partition):
            rank, nm = _jax.process_index(), _jax.process_count()

        def _magic(p):
            try:
                with open(p, "rb") as fh:
                    return fh.read(8)
            except OSError:
                return b""

        # ---- explicit binary-cache input short-circuits the text
        # loader entirely (the cache magic is checked before any
        # parsing). Multi-process ranks resolve their own shard file
        # (<path>.rank<r>of<w>) first; the take-the-cache decision must
        # be UNANIMOUS across the cohort — a rank whose shard is
        # missing would fall through to the text path and block in a
        # binning-sample collective its cache-hitting peers never join
        shard = cache_shard_path(path, rank, nm)
        head = _magic(path)
        local_cache = None
        if nm > 1 and _magic(shard) == CACHE_MAGIC:
            local_cache = shard
        elif head in (CACHE_MAGIC, b"LGBMTPU1"):
            local_cache = path
        if nm > 1:
            any_hit, all_hit = _cohort_votes(local_cache is not None)
            if any_hit and not all_hit:
                # EVERY rank raises (both sides see the split), so the
                # cohort fails together instead of hanging
                raise CacheError(
                    f"binary cache shards for {path} exist on some "
                    "ranks only — rebuild every rank's shard "
                    "(save_binary under the current launcher layout) "
                    "or point data= at the text source")
            if not all_hit:
                local_cache = None
        if local_cache is not None:
            if _magic(local_cache) == b"LGBMTPU1":   # legacy v1 pickle
                self._inner = TpuDataset.load_binary(local_cache)
            else:
                self._inner = load_dataset_cache(
                    local_cache, expect_rank=rank, expect_world=nm)
            self._apply_explicit_metadata()
            return True

        # ---- auto-maintained sidecar cache (save_binary=true): hit
        # only when the source fingerprint (size/mtime/dataset params),
        # rank layout AND binning provenance (standalone vs
        # reference-binned) still match — anything else rebuilds.
        # Multi-process: the hit/miss decision must be COHORT-WIDE —
        # the rebuild path joins the binning-sample allgather, so one
        # rank hitting while another rebuilds would deadlock the
        # collective; every rank reaches the agreement allgather below
        # whether or not its own shard file exists.
        cats, feature_names = self._resolve_cats_names()
        auto_cache = None
        if bool(cfg.save_binary):
            auto_cache = cache_shard_path(path + ".bin", rank, nm)
            loaded = None
            if os.path.exists(auto_cache):
                try:
                    manifest = read_manifest(auto_cache)
                    cur = source_fingerprint(
                        path, dataset_params_digest(cfg, cats))
                    if manifest.get("source") == cur \
                            and int(manifest.get("world", 1)) == nm \
                            and bool(manifest.get("reference_binned",
                                                  False)) \
                            == (self.reference is not None):
                        # full load INCLUDING hash verification here, so
                        # a corrupt-bins shard counts as a miss at the
                        # agreement point instead of crashing post-vote
                        loaded = load_dataset_cache(
                            auto_cache, expect_rank=rank,
                            expect_world=nm)
                    else:
                        log.info("binary cache %s is stale (source, "
                                 "params, layout or provenance "
                                 "changed); rebuilding", auto_cache)
                except CacheError as e:
                    log.warning("ignoring unusable binary cache: %s", e)
            if loaded is not None and self.reference is not None:
                # an auto (validation) sidecar whose reference dataset
                # was itself rebuilt carries outdated mappers: on this
                # best-effort path that is a MISS to rebuild, not the
                # hard error the explicitly-passed-cache path raises
                if not _mappers_match(self.reference.construct()._inner,
                                      loaded):
                    log.info("binary cache %s no longer matches its "
                             "reference dataset's mappers; rebuilding",
                             auto_cache)
                    loaded = None
            hit = loaded is not None
            if nm > 1:
                hit = _cohort_all_agree(hit)
            if hit:
                self._inner = loaded
                self._apply_explicit_metadata()
                return True

        eligible, _reason = streaming_eligible(cfg, path)
        if eligible:
            ref_inner = None
            if self.reference is not None:
                ref_inner = self.reference.construct()._inner
            def _stream(cache_to):
                return ingest_text_streamed(
                    path, cfg,
                    label_column=self.params.get("label_column"),
                    rank=rank, num_machines=nm,
                    categorical_feature=cats,
                    feature_names=feature_names, reference=ref_inner,
                    cache_out=cache_to, world=nm)
            try:
                inner, y, _side = _stream(auto_cache)
            except (CacheError, OSError) as e:
                if auto_cache is None:
                    raise
                # the sidecar cache is best-effort: a full disk or a
                # read-only data directory must not kill the build —
                # re-stream assembling in memory instead
                log.warning("binary cache not written (%s); streaming "
                            "without a cache", e)
                inner, y, _side = _stream(None)
            self._inner = inner
            self._apply_explicit_metadata()
            return True

        # ---- monolithic fallback: parse the shard as one array and
        # let the generic tail bin it; with save_binary the built
        # dataset is cached after construction
        from .io.file_loader import load_text_file
        X, y, side = load_text_file(
            path, label_column=self.params.get("label_column"),
            rank=rank, num_machines=nm)
        self.data = X
        if self.label is None and y is not None:
            self.label = y
        if self.weight is None and "weight" in side:
            self.weight = side["weight"]
        if self.group is None and "group" in side:
            self.group = side["group"]
        if self.init_score is None and "init_score" in side:
            self.init_score = side["init_score"]
        if auto_cache is not None:
            self._pending_cache_write = (
                auto_cache, path, rank, nm,
                dataset_params_digest(cfg, cats))
        return False

    def _write_sidecar_cache(self, cache_path: str, src_path: str,
                             rank: int, world: int,
                             params_digest: str) -> None:
        """Post-construction cache write for the monolithic path
        (streamed ingest writes during pass 2 instead)."""
        from .ingest.cache import (CacheError, save_dataset_cache,
                                   source_fingerprint)
        try:
            save_dataset_cache(
                self._inner, cache_path, rank=rank, world=world,
                source=source_fingerprint(src_path, params_digest))
            # marker for callers (cli task=save_binary): the artifact at
            # this path is fresh and fingerprinted — do not rewrite it
            self._inner.sidecar_cache_path = cache_path
        except (CacheError, OSError) as e:
            # best-effort: ineligible datasets (CacheError) and write
            # failures (disk full, read-only dir) warn, never abort a
            # successfully-built construct
            log.warning("binary cache not written: %s", e)

    # ------------------------------------------------------------------
    def set_label(self, label) -> "Dataset":
        self.label = label
        if self._inner is not None and label is not None:
            self._inner.metadata.set_label(np.asarray(label))
        return self

    def set_weight(self, weight) -> "Dataset":
        self.weight = weight
        if self._inner is not None:
            self._inner.metadata.set_weight(
                None if weight is None else np.asarray(weight))
        return self

    def set_group(self, group) -> "Dataset":
        self.group = group
        if self._inner is not None and group is not None:
            self._inner.metadata.set_group(np.asarray(group))
        return self

    def set_init_score(self, init_score) -> "Dataset":
        self.init_score = init_score
        if self._inner is not None:
            self._inner.metadata.set_init_score(
                None if init_score is None else np.asarray(init_score))
        return self

    def set_field(self, field_name: str, data) -> "Dataset":
        """(ref: basic.py Dataset.set_field)"""
        if field_name == "label":
            return self.set_label(data)
        if field_name == "weight":
            return self.set_weight(data)
        if field_name == "group":
            return self.set_group(data)
        if field_name == "init_score":
            return self.set_init_score(data)
        raise ValueError(f"Unknown field name: {field_name}")

    def get_field(self, field_name: str):
        md = self.construct()._inner.metadata
        if field_name == "label":
            return md.label
        if field_name == "weight":
            return md.weight
        if field_name == "group":
            return md.query_boundaries
        if field_name == "init_score":
            return md.init_score
        raise ValueError(f"Unknown field name: {field_name}")

    def get_label(self):
        return self.get_field("label")

    def get_weight(self):
        return self.get_field("weight")

    def get_init_score(self):
        return self.get_field("init_score")

    def get_group(self):
        # boundaries -> per-query sizes (ref: basic.py:2321 get_group diffs)
        boundaries = self.get_field("group")
        return None if boundaries is None else np.diff(boundaries)

    # ------------------------------------------------------------------
    def add_features_from(self, other: "Dataset") -> "Dataset":
        """(ref: basic.py Dataset.add_features_from)"""
        self.construct()
        other.construct()
        self._inner.add_features_from(other._inner)
        return self

    def num_data(self) -> int:
        return self.construct()._inner.num_data

    def num_feature(self) -> int:
        return self.construct()._inner.num_total_features

    def get_feature_name(self) -> List[str]:
        return self.construct()._inner.feature_names

    def subset(self, used_indices, params=None) -> "Dataset":
        """Row subset sharing bin mappers (ref: basic.py Dataset.subset)."""
        self.construct()
        sub = Dataset.__new__(Dataset)
        sub.data = None
        sub.label = None
        sub.reference = self
        sub.weight = None
        sub.group = None
        sub.init_score = None
        sub.feature_name = self.feature_name
        sub.categorical_feature = self.categorical_feature
        sub.params = dict(self.params)
        if params:
            sub.params.update(params)
        sub.free_raw_data = self.free_raw_data
        sub.used_indices = np.asarray(used_indices)
        sub._inner = self._inner.subset(sub.used_indices)
        sub._predictor = None
        if self.data is not None:
            sub.data = _to_2d_numpy(self.data)[sub.used_indices]
        return sub

    def create_valid(self, data, label=None, weight=None, group=None,
                     init_score=None, params=None) -> "Dataset":
        """(ref: basic.py Dataset.create_valid)"""
        return Dataset(data, label=label, reference=self, weight=weight,
                       group=group, init_score=init_score,
                       params=params or self.params)

    def save_binary(self, filename: str) -> "Dataset":
        self.construct()._inner.save_binary(filename)
        return self


class Booster:
    """Booster: training + prediction handle (ref: basic.py:2512)."""

    def __init__(self, params: Optional[Dict[str, Any]] = None,
                 train_set: Optional[Dataset] = None,
                 model_file: Optional[str] = None,
                 model_str: Optional[str] = None):
        self.params = dict(params) if params else {}
        self.best_iteration = -1
        self.best_score: Dict[str, Dict[str, float]] = {}
        self._gbdt = None
        self.models: List[HostTree] = []
        self.objective = None
        self.config: Optional[Config] = None
        self.train_set: Optional[Dataset] = None
        self.valid_sets: List[Dataset] = []
        self.name_valid_sets: List[str] = []
        self.loaded_parameter = ""
        self.average_output = False
        self.num_class = 1
        self.num_tree_per_iteration = 1
        self.max_feature_idx = 0
        self.feature_names: List[str] = []
        self._model_version = 0  # bumped on every model-list mutation
        self.feature_infos: List[str] = []
        self.monotone_constraints = None
        self.label_index = 0
        # drift & lineage plane (obs/drift.py): the training-data
        # profile and provenance record ride the model artifact and
        # checkpoint payloads; None for pre-plane artifacts (serving
        # degrades structurally — see docs/Observability.md §13)
        self.data_profile: Optional[Dict[str, Any]] = None
        self.provenance: Optional[Dict[str, Any]] = None

        if train_set is not None:
            self._init_train(train_set)
        elif model_file is not None:
            with open(model_file, "r") as fh:
                self._load_model_string(fh.read())
        elif model_str is not None:
            self._load_model_string(model_str)

    # ------------------------------------------------------------------
    def _init_train(self, train_set: Dataset) -> None:
        if not isinstance(train_set, Dataset):
            raise TypeError("Training data should be Dataset instance")
        merged = dict(train_set.params)
        merged.update(self.params)
        self.config = Config(merged)
        train_set.params = merged
        train_set.construct()
        self.train_set = train_set
        inner = train_set._inner
        # a binary-cache-loaded dataset restores the binning-defining
        # params it was built with (construction may have happened just
        # now, AFTER the config snapshot above): fold them in unless
        # the user explicitly set a conflicting value, so the resolved
        # config (and the serialized parameters echo) matches the
        # original build's
        restored = {k: v for k, v in (getattr(inner, "dataset_params",
                                              None) or {}).items()
                    if not self.config.was_set(k)}
        if restored:
            self.config.update(restored)
            train_set.params.update(restored)
        self.objective = create_objective(self.config)
        if self.objective is not None:
            if inner.metadata.label is None:
                raise ValueError("Label should not be None")
            self.objective.init(inner.metadata, inner.num_data)
        self.num_class = max(1, int(self.config.num_class))
        self._gbdt = create_boosting(self.config)
        train_metrics = []
        if self.config.is_provide_training_metric:
            train_metrics = self._make_metrics(inner)
        self._gbdt.init(self.config, inner, self.objective, train_metrics)
        self.num_tree_per_iteration = self._gbdt.num_tree_per_iteration
        self.average_output = getattr(self._gbdt, "average_output", False)
        self.models = self._gbdt.models
        self.max_feature_idx = inner.num_total_features - 1
        self.feature_names = inner.feature_names
        self.feature_infos = inner.feature_infos()
        if inner.monotone_constraints is not None:
            self.monotone_constraints = inner.monotone_constraints
        if bool(getattr(self.config, "drift_profile", True)):
            self._capture_profile(train_set, inner)

    def _capture_profile(self, train_set: Dataset, inner) -> None:
        """Capture the DataProfile + provenance record at train init
        (the packed bins and frozen mappers exist; one bincount per
        feature, no device work).  Mirrored onto the driver so
        checkpoint payloads and the run report carry them."""
        try:
            from .ingest.pipeline import dataset_params_digest
            from .obs import drift as _drift
            try:
                import jax as _jax
                world = int(_jax.process_count())
            except Exception:
                world = 1
            if world > 1:
                # multiprocess ranks hold rank-local row shards: a
                # per-rank profile would make the rank artifacts
                # diverge, breaking the cross-rank model-identity
                # contract. Skip embedding — serving such a model takes
                # the structural drift_unavailable degrade path.
                log.debug("drift profile skipped: %d-process training "
                          "shards rows rank-locally", world)
                return
            cats = [int(j) for k, j in enumerate(inner.used_features)
                    if inner.is_categorical[k]]
            self.data_profile = _drift.build_profile(inner)
            # run_id is left for build_provenance to content-derive:
            # embedding the (per-process) telemetry run_id would break
            # byte-equality of identical trainings' model strings
            self.provenance = _drift.build_provenance(
                params_digest=dataset_params_digest(self.config, cats),
                source=_drift.source_fingerprint(train_set.data,
                                                 self.data_profile),
                parent_checkpoint="",
                profile=self.data_profile)
            self._gbdt.data_profile = self.data_profile
            self._gbdt.provenance = self.provenance
        except Exception as exc:  # never fail training over telemetry
            log.warning("data-profile capture failed: %s", exc)

    def _make_metrics(self, inner: TpuDataset) -> List:
        names = [str(m) for m in self.config.metric]
        if not names:
            default = default_metric_for_objective(self.config.objective)
            names = [default] if default else []
        metrics = []
        for name in names:
            m = create_metric(name, self.config)
            if m is not None:
                m.init(inner.metadata, inner.num_data)
                metrics.append(m)
        return metrics

    # ------------------------------------------------------------------
    def add_valid(self, data: Dataset, name: str) -> "Booster":
        """(ref: basic.py Booster.add_valid)"""
        if self._gbdt is None:
            raise Exception("Booster was not trained with a train_set")
        if data.reference is not self.train_set:
            data.reference = self.train_set
        data.construct()
        metrics = self._make_metrics(data._inner)
        self._gbdt.add_valid_data(data._inner, name, metrics)
        self.valid_sets.append(data)
        self.name_valid_sets.append(name)
        return self

    # ------------------------------------------------------------------
    def update(self, train_set: Optional[Dataset] = None, fobj=None) -> bool:
        """One boosting iteration; True if no further splits possible
        (ref: basic.py:2936 Booster.update)."""
        if train_set is not None and train_set is not self.train_set:
            raise Exception("Replacing train_set is not supported yet")
        self._model_version += 1
        if fobj is None:
            return self._gbdt.train_one_iter()
        if self.objective is not None:
            raise Exception(
                "Cannot use custom objective when the booster was created "
                "with a built-in objective; set objective='none'")
        grad, hess = fobj(self.__inner_predict_train(), self.train_set)
        return self.__boost(grad, hess)

    def __boost(self, grad, hess) -> bool:
        grad = np.asarray(grad, np.float32).reshape(
            self.num_tree_per_iteration, -1)
        hess = np.asarray(hess, np.float32).reshape(
            self.num_tree_per_iteration, -1)
        return self._gbdt.train_one_iter(grad, hess)

    def rollback_one_iter(self) -> "Booster":
        self._gbdt.rollback_one_iter()
        self._model_version += 1
        return self

    # ------------------------------------------------------------------
    def telemetry(self) -> Dict[str, Any]:
        """Snapshot of the training telemetry registry (obs/): counters,
        gauges, per-section timing distributions and the recent
        structured-event ring. Empty dict for model-file boosters (no
        live driver); {"enabled": False, ...} shell when telemetry was
        never enabled (enable it with the ``telemetry_out`` param or the
        ``record_telemetry`` callback). See docs/Observability.md."""
        if self._gbdt is None:
            return {}
        self._gbdt.drain_pending()
        return self._gbdt.telemetry.snapshot()

    def _finalize_telemetry(self) -> None:
        """End-of-training telemetry epilogue (engine.train calls this):
        profiler stop + summary event + trace export + JSONL flush."""
        if self._gbdt is not None:
            if self.data_profile is not None \
                    and "score" not in self.data_profile:
                # final train-margin distribution: the scores are being
                # fetched to host here anyway — no extra dispatch
                try:
                    from .obs.drift import (add_score_distribution,
                                            profile_digest)
                    scores = getattr(self._gbdt, "scores", None)
                    if scores is not None:
                        add_score_distribution(self.data_profile,
                                               np.asarray(scores))
                        if self.provenance is not None:
                            self.provenance["profile_digest"] = \
                                profile_digest(self.data_profile)
                except Exception as exc:
                    log.warning("score-profile capture failed: %s", exc)
            self._gbdt.finalize_telemetry()

    def _dump_crash(self, exc: BaseException) -> None:
        """Crash flight recorder hook (engine.train calls this when an
        exception unwinds out of the train loop): dump the telemetry
        ring + section stack + config to <telemetry_out>.crash.json."""
        if self._gbdt is not None:
            self._gbdt.dump_crash(exc)

    def _drain(self) -> None:
        """Materialise any device trees still queued by the training fast
        path before reading the host model list."""
        if self._gbdt is not None:
            self._gbdt.drain_pending()

    def current_iteration(self) -> int:
        """Iterations trained so far. PROVISIONAL under the pipelined
        driver: queued-but-undrained iterations count, and a later drain
        may discard some of them via the deferred no-split stop — poll
        num_trees() (which drains) for a settled count."""
        return self._gbdt.iter if self._gbdt is not None else \
            len(self.models) // max(1, self.num_tree_per_iteration)

    def num_trees(self) -> int:
        self._drain()
        return len(self.models)

    def num_model_per_iteration(self) -> int:
        return self.num_tree_per_iteration

    def __inner_predict_train(self) -> np.ndarray:
        g = self._gbdt
        if getattr(g, "mp", None) is not None:
            # multi-process: fobj is rank-local like the reference's
            # distributed custom objective — this rank's rows only
            loc = g.mp.local_block(g.scores, axis=1)[:, :g.mp.local_real]
            return np.asarray(loc, np.float64).reshape(-1)
        return np.asarray(g.scores, np.float64).reshape(-1)

    # ------------------------------------------------------------------
    def eval_train(self, feval=None) -> List:
        return self._eval_set("training", None, feval)

    def eval_valid(self, feval=None) -> List:
        out = []
        for i, name in enumerate(self.name_valid_sets):
            out.extend(self._eval_set(name, i, feval))
        return out

    def eval(self, data: Dataset, name: str, feval=None) -> List:
        if data is self.train_set:
            return self.eval_train(feval)
        for i, vs in enumerate(self.valid_sets):
            if vs is data:
                return self._eval_set(self.name_valid_sets[i], i, feval)
        raise Exception("Data should be added with add_valid first")

    def _eval_set(self, name: str, valid_idx: Optional[int], feval) -> List:
        """Returns [(dataset_name, metric_name, value, is_higher_better)].

        Metrics with a device formulation evaluate on the live device
        scores without draining the pipelined driver or pulling the score
        matrix (one batched scalar fetch at the end); host-only metrics,
        custom ``feval``s, and RF score averaging take the classic path."""
        import jax
        g = self._gbdt
        out = []
        if valid_idx is None:
            score_dev = g.scores
            metrics = g.training_metrics
            dataset = self.train_set
        else:
            score_dev = g.valid_scores[valid_idx]
            metrics = g.valid_metrics[valid_idx]
            dataset = self.valid_sets[valid_idx]
        if getattr(g, "average_output", False) or feval is not None:
            self._drain()   # needs the settled model count / host scores
            # re-capture: the drain may apply the deferred no-split-stop
            # subtraction, so the device rows captured above are stale
            score_dev = (g.scores if valid_idx is None
                         else g.valid_scores[valid_idx])
        if getattr(g, "average_output", False):
            score_dev = score_dev / max(1, g.num_iterations_trained)
        out.extend(g.eval_metric_set(name, metrics, score_dev))
        if feval is not None:
            if not getattr(score_dev, "is_fully_addressable", True):
                raise ValueError(
                    "custom feval needs the full score matrix on one "
                    "host; not supported with multi-process training")
            host_score = np.asarray(score_dev, np.float64)
            for f in (feval if isinstance(feval, list) else [feval]):
                ret = f(host_score.reshape(-1), dataset)
                rets = ret if isinstance(ret, list) else [ret]
                for mn, v, hb in rets:
                    out.append((name, mn, v, hb))
        fetched = jax.device_get([v for (_, _, v, _) in out])
        return [(d, n, float(v), b)
                for (d, n, _, b), v in zip(out, fetched)]

    # ------------------------------------------------------------------
    def predict(self, data, start_iteration: int = 0,
                num_iteration: Optional[int] = None, raw_score: bool = False,
                pred_leaf: bool = False, pred_contrib: bool = False,
                pred_early_stop: bool = False,
                pred_early_stop_freq: int = 10,
                pred_early_stop_margin: float = 10.0,
                **kwargs) -> np.ndarray:
        """(ref: basic.py:3449 Booster.predict → predictor.hpp)"""
        from .utils.timer import global_timer as _timer
        with _timer.section("Predictor::Predict"):
            return self._predict_body(
                data, start_iteration, num_iteration, raw_score, pred_leaf,
                pred_contrib, pred_early_stop, pred_early_stop_freq,
                pred_early_stop_margin)

    def _predict_body(self, data, start_iteration, num_iteration, raw_score,
                      pred_leaf, pred_contrib, pred_early_stop,
                      pred_early_stop_freq,
                      pred_early_stop_margin) -> np.ndarray:
        self._drain()
        # float32 sources are exactly representable in the raw-value
        # device predictor's compares; remember before the f64 upcast
        f32_input = getattr(data, "dtype", None) == np.float32
        if _is_scipy_sparse(data):
            # the batch predictor densifies per chunk; host-walk paths
            # (pred_leaf/contrib/early-stop) densify below as needed
            X = data.tocsr()
        else:
            X = _to_2d_numpy(data).astype(np.float64)
        n = X.shape[0]
        k = self.num_tree_per_iteration
        # only num_iteration=None means "use best_iteration"; an explicit
        # <=0 means all trees (ref: basic.py predict num_iteration handling)
        if num_iteration is None:
            num_iteration = self.best_iteration \
                if self.best_iteration > 0 else -1
        total_iter = len(self.models) // max(1, k)
        if num_iteration <= 0:
            num_iteration = total_iter - start_iteration
        num_iteration = min(num_iteration, total_iter - start_iteration)
        lo = start_iteration * k
        hi = (start_iteration + num_iteration) * k

        if _is_scipy_sparse(X) and (pred_leaf or pred_contrib
                                    or pred_early_stop):
            # host-walk paths operate row-wise on raw values
            X = np.asarray(X.todense(), np.float64)

        if pred_leaf:
            out = np.zeros((n, hi - lo), np.int32)
            for i, t in enumerate(self.models[lo:hi]):
                out[:, i] = t.predict_leaf_index(X)
            return out
        if pred_contrib:
            from .io.shap import predict_contrib
            return predict_contrib(self, X, lo, hi)

        if pred_early_stop and self.num_tree_per_iteration >= 1 \
                and not self.average_output:
            raw = self._predict_raw_early_stop(
                X, lo, hi, pred_early_stop_freq, pred_early_stop_margin)
        else:
            raw = self._predict_raw(X, lo, hi, f32_input=f32_input)
        return finalize_raw_predictions(raw, k, self.objective,
                                        self.average_output,
                                        num_iteration, raw_score)

    # ------------------------------------------------------------------
    def _predict_raw_early_stop(self, X: np.ndarray, lo: int, hi: int,
                                freq: int, margin: float) -> np.ndarray:
        """Margin-based prediction early stopping (ref:
        src/boosting/prediction_early_stop.cpp — binary: |raw| > margin;
        multiclass: top1 - top2 > margin; checked every ``freq`` trees).
        Rows whose margin clears the threshold stop accumulating trees."""
        n = X.shape[0]
        k = self.num_tree_per_iteration
        raw = np.zeros((k, n), np.float64)
        active = np.ones(n, bool)
        for i, t in enumerate(self.models[lo:hi]):
            if not active.any():
                break
            idx = np.nonzero(active)[0]
            raw[(lo + i) % k, idx] += t.predict_rows(X[idx])
            if (i + 1) % (freq * k) == 0:
                if k == 1:
                    done = np.abs(raw[0, idx]) > margin
                else:
                    part = np.sort(raw[:, idx], axis=0)
                    done = (part[-1] - part[-2]) > margin
                active[idx[done]] = False
        return raw

    def _pred_device_min_work(self) -> int:
        """Resolved ``pred_device_min_work`` threshold (rows x trees at
        or above which predict routes through the device predictor) —
        from the live training config when one exists, else from the
        booster params (model-file boosters)."""
        if self.config is not None:
            return int(self.config.pred_device_min_work)
        cached = getattr(self, "_pred_min_work_cache", None)
        if cached is None:
            # resolve the ONE key by hand — constructing a full Config
            # here would re-run its _post_process side effects (global
            # log level!) on every first predict of a model-file booster
            cached = 2_000_000
            for key, value in self.params.items():
                if Config.resolve_key(str(key)) == "pred_device_min_work" \
                        and value is not None:
                    cached = int(float(value))
            self._pred_min_work_cache = cached
        return cached

    def _pred_min_work_user_set(self) -> bool:
        """Did the user explicitly set ``pred_device_min_work``?  An
        explicit value is the opt-in that lets float64 input take the
        float32 raw-routing device path."""
        if self.config is not None:
            return self.config.was_set("pred_device_min_work")
        return any(Config.resolve_key(str(key)) == "pred_device_min_work"
                   for key in self.params)

    def _predict_raw(self, X: np.ndarray, lo: int, hi: int,
                     f32_input: bool = False) -> np.ndarray:
        """Raw scores [k, n]: device batch path for big jobs (one jit
        scan over a stacked tree tensor — ref: predictor.hpp:30 replaced
        per SURVEY §3.3; binned routing through the training mappers
        when a training dataset is attached, raw-value-threshold routing
        otherwise, so model-file boosters get the device path too), host
        tree walk below ``pred_device_min_work`` rows x trees (exact
        float64 accumulation).

        The raw-routing variant compares in float32: leaf routing is
        bit-identical to the host walk only for float32-representable
        input, so it auto-engages only when the source data was float32
        — float64 callers keep the exact host walk unless they opted in
        by setting ``pred_device_min_work`` themselves."""
        n = X.shape[0]
        k = self.num_tree_per_iteration
        n_trees = hi - lo
        if n * max(n_trees, 1) >= self._pred_device_min_work():
            has_train = (self.train_set is not None
                         and self.train_set._inner is not None)
            if not has_train and not f32_input \
                    and not self._pred_min_work_user_set():
                return host_walk_raw(self.models, X, lo, hi, k)
            pred = getattr(self, "_device_predictor", None)
            if pred is None or pred_trees_stale(pred, self):
                if has_train:
                    from .models.predictor import DevicePredictor
                    pred = DevicePredictor(self.models,
                                           self.train_set._inner, k)
                else:
                    from .models.predictor import RawDevicePredictor
                    pred = RawDevicePredictor(self.models,
                                              self.max_feature_idx + 1, k)
                # cache failed packs too: the ineligibility decision
                # (linear trees, oversized cat vocab) is per model
                # state, and re-scanning every tree per predict call
                # would tax exactly the repeated-predict workloads the
                # device path exists for
                pred.model_version = self._model_version
                self._device_predictor = pred
            if pred.ok:
                return pred.predict_raw(X, lo, hi)
        return host_walk_raw(self.models, X, lo, hi, k)

    # ------------------------------------------------------------------
    def set_network(self, machines: str, local_listen_port: int = 12400,
                    listen_time_out: int = 120,
                    num_machines: int = 1) -> "Booster":
        """Multi-host setup shim (ref: basic.py:2687 Booster.set_network);
        maps the reference's machine-list parameters onto
        jax.distributed.initialize — see parallel/distributed.py."""
        from .parallel import distributed
        distributed.set_network(machines, local_listen_port, num_machines,
                                listen_time_out)
        return self

    def free_network(self) -> "Booster":
        """(ref: basic.py:2721)"""
        from .parallel import distributed
        distributed.free_network()
        return self

    # ------------------------------------------------------------------
    def reset_parameter(self, params: Dict[str, Any]) -> "Booster":
        """(ref: basic.py Booster.reset_parameter → gbdt.cpp ResetConfig)"""
        self.params.update(params)
        # model-file boosters resolve predict-time keys from params —
        # drop the cached threshold so the new value takes effect
        self._pred_min_work_cache = None
        if self._gbdt is not None:
            self.config.update(params)
            self._gbdt.reset_config(self.config)
        return self

    # ------------------------------------------------------------------
    def model_to_string(self, start_iteration: int = 0,
                        num_iteration: Optional[int] = None,
                        importance_type: Union[int, str] = "split") -> str:
        self._drain()
        if num_iteration is None:
            # stock semantics: default to the early-stopped best iteration
            # (an explicit <= 0 still means "all trees")
            num_iteration = (self.best_iteration
                             if self.best_iteration > 0 else -1)
        it = 0 if importance_type in (0, "split") else 1
        return model_io.save_model_to_string(self, start_iteration,
                                             num_iteration, it)

    def save_model(self, filename: str, start_iteration: int = 0,
                   num_iteration: Optional[int] = None,
                   importance_type: Union[int, str] = "split") -> "Booster":
        # serialize first, then atomic write-then-rename: a crash mid-
        # snapshot (the engine's snapshot_freq files double as resume
        # checkpoints) can never leave a truncated model file behind
        from .resilience.atomicio import atomic_write_text
        text = self.model_to_string(start_iteration, num_iteration,
                                    importance_type)
        atomic_write_text(str(filename), text)
        return self

    def dump_model(self, start_iteration: int = 0,
                   num_iteration: Optional[int] = None) -> dict:
        self._drain()
        if num_iteration is None:
            num_iteration = (self.best_iteration
                             if self.best_iteration > 0 else -1)
        import json as _json
        return _json.loads(model_io.dump_model_json(self, start_iteration,
                                                    num_iteration))

    def _load_model_string(self, model_str: str) -> None:
        header, trees, params = model_io.parse_model_string(model_str)
        self.models = trees
        self.loaded_parameter = params
        self.num_class = int(header.get("num_class", 1))
        self.num_tree_per_iteration = int(
            header.get("num_tree_per_iteration", 1))
        self.max_feature_idx = int(header.get("max_feature_idx", 0))
        self.label_index = int(header.get("label_index", 0))
        self.average_output = header.get("average_output", "0") == "1"
        self.feature_names = header.get("feature_names", "").split()
        self.feature_infos = header.get("feature_infos", "").split()
        obj_str = header.get("objective", "none")
        self._objective_str = obj_str
        self.objective = create_objective_from_string(obj_str)
        # pre-plane artifacts have neither block -> None (serving emits
        # one drift_unavailable event instead of monitoring)
        self.data_profile = model_io.extract_data_profile(model_str)
        self.provenance = model_io.extract_provenance(model_str)

    # ------------------------------------------------------------------
    def feature_importance(self, importance_type: str = "split",
                           iteration: Optional[int] = None) -> np.ndarray:
        it = 0 if importance_type == "split" else 1
        self._drain()
        models = self.models
        if iteration is not None and iteration > 0:
            models = models[:iteration * self.num_tree_per_iteration]
        return model_io.feature_importance(models, self.max_feature_idx + 1,
                                           it)

    def feature_name(self) -> List[str]:
        return self.feature_names

    def num_feature(self) -> int:
        return self.max_feature_idx + 1

    # ------------------------------------------------------------------
    def refit(self, data, label, decay_rate: float = 0.9, **kwargs):
        """Refit leaf values on new data (ref: basic.py:3506 Booster.refit,
        gbdt.cpp:287 RefitTree)."""
        X = _to_2d_numpy(data).astype(np.float64)
        label = np.asarray(label, np.float64).reshape(-1)
        import copy
        self._drain()
        new_booster = copy.deepcopy(self)
        # leaf assignment per tree, then leaf values blended:
        # new = decay * old + (1-decay) * newly-fitted mean residual value
        cfg = Config(self.params) if self.params else Config({})
        obj = self.objective
        k = self.num_tree_per_iteration
        n = X.shape[0]
        scores = np.zeros((k, n))
        if obj is not None:
            import jax.numpy as jnp
            from .dataset import Metadata
            md = Metadata(n)
            md.set_label(label)
            obj.init(md, n)
        for i, t in enumerate(new_booster.models):
            tid = i % k
            leaves = t.predict_leaf_index(X)
            if obj is not None:
                g, h = obj.get_gradients(jnp.asarray(scores, jnp.float32))
                g, h = np.asarray(g), np.asarray(h)
            else:
                g = scores - label[None, :]
                h = np.ones_like(g)
            for leaf in range(t.num_leaves):
                rows = leaves == leaf
                if rows.any():
                    sum_g = g[tid, rows].sum()
                    sum_h = h[tid, rows].sum()
                    new_out = -sum_g / (sum_h + cfg.lambda_l2) \
                        * t.shrinkage if sum_h > 0 else 0.0
                    t.leaf_value[leaf] = (decay_rate * t.leaf_value[leaf]
                                          + (1.0 - decay_rate) * new_out)
            scores[tid] += t.predict_rows(X)
        return new_booster

    def reset_training_data(self, train_set: "Dataset") -> "Booster":
        """Attach (or replace) training data on an existing model
        (ref: c_api.cpp:1631 LGBM_BoosterResetTrainingData ->
        gbdt.cpp:686 GBDT::ResetTrainingData): previously loaded/merged
        trees become the init segment (scores NOT replayed, matching the
        reference's iter_-only replay loop), while trees trained in this
        booster's own lifetime are kept trainable and their scores are
        replayed on the new data. New data must share bin mappers with
        the old (CheckAlign)."""
        self._drain()
        old_models = list(self.models) if self.models else []
        old_g = getattr(self, "_gbdt", None)
        post = []              # (host, device) trees trained post-init
        init_models = old_models
        if old_g is not None:
            k = max(1, old_g.num_tree_per_iteration)
            n_init = old_g.num_init_iteration * k
            init_models = old_models[:n_init]
            post = list(zip(old_g.models[n_init:],
                            old_g.device_trees[n_init:]))
            train_set.construct()
            if self.train_set is not None \
                    and train_set is not self.train_set \
                    and train_set._inner.feature_infos() \
                    != self.train_set._inner.feature_infos():
                raise ValueError(
                    "Cannot reset training data, since new training data "
                    "has different bin mappers")
        # a model-file/string booster carries its objective in the header,
        # not in params — restore name AND sub-parameters ("binary
        # sigmoid:2" -> objective=binary, sigmoid=2) so _init_train
        # rebuilds the same one
        if "objective" not in self.params \
                and getattr(self, "_objective_str", None):
            toks = self._objective_str.split()
            self.params["objective"] = toks[0]
            for t in toks[1:]:
                if ":" in t:
                    k, v = t.split(":", 1)
                    self.params.setdefault(k, v)
        if self.num_class > 1:
            self.params.setdefault("num_class", self.num_class)
        self._init_train(train_set)
        g = self._gbdt
        if init_models:
            g.adopt_init_models(init_models)
        # post-init trees: keep trainable, replay scores on the new data
        # (binned thresholds stay valid under the CheckAlign contract)
        for idx, (ht, dt) in enumerate(post):
            tid = idx % g.num_tree_per_iteration
            g.models.append(ht)
            g.device_trees.append(dt)
            g.scores = g._add_tree_to_score(g.scores, g.bins_dev, dt, tid,
                                            bundle=g._train_bundle())
        g.iter = len(post) // max(1, g.num_tree_per_iteration)
        self.models = g.models
        self._model_version += 1
        return self

    def refit_by_leaf_preds(self, leaf_preds: np.ndarray) -> "Booster":
        """In-place leaf-value refit from a precomputed leaf-assignment
        matrix (ref: c_api.cpp:1665 LGBM_BoosterRefit -> gbdt.cpp:287
        RefitTree). Needs live training data — load the model, then
        reset_training_data() first."""
        if getattr(self, "_gbdt", None) is None:
            raise ValueError(
                "BoosterRefit needs training data; call "
                "reset_training_data()/LGBM_BoosterResetTrainingData first")
        self._gbdt.refit_by_leaf_preds(
            np.asarray(leaf_preds, np.int32).reshape(
                self._gbdt.num_data, -1))
        self._model_version += 1
        return self

    def __copy__(self):
        return self.__deepcopy__(None)

    def __deepcopy__(self, memo):
        model_str = self.model_to_string(num_iteration=-1)
        booster = Booster(model_str=model_str)
        booster.params = dict(self.params)
        return booster
