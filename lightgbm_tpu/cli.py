"""Command-line application.

Behavioral analog of the reference CLI (ref: src/main.cpp:11,
src/application/application.cpp:31): ``k=v`` arguments plus an optional
``config=<file>`` (one ``k=v`` per line, ``#`` comments; command-line
wins), tasks train / predict / refit-free convert paths:

    python -m lightgbm_tpu config=train.conf
    python -m lightgbm_tpu task=train data=train.csv valid=test.csv \\
        objective=binary num_iterations=100 output_model=model.txt
    python -m lightgbm_tpu task=predict data=test.csv \\
        input_model=model.txt output_result=preds.tsv

Observability flags (docs/Observability.md): ``telemetry_out=<path>``
streams structured JSONL telemetry (``telemetry_granularity=batch``,
the default, keeps the pipelined/megastep fast path and attributes time
per drained batch; ``iteration``/``section`` trade speed for finer
attribution), ``trace_out=<path>`` exports a Perfetto/Chrome-trace
timeline (one track per rank), ``health_check_period=N`` turns on the
cross-rank health auditor, ``profile_dir=<dir>`` captures a
jax.profiler trace of the training loop, and ``metrics_port=<p>``
serves the LIVE telemetry registry as an OpenMetrics/Prometheus
endpoint on ``http://127.0.0.1:<p>/metrics`` while the run is going
(rank r binds ``<p>+r`` under the multiproc launcher; rank 0 appends
the fleet counter view) — all ordinary config keys, so they work from
the command line and from config files alike. On a crash with
``telemetry_out`` set, the flight recorder dumps
``<telemetry_out>.crash.json``. ``compilation_cache_dir=<dir>`` makes
repeated CLI runs skip XLA recompiles (docs/Performance.md).

Resilience flags (docs/Reliability.md): ``checkpoint_dir=<dir>
checkpoint_period=N`` write async resumable checkpoints during
training, and ``task=train resume=<path>`` restores one (a concrete
``ckpt_<iteration>`` directory or the checkpoint_dir root — the newest
complete checkpoint is selected) and continues bit-identically to an
uninterrupted run.
"""
from __future__ import annotations

import sys
from typing import Dict, List

import numpy as np

from .basic import Booster, Dataset
from .engine import train as _train
from .utils import log


def parse_args(argv: List[str]) -> Dict[str, str]:
    """k=v args + config file (ref: application.cpp:50-83 LoadParameters;
    command-line overrides the file)."""
    cli: Dict[str, str] = {}
    for a in argv:
        if "=" not in a:
            raise SystemExit(f"unrecognized argument: {a} (expected k=v)")
        k, v = a.split("=", 1)
        cli[k.strip()] = v.strip()
    params: Dict[str, str] = {}
    conf = cli.pop("config", None)
    if conf:
        with open(conf) as f:
            for line in f:
                line = line.split("#", 1)[0].strip()
                if not line or "=" not in line:
                    continue
                k, v = line.split("=", 1)
                params[k.strip()] = v.strip()
    params.update(cli)
    return params


def run_train(params: Dict[str, str]) -> None:
    data = params.pop("data", None)
    if not data:
        raise SystemExit("task=train requires data=<file>")
    valid = params.pop("valid", params.pop("valid_data", ""))
    output_model = params.get("output_model", "LightGBM_model.txt")
    n_rounds = int(params.get("num_iterations",
                              params.get("num_boost_round", 100)))
    train_set = Dataset(data, params=dict(params))
    valid_sets = []
    valid_names = []
    for i, v in enumerate(p for p in valid.split(",") if p):
        valid_sets.append(Dataset(v, params=dict(params),
                                  reference=train_set))
        valid_names.append(f"valid_{i}")
    booster = _train(dict(params), train_set, num_boost_round=n_rounds,
                     valid_sets=valid_sets or None,
                     valid_names=valid_names or None)
    # the reference CLI saves ALL trees even after early stopping
    # (Application::Train -> SaveModelToFile(0, -1, ...)); -1 beats the
    # Python facade's best_iteration default
    booster.save_model(output_model, num_iteration=-1)
    log.info("Finished training; model saved to %s", output_model)
    tel_out = params.get("telemetry_out", params.get("telemetry_output"))
    if tel_out:
        log.info("Telemetry JSONL written to %s", tel_out)
    trace_out = params.get("trace_out", params.get("trace_output"))
    if trace_out:
        log.info("Load %s in chrome://tracing or ui.perfetto.dev",
                 trace_out)
    mp = getattr(getattr(booster, "_gbdt", None), "_metrics", None)
    if mp is not None and mp.url:
        log.info("OpenMetrics endpoint still live at %s (until this "
                 "process exits)", mp.url)


def run_predict(params: Dict[str, str]) -> None:
    data = params.pop("data", None)
    model = params.pop("input_model", None)
    if not data or not model:
        raise SystemExit("task=predict requires data=<file> and "
                         "input_model=<file>")
    out_path = params.pop("output_result", "LightGBM_predict_result.txt")
    booster = Booster(model_file=model)
    # predict-time keys (pred_device_min_work, pred_early_stop, ...)
    # ride the booster params so the path choice is CLI-controllable
    booster.params.update(params)
    from .io.file_loader import load_text_file
    # a prediction file may or may not carry the label column; default to
    # stripping column 0 only when the width says one extra column is
    # present (the reference requires the same layout as training data)
    lc = params.get("label_column")
    X, _, _ = load_text_file(data, label_column=-1 if lc is None else lc)
    n_feat = booster.num_feature()
    if lc is None and X.shape[1] == n_feat + 1:
        X = X[:, 1:]    # training-style file: first column is the label
    if X.shape[1] != n_feat:
        raise SystemExit(
            f"prediction data has {X.shape[1]} columns but the model "
            f"expects {n_feat} features (pass label_column=... if a "
            f"label column is present)")
    preds = booster.predict(
        X, raw_score=str(params.get("predict_raw_score",
                                    "false")).lower() == "true",
        pred_leaf=str(params.get("predict_leaf_index",
                                 "false")).lower() == "true",
        pred_contrib=str(params.get("predict_contrib",
                                    "false")).lower() == "true")
    np.savetxt(out_path, np.asarray(preds), fmt="%.9g", delimiter="\t")
    log.info("Finished prediction; results saved to %s", out_path)


def run_refit(params: Dict[str, str]) -> None:
    """(ref: application.cpp task=refit + gbdt.cpp:287 RefitTree)"""
    data = params.pop("data", None)
    model = params.pop("input_model", None)
    if not data or not model:
        raise SystemExit("task=refit requires data=<file> and "
                         "input_model=<file>")
    out_path = params.get("output_model", "LightGBM_model.txt")
    booster = Booster(model_file=model)
    from .io.file_loader import load_text_file
    X, y, _ = load_text_file(data,
                             label_column=params.get("label_column", 0))
    if y is None:
        raise SystemExit("refit data must carry a label column")
    decay = float(params.get("refit_decay_rate", 0.9))
    new_booster = booster.refit(X, y, decay_rate=decay)
    new_booster.save_model(out_path)
    log.info("Finished refit; model saved to %s", out_path)


def run_convert_model(params: Dict[str, str]) -> None:
    """(ref: application.cpp task=convert_model -> gbdt_model_text.cpp
    SaveModelToIfElse / tree.cpp:562 ToIfElse)"""
    model = params.pop("input_model", None)
    if not model:
        raise SystemExit("task=convert_model requires input_model=<file>")
    lang = params.get("convert_model_language", "cpp")
    if lang not in ("cpp", ""):
        raise SystemExit(f"convert_model_language={lang} is not supported "
                         "(cpp only, like the reference)")
    out_path = params.get("convert_model", "gbdt_prediction.cpp")
    from .io.model_io import model_to_if_else
    booster = Booster(model_file=model)
    with open(out_path, "w") as fh:
        fh.write(model_to_if_else(booster))
    log.info("Finished converting model; code saved to %s", out_path)


def run_save_binary(params: Dict[str, str]) -> None:
    """(ref: application.cpp:70-83 task=save_binary — load the training
    data, write the binary cache next to it, exit)

    Writes the sharded v2 cache artifact (docs/Data.md): versioned,
    SHA-256-manifested, mmap-able; ``Dataset(data="<file>.bin")`` /
    ``data=<file>.bin`` on a later run skips text parsing and binning
    entirely.  The build itself streams in bounded chunks
    (``two_round`` defaults ON here so host RSS stays O(chunk) — pass
    ``two_round=false`` to force the monolithic load;
    ``ingest_chunk_rows`` sizes the chunks)."""
    from .ingest.cache import CacheError
    data = params.pop("data", None)
    if not data:
        raise SystemExit("task=save_binary requires data=<file>")
    out = params.get("output_model", data + ".bin")
    params.setdefault("two_round", "true")
    if out == data + ".bin":
        # default destination == the auto-cache sidecar: stream packed
        # chunks STRAIGHT into the artifact (the parsed shard never
        # exists in RAM at once), fingerprinted for later auto-hits
        params.setdefault("save_binary", "true")
    ds = Dataset(data, params=dict(params))
    ds.construct()
    # the construct may already have produced the artifact at `out`
    # (streamed cache_out or the sidecar auto-write) — rewriting it
    # here would REPLACE the fingerprinted manifest with a source-less
    # one and turn every later save_binary auto-load into a miss
    stats = getattr(ds._inner, "ingest_stats", None) or {}
    already = (stats.get("cache_path") == out
               or getattr(ds._inner, "sidecar_cache_path", None) == out)
    if not already:
        try:
            ds._inner.save_binary(out)
        except CacheError as e:
            raise SystemExit(f"cannot save binary dataset: {e}")
    log.info("Finished saving binary dataset to %s", out)


def main(argv: List[str] = None) -> None:
    from .utils.platform import pin_jax_platforms
    pin_jax_platforms()
    params = parse_args(sys.argv[1:] if argv is None else argv)
    task = params.pop("task", "train")
    if task == "train":
        run_train(params)
    elif task in ("predict", "prediction", "test"):
        run_predict(params)
    elif task == "refit":
        run_refit(params)
    elif task == "convert_model":
        run_convert_model(params)
    elif task == "save_binary":
        run_save_binary(params)
    else:
        raise SystemExit(f"unknown task: {task}")


if __name__ == "__main__":
    main()
