"""Histogram-plane layout: the ONE source of truth for how (feature, bin)
pairs map onto the kernels' 128-lane-aligned flat axis.

Two layouts:

- **padded** (`feature_layout`): every feature widened to the global pow2
  bin count ``Bp`` and the feature count rounded so ``(Fp * Bp) % 128 ==
  0``.  This is the round-2 contract both kernels used to compute
  independently (``ops/fused_level.feature_layout`` and
  ``ops/pallas_histogram.pad_feature_layout``) — consolidated here so a
  layout change cannot drift between the standalone and fused kernels.
- **packed** (`packed_feature_layout`): adaptive per-feature bin widths
  (arxiv 2603.00326).  Each feature gets its own pow2 width ``>= its
  effective bin count`` and features are grouped by width class, each
  class region padded to the 128 lane quantum, instead of padding every
  feature to the global ``Bp``.  On heterogeneous-cardinality data this
  shrinks the ``[C, FB]`` one-hot scratch and the ``[FB, nch*Sp]``
  accumulator — the VMEM/HBM terms that set the fused kernel's floor.
  The packed layout is a pure re-indexing: per-(feature, bin) sums are
  accumulated in the same row-tile order as the padded layout, so the
  decoded histograms are BIT-IDENTICAL to the padded ones (the
  adaptive-bin A/B contract; the caller must keep the row-tile width at
  the padded formula for that to hold — see
  ``fused_level.level_pass``).

The byte model (`hist_plane_bytes`) quantifies what the histogram plane
reads, builds, and keeps per level pass — the figure the driver exports
as ``hist.bytes_per_level`` and the bench gates as
``hist_bytes_per_iter``.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import numpy as np


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _next_pow2(x: int) -> int:
    return 1 << max(0, (x - 1).bit_length())


LANE = 128
MIN_WIDTH = 8   # sublane quantum: a feature slab is never narrower


def feature_layout(num_features: int, max_bin: int) -> Tuple[int, int]:
    """(Fp, Bp) with Bp = pow2 >= max_bin and (Fp * Bp) % 128 == 0.

    Fp is the one-hot feature count (>= num_features); padded features
    must carry bin 0 everywhere and be masked out of the split scan.
    The single shared contract of the fused and standalone kernels.
    """
    Bp = max(MIN_WIDTH, _next_pow2(max_bin))
    quota = max(1, LANE // min(Bp, LANE))
    Fp = _round_up(max(num_features, 1), quota)
    return Fp, Bp


@dataclasses.dataclass(frozen=True)
class PackedLayout:
    """Adaptive per-feature bin packing (hashable: rides jit static args).

    ``classes``: ordered (width, count) groups; features appear in the
    kernel's bin matrix in ``feat_order`` (grouped by width class), each
    class's flat region padded to the 128 lane quantum.  ``f_oh``/``bp``
    keep the LOGICAL padded layout the split search / pools / route
    tables stay on; only the kernel's flat axis is packed.
    """
    classes: Tuple[Tuple[int, int], ...]   # (width, n_features) per class
    feat_order: Tuple[int, ...]            # logical ids, kernel row order
    widths: Tuple[int, ...]                # per feat_order entry
    fb: int                                # packed flat width (% 128 == 0)
    f_oh: int                              # logical padded feature count
    bp: int                                # logical pow2 bin width

    # ---- derived static index maps (numpy, cached per layout) ----
    @functools.cached_property
    def flat_offsets(self) -> np.ndarray:
        """[len(feat_order)] flat offset of each packed feature's slab."""
        offs = np.zeros(len(self.feat_order), np.int64)
        o = 0
        j = 0
        for w, cnt in self.classes:
            for _ in range(cnt):
                offs[j] = o
                o += w
                j += 1
            o = _round_up(o, LANE)
        return offs

    @functools.cached_property
    def row_offsets(self) -> np.ndarray:
        """[n_classes] first bin-matrix row of each class region."""
        out = np.zeros(len(self.classes), np.int64)
        r = 0
        for i, (_, cnt) in enumerate(self.classes):
            out[i] = r
            r += cnt
        return out

    @functools.cached_property
    def class_flat_offsets(self) -> np.ndarray:
        """[n_classes] flat offset of each class region."""
        out = np.zeros(len(self.classes), np.int64)
        o = 0
        for i, (w, cnt) in enumerate(self.classes):
            out[i] = o
            o = _round_up(o + w * cnt, LANE)
        return out

    @functools.cached_property
    def padded_to_packed(self) -> np.ndarray:
        """[f_oh * bp] -> packed flat index (0 where invalid)."""
        idx = np.zeros(self.f_oh * self.bp, np.int32)
        for j, f in enumerate(self.feat_order):
            w = self.widths[j]
            o = int(self.flat_offsets[j])
            idx[f * self.bp: f * self.bp + w] = o + np.arange(w)
        return idx

    @functools.cached_property
    def padded_valid(self) -> np.ndarray:
        """[f_oh * bp] bool: position exists in the packed layout."""
        v = np.zeros(self.f_oh * self.bp, bool)
        for j, f in enumerate(self.feat_order):
            v[f * self.bp: f * self.bp + self.widths[j]] = True
        return v

    @functools.cached_property
    def packed_to_padded(self) -> np.ndarray:
        """[fb] -> padded flat index (0 where class padding)."""
        idx = np.zeros(self.fb, np.int32)
        for j, f in enumerate(self.feat_order):
            w = self.widths[j]
            o = int(self.flat_offsets[j])
            idx[o:o + w] = f * self.bp + np.arange(w)
        return idx

    @functools.cached_property
    def packed_valid(self) -> np.ndarray:
        v = np.zeros(self.fb, bool)
        for j in range(len(self.feat_order)):
            o = int(self.flat_offsets[j])
            v[o:o + self.widths[j]] = True
        return v

    @functools.cached_property
    def feat_of_packed(self) -> np.ndarray:
        """[fb] logical feature id per packed position (0 where pad)."""
        f = np.zeros(self.fb, np.int32)
        for j, fid in enumerate(self.feat_order):
            o = int(self.flat_offsets[j])
            f[o:o + self.widths[j]] = fid
        return f


def packed_feature_layout(num_bin_per_feat, max_bin: int,
                          f_oh: Optional[int] = None) -> PackedLayout:
    """Adaptive layout from per-feature effective bin counts.

    Features are grouped by pow2 width class (descending width, so the
    widest slabs come first and the leftovers pack the narrow tail);
    padding features (num_bin <= 0) are dropped from the kernel layout
    entirely — their decoded planes are zero by construction.
    """
    nb = np.asarray(num_bin_per_feat, np.int64)
    F = int(nb.shape[0])
    Fp, Bp = feature_layout(F, max_bin)
    if f_oh is None:
        f_oh = Fp
    widths_all = np.where(nb > 0,
                          np.maximum(MIN_WIDTH,
                                     2 ** np.ceil(np.log2(
                                         np.maximum(nb, 2))).astype(np.int64)),
                          0)
    classes = []
    feat_order = []
    widths = []
    for w in sorted({int(x) for x in widths_all if x > 0}, reverse=True):
        feats = [int(f) for f in np.nonzero(widths_all == w)[0]]
        classes.append((w, len(feats)))
        feat_order.extend(feats)
        widths.extend([w] * len(feats))
    fb = 0
    for w, cnt in classes:
        fb = _round_up(fb + w * cnt, LANE)
    fb = max(fb, LANE)
    return PackedLayout(classes=tuple(classes), feat_order=tuple(feat_order),
                        widths=tuple(widths), fb=int(fb), f_oh=int(f_oh),
                        bp=int(Bp))


def hist_plane_bytes(fb: int, nch: int, sp: int, rows_padded: int,
                     tile_rows: int, quant_bits: int) -> int:
    """Bytes the histogram plane touches per level pass: the [FB, C]
    one-hot scratch (built once per row tile, re-read by both MXU dots),
    the [FB, nch*Sp] accumulator, and the [8, R] gh channel stream.
    Quantization (``tpu_quantized_grad``) halves the one-hot and gh
    element widths (int8 channels vs bf16); adaptive bins shrink ``fb``.
    The bins/leaf/W streams are layout-independent and excluded — this
    figure isolates exactly what the three histogram-plane cuts move."""
    oh_elem = 1 if quant_bits else 2
    gh_elem = 1 if quant_bits else 2
    acc_elem = 4   # f32 or int32 accumulator
    n_tiles = max(1, rows_padded // max(1, tile_rows))
    oh = fb * tile_rows * oh_elem * n_tiles
    acc = fb * nch * sp * acc_elem
    gh = 8 * rows_padded * gh_elem
    return int(oh + acc + gh)
