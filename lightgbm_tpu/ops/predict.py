"""On-device tree routing over binned features.

Used for validation-score updates during training (the reference walks
pointer trees per row on the host, gbdt.cpp UpdateScore /
score_updater.hpp:88; here the whole valid set advances one tree level per
fused pass — no host round trips).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _route_left(b, t, default_left, nb, mt, db):
    """Split decision on bin values with missing routing
    (ref: src/io/dense_bin.hpp Split)."""
    missing = (((mt == 1) & (b == db)) | ((mt == 2) & (b == nb - 1)))
    return jnp.where(missing, default_left, b <= t)


@functools.partial(jax.jit, static_argnames=("max_steps",))
def route_rows_to_leaves(bins: jax.Array, split_feature: jax.Array,
                         threshold_bin: jax.Array, default_left: jax.Array,
                         left_child: jax.Array, right_child: jax.Array,
                         num_bin: jax.Array, missing_type: jax.Array,
                         default_bin: jax.Array, max_steps: int,
                         cat_flag: jax.Array = None,
                         cat_mask: jax.Array = None,
                         bundle: tuple = None) -> jax.Array:
    """Leaf index per row for one tree (arrays follow the TreeArrays
    convention: child >= 0 internal node, child < 0 means ~leaf).

    ``max_steps`` must be >= tree depth.  Single-leaf trees (no node 0)
    are handled by the caller (leaf 0 for every row).
    ``cat_flag``/``cat_mask`` ([N], [N, B]) enable categorical bitset
    decisions (ref: tree.h CategoricalDecision on bin space).
    ``bundle``: (col_of_feat, offset_of_feat, most_freq_bin) when ``bins``
    holds EFB BUNDLE columns (sparse-built datasets) — the logical bin is
    decoded per node: in-window values shift by the feature's offset,
    out-of-window rows are bundle-default and carry the feature's most
    frequent bin (ops/efb.py encoding).
    """
    R = bins.shape[0]
    node = jnp.zeros((R,), jnp.int32)

    def body(_, node):
        is_internal = node >= 0
        nd = jnp.maximum(node, 0)
        f = split_feature[nd]
        if bundle is None:
            b = jnp.take_along_axis(bins, f[:, None].astype(jnp.int32),
                                    axis=1)[:, 0].astype(jnp.int32)
        else:
            col_of_feat, offset_of_feat, mfb = bundle
            raw = jnp.take_along_axis(
                bins, col_of_feat[f][:, None].astype(jnp.int32),
                axis=1)[:, 0].astype(jnp.int32)
            off = offset_of_feat[f]
            in_win = (raw >= off) & (raw < off + num_bin[f])
            b = jnp.where(in_win, raw - off, mfb[f])
        go_left = _route_left(b, threshold_bin[nd], default_left[nd],
                              num_bin[f], missing_type[f], default_bin[f])
        if cat_flag is not None:
            cat_left = cat_mask[nd, b]
            go_left = jnp.where(cat_flag[nd], cat_left, go_left)
        nxt = jnp.where(go_left, left_child[nd], right_child[nd])
        return jnp.where(is_internal, nxt, node)

    node = jax.lax.fori_loop(0, max_steps, body, node)
    return jnp.where(node < 0, ~node, 0)


@functools.partial(jax.jit, static_argnames=("max_steps",))
def add_tree_score(score: jax.Array, bins: jax.Array, leaf_value: jax.Array,
                   split_feature: jax.Array, threshold_bin: jax.Array,
                   default_left: jax.Array, left_child: jax.Array,
                   right_child: jax.Array, num_bin: jax.Array,
                   missing_type: jax.Array, default_bin: jax.Array,
                   max_steps: int, cat_flag: jax.Array = None,
                   cat_mask: jax.Array = None,
                   bundle: tuple = None) -> jax.Array:
    """score += leaf_value[route(row)] in one fused pass."""
    leaves = route_rows_to_leaves(bins, split_feature, threshold_bin,
                                  default_left, left_child, right_child,
                                  num_bin, missing_type, default_bin,
                                  max_steps, cat_flag, cat_mask,
                                  bundle=bundle)
    return score + leaf_value[leaves]
