"""On-device tree routing over binned features.

Used for validation-score updates during training (the reference walks
pointer trees per row on the host, gbdt.cpp UpdateScore /
score_updater.hpp:88; here the whole valid set advances one tree level per
fused pass — no host round trips).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _route_left(b, t, default_left, nb, mt, db):
    """Split decision on bin values with missing routing
    (ref: src/io/dense_bin.hpp Split)."""
    missing = (((mt == 1) & (b == db)) | ((mt == 2) & (b == nb - 1)))
    return jnp.where(missing, default_left, b <= t)


@functools.partial(jax.jit, static_argnames=("max_steps",))
def route_rows_to_leaves(bins: jax.Array, split_feature: jax.Array,
                         threshold_bin: jax.Array, default_left: jax.Array,
                         left_child: jax.Array, right_child: jax.Array,
                         num_bin: jax.Array, missing_type: jax.Array,
                         default_bin: jax.Array, max_steps: int,
                         cat_flag: jax.Array = None,
                         cat_mask: jax.Array = None,
                         bundle: tuple = None) -> jax.Array:
    """Leaf index per row for one tree (arrays follow the TreeArrays
    convention: child >= 0 internal node, child < 0 means ~leaf).

    ``max_steps`` must be >= tree depth.  Single-leaf trees (no node 0)
    are handled by the caller (leaf 0 for every row).
    ``cat_flag``/``cat_mask`` ([N], [N, B]) enable categorical bitset
    decisions (ref: tree.h CategoricalDecision on bin space).
    ``bundle``: (col_of_feat, offset_of_feat, most_freq_bin) when ``bins``
    holds EFB BUNDLE columns (sparse-built datasets) — the logical bin is
    decoded per node: in-window values shift by the feature's offset,
    out-of-window rows are bundle-default and carry the feature's most
    frequent bin (ops/efb.py encoding).
    """
    R = bins.shape[0]
    node = jnp.zeros((R,), jnp.int32)

    def body(_, node):
        is_internal = node >= 0
        nd = jnp.maximum(node, 0)
        f = split_feature[nd]
        if bundle is None:
            b = jnp.take_along_axis(bins, f[:, None].astype(jnp.int32),
                                    axis=1)[:, 0].astype(jnp.int32)
        else:
            col_of_feat, offset_of_feat, mfb = bundle
            raw = jnp.take_along_axis(
                bins, col_of_feat[f][:, None].astype(jnp.int32),
                axis=1)[:, 0].astype(jnp.int32)
            off = offset_of_feat[f]
            in_win = (raw >= off) & (raw < off + num_bin[f])
            b = jnp.where(in_win, raw - off, mfb[f])
        go_left = _route_left(b, threshold_bin[nd], default_left[nd],
                              num_bin[f], missing_type[f], default_bin[f])
        if cat_flag is not None:
            cat_left = cat_mask[nd, b]
            go_left = jnp.where(cat_flag[nd], cat_left, go_left)
        nxt = jnp.where(go_left, left_child[nd], right_child[nd])
        return jnp.where(is_internal, nxt, node)

    node = jax.lax.fori_loop(0, max_steps, body, node)
    return jnp.where(node < 0, ~node, 0)


@functools.partial(jax.jit, static_argnames=("max_steps",))
def route_raw_rows_to_leaves(values: jax.Array, split_feature: jax.Array,
                             threshold: jax.Array, default_left: jax.Array,
                             missing_type: jax.Array, left_child: jax.Array,
                             right_child: jax.Array, max_steps: int,
                             cat_flag: jax.Array = None,
                             cat_mask: jax.Array = None) -> jax.Array:
    """Leaf index per row for one tree routed on RAW feature values —
    the serving-side variant for boosters without training BinMappers
    (model-file loads).  Mirrors the host walk exactly
    (ref: tree.h NumericalDecision / CategoricalDecision):

    - ``missing_type`` is PER NODE here (decoded from the model's
      decision_type bitfield), not per feature;
    - NaN with missing_type none/zero is treated as 0.0;
    - ``threshold`` must be pre-rounded to the largest float32 <= the
      model's float64 threshold (models/predictor.threshold_to_f32), so
      the float32 compare routes float32-representable inputs
      bit-identically to the float64 host compare;
    - ``cat_mask`` ([N, C]) is indexed by the raw integer category value
      (bounded by the packer); out-of-range/negative goes right.
    """
    R = values.shape[0]
    node = jnp.zeros((R,), jnp.int32)

    def body(_, node):
        is_internal = node >= 0
        nd = jnp.maximum(node, 0)
        f = split_feature[nd]
        v = jnp.take_along_axis(values, f[:, None].astype(jnp.int32),
                                axis=1)[:, 0]
        mt = missing_type[nd]
        nan_mask = jnp.isnan(v)
        zero_mask = jnp.abs(v) <= 1e-35          # kZeroThreshold
        is_missing = jnp.where(mt == 2, nan_mask,
                               jnp.where(mt == 1, zero_mask | nan_mask,
                                         False))
        v_eff = jnp.where(nan_mask & (mt != 2), jnp.float32(0.0), v)
        go_left = jnp.where(is_missing, default_left[nd],
                            v_eff <= threshold[nd])
        if cat_flag is not None:
            C = cat_mask.shape[1]
            # range-check BEFORE the int cast: float->int32 of values
            # past 2^31 is implementation-defined in XLA (wrap or
            # saturate), and a wrapped value could land inside [0, C)
            # and read mask garbage.  The bound is v <= -1, not v < 0:
            # the host walk truncates toward zero, so (-1, 0) becomes
            # category 0 there and must here too
            bad = nan_mask | (v <= -1.0) | (v >= jnp.float32(C))
            iv = jnp.where(bad, jnp.float32(-1), v).astype(jnp.int32)
            in_range = iv >= 0
            cat_left = cat_mask[nd, jnp.clip(iv, 0, C - 1)] & in_range
            go_left = jnp.where(cat_flag[nd], cat_left, go_left)
        nxt = jnp.where(go_left, left_child[nd], right_child[nd])
        return jnp.where(is_internal, nxt, node)

    node = jax.lax.fori_loop(0, max_steps, body, node)
    return jnp.where(node < 0, ~node, 0)


@functools.partial(jax.jit, static_argnames=("max_steps",))
def add_tree_score(score: jax.Array, bins: jax.Array, leaf_value: jax.Array,
                   split_feature: jax.Array, threshold_bin: jax.Array,
                   default_left: jax.Array, left_child: jax.Array,
                   right_child: jax.Array, num_bin: jax.Array,
                   missing_type: jax.Array, default_bin: jax.Array,
                   max_steps: int, cat_flag: jax.Array = None,
                   cat_mask: jax.Array = None,
                   bundle: tuple = None) -> jax.Array:
    """score += leaf_value[route(row)] in one fused pass."""
    leaves = route_rows_to_leaves(bins, split_feature, threshold_bin,
                                  default_left, left_child, right_child,
                                  num_bin, missing_type, default_bin,
                                  max_steps, cat_flag, cat_mask,
                                  bundle=bundle)
    return score + leaf_value[leaves]
