"""Fused per-level Pallas kernel: route + histogram in ONE pass over rows.

This is the round-2 hot path, replacing ops/pallas_histogram.py +
the per-slot routing loop of models/frontier.py. It replaces the
reference's hottest loops (ref: src/io/dense_bin.hpp ConstructHistogram,
src/treelearner/serial_tree_learner.cpp:355-453, ocl/histogram256.cl) with
a single streaming kernel per tree level.

Design (all measured on the attached TPU, see PROFILE.md):

- Layout is TRANSPOSED vs round 1: rows ride the 128-wide lane dimension,
  features/bins/slots ride sublanes. The bin one-hot build then uses only
  native sublane broadcasts (no per-feature lane broadcast / int8 sublane
  extraction, which cost 2-3x in round 1's kernel).
- The one-hot ``oh[f*B+b, r] = (bins[f, r] == b)`` is built ONCE per row
  tile with a bulk int8->int32 convert + ``jnp.repeat`` + one compare, then
  feeds BOTH matmuls:
    * routing:   ``D = W @ oh``            -> [S, C]  (W encodes this
      level's split thresholds + missing routing per slot)
    * histogram: ``hist += oh @ ghs^T``    -> [FB, nch*S]
  so routing costs one extra MXU pass instead of a separate O(S*R)
  column-load loop over HBM (round 1's dominant cost).
- All gh channels are packed into ONE dot (N = nch*S): measured MXU
  efficiency rises sharply with N (45 TF/s at N=192 -> 83 TF/s at N=384).
- Channels (``nch=5``, default): g_hi, g_lo, h_hi, h_lo, w — grad/hess are
  split into two bfloat16 halves (hi + exact residual) so the accumulated
  histogram carries ~fp32 input precision, matching the reference GPU
  precision contract (ref: docs/GPU-Performance.rst:130-160) instead of
  round 1's raw-bf16 rounding. ``nch=3`` (g, h, w single-bf16) is the fast
  mode.
- The grid is sequential on a TPU core, so the [FB, nch*S] output block
  accumulates across row tiles race-free; the updated row->leaf vector is
  emitted per-tile alongside.
- The ROOT pass needs no special kernel: tables with leaf_of_slot=[0],
  W[0, 0:B] = 1 (every row "goes left" on feature 0) and small_is_left=1
  make slot 0 collect the full-data histogram.

The smaller child of each split is histogrammed (caller puts the smaller
side in the slot tables); the sibling is reconstructed outside by
subtraction (ref: serial_tree_learner.cpp:423-425).
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from .layout import PackedLayout, feature_layout  # noqa: F401  (shared
# single-source layout contract — re-exported for existing callers)
from . import quantize

try:  # pragma: no cover - exotic backends fall back to interpret mode
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    # jax < 0.5 names it TPUCompilerParams (same kwargs)
    _CompilerParams = getattr(pltpu, "CompilerParams", None) \
        or pltpu.TPUCompilerParams
    HAS_PALLAS = True
except Exception:  # pragma: no cover
    HAS_PALLAS = False

NCH_PRECISE = 5   # g_hi, g_lo, h_hi, h_lo, w
NCH_FAST = 3      # g, h, w


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _next_pow2(x: int) -> int:
    return 1 << max(0, (x - 1).bit_length())


VMEM_BUDGET = 15 * 1024 * 1024  # scoped-vmem stack limit is 16 MB; leave
# headroom for W/ghs/D values and the pipeline's operand double buffers


def default_tile_rows(Sp: int, FB: int, nch: int,
                      wide_bins: bool = False) -> int:
    """Row-tile width: the [FB, C] bf16 one-hot scratch (2 B/elem), the
    [FB, C] repeated-bins intermediate, the [FB, C] iota plane (both
    2 B/elem bf16 for B <= 256, else 4 B/elem f32 — see _write_onehot)
    and the [FB, nch*Sp] f32 accumulator must fit the scoped-VMEM stack
    together. Round 2's formula ignored the build intermediate entirely
    and a 255-bin config exceeded the 16 MB stack limit — caught on-chip
    in round 3. The iota term is charged CONSERVATIVELY (advisor r4):
    Mosaic may fold the broadcasted_iota into the subtract, but that
    cannot be verified off-chip and an overflow is a hard compile/run
    failure; the pending on-chip ablation (scripts/ablate_kernel.py
    sweeps tile sizes) is the evidence either way.

    Shallow levels (small Sp -> small accumulator) get LARGER tiles:
    their per-pass cost is floor-bound (oh-build + per-tile overheads,
    PROFILE.md §5 — the Sp<=8 passes cost half the tree), so halving the
    tile count halves the fixed per-tile cost where the MXU is padded
    anyway."""
    acc = FB * nch * Sp * 4
    avail = max(VMEM_BUDGET - acc, 2 * 1024 * 1024)
    per_elem = 4 if wide_bins else 2       # big + iota_b dtype width
    c = avail // ((2 + 2 * per_elem) * FB)
    c = 1 << max(7, (int(c)).bit_length() - 1)      # floor to pow2, >= 128
    return int(min(2048, c))


def _fit_tile(C: int, R: int) -> int:
    """Largest pow2 tile <= C dividing the padded row count."""
    while C > 128 and R % C:
        C //= 2
    return C


def _write_onehot(bins_ref, oh_ref, F_oh: int, B: int,
                  packed: PackedLayout = None, fm_ref=None) -> None:
    """oh[f*B+b, r] = 1.0 iff bins[f, r] == b, written to the VMEM
    scratch. Built ARITHMETICALLY — relu(1 - |bins - b|) — in bf16:
    integers <= 256 are exact in bf16, so the result is bit-identical to
    a compare while the repeated-bins intermediate stays 2 B/elem
    (Mosaic on this target compiles only i32 compares, which forced a
    4 B/elem intermediate in the round-2/3 build). Bin counts > 256
    (wide EFB bundle columns) use an f32 intermediate instead.

    Variants (tentpole cuts; the default path above is byte-unchanged):
    - int8 scratch (quantized histograms): a plain i32 compare cast to
      int8 — the intermediate cost returns, but the scratch and both
      MXU dots halve to 1 B/elem on the native s8 path;
    - ``packed`` (adaptive per-feature bins): the bin matrix rows are
      pre-permuted into width classes, so each class region builds with
      the same bulk repeat+compare at ITS width instead of the global
      pow2 B — class padding regions are zeroed;
    - ``fm_ref`` ([FB, 128], col 0 live): gain-screened features'
      slabs are zeroed after the build so they contribute nothing to
      either dot (the dynamic-mask form of skipping the slab; the
      static slab-skip is the on-chip ablation's follow-up).
    """
    quant = oh_ref.dtype == jnp.int8
    C = bins_ref.shape[1]

    def build(seg_ref_rows, w, span):
        """[rows] x width w -> one-hot block [rows*w, C]."""
        if quant:
            big = jnp.repeat(seg_ref_rows.astype(jnp.int32), w, axis=0)
            iota_b = jax.lax.broadcasted_iota(jnp.int32, (span, C), 0) % w
            return (big == iota_b).astype(jnp.int8)
        dt = jnp.bfloat16 if w <= 256 else jnp.float32
        big = jnp.repeat(seg_ref_rows.astype(dt), w, axis=0)
        iota_b = (jax.lax.broadcasted_iota(jnp.int32, (span, C), 0) % w) \
            .astype(dt)
        return jnp.maximum(1.0 - jnp.abs(big - iota_b), 0.0) \
            .astype(jnp.bfloat16)

    if packed is None:
        oh_ref[:] = build(bins_ref[:F_oh], B, F_oh * B)
    else:
        for ci, (w, cnt) in enumerate(packed.classes):
            r0 = int(packed.row_offsets[ci])
            o0 = int(packed.class_flat_offsets[ci])
            span = cnt * w
            oh_ref[o0:o0 + span] = build(bins_ref[r0:r0 + cnt], w, span)
            pad = _round_up(span, 128) - span
            if pad:
                oh_ref[o0 + span:o0 + span + pad] = jnp.zeros(
                    (pad, C), oh_ref.dtype)
    if fm_ref is not None:
        oh_ref[:] = oh_ref[:] * fm_ref[:, 0:1]


def max_slot_cap(FB: int, nch: int, budget: int = 4 * 1024 * 1024) -> int:
    """Largest per-level slot count whose [FB, nch*Sp] f32 accumulator fits
    in ``budget`` bytes of VMEM (wide-bin datasets get narrower levels and
    more of them)."""
    cap = budget // (FB * nch * 4)
    cap = 1 << max(3, int(cap).bit_length() - 1)
    return int(min(128, cap))


def pack_gh(grad: jax.Array, hess: jax.Array, weight: jax.Array,
            nch: int) -> jax.Array:
    """[8, R] bfloat16 channel block for the kernel.

    nch=5: g_hi, g_lo, h_hi, h_lo, w  (hi/lo bf16 split => fp32-grade sums)
    nch=3: g, h, w
    Rows beyond nch are zero padding (the sublane block is 8 tall anyway).
    """
    R = grad.shape[-1]
    z = jnp.zeros((R,), jnp.bfloat16)
    if nch == NCH_PRECISE:
        g_hi = grad.astype(jnp.bfloat16)
        g_lo = (grad - g_hi.astype(jnp.float32)).astype(jnp.bfloat16)
        h_hi = hess.astype(jnp.bfloat16)
        h_lo = (hess - h_hi.astype(jnp.float32)).astype(jnp.bfloat16)
        rows = [g_hi, g_lo, h_hi, h_lo, weight.astype(jnp.bfloat16), z, z, z]
    else:
        rows = [grad.astype(jnp.bfloat16), hess.astype(jnp.bfloat16),
                weight.astype(jnp.bfloat16), z, z, z, z, z]
    return jnp.stack(rows, axis=0)


def pack_gh_quant(grad: jax.Array, hess: jax.Array, weight: jax.Array,
                  bits: int, seed) -> Tuple[jax.Array, jax.Array]:
    """Quantized sibling of :func:`pack_gh` (``tpu_quantized_grad``):
    stochastic-rounded fixed-point grad/hess under a per-iteration
    global scale from a traced max-abs reduction (ops/quantize.py).

    Returns ([8, R] int8 channel block, [2] f32 scales).  bits=8 packs
    (g, h, w); bits=16 packs the int8 hi/lo split (g_hi, g_lo, h_hi,
    h_lo, w) so the MXU's native s8 x s8 -> s32 path accumulates the
    full 16-bit grid exactly.  ``weight`` must be a 0/1 in-bag mask
    (the fast paths' contract); zero-weight rows encode exactly zero.
    """
    R = grad.shape[-1]
    scales = quantize.quant_scales(grad, hess, bits)
    qg, qh = quantize.quantize_gh(grad, hess, scales, bits, seed)
    rows = quantize.encode_channels(qg, qh, weight, bits)
    z = jnp.zeros((R,), jnp.int8)
    rows = rows + [z] * (8 - len(rows))
    return jnp.stack(rows, axis=0), scales


def pack_route_table(W: jax.Array, packed: PackedLayout) -> jax.Array:
    """Padded-layout route table [Sp, F_oh*Bp] -> packed layout
    [Sp, packed.fb] (class-padding columns zero)."""
    idx = jnp.asarray(packed.packed_to_padded, jnp.int32)
    valid = jnp.asarray(packed.packed_valid)
    Wp = jnp.take(W, idx, axis=1)
    return jnp.where(valid[None, :], Wp, 0).astype(W.dtype)


def unpack_packed_flat(hist: jax.Array, packed: PackedLayout) -> jax.Array:
    """[packed.fb, X] kernel accumulator -> [F_oh*Bp, X] padded flat
    layout (exact gather — the accumulated per-(feature, bin) sums are
    the padded layout's, just re-indexed, so the decode is
    bit-identical to the padded kernel's output)."""
    idx = jnp.asarray(packed.padded_to_packed, jnp.int32)
    valid = jnp.asarray(packed.padded_valid)
    out = jnp.take(hist, idx, axis=0)
    return jnp.where(valid[:, None], out, 0)


def expand_feature_mask(fm: jax.Array, F_oh: int, B: int,
                        packed: PackedLayout = None) -> jax.Array:
    """Per-feature bool mask [F_oh] -> per-flat-position bool [FB] in
    the kernel layout (class/feature padding positions False)."""
    if packed is None:
        return jnp.repeat(fm, B, total_repeat_length=F_oh * B)
    f_of = jnp.asarray(packed.feat_of_packed, jnp.int32)
    valid = jnp.asarray(packed.packed_valid)
    return jnp.take(fm, f_of) & valid


def hist_planes(hist: jax.Array, nch: int, Sp: int, F_oh: int, B: int,
                packed: PackedLayout = None, quant_bits: int = 0,
                scales: jax.Array = None):
    """[FB, nch*Sp] kernel output -> (grad, hess, cnt) planes [Sp, F_oh, B]
    in float32 (hi/lo recombined when nch=5).

    ``packed`` re-indexes an adaptive-layout accumulator back onto the
    padded logical layout first (exact); ``quant_bits`` decodes int32
    integer sums through the ONE f32 rescale boundary (ops/quantize.py)
    — everything above (split search, pools, subtraction) stays f32 and
    unchanged."""
    if packed is not None:
        hist = unpack_packed_flat(hist, packed)

    def plane(c):
        return hist[:, c * Sp:(c + 1) * Sp]
    if quant_bits:
        g, h, c = quantize.decode_sums(
            [plane(i) for i in range(quantize.QNCH[quant_bits])],
            scales, quant_bits)
    elif nch == NCH_PRECISE:
        g = plane(0) + plane(1)
        h = plane(2) + plane(3)
        c = plane(4)
    else:
        g, h, c = plane(0), plane(1), plane(2)
    to = lambda x: x.T.reshape(Sp, F_oh, B)
    return to(g), to(h), to(c)


def build_route_table(feature: jax.Array, threshold: jax.Array,
                      default_left: jax.Array, num_bin: jax.Array,
                      missing_type: jax.Array, default_bin: jax.Array,
                      Sp: int, F_oh: int, B: int,
                      cat_flag: jax.Array = None,
                      cat_mask: jax.Array = None) -> jax.Array:
    """W [Sp, F_oh*B] bfloat16: W[k, f*B+b] = 1 iff a row with bin b of
    feature f goes LEFT under slot k's split. Missing-bin routing follows
    default_left (ref: src/io/dense_bin.hpp Split: zero/NaN bins ride the
    default direction). feature=-1 rows are all-zero (inactive slot).

    Args are per-slot [Sp] (feature/threshold/default_left, and optionally
    cat_flag [Sp] + cat_mask [Sp, B] for categorical splits where "left"
    membership is an explicit bin set) and per-feature [F] metadata.
    """
    F = num_bin.shape[0]
    f_iota = jnp.arange(F_oh, dtype=jnp.int32)[None, :, None]      # [1,Foh,1]
    b_iota = jnp.arange(B, dtype=jnp.int32)[None, None, :]         # [1,1,B]
    nb = jnp.zeros((F_oh,), jnp.int32).at[:F].set(num_bin)
    mt = jnp.zeros((F_oh,), jnp.int32).at[:F].set(missing_type)
    db = jnp.zeros((F_oh,), jnp.int32).at[:F].set(default_bin)
    nb = nb[None, :, None]
    mt = mt[None, :, None]
    db = db[None, :, None]

    feat = feature[:, None, None]                                  # [Sp,1,1]
    thr = threshold[:, None, None]
    dl = default_left[:, None, None]

    is_missing = (((mt == 1) & (b_iota == db))
                  | ((mt == 2) & (b_iota == nb - 1)))
    numeric_left = jnp.where(is_missing, dl, b_iota <= thr)
    if cat_flag is not None:
        cat_left = cat_mask[:, None, :]                            # [Sp,1,B]
        go_left = jnp.where(cat_flag[:, None, None], cat_left, numeric_left)
    else:
        go_left = numeric_left
    w = (f_iota == feat) & go_left & (feat >= 0)
    return w.reshape(Sp, F_oh * B).astype(jnp.bfloat16)


def build_route_table_bundled(feature: jax.Array, threshold: jax.Array,
                              default_left: jax.Array, num_bin: jax.Array,
                              missing_type: jax.Array,
                              default_bin: jax.Array,
                              most_freq_bin: jax.Array,
                              col_of_feat: jax.Array,
                              offset_of_feat: jax.Array,
                              C_cols: int, Bp: int,
                              cat_flag: jax.Array = None,
                              cat_mask: jax.Array = None) -> jax.Array:
    """W [Sp, C_cols*Bp] for LOGICAL splits over EFB bundle columns.

    A bundle-bin bb of column c decodes to logical feature f's bin as
    ``bb - offset_f`` when bb lies in f's window, and to f's
    most-frequent bin otherwise (rows default in every bundled feature
    share bundle bin 0 — ops/efb.py encoding). Only the owning column
    carries the decision; all other columns stay zero so the routing dot
    D = W @ one_hot still reads each row's verdict from exactly one
    lane. Missing-bin semantics follow the numerical rule on the DECODED
    bin (ref: src/io/dense_bin.hpp Split); categorical splits test the
    DECODED bin's membership in ``cat_mask`` [Sp, B_logical]."""
    F = num_bin.shape[0]
    Sp = feature.shape[0]
    c_iota = jnp.arange(C_cols, dtype=jnp.int32)[None, :, None]
    b_iota = jnp.arange(Bp, dtype=jnp.int32)[None, None, :]

    feat_safe = jnp.maximum(feature, 0)
    nb = num_bin[feat_safe][:, None, None]
    mt = missing_type[feat_safe][:, None, None]
    db = default_bin[feat_safe][:, None, None]
    mfb = most_freq_bin[feat_safe][:, None, None]
    col = col_of_feat[feat_safe][:, None, None]
    off = offset_of_feat[feat_safe][:, None, None]
    thr = threshold[:, None, None]
    dl = default_left[:, None, None]

    in_window = (b_iota >= off) & (b_iota < off + nb)
    logical_bin = jnp.where(in_window, b_iota - off, mfb)
    is_missing = (((mt == 1) & (logical_bin == db))
                  | ((mt == 2) & (logical_bin == nb - 1)))
    go_left = jnp.where(is_missing, dl, logical_bin <= thr)
    if cat_flag is not None:
        B = cat_mask.shape[1]
        lb = jnp.clip(logical_bin, 0, B - 1)
        cat_left = cat_mask[jnp.arange(Sp)[:, None, None], lb]
        go_left = jnp.where(cat_flag[:, None, None], cat_left, go_left)
    w = (c_iota == col) & go_left & (feature[:, None, None] >= 0)
    return w.reshape(Sp, C_cols * Bp).astype(jnp.bfloat16)


def bundle_plane_views(plane: jax.Array, flat_idx: jax.Array,
                       valid: jax.Array, default_bin: jax.Array
                       ) -> jax.Array:
    """Bundle histogram -> logical per-feature view with the FixHistogram
    residual on each feature's most-frequent bin (ref:
    src/io/dataset.cpp:1265). The single shared implementation for both
    the fused engine and models/learner.bundle_views.

    plane: [Sp, C_cols, Bp] or [Sp, C_cols, Bp, ch]. Returns the same
    rank with (C_cols, Bp) -> (F, B). Slot totals come from column 0 —
    every row lands in some bin of every column. Padding features (no
    valid bins) stay all-zero."""
    squeeze = plane.ndim == 3
    if squeeze:
        plane = plane[..., None]
    Sp, C, Bp, ch = plane.shape
    F, B = flat_idx.shape
    flat = plane.reshape(Sp, C * Bp, ch)
    view = jnp.take(flat, flat_idx.reshape(-1), axis=1) \
        .reshape(Sp, F, B, ch)
    view = jnp.where(valid[None, :, :, None], view, 0.0)
    totals = jnp.sum(plane[:, 0, :, :], axis=1)                 # [Sp, ch]
    residual = totals[:, None, :] - jnp.sum(view, axis=2)       # [Sp, F, ch]
    residual = residual * jnp.any(valid, axis=1)[None, :, None]
    out = view.at[jnp.arange(Sp)[:, None], jnp.arange(F)[None, :],
                  default_bin[None, :]].add(residual)
    return out[..., 0] if squeeze else out


def _level_kernel(*refs, B: int, F_oh: int, Sp: int, nch: int,
                  quant: bool = False, packed: PackedLayout = None,
                  has_fm: bool = False):
    if has_fm:
        (bins_ref, leaf_ref, gh_ref, w_ref, tbl_ref, fm_ref,
         hist_ref, newleaf_ref, oh_ref) = refs
    else:
        (bins_ref, leaf_ref, gh_ref, w_ref, tbl_ref,
         hist_ref, newleaf_ref, oh_ref) = refs
        fm_ref = None
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        hist_ref[:] = jnp.zeros_like(hist_ref)

    C = bins_ref.shape[1]

    _write_onehot(bins_ref, oh_ref, F_oh, B, packed=packed, fm_ref=fm_ref)

    leafb = leaf_ref[:]                                        # [1, C] i32

    # ---- routing: D[k, r] = 1 iff row r goes left under slot k's split.
    # Quantized mode routes on the same int8 one-hot through the MXU's
    # native s8 x s8 -> s32 path (W is 0/1-valued either way).
    oh = oh_ref[:]
    if quant:
        D = jax.lax.dot_general(w_ref[:], oh, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.int32)
        left_i = (D > 0).astype(jnp.int32)                     # [Sp, C] 0/1
    else:
        D = jax.lax.dot_general(w_ref[:], oh, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        # Mask algebra stays in i32/bf16 throughout: broadcast i1 vectors
        # hit a Mosaic relayout bug on this toolchain ("Invalid relayout
        # ... 8x1024xi1" when an [Sp,1] bool meets an [Sp,C] bool), and
        # int select lowers to the same VPU ops anyway.
        left_i = (D > 0.5).astype(jnp.int32)                   # [Sp, C] 0/1

    # ---- slot membership
    leaf_of_slot = tbl_ref[:, 0:1]                             # [Sp, 1]
    right_delta = tbl_ref[:, 1:2]
    small_left_i = (tbl_ref[:, 2:3] > 0).astype(jnp.int32)     # [Sp, 1] 0/1
    P_i = (jnp.broadcast_to(leafb, (Sp, C))
           == leaf_of_slot).astype(jnp.int32)                  # [Sp, C] 0/1
    same_i = 1 - jnp.bitwise_xor(left_i, small_left_i)         # left==small
    ch_dt = jnp.int8 if quant else jnp.bfloat16
    in_small = (P_i * same_i).astype(ch_dt)                    # [Sp, C] 0/1

    # ---- histogram: one wide-N dot, all channels packed. mask*g instead of
    # a select (i1 selects also hit the relayout bug); requires FINITE
    # grad/hess — a NaN/Inf row would leak 0*NaN into other slots' bins,
    # but non-finite gradients wreck training under any formulation.
    # Quantized mode: int8 channels, int32 accumulator — integer sums are
    # EXACT and associative (ops/quantize.py), rescaled outside.
    chans = []
    for ch in range(nch):
        g = gh_ref[ch:ch + 1, :]                               # [1, C]
        chans.append(in_small * jnp.broadcast_to(g, (Sp, C)))
    ghs = jnp.concatenate(chans, axis=0)                       # [nch*Sp, C]
    hist_ref[:] += jax.lax.dot_general(
        oh, ghs, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32 if quant else jnp.float32)

    # ---- row->leaf update: right-child rows move to their new leaf id
    go_right = P_i * (1 - left_i)                              # [Sp, C] 0/1
    delta = jnp.sum(go_right * jnp.broadcast_to(right_delta, (Sp, C)),
                    axis=0, keepdims=True)                     # [1, C] i32
    newleaf_ref[:] = leafb + delta


def _kernel_fb(f_oh: int, num_bins: int, packed: PackedLayout) -> int:
    return packed.fb if packed is not None else f_oh * num_bins


@functools.partial(
    jax.jit,
    static_argnames=("num_slots", "num_bins", "f_oh", "nch", "tile_rows",
                     "interpret", "quant_bits", "packed"))
def level_pass(bins_T: jax.Array, leaf_T: jax.Array, gh_T: jax.Array,
               W: jax.Array, tbl: jax.Array, fmask: jax.Array = None,
               *, num_slots: int, num_bins: int, f_oh: int,
               nch: int = NCH_PRECISE, tile_rows: int = 0,
               interpret: bool = False, quant_bits: int = 0,
               packed: PackedLayout = None):
    """One fused route+histogram pass over all rows.

    Args:
      bins_T: [Fp, R] int8 binned matrix, transposed (Fp >= f_oh; padded
        feature rows all-zero). R must be a multiple of the tile size
        (pad rows carry leaf_T = -1 so they contribute nothing). With
        ``packed`` the rows are pre-permuted into width-class order
        (packed.feat_order).
      leaf_T: [1, R] int32 row->leaf ids (-1 = inactive/padding row).
      gh_T: [8, R] bfloat16 channel block from pack_gh(), or the int8
        block from pack_gh_quant() when ``quant_bits`` is set.
      W: [Sp, FB] bfloat16 route table (build_route_table, packed via
        pack_route_table under ``packed``).
      tbl: [Sp, 128] int32; col 0 leaf_of_slot (-1 = inactive slot),
        col 1 right_delta (new_leaf_id - leaf_id), col 2 small_is_left
        (any value > 0 means left). grad/hess/weight must be FINITE: the
        kernel masks channels by multiplication (Mosaic i1-select
        workaround), so a NaN/Inf row would bleed into other slots.
      fmask: optional [FB, 128] (col 0 live) gain-screening mask — the
        masked slabs of the one-hot are zeroed so screened-out features
        contribute to neither dot.
      quant_bits: 0 (f32 path, unchanged), 8 or 16 — integer MXU/VPU
        accumulation into an int32 [FB, nch*Sp] accumulator; the caller
        rescales via hist_planes(quant_bits=..., scales=...).
      packed: adaptive per-feature bin layout (ops/layout.py). The row
        TILE is still derived from the PADDED layout's f_oh*num_bins so
        the per-element accumulation order — and hence the f32 sums —
        stay bit-identical to the padded kernel's (the adaptive-bin A/B
        contract); the win is the smaller scratch/accumulator, and the
        on-chip ablation (scripts/ablate_hist.py) measures larger tiles.

    Returns:
      hist: [FB, nch*Sp] float32 (int32 under quant_bits) smaller-child
        histograms, FB = packed.fb or f_oh*num_bins.
      new_leaf: [1, R] int32 updated assignment.
    """
    if not HAS_PALLAS:
        raise ImportError("jax.experimental.pallas is unavailable on this "
                          "backend; use the XLA histogram path instead")
    Fp, R = bins_T.shape
    B = num_bins
    FB = _kernel_fb(f_oh, B, packed)
    FB_tiles = f_oh * B       # padded formula: keeps tiling A/B-stable
    Sp = tbl.shape[0]
    C = _fit_tile(tile_rows or default_tile_rows(Sp, FB_tiles, nch,
                                                 wide_bins=B > 256), R)
    assert R % C == 0, f"rows {R} not padded to tile {C}"
    T = R // C
    quant = quant_bits > 0
    oh_dt = jnp.int8 if quant else jnp.bfloat16
    acc_dt = jnp.int32 if quant else jnp.float32
    if quant:
        W = W.astype(jnp.int8)

    kernel = functools.partial(_level_kernel, B=B, F_oh=f_oh, Sp=Sp,
                               nch=nch, quant=quant, packed=packed,
                               has_fm=fmask is not None)
    in_specs = [
        pl.BlockSpec((Fp, C), lambda t: (0, t)),
        pl.BlockSpec((1, C), lambda t: (0, t)),
        pl.BlockSpec((8, C), lambda t: (0, t)),
        pl.BlockSpec((Sp, FB), lambda t: (0, 0)),
        pl.BlockSpec((Sp, 128), lambda t: (0, 0)),
    ]
    operands = [bins_T, leaf_T, gh_T, W, tbl]
    if fmask is not None:
        in_specs.append(pl.BlockSpec((FB, 128), lambda t: (0, 0)))
        operands.append(fmask.astype(oh_dt))
    hist, new_leaf = pl.pallas_call(
        kernel,
        grid=(T,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((FB, nch * Sp), lambda t: (0, 0)),
            pl.BlockSpec((1, C), lambda t: (0, t)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((FB, nch * Sp), acc_dt),
            jax.ShapeDtypeStruct((1, R), jnp.int32),
        ],
        scratch_shapes=[pltpu.VMEM((FB, C), oh_dt)],
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(*operands)
    return hist, new_leaf


def _route_kernel(bins_ref, leaf_ref, w_ref, tbl_ref, newleaf_ref,
                  oh_ref, *, B: int, F_oh: int, Sp: int,
                  packed: PackedLayout = None):
    """Routing-only sibling of _level_kernel: updates row->leaf without
    accumulating histograms. Used for passes whose histograms can never be
    consumed (the leaf budget is exhausted, or no further pass follows) —
    the histogram dot is ~60% of a deep pass's cost. Routing keeps the
    bf16 formulation under quantization (no precision at stake); only
    the ``packed`` layout matters here (the bin rows are permuted)."""
    C = bins_ref.shape[1]
    _write_onehot(bins_ref, oh_ref, F_oh, B, packed=packed)
    leafb = leaf_ref[:]
    D = jax.lax.dot_general(w_ref[:], oh_ref[:], (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    left_i = (D > 0.5).astype(jnp.int32)
    leaf_of_slot = tbl_ref[:, 0:1]
    right_delta = tbl_ref[:, 1:2]
    P_i = (jnp.broadcast_to(leafb, (Sp, C))
           == leaf_of_slot).astype(jnp.int32)
    go_right = P_i * (1 - left_i)
    delta = jnp.sum(go_right * jnp.broadcast_to(right_delta, (Sp, C)),
                    axis=0, keepdims=True)
    newleaf_ref[:] = leafb + delta


@functools.partial(
    jax.jit,
    static_argnames=("num_slots", "num_bins", "f_oh", "tile_rows",
                     "interpret", "packed"))
def route_pass(bins_T: jax.Array, leaf_T: jax.Array, W: jax.Array,
               tbl: jax.Array, *, num_slots: int, num_bins: int,
               f_oh: int, tile_rows: int = 0,
               interpret: bool = False,
               packed: PackedLayout = None) -> jax.Array:
    """Row->leaf update only (same W/tbl contract as level_pass)."""
    if not HAS_PALLAS:
        raise ImportError("jax.experimental.pallas is unavailable on this "
                          "backend; use the XLA histogram path instead")
    Fp, R = bins_T.shape
    B = num_bins
    FB = _kernel_fb(f_oh, B, packed)
    Sp = tbl.shape[0]
    C = _fit_tile(tile_rows or default_tile_rows(Sp, f_oh * B, NCH_FAST,
                                                 wide_bins=B > 256), R)
    assert R % C == 0, f"rows {R} not padded to tile {C}"
    kernel = functools.partial(_route_kernel, B=B, F_oh=f_oh, Sp=Sp,
                               packed=packed)
    new_leaf = pl.pallas_call(
        kernel,
        grid=(R // C,),
        in_specs=[
            pl.BlockSpec((Fp, C), lambda t: (0, t)),
            pl.BlockSpec((1, C), lambda t: (0, t)),
            pl.BlockSpec((Sp, FB), lambda t: (0, 0)),
            pl.BlockSpec((Sp, 128), lambda t: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, C), lambda t: (0, t)),
        out_shape=jax.ShapeDtypeStruct((1, R), jnp.int32),
        scratch_shapes=[pltpu.VMEM((FB, C), jnp.bfloat16)],
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(bins_T, leaf_T, W, tbl)
    return new_leaf


def _epilogue_kernel(bins_ref, leaf_ref, w_ref, tbl_ref, lv_ref, score_ref,
                     op_ref, bag_ref, hist_ref, newscore_ref, gh_ref,
                     oh_ref, *, B: int, F_oh: int, Sp: int, Lp: int,
                     nch: int, kind: str, sigmoid: float):
    """Fused boosting epilogue: final-level routing + leaf-value score
    update + objective gradients + bf16 hi/lo channel pack + next tree's
    ROOT histogram, in ONE streaming pass over the rows.

    Replaces four separate O(R) streams of the round-2 driver (the final
    route_pass, the table_lookup score update, the elementwise gradient/
    pack, and the next grow's root level_pass) — each of which paid the
    full per-pass floor (oh-build + narrow-N dot, PROFILE.md §5).
    The ref host loop being fused: gbdt.cpp:371 TrainOneIter's
    UpdateScore -> Boosting(GetGradients) -> next BeforeTrain root.

    Output hist layout matches the root pass ([FB, nch*8], slot 0 live)
    so grow_tree_fused can consume it as ``root_hist`` directly.
    """
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        hist_ref[:] = jnp.zeros_like(hist_ref)

    C = bins_ref.shape[1]
    FB = F_oh * B
    _write_onehot(bins_ref, oh_ref, F_oh, B)
    oh = oh_ref[:]

    # ---- final-level routing (same contract as _route_kernel; an
    # all-inactive table — leaf_of_slot=-2 — routes nothing)
    leafb = leaf_ref[:]
    D = jax.lax.dot_general(w_ref[:], oh, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    left_i = (D > 0.5).astype(jnp.int32)
    leaf_of_slot = tbl_ref[:, 0:1]
    right_delta = tbl_ref[:, 1:2]
    P_i = (jnp.broadcast_to(leafb, (Sp, C)) == leaf_of_slot).astype(jnp.int32)
    go_right = P_i * (1 - left_i)
    delta_l = jnp.sum(go_right * jnp.broadcast_to(right_delta, (Sp, C)),
                      axis=0, keepdims=True)
    leaf2 = leafb + delta_l                                    # [1, C]

    # ---- leaf-value score update (sublane one-hot, as _lookup_kernel;
    # padding rows at leaf -1 match nothing -> delta 0)
    iota_l = jax.lax.broadcasted_iota(jnp.int32, (Lp, C), 0)
    Pl = jnp.broadcast_to(leaf2, (Lp, C)) == iota_l
    lvals = jnp.broadcast_to(lv_ref[:, 0:1], (Lp, C))
    delta = jnp.sum(jnp.where(Pl, lvals, 0.0), axis=0, keepdims=True)
    score2 = score_ref[:] + delta                              # [1, C] f32
    newscore_ref[:] = score2

    # ---- objective gradients from the UPDATED score (closed forms of the
    # epilogue_spec protocol; ref: binary_objective.hpp:107-136,
    # regression_objective.hpp:127-141)
    if kind == "binary":
        lv = op_ref[0:1, :]
        lw = op_ref[1:2, :]
        resp = -lv * sigmoid / (1.0 + jnp.exp(lv * sigmoid * score2))
        ar = jnp.abs(resp)
        g = resp * lw
        h = ar * (sigmoid - ar) * lw
    else:  # "l2"
        label = op_ref[0:1, :]
        w_row = op_ref[1:2, :]
        g = (score2 - label) * w_row
        h = w_row
    bag = bag_ref[:]                                           # [1, C]
    g = g * bag
    h = h * bag

    # ---- bf16 channel pack (pack_gh layout) + root histogram: slot 0 of
    # an 8-slot block carries every row, slots 1-7 stay zero so the
    # output matches the root level_pass layout bit-for-bit
    zero7 = jnp.zeros((7, C), jnp.bfloat16)
    if nch == NCH_PRECISE:
        g_hi = g.astype(jnp.bfloat16)
        g_lo = (g - g_hi.astype(jnp.float32)).astype(jnp.bfloat16)
        h_hi = h.astype(jnp.bfloat16)
        h_lo = (h - h_hi.astype(jnp.float32)).astype(jnp.bfloat16)
        w_ch = bag.astype(jnp.bfloat16)
        rows = [g_hi, g_lo, h_hi, h_lo, w_ch]
    else:
        rows = [g.astype(jnp.bfloat16), h.astype(jnp.bfloat16),
                bag.astype(jnp.bfloat16)]
    gh_ref[:] = jnp.concatenate(
        rows + [jnp.zeros((8 - nch, C), jnp.bfloat16)], axis=0)
    ghs = jnp.concatenate([jnp.concatenate([r, zero7], axis=0)
                           for r in rows], axis=0)             # [nch*8, C]
    hist_ref[:] += jax.lax.dot_general(
        oh, ghs, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)                    # [FB, nch*8]


@functools.partial(
    jax.jit,
    static_argnames=("num_bins", "f_oh", "nch", "kind", "sigmoid",
                     "tile_rows", "interpret"))
def epilogue_pass(bins_T: jax.Array, leaf_T: jax.Array, W: jax.Array,
                  tbl: jax.Array, leaf_values: jax.Array,
                  score_T: jax.Array, ops_T: jax.Array, bag_T: jax.Array,
                  *, num_bins: int, f_oh: int, nch: int = NCH_PRECISE,
                  kind: str = "binary", sigmoid: float = 1.0,
                  tile_rows: int = 0, interpret: bool = False):
    """One fused epilogue pass (see _epilogue_kernel).

    Args:
      bins_T/leaf_T: as level_pass (leaf_T is the PRE-final-route
        assignment; padding rows carry -1).
      W/tbl: the deferred final level's route tables (grow_tree_fused with
        defer_final_route=True); an all-inactive tbl routes nothing.
      leaf_values: [L] f32 — shrinkage-scaled leaf outputs of the tree
        just grown (zeroed by the caller when the tree grew no splits).
      score_T: [1, R] f32 current scores.
      ops_T: [8, R] f32 objective operand rows (binary: label_val,
        label_weight; l2: label, weight).
      bag_T: [1, R] f32 NEXT iteration's bagging weights (0 for padding
        rows — they zero the histogram and gh channels).

    Returns (hist [FB, nch*8] f32 root histogram for the next tree,
    new_score [1, R] f32, gh_T [8, R] bf16 pack_gh block for the next
    tree's level passes).
    """
    if not HAS_PALLAS:
        raise ImportError("jax.experimental.pallas is unavailable on this "
                          "backend; use the XLA histogram path instead")
    Fp, R = bins_T.shape
    B = num_bins
    FB = f_oh * B
    Sp = tbl.shape[0]
    L = leaf_values.shape[0]
    Lp = _round_up(max(L, 8), 8)
    C = _fit_tile(tile_rows or default_tile_rows(8, FB, nch,
                                                 wide_bins=B > 256), R)
    assert R % C == 0, f"rows {R} not padded to tile {C}"
    lvp = jnp.zeros((Lp, 128), jnp.float32).at[:L, 0].set(leaf_values)
    kernel = functools.partial(_epilogue_kernel, B=B, F_oh=f_oh, Sp=Sp,
                               Lp=Lp, nch=nch, kind=kind,
                               sigmoid=float(sigmoid))
    hist, new_score, gh_T = pl.pallas_call(
        kernel,
        grid=(R // C,),
        in_specs=[
            pl.BlockSpec((Fp, C), lambda t: (0, t)),
            pl.BlockSpec((1, C), lambda t: (0, t)),
            pl.BlockSpec((Sp, FB), lambda t: (0, 0)),
            pl.BlockSpec((Sp, 128), lambda t: (0, 0)),
            pl.BlockSpec((Lp, 128), lambda t: (0, 0)),
            pl.BlockSpec((1, C), lambda t: (0, t)),
            pl.BlockSpec((8, C), lambda t: (0, t)),
            pl.BlockSpec((1, C), lambda t: (0, t)),
        ],
        out_specs=[
            pl.BlockSpec((FB, nch * 8), lambda t: (0, 0)),
            pl.BlockSpec((1, C), lambda t: (0, t)),
            pl.BlockSpec((8, C), lambda t: (0, t)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((FB, nch * 8), jnp.float32),
            jax.ShapeDtypeStruct((1, R), jnp.float32),
            jax.ShapeDtypeStruct((8, R), jnp.bfloat16),
        ],
        scratch_shapes=[pltpu.VMEM((FB, C), jnp.bfloat16)],
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(bins_T, leaf_T, W, tbl, lvp, score_T, ops_T, bag_T)
    return hist, new_score, gh_T


def _lookup_kernel(idx_ref, tbl_ref, out_ref, *, Lp: int):
    C = idx_ref.shape[1]
    iota_l = jax.lax.broadcasted_iota(jnp.int32, (Lp, C), 0)
    P = jnp.broadcast_to(idx_ref[:], (Lp, C)) == iota_l
    vals = jnp.broadcast_to(tbl_ref[:, 0:1], (Lp, C))
    out_ref[:] = jnp.sum(jnp.where(P, vals, 0.0), axis=0, keepdims=True)


@functools.partial(jax.jit, static_argnames=("tile_rows", "interpret"))
def table_lookup(idx_T: jax.Array, table: jax.Array, *,
                 tile_rows: int = 2048, interpret: bool = False) -> jax.Array:
    """out[0, r] = table[idx_T[0, r]] for a SMALL table, without the
    ~30 ns/row random-gather penalty of XLA's [R]-from-[L] gather on TPU:
    one streaming pass with a sublane one-hot reduction.

    idx values outside [0, len(table)) return 0. Used for per-row leaf-value
    score updates (ref: src/boosting/score_updater.hpp:88 AddScore).
    """
    (_, R) = idx_T.shape
    L = table.shape[0]
    Lp = _round_up(max(L, 8), 8)
    C = min(tile_rows, _round_up(R, 128))
    Rp = _round_up(R, C)
    if Rp != R:
        idx_T = jnp.pad(idx_T, ((0, 0), (0, Rp - R)), constant_values=-1)
    tblp = jnp.zeros((Lp, 128), table.dtype).at[:L, 0].set(table)
    kernel = functools.partial(_lookup_kernel, Lp=Lp)
    out = pl.pallas_call(
        kernel,
        grid=(Rp // C,),
        in_specs=[
            pl.BlockSpec((1, C), lambda t: (0, t)),
            pl.BlockSpec((Lp, 128), lambda t: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, C), lambda t: (0, t)),
        out_shape=jax.ShapeDtypeStruct((1, Rp), table.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(idx_T, tblp)
    return out[:, :R]
