"""Trace-time measurement of in-jit collective payloads.

The telemetry registry used to report the distributed growers' traffic
from per-learner ANALYTIC estimates (``collective_profile``: num_leaves
x histogram bytes). Those models drift from the lowered program — the
fused grower's level schedule is static (level_caps), the voting
exchange sums packed hi/lo channels, padding widths differ from the
logical feature count. This module measures instead: every ``psum`` /
``pmax`` the tree learners issue routes through :func:`record_psum` /
:func:`record_pmax`, and while a :class:`CollectiveTrace` is active the
wrapper accumulates the STATIC per-shard payload (aval size x itemsize)
of each collective at trace time. Tracing happens exactly once per jit
signature, so the driver activates a recorder around the FIRST call of
each fresh grower/megastep function and caches the totals — the
recorded figures are the real shapes XLA lowers, not a wire model
(XLA may still fuse or reduce-scatter under the hood, the same caveat
the estimates carried).

Per-shard shapes ARE the reduced-tensor shapes (the recorder runs
inside shard_map bodies), matching the reference's convention of
counting the exchanged histogram payload
(data_parallel_tree_learner.cpp:155-189).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


class CollectiveTrace:
    """Context manager accumulating (count, bytes) of every collective
    traced while active. Nesting is not supported (the driver records
    one fresh function at a time); re-entering replaces the active
    recorder for its scope and restores the outer one on exit."""

    _active: Optional["CollectiveTrace"] = None

    def __init__(self):
        self.count = 0
        self.bytes = 0
        # per-dtype (count, bytes) breakdown: the quantized histogram
        # path (tpu_quantized_grad) psums int32 accumulators and the
        # adaptive layout shrinks their flat width — the breakdown is
        # what the histogram-plane composition tests assert against
        self.by_dtype: dict = {}
        self._outer: Optional["CollectiveTrace"] = None

    def __enter__(self) -> "CollectiveTrace":
        self._outer = CollectiveTrace._active
        CollectiveTrace._active = self
        return self

    def __exit__(self, *exc) -> None:
        CollectiveTrace._active = self._outer
        self._outer = None
        return None

    @property
    def profile(self):
        return self.count, self.bytes

    def _add(self, tree) -> None:
        for leaf in jax.tree_util.tree_leaves(tree):
            a = leaf if hasattr(leaf, "dtype") else jnp.asarray(leaf)
            nbytes = int(a.size) * int(a.dtype.itemsize)
            self.count += 1
            self.bytes += nbytes
            cnt, byt = self.by_dtype.get(str(a.dtype), (0, 0))
            self.by_dtype[str(a.dtype)] = (cnt + 1, byt + nbytes)


def _record(x) -> None:
    rec = CollectiveTrace._active
    if rec is not None:
        rec._add(x)


def record_psum(x, axis_name):
    """``jax.lax.psum`` with trace-time payload accounting."""
    _record(x)
    return jax.lax.psum(x, axis_name)


def record_pmax(x, axis_name):
    """``jax.lax.pmax`` with trace-time payload accounting."""
    _record(x)
    return jax.lax.pmax(x, axis_name)
