"""Pallas TPU histogram kernel — the make-or-break hot loop.

Replaces the reference's hand-tuned histogram kernels (ref:
src/io/dense_bin.hpp ConstructHistogram 4-way unrolled CPU loops,
src/treelearner/ocl/histogram16/64/256.cl workgroup-atomic GPU kernels,
src/treelearner/kernels/histogram_16_64_256.cu).

TPU constraints that shape the design (all measured on v5e):
- no fast atomics -> scatter-add formulations (XLA segment_sum) serialize on
  colliding indices: ~1.2 s per 1M x 28 pass at 255 slots;
- random per-row gathers/scatters run at ~30 ns/element, so sort/partition
  based layouts (the reference's per-leaf index lists) are off the table;
- the pure-XLA one-hot einsum formulation is MXU-bound but must materialize
  the [rows, features*bins] one-hot in HBM (~1.8 GB/level): a ~16 ms floor.

So: stream row tiles in place on the sequential TPU grid; per tile build the
bin one-hot [C, F*B] AND the slot one-hot [C, S] in VMEM only, then contract
per gh-channel on the MXU:

    hist[ch] += (slot_onehot * gh[:, ch])^T  @  bin_onehot     # [S, F*B]

Accumulation into the VMEM-resident output across grid steps is safe because
the TPU grid executes sequentially.  Cost scales with S (the slot dimension
rides the MXU), so callers pass the per-level live-slot count rather than a
global maximum.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from . import quantize
from .layout import feature_layout

try:  # optional: exotic backends fall back to the XLA implementations
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    HAS_PALLAS = True
except Exception:  # pragma: no cover
    HAS_PALLAS = False

NUM_CH = 3


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def pad_feature_layout(num_features: int, max_bin: int) -> Tuple[int, int]:
    """(Fp, Bp) with Bp = pow2 >= max_bin and (Fp * Bp) % 128 == 0.
    Delegates to ops.layout.feature_layout — the ONE layout contract
    shared with the fused kernel, so an adaptive/packed layout change
    cannot drift between the standalone and fused formulations."""
    return feature_layout(num_features, max_bin)


def _hist_kernel(bins_ref, slot_ref, gh_ref, out_ref, oh_ref, *,
                 Bp: int, S: int, Sp: int, nch: int = NUM_CH,
                 quant: bool = False):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    C, Fp = bins_ref.shape
    oh_dt = jnp.int8 if quant else jnp.bfloat16
    acc_dt = jnp.int32 if quant else jnp.float32
    # ---- bin one-hot, built into VMEM scratch in 128-lane-aligned slabs
    # (Mosaic cannot shape-cast [C, Fp, Bp] to [C, Fp*Bp], and sub-128-lane
    # stores are slow); k features share one slab when Bp < 128
    k = max(1, 128 // Bp)
    slab = k * Bp
    iota = jax.lax.broadcasted_iota(jnp.int32, (C, slab), 1)
    bin_in_slab = iota % Bp if k > 1 else iota
    for f0 in range(0, Fp, k):
        sel = bins_ref[:, f0:f0 + 1]
        for j in range(1, k):
            sel = jnp.where(iota // Bp == j, bins_ref[:, f0 + j:f0 + j + 1],
                            sel)
        oh_ref[:, f0 * Bp:f0 * Bp + slab] = (sel == bin_in_slab) \
            .astype(oh_dt)

    # ---- slot one-hot [C, Sp] as a value (negative slot = no contribution)
    s_col = slot_ref[:]                                     # [C, 1]
    iota_s = jax.lax.broadcasted_iota(jnp.int32, (C, Sp), 1)
    soh = (s_col == iota_s).astype(oh_dt)                   # [C, Sp]

    # ---- one MXU contraction per gh channel (quant: the native s8 x s8
    # -> s32 path with EXACT integer accumulation, ops/quantize.py)
    oh = oh_ref[:]
    for ch in range(nch):
        ghs = soh * gh_ref[:, ch:ch + 1].astype(oh_dt)
        part = jax.lax.dot_general(
            ghs, oh, (((0,), (0,)), ((), ())),
            preferred_element_type=acc_dt)                  # [Sp, Fp*Bp]
        out_ref[ch * Sp:(ch + 1) * Sp, :] += part


def _run_hist_kernel(bins_i32, gh, row_slot, *, S, Bp, C, nch, quant,
                     interpret):
    """Shared pallas_call wrapper: [nch*Sp, Fp*Bp] raw accumulator."""
    R, Fp = bins_i32.shape
    Sp = _round_up(max(S, 8), 8)
    R_pad = _round_up(R, C)
    if R_pad != R:
        pad = R_pad - R
        bins_i32 = jnp.pad(bins_i32, ((0, pad), (0, 0)))
        gh = jnp.pad(gh, ((0, pad), (0, 0)))
        row_slot = jnp.pad(row_slot, (0, pad), constant_values=-1)
    T = R_pad // C
    oh_dt = jnp.int8 if quant else jnp.bfloat16
    acc_dt = jnp.int32 if quant else jnp.float32
    kernel = functools.partial(_hist_kernel, Bp=Bp, S=S, Sp=Sp, nch=nch,
                               quant=quant)
    out = pl.pallas_call(
        kernel,
        grid=(T,),
        in_specs=[
            pl.BlockSpec((C, Fp), lambda t: (t, 0)),
            pl.BlockSpec((C, 1), lambda t: (t, 0)),
            pl.BlockSpec((C, nch), lambda t: (t, 0)),
        ],
        out_specs=pl.BlockSpec((nch * Sp, Fp * Bp), lambda t: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((nch * Sp, Fp * Bp), acc_dt),
        scratch_shapes=[pltpu.VMEM((C, Fp * Bp), oh_dt)],
        interpret=interpret,
    )(bins_i32, row_slot[:, None], gh)
    return out.reshape(nch, Sp, Fp, Bp)


@functools.partial(
    jax.jit, static_argnames=("num_slots", "num_bins", "tile_rows",
                              "interpret"))
def build_histograms_pallas(bins_i32: jax.Array, gh3: jax.Array,
                            row_slot: jax.Array, *, num_slots: int,
                            num_bins: int, tile_rows: int = 512,
                            interpret: bool = False) -> jax.Array:
    """Histogram via the Pallas kernel.

    Args:
      bins_i32: [R, Fp] int32, Fp pre-padded so (Fp * num_bins) % 128 == 0,
        padded feature columns all-zero.
      gh3: [R, 3] float32 (grad, hess, weight). Masked rows are excluded
        by their SLOT alone: slot -1 matches no column of the slot
        one-hot, so a masked row contributes nothing even when its gh
        channels are nonzero (callers need not zero them; the XLA
        formulations route slot -1 to a dump bucket with the same
        guarantee — asserted by the masked-row unit tests).
      row_slot: [R] int32 target slot, -1 = ignored.

    Returns: [num_slots, Fp, num_bins, 3] float32.
    """
    S = num_slots
    hist = _run_hist_kernel(bins_i32, gh3, row_slot, S=S, Bp=num_bins,
                            C=tile_rows, nch=NUM_CH, quant=False,
                            interpret=interpret)[:, :S]
    return jnp.transpose(hist, (1, 2, 3, 0))


@functools.partial(
    jax.jit, static_argnames=("num_slots", "num_bins", "tile_rows",
                              "interpret"))
def build_histograms_pallas_cm(bins_i32: jax.Array, gh3: jax.Array,
                               row_slot: jax.Array, *, num_slots: int,
                               num_bins: int, tile_rows: int = 512,
                               interpret: bool = False):
    """Channel-major variant: returns (grad, hess, count) planes
    [S, Fp, Bp] each, avoiding the channel-minor transpose entirely.
    Masked (slot == -1) rows contribute nothing regardless of their gh
    values (see build_histograms_pallas)."""
    S = num_slots
    hist = _run_hist_kernel(bins_i32, gh3, row_slot, S=S, Bp=num_bins,
                            C=tile_rows, nch=NUM_CH, quant=False,
                            interpret=interpret)
    return hist[0, :S], hist[1, :S], hist[2, :S]


@functools.partial(
    jax.jit, static_argnames=("num_slots", "num_bins", "tile_rows",
                              "quant_bits", "interpret"))
def build_histograms_pallas_quant(bins_i32: jax.Array, gh3: jax.Array,
                                  row_slot: jax.Array, *, num_slots: int,
                                  num_bins: int, quant_bits: int = 16,
                                  seed=0, tile_rows: int = 512,
                                  interpret: bool = False):
    """Quantized-accumulator variant (``tpu_quantized_grad``): grad/hess
    stochastically rounded onto the fixed-point grid (ops/quantize.py),
    int8 channel x int8 one-hot MXU dots accumulate into int32 EXACTLY,
    and the per-level f32 rescale happens here at the decode boundary.
    Returns (grad, hess, count) f32 planes [S, Fp, Bp], like _cm."""
    S = num_slots
    g, h, w = gh3[:, 0], gh3[:, 1], gh3[:, 2]
    scales = quantize.quant_scales(g, h, quant_bits)
    qg, qh = quantize.quantize_gh(g, h, scales, quant_bits, seed)
    rows = quantize.encode_channels(qg, qh, w, quant_bits)
    nch = len(rows)
    gh_q = jnp.stack(rows, axis=1)                          # [R, nch] int8
    hist = _run_hist_kernel(bins_i32, gh_q, row_slot, S=S, Bp=num_bins,
                            C=tile_rows, nch=nch, quant=True,
                            interpret=interpret)
    Sp = hist.shape[1]
    Fp, Bp = hist.shape[2], hist.shape[3]
    planes = [hist[c].reshape(Sp, Fp * Bp).T for c in range(nch)]
    g_s, h_s, c_s = quantize.decode_sums(planes, scales, quant_bits)
    back = lambda x: x.T.reshape(Sp, Fp, Bp)[:S]
    return back(g_s), back(h_s), back(c_s)
