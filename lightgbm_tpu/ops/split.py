"""On-device best-split search over histograms.

TPU-native replacement for the reference's per-(leaf,feature) sequential
threshold scan (ref: src/treelearner/feature_histogram.hpp:85
FindBestThreshold, :858-1090 FindBestThresholdSequentially).  The reference
walks bins one-by-one per feature on the host; here the whole
``[slots, features, bins]`` tensor is scanned at once with cumulative sums and
an argmax — no host round trip per leaf (the design wart called out in
SURVEY.md §3.5).

Semantics replicated from the reference dispatch
(feature_histogram.hpp:158-200 FuncForNumricalL3):
- missing None  -> reverse scan only (default_left=True always).
- missing Zero  -> reverse + forward scans, the zero (default) bin excluded
  from the directional accumulation so its rows ride the default direction;
  threshold == default_bin (forward) / default_bin-1 (reverse) skipped.
- missing NaN   -> reverse + forward; the NaN bin (last) is excluded from the
  reverse accumulation so NaN rows go left; forward leaves it on the right.
- num_bin <= 2  -> single scan (forward iff missing NaN).
- Ties: reverse beats forward; earlier feature beats later; within forward the
  smallest threshold wins, within reverse the largest (scan orders).

Gain/leaf-output formulas are the closed-form Newton expressions with
L1 thresholding, max_delta_step clipping and path smoothing
(ref: feature_histogram.hpp:737-856 ThresholdL1 / CalculateSplittedLeafOutput /
GetLeafGain / GetSplitGains).

Precision contract: every scan in this module consumes f32 (grad, hess,
count) planes.  The quantized histogram path (``tpu_quantized_grad``,
ops/quantize.py) rescales its exact int32 fixed-point sums to f32 AT the
decode boundary (ops/fused_level.hist_planes) — this module is unchanged
above that boundary, so the split semantics are identical between the
f32 and quantized planes up to the quantization noise already present in
the sums.
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

K_EPSILON = 1e-15
K_MIN_SCORE = -jnp.inf

MISSING_NONE = 0
MISSING_ZERO = 1
MISSING_NAN = 2


class SplitParams(NamedTuple):
    """Static split-finding hyper-parameters (subset of ref Config used by
    FeatureHistogram)."""
    lambda_l1: float = 0.0
    cegb_tradeoff: float = 1.0
    cegb_penalty_split: float = 0.0
    lambda_l2: float = 0.0
    max_delta_step: float = 0.0
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    path_smooth: float = 0.0
    monotone_penalty: float = 0.0
    # categorical split search (ref: config.h cat_l2/cat_smooth/...)
    max_cat_to_onehot: int = 4
    max_cat_threshold: int = 32
    cat_l2: float = 10.0
    cat_smooth: float = 10.0
    min_data_per_group: int = 100


def threshold_l1(s, l1):
    # ref: feature_histogram.hpp:737 ThresholdL1
    reg = jnp.maximum(0.0, jnp.abs(s) - l1)
    return jnp.sign(s) * reg


def calculate_leaf_output(sum_grad, sum_hess, p: SplitParams,
                          num_data=None, parent_output=0.0, l2=None):
    """Closed-form Newton leaf value
    (ref: feature_histogram.hpp:742 CalculateSplittedLeafOutput).
    ``l2`` overrides p.lambda_l2 (categorical splits add cat_l2)."""
    ret = -threshold_l1(sum_grad, p.lambda_l1) / (
        sum_hess + (p.lambda_l2 if l2 is None else l2))
    if p.max_delta_step > 0:
        ret = jnp.clip(ret, -p.max_delta_step, p.max_delta_step)
    if p.path_smooth > 0 and num_data is not None:
        n_s = num_data / p.path_smooth
        ret = ret * n_s / (n_s + 1.0) + parent_output / (n_s + 1.0)
    return ret


def leaf_gain_given_output(sum_grad, sum_hess, p: SplitParams, output,
                           l2=None):
    # ref: feature_histogram.hpp:846 GetLeafGainGivenOutput
    sg = threshold_l1(sum_grad, p.lambda_l1)
    return -(2.0 * sg * output
             + (sum_hess + (p.lambda_l2 if l2 is None else l2))
             * output * output)


def leaf_gain(sum_grad, sum_hess, p: SplitParams, num_data=None,
              parent_output=0.0, l2=None):
    # ref: feature_histogram.hpp:828 GetLeafGain
    if p.max_delta_step <= 0 and p.path_smooth <= 0:
        sg = threshold_l1(sum_grad, p.lambda_l1)
        return (sg * sg) / (sum_hess + (p.lambda_l2 if l2 is None else l2))
    out = calculate_leaf_output(sum_grad, sum_hess, p, num_data,
                                parent_output, l2)
    return leaf_gain_given_output(sum_grad, sum_hess, p, out, l2)


class BestSplit(NamedTuple):
    """Per-slot best split record — the SplitInfo analog
    (ref: src/treelearner/split_info.hpp:22)."""
    feature: jax.Array        # int32 [S], inner feature index, -1 if none
    threshold: jax.Array      # int32 [S], bin threshold (left: bin <= t)
    default_left: jax.Array   # bool  [S]
    gain: jax.Array           # f32   [S], gain minus shift; -inf if invalid
    left_output: jax.Array    # f32   [S]
    right_output: jax.Array
    left_sum_grad: jax.Array
    left_sum_hess: jax.Array
    left_count: jax.Array     # f32 (weighted count channel)
    right_sum_grad: jax.Array
    right_sum_hess: jax.Array
    right_count: jax.Array
    cat_flag: jax.Array       # bool [S] categorical split?
    cat_mask: jax.Array       # bool [S, B] bins routed left (cat only)


def _no_cat(S: int, B: int):
    return (jnp.zeros((S,), bool), jnp.zeros((S, B), bool))


@functools.partial(jax.jit, static_argnames=("params",))
def best_numerical_split(hist: jax.Array, num_bin_per_feat: jax.Array,
                         missing_type: jax.Array, default_bin: jax.Array,
                         feature_mask: jax.Array, monotone: jax.Array,
                         params: SplitParams,
                         parent_output: jax.Array) -> BestSplit:
    """Best numerical split per slot from a channel-minor histogram.

    Args:
      hist: ``[S, F, B, 3]`` float32 (grad, hess, count).
      (see best_numerical_split_cm for the remaining args)
    """
    return best_numerical_split_cm(
        hist[..., 0], hist[..., 1], hist[..., 2], num_bin_per_feat,
        missing_type, default_bin, feature_mask, monotone, params,
        parent_output)


@functools.partial(jax.jit,
                   static_argnames=("params", "per_feature_gains",
                                    "use_bounds"))
def best_numerical_split_cm(grad: jax.Array, hess: jax.Array,
                            cnt: jax.Array, num_bin_per_feat: jax.Array,
                            missing_type: jax.Array, default_bin: jax.Array,
                            feature_mask: jax.Array, monotone: jax.Array,
                            params: SplitParams,
                            parent_output: jax.Array,
                            per_feature_gains: bool = False,
                            use_bounds: bool = False,
                            bound_lo: jax.Array = None,
                            bound_hi: jax.Array = None,
                            leaf_depth: jax.Array = None,
                            cegb_delta: jax.Array = None,
                            bound_lo_plane: jax.Array = None,
                            bound_hi_plane: jax.Array = None) -> BestSplit:
    """Best numerical split per slot (channel-major inputs — TPU relayouts
    of channel-minor ``[..., 3]`` arrays are expensive, so the hot path keeps
    grad/hess/count as separate ``[S, F, B]`` planes).

    Args:
      grad/hess/cnt: ``[S, F, B]`` float32 histogram planes.
      num_bin_per_feat: ``[F]`` int32 actual bin counts (rest is padding).
      missing_type: ``[F]`` int32 (0 none / 1 zero / 2 nan).
      default_bin: ``[F]`` int32 (bin of value 0; the zero-missing bin).
      feature_mask: ``[F]`` bool — feature_fraction / interaction constraints.
      monotone: ``[F]`` int32 in {-1, 0, 1}.
      parent_output: ``[S]`` f32 leaf outputs (for path smoothing).

    Returns a ``BestSplit`` with per-slot winners.
    """
    S, F, B = grad.shape
    p = params
    # feature_mask may be [F] (global) or [S, F] (per-slot validity, used
    # by the voting-parallel learner whose shards only hold globally-summed
    # histograms for vote-winning features)
    fm3 = (feature_mask[None, :, None] if feature_mask.ndim == 1
           else feature_mask[:, :, None])

    t_iota = jnp.arange(B, dtype=jnp.int32)[None, None, :]
    nb = num_bin_per_feat[None, :, None]          # [1,F,1]
    mt = missing_type[None, :, None]
    db = default_bin[None, :, None]
    is_pad = t_iota >= nb

    # leaf totals: every feature's bins partition the same rows, so feature 0's
    # bin sums are the leaf totals (padding bins hold no mass)
    tot_g = jnp.sum(grad[:, 0, :], axis=1)[:, None, None]   # [S,1,1]
    tot_h = (jnp.sum(hess[:, 0, :], axis=1)
             + 2.0 * K_EPSILON)[:, None, None]
    tot_c = jnp.sum(cnt[:, 0, :], axis=1)[:, None, None]

    parent_out = parent_output[:, None, None]
    num_data = tot_c
    gain_shift = leaf_gain(tot_g, tot_h, p, num_data, parent_out)
    min_gain_shift = gain_shift + p.min_gain_to_split      # [S,1,1]

    nan_bin = nb - 1
    is_missing_bin_fwd = (mt == MISSING_ZERO) & (t_iota == db)
    is_missing_bin_rev = is_missing_bin_fwd | ((mt == MISSING_NAN)
                                               & (t_iota == nan_bin))

    def directional_best(excl_missing_mask, thresh_valid, reverse):
        """Cumulative scan in one direction; missing-bin mass excluded from
        the accumulated side so it rides the default direction."""
        m = (~is_pad) & (~excl_missing_mask)
        g = jnp.where(m, grad, 0.0)
        h = jnp.where(m, hess, 0.0)
        c = jnp.where(m, cnt, 0.0)
        if not reverse:
            left_g = jnp.cumsum(g, axis=2)
            left_h = jnp.cumsum(h, axis=2) + K_EPSILON
            left_c = jnp.cumsum(c, axis=2)
            right_g = tot_g - left_g
            right_h = tot_h - left_h
            right_c = tot_c - left_c
        else:
            # right side accumulates bins > t (scan from the right)
            rg = jnp.cumsum(g[..., ::-1], axis=2)[..., ::-1]
            rh = jnp.cumsum(h[..., ::-1], axis=2)[..., ::-1]
            rc = jnp.cumsum(c[..., ::-1], axis=2)[..., ::-1]
            # threshold t: right = bins >= t+1
            right_g = jnp.concatenate([rg[..., 1:], jnp.zeros_like(rg[..., :1])],
                                      axis=2)
            right_h = jnp.concatenate([rh[..., 1:], jnp.zeros_like(rh[..., :1])],
                                      axis=2) + K_EPSILON
            right_c = jnp.concatenate([rc[..., 1:], jnp.zeros_like(rc[..., :1])],
                                      axis=2)
            left_g = tot_g - right_g
            left_h = tot_h - right_h
            left_c = tot_c - right_c

        ok = (thresh_valid
              & (left_c >= p.min_data_in_leaf)
              & (right_c >= p.min_data_in_leaf)
              & (left_h >= p.min_sum_hessian_in_leaf)
              & (right_h >= p.min_sum_hessian_in_leaf)
              & fm3)

        mono = monotone[None, :, None]
        lo = calculate_leaf_output(left_g, left_h, p, left_c, parent_out)
        ro = calculate_leaf_output(right_g, right_h, p, right_c, parent_out)
        if bound_hi_plane is not None:
            # ADVANCED monotone mode: per-(feature, bin-SEGMENT) bounds
            # (ref: monotone_constraints.hpp:856 AdvancedLeafConstraints —
            # a constraint from an adjacent leaf applies only to the part
            # of this leaf's region the neighbor shadows, so a candidate
            # child that escapes the shadow escapes the bound). The
            # child's bound = extremum of the plane over the bins it
            # covers: prefix scans for the left child, suffix for the
            # right; the missing-bin mass rides the default direction and
            # folds its plane entry into that side.
            inf = jnp.inf
            hi_pl = jnp.where(is_pad, inf, bound_hi_plane)
            lo_pl = jnp.where(is_pad, -inf, bound_lo_plane)
            hi_pref = jax.lax.cummin(hi_pl, axis=2)
            lo_pref = jax.lax.cummax(lo_pl, axis=2)
            hi_suf = jax.lax.cummin(hi_pl[..., ::-1], axis=2)[..., ::-1]
            lo_suf = jax.lax.cummax(lo_pl[..., ::-1], axis=2)[..., ::-1]
            hi_right = jnp.concatenate(
                [hi_suf[..., 1:], jnp.full_like(hi_suf[..., :1], inf)],
                axis=2)
            lo_right = jnp.concatenate(
                [lo_suf[..., 1:], jnp.full_like(lo_suf[..., :1], -inf)],
                axis=2)
            mm = excl_missing_mask & ~is_pad
            miss_hi = jnp.min(jnp.where(mm, hi_pl, inf), axis=2,
                              keepdims=True)
            miss_lo = jnp.max(jnp.where(mm, lo_pl, -inf), axis=2,
                              keepdims=True)
            if reverse:     # missing rides LEFT
                l_hi = jnp.minimum(hi_pref, miss_hi)
                l_lo = jnp.maximum(lo_pref, miss_lo)
                r_hi, r_lo = hi_right, lo_right
            else:           # missing rides RIGHT
                l_hi, l_lo = hi_pref, lo_pref
                r_hi = jnp.minimum(hi_right, miss_hi)
                r_lo = jnp.maximum(lo_right, miss_lo)
            lo = jnp.clip(lo, l_lo, l_hi)
            ro = jnp.clip(ro, r_lo, r_hi)
            gains = (leaf_gain_given_output(left_g, left_h, p, lo)
                     + leaf_gain_given_output(right_g, right_h, p, ro))
        elif use_bounds:
            # per-leaf monotone bounds: candidate outputs are clipped into
            # the leaf's feasible interval and the gain recomputed with the
            # clipped outputs (ref: monotone_constraints.hpp BasicLeaf
            # Constraints + feature_histogram GetSplitGains USE_MC)
            blo = bound_lo[:, None, None]
            bhi = bound_hi[:, None, None]
            lo = jnp.clip(lo, blo, bhi)
            ro = jnp.clip(ro, blo, bhi)
            gains = (leaf_gain_given_output(left_g, left_h, p, lo)
                     + leaf_gain_given_output(right_g, right_h, p, ro))
        else:
            gains = (leaf_gain(left_g, left_h, p, left_c, parent_out)
                     + leaf_gain(right_g, right_h, p, right_c, parent_out))
        # monotone direction check (ref: GetSplitGains USE_MC -> 0)
        viol = ((mono > 0) & (lo > ro)) | ((mono < 0) & (lo < ro))
        gains = jnp.where(viol, 0.0, gains)
        gains = jnp.where(ok & (gains > min_gain_shift), gains, K_MIN_SCORE)

        if reverse:
            # prefer LARGEST threshold on ties (reverse scan visits high t
            # first and replaces only on strictly-greater gain)
            idx_rev = jnp.argmax(gains[..., ::-1], axis=2)
            t_best = B - 1 - idx_rev
        else:
            t_best = jnp.argmax(gains, axis=2)
        g_best = jnp.take_along_axis(gains, t_best[..., None], axis=2)[..., 0]
        pack = [left_g, left_h, left_c, right_g, right_h, right_c]
        picked = [jnp.take_along_axis(a, t_best[..., None], axis=2)[..., 0]
                  for a in pack]
        return t_best.astype(jnp.int32), g_best, picked

    # reverse scan (missing -> left; valid thresholds 0..nb-2-isNaN, skip
    # default_bin-1 for zero-missing); run unless (nb<=2 and missing NaN)
    rev_thresh_valid = ((t_iota <= nb - 2 - (mt == MISSING_NAN))
                        & ~((mt == MISSING_ZERO) & (t_iota == db - 1))
                        & ~((nb <= 2) & (mt == MISSING_NAN)))
    t_rev, g_rev, s_rev = directional_best(is_missing_bin_rev,
                                           rev_thresh_valid, reverse=True)

    # forward scan (missing -> right); run iff (nb>2 and missing != None) or
    # (nb<=2 and missing NaN)
    fwd_runs = jnp.where(nb > 2, mt != MISSING_NONE, mt == MISSING_NAN)
    fwd_thresh_valid = ((t_iota <= nb - 2)
                        & ~((mt == MISSING_ZERO) & (t_iota == db))
                        & fwd_runs)
    t_fwd, g_fwd, s_fwd = directional_best(is_missing_bin_fwd,
                                           fwd_thresh_valid, reverse=False)

    # reverse wins ties (it runs first in the reference)
    use_fwd = g_fwd > g_rev
    t_best = jnp.where(use_fwd, t_fwd, t_rev)                       # [S,F]
    g_best = jnp.where(use_fwd, g_fwd, g_rev)
    stats = [jnp.where(use_fwd, a, b) for a, b in zip(s_fwd, s_rev)]
    default_left = ~use_fwd
    if use_bounds and p.monotone_penalty > 0:
        # depth-based penalty on the NET gain of monotone-feature splits,
        # after validity gating on the gross gain (ref:
        # monotone_constraints.hpp:355 ComputeMonotoneSplitGainPenalty,
        # applied to SplitInfo.gain = best_gain - min_gain_shift)
        pen = p.monotone_penalty
        d = leaf_depth[:, None].astype(jnp.float32)
        factor = jnp.where(
            pen >= d + 1.0, K_EPSILON,
            jnp.where(pen <= 1.0,
                      1.0 - pen / jnp.exp2(d) + K_EPSILON,
                      1.0 - jnp.exp2(pen - 1.0 - d) + K_EPSILON))
        shift2 = min_gain_shift[:, :, 0]
        net = jnp.where(jnp.isfinite(g_best),
                        (g_best - shift2) * factor + shift2, g_best)
        g_best = jnp.where(monotone[None, :] != 0, net, g_best)
    if cegb_delta is not None:
        # cost-effective gradient boosting: per-(leaf,feature) acquisition
        # cost subtracted from the candidate gain before feature choice
        # (ref: cost_effective_gradient_boosting.hpp:66 DetlaGain,
        # serial_tree_learner.cpp:769-777)
        g_best = jnp.where(jnp.isfinite(g_best), g_best - cegb_delta,
                           g_best)
    if per_feature_gains:
        # voting-parallel wants the [S, F] gain plane, not the argmax
        # (ref: voting_parallel_tree_learner.cpp:151 votes by local gain)
        return g_best

    # across features: first feature wins ties (argmax picks first max)
    f_best = jnp.argmax(g_best, axis=1)                              # [S]
    take = lambda a: jnp.take_along_axis(a, f_best[:, None], axis=1)[:, 0]
    gain = take(g_best)
    lg, lh, lc, rg, rh, rc = [take(a) for a in stats]
    valid = jnp.isfinite(gain)

    left_out = calculate_leaf_output(lg, lh, p, lc, parent_output)
    right_out = calculate_leaf_output(rg, rh, p, rc, parent_output)
    if use_bounds:
        left_out = jnp.clip(left_out, bound_lo, bound_hi)
        right_out = jnp.clip(right_out, bound_lo, bound_hi)
    out_gain = jnp.where(valid, gain - min_gain_shift[:, 0, 0], K_MIN_SCORE)
    no_flag, no_mask = _no_cat(S, B)
    return BestSplit(
        feature=jnp.where(valid, f_best.astype(jnp.int32), -1),
        threshold=take(t_best),
        default_left=take(default_left),
        gain=out_gain,
        left_output=left_out,
        right_output=right_out,
        left_sum_grad=lg, left_sum_hess=lh - K_EPSILON, left_count=lc,
        right_sum_grad=rg, right_sum_hess=rh - K_EPSILON, right_count=rc,
        cat_flag=no_flag,
        cat_mask=no_mask,
    )


@functools.partial(jax.jit, static_argnames=("params",
                                             "per_feature_gains"))
def best_categorical_split_cm(grad: jax.Array, hess: jax.Array,
                              cnt: jax.Array, num_bin_per_feat: jax.Array,
                              cat_feature_mask: jax.Array,
                              params: SplitParams,
                              parent_output: jax.Array,
                              cegb_delta: jax.Array = None,
                              per_feature_gains: bool = False) -> BestSplit:
    """Best categorical split per slot (ref: feature_histogram.hpp:278-470
    FindBestThresholdCategoricalInner).

    Two modes, per the reference:
    - one-vs-rest when ``num_bin <= max_cat_to_onehot`` (plain lambda_l2);
    - otherwise: bins with count >= cat_smooth sorted by
      grad/(hess+cat_smooth), prefix scans from both ends up to
      ``min(max_cat_threshold, (used+1)//2)`` categories, gains with
      lambda_l2 + cat_l2 and min_data_per_group batching.

    Divergence from the reference, deliberate: the reference estimates bin
    counts as ``hess * num_data / sum_hess`` because its categorical
    histograms carry no count channel; ours do, so real counts are used.

    Bin 0 is the NaN/other catch-all (binning.py) and is never a member of
    the left set — matching the reference's ``bin_start = 1`` scan and the
    predict-side convention that unseen categories go right.

    Args:
      grad/hess/cnt: [S, F, B] float32 histogram planes.
      num_bin_per_feat: [F] int32.
      cat_feature_mask: [F] bool — True for categorical features that may
        be used (feature sampling already folded in).
      parent_output: [S] f32.

    Returns a BestSplit whose winners are categorical (cat_flag True,
    cat_mask = left-bin set, default_left False, threshold 0).
    """
    S, F, B = grad.shape
    p = params
    l2_cat = p.lambda_l2 + p.cat_l2
    eps = K_EPSILON

    b_iota = jnp.arange(B, dtype=jnp.int32)[None, None, :]
    nb = num_bin_per_feat[None, :, None]
    in_range = (b_iota >= 1) & (b_iota < nb)          # bin 0 = NaN/other

    tot_g = jnp.sum(grad, axis=2)                     # [S, F]
    tot_h = jnp.sum(hess, axis=2) + 2.0 * eps
    tot_c = jnp.sum(cnt, axis=2)
    parent_out = parent_output[:, None]

    gain_shift = leaf_gain(tot_g, tot_h, p, tot_c, parent_out)
    min_gain_shift = gain_shift + p.min_gain_to_split  # [S, F]

    # ---------------- one-vs-rest (ref :318-374)
    lg1 = grad
    lh1 = hess + eps
    lc1 = cnt
    rg1 = tot_g[..., None] - lg1
    rh1 = tot_h[..., None] - lh1 - eps
    rc1 = tot_c[..., None] - lc1
    ok1 = (in_range
           & (lc1 >= p.min_data_in_leaf) & (lh1 >= p.min_sum_hessian_in_leaf)
           & (rc1 >= p.min_data_in_leaf) & (rh1 >= p.min_sum_hessian_in_leaf))
    gains1 = (leaf_gain(lg1, lh1, p, lc1, parent_out[..., None])
              + leaf_gain(rg1, rh1, p, rc1, parent_out[..., None]))
    gains1 = jnp.where(ok1 & (gains1 > min_gain_shift[..., None]), gains1,
                       K_MIN_SCORE)
    t1 = jnp.argmax(gains1, axis=2)                   # [S, F]
    g1 = jnp.take_along_axis(gains1, t1[..., None], axis=2)[..., 0]
    onehot_allowed = (num_bin_per_feat <= p.max_cat_to_onehot)[None, :]
    g1 = jnp.where(onehot_allowed, g1, K_MIN_SCORE)

    # ---------------- sorted-subset (ref :376-473)
    ok_bin = in_range & (cnt >= p.cat_smooth)
    ratio = jnp.where(ok_bin, grad / (hess + p.cat_smooth), jnp.inf)
    order = jnp.argsort(ratio, axis=2, stable=True)   # filtered bins last
    sg = jnp.take_along_axis(grad, order, axis=2)
    sh = jnp.take_along_axis(hess, order, axis=2)
    sc = jnp.take_along_axis(cnt, order, axis=2)
    used = jnp.sum(ok_bin.astype(jnp.int32), axis=2)  # [S, F]
    max_num_cat = jnp.minimum(p.max_cat_threshold, (used + 1) // 2)

    def scan_dir(seq_g, seq_h, seq_c):
        """Prefix scan over the sorted sequence; returns per-position gains
        [S, F, B] (K_MIN_SCORE where not a candidate)."""
        def step(carry, xs):
            sum_g, sum_h, sum_c, grp = carry
            tg, th, tc, i = xs
            live = (i < used) & (i < max_num_cat)
            sum_g = sum_g + jnp.where(live, tg, 0.0)
            sum_h = sum_h + jnp.where(live, th, 0.0)
            sum_c = sum_c + jnp.where(live, tc, 0.0)
            grp = grp + jnp.where(live, tc, 0.0)
            rc = tot_c - sum_c
            rh = tot_h - sum_h - eps
            ok = (live
                  & (sum_c >= p.min_data_in_leaf)
                  & (sum_h + eps >= p.min_sum_hessian_in_leaf)
                  & (rc >= p.min_data_in_leaf)
                  & (rc >= p.min_data_per_group)
                  & (rh >= p.min_sum_hessian_in_leaf)
                  & (grp >= p.min_data_per_group))
            rg = tot_g - sum_g
            gain = (leaf_gain(sum_g, sum_h + eps, p, sum_c, parent_out,
                              l2_cat)
                    + leaf_gain(rg, rh, p, rc, parent_out, l2_cat))
            gain = jnp.where(ok & (gain > min_gain_shift), gain, K_MIN_SCORE)
            grp = jnp.where(ok, 0.0, grp)
            return (sum_g, sum_h, sum_c, grp), gain

        init = (jnp.zeros((S, F)), jnp.zeros((S, F)), jnp.zeros((S, F)),
                jnp.zeros((S, F)))
        xs = (jnp.moveaxis(seq_g, 2, 0), jnp.moveaxis(seq_h, 2, 0),
              jnp.moveaxis(seq_c, 2, 0),
              jnp.arange(B, dtype=jnp.int32))
        _, gains = jax.lax.scan(step, init, xs)
        return jnp.moveaxis(gains, 0, 2)              # [S, F, B]

    gains_fwd = scan_dir(sg, sh, sc)
    # reverse: walk the valid region from its end (position used-1-i)
    rev_idx = jnp.clip(used[..., None] - 1 - jnp.arange(B)[None, None, :],
                       0, B - 1)
    gains_rev = scan_dir(jnp.take_along_axis(sg, rev_idx, axis=2),
                         jnp.take_along_axis(sh, rev_idx, axis=2),
                         jnp.take_along_axis(sc, rev_idx, axis=2))

    i_fwd = jnp.argmax(gains_fwd, axis=2)
    g_fwd = jnp.take_along_axis(gains_fwd, i_fwd[..., None], axis=2)[..., 0]
    i_rev = jnp.argmax(gains_rev, axis=2)
    g_rev = jnp.take_along_axis(gains_rev, i_rev[..., None], axis=2)[..., 0]

    # ---------------- combine modes per feature, then across features
    # (onehot vs sorted are exclusive per feature; fwd beats rev on ties —
    # the reference scans fwd first and replaces only on strictly greater)
    use_rev = g_rev > g_fwd
    g_sorted = jnp.where(use_rev, g_rev, g_fwd)
    g_feat = jnp.where(onehot_allowed, g1, g_sorted)   # [S, F]
    if cegb_delta is not None:
        # CEGB acquisition costs apply to every candidate feature
        # (ref: serial_tree_learner.cpp:769-777)
        g_feat = jnp.where(jnp.isfinite(g_feat), g_feat - cegb_delta,
                           g_feat)
    cfm = (cat_feature_mask[None, :] if cat_feature_mask.ndim == 1
           else cat_feature_mask)
    g_feat = jnp.where(cfm, g_feat, K_MIN_SCORE)
    if per_feature_gains:
        # voting-parallel ranks categorical features in the vote too
        # (ref: voting_parallel_tree_learner.cpp:151 votes by local gain)
        return g_feat
    f_best = jnp.argmax(g_feat, axis=1)                # [S]
    take = lambda a: jnp.take_along_axis(a, f_best[:, None], axis=1)[:, 0]
    gain = take(g_feat)
    valid = jnp.isfinite(gain)

    is_onehot = take(onehot_allowed.astype(jnp.int32) *
                     jnp.ones((S, F), jnp.int32)) > 0
    tb = take(t1)                                      # [S] onehot bin
    ifw = take(i_fwd)
    irv = take(i_rev)
    urev = take(use_rev.astype(jnp.int32)) > 0
    usedb = take(used)

    # left-set membership mask over bins [S, B]
    rank = jnp.zeros((S, F, B), jnp.int32)
    rank = jnp.put_along_axis(
        rank, order, jnp.broadcast_to(jnp.arange(B, dtype=jnp.int32),
                                      (S, F, B)), axis=2,
        inplace=False)
    rank_b = jnp.take_along_axis(
        rank, f_best[:, None, None].repeat(B, 2), axis=1)[:, 0, :]  # [S, B]
    okb_b = jnp.take_along_axis(
        ok_bin, f_best[:, None, None].repeat(B, 2), axis=1)[:, 0, :]
    mask_fwd = okb_b & (rank_b <= ifw[:, None])
    mask_rev = okb_b & (rank_b >= (usedb - 1 - irv)[:, None])
    mask_sorted = jnp.where(urev[:, None], mask_rev, mask_fwd)
    mask_onehot = jnp.arange(B)[None, :] == tb[:, None]
    cat_mask = jnp.where(is_onehot[:, None], mask_onehot, mask_sorted)
    cat_mask = cat_mask & valid[:, None]

    # left-side stats of the winner
    gb = jnp.take_along_axis(
        grad, f_best[:, None, None].repeat(B, 2), axis=1)[:, 0, :]
    hb = jnp.take_along_axis(
        hess, f_best[:, None, None].repeat(B, 2), axis=1)[:, 0, :]
    cb = jnp.take_along_axis(
        cnt, f_best[:, None, None].repeat(B, 2), axis=1)[:, 0, :]
    lg = jnp.sum(jnp.where(cat_mask, gb, 0.0), axis=1)
    lh = jnp.sum(jnp.where(cat_mask, hb, 0.0), axis=1) + eps
    lc = jnp.sum(jnp.where(cat_mask, cb, 0.0), axis=1)
    tg = take(tot_g)
    th = take(tot_h)
    tc = take(tot_c)
    rg = tg - lg
    rh = th - lh - eps
    rc = tc - lc

    l2_out = jnp.where(is_onehot, p.lambda_l2, l2_cat)
    left_out = calculate_leaf_output(lg, lh, p, lc, parent_output, l2_out)
    right_out = calculate_leaf_output(rg, rh, p, rc, parent_output, l2_out)
    out_gain = jnp.where(valid, gain - take(min_gain_shift), K_MIN_SCORE)
    return BestSplit(
        feature=jnp.where(valid, f_best.astype(jnp.int32), -1),
        threshold=jnp.zeros((S,), jnp.int32),
        default_left=jnp.zeros((S,), bool),
        gain=out_gain,
        left_output=left_out,
        right_output=right_out,
        left_sum_grad=lg, left_sum_hess=lh - eps, left_count=lc,
        right_sum_grad=rg, right_sum_hess=rh, right_count=rc,
        cat_flag=valid,
        cat_mask=cat_mask,
    )


@functools.partial(jax.jit,
                   static_argnames=("params", "has_cat", "use_bounds"))
def best_split_cm(grad: jax.Array, hess: jax.Array, cnt: jax.Array,
                  num_bin_per_feat: jax.Array, missing_type: jax.Array,
                  default_bin: jax.Array, feature_mask: jax.Array,
                  is_cat: jax.Array, monotone: jax.Array,
                  params: SplitParams, parent_output: jax.Array,
                  has_cat: bool = False, use_bounds: bool = False,
                  bound_lo: jax.Array = None, bound_hi: jax.Array = None,
                  leaf_depth: jax.Array = None,
                  cegb_delta: jax.Array = None,
                  bound_lo_plane: jax.Array = None,
                  bound_hi_plane: jax.Array = None) -> BestSplit:
    """Combined numerical + categorical best split per slot (the analog of
    FeatureHistogram::FindBestThreshold dispatch on bin_type,
    ref: feature_histogram.hpp:85). ``has_cat`` is static: all-numerical
    datasets skip the categorical scan entirely at trace time. Optional
    ``bound_*_plane`` [S, F, B] segment bounds select the ADVANCED
    monotone scan for numerical features (categorical winners keep the
    scalar whole-leaf clamp below)."""
    ic = is_cat[None, :] if feature_mask.ndim == 2 else is_cat
    num = best_numerical_split_cm(
        grad, hess, cnt, num_bin_per_feat, missing_type, default_bin,
        feature_mask & ~ic, monotone, params, parent_output,
        use_bounds=use_bounds, bound_lo=bound_lo, bound_hi=bound_hi,
        leaf_depth=leaf_depth, cegb_delta=cegb_delta,
        bound_lo_plane=bound_lo_plane, bound_hi_plane=bound_hi_plane)
    if not has_cat:
        return num
    cat = best_categorical_split_cm(
        grad, hess, cnt, num_bin_per_feat, feature_mask & ic, params,
        parent_output, cegb_delta=cegb_delta)
    if use_bounds:
        # categorical features carry no monotone direction, but the leaf's
        # feasible output interval still applies (winner-level clamp;
        # divergence: the reference clips per candidate)
        cat = cat._replace(
            left_output=jnp.clip(cat.left_output, bound_lo, bound_hi),
            right_output=jnp.clip(cat.right_output, bound_lo, bound_hi))
    use_cat = cat.gain > num.gain
    merged = [jnp.where(use_cat if a.ndim == 1 else use_cat[:, None], a, b)
              for a, b in zip(cat, num)]
    return BestSplit(*merged)


def per_feature_gains_cm(grad, hess, cnt, num_bin_per_feat, missing_type,
                         default_bin, feature_mask, is_cat, monotone,
                         params, parent_output,
                         has_cat: bool = False) -> jax.Array:
    """[S, F] best-candidate gain per feature — what voting-parallel
    shards rank locally before the vote (ref:
    voting_parallel_tree_learner.cpp:151 GlobalVoting). Categorical
    features rank by their categorical gain (one-hot / sorted-subset),
    numerical by the threshold scan."""
    ic = is_cat[None, :] if feature_mask.ndim == 2 else is_cat
    g = best_numerical_split_cm(
        grad, hess, cnt, num_bin_per_feat, missing_type, default_bin,
        feature_mask & ~ic, monotone, params, parent_output,
        per_feature_gains=True)
    if has_cat:
        gc = best_categorical_split_cm(
            grad, hess, cnt, num_bin_per_feat, feature_mask & ic, params,
            parent_output, per_feature_gains=True)
        g = jnp.maximum(g, gc)
    return g
