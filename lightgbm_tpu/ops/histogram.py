"""On-device gradient/hessian histogram construction.

TPU-native replacement for the reference's histogram kernels — the hottest
loop of GBDT training (ref: src/io/dense_bin.hpp ConstructHistogram,
src/treelearner/ocl/histogram{16,64,256}.cl, src/treelearner/kernels/
histogram_16_64_256.cu).  The reference uses per-thread/per-workgroup
scatter-adds with atomics; TPUs have no fast atomics, so the formulations here
are dense-array programs XLA can tile:

- ``segment``: one ``jax.ops.segment_sum`` over a joint (slot, feature, bin)
  index per row-chunk, scanned over chunks.  Works for any number of target
  leaves (depth-wise frontier batches).
- ``onehot``: builds a ``[chunk, F, B]`` one-hot of the bin indices and
  contracts it with (grad, hess, count) on the MXU.  Fastest when targeting a
  single leaf (leaf-wise growth; the smaller-child + subtraction trick,
  ref: serial_tree_learner.cpp:423-425).
- a Pallas kernel (ops/pallas_histogram.py) specializes the onehot formulation
  with VMEM-resident accumulators to avoid materializing the one-hot in HBM.

Histograms are ``float32 [num_slots, F, B, 3]`` with channels (sum_grad,
sum_hess, count); the reference accumulates float64 on CPU and float32 on GPU
with acceptable AUC drift (ref: docs/GPU-Performance.rst:130-160) — we match
the GPU precision contract by default.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

# channels: grad, hess, count
NUM_CH = 3


def _choose_chunk(num_rows: int, num_features: int, num_bins: int,
                  budget_bytes: int = 1 << 26) -> int:
    """Row-chunk size keeping the materialized one-hot under ``budget_bytes``."""
    c = budget_bytes // max(1, num_features * num_bins * 4)
    c = max(256, min(int(c), 1 << 15, max(256, num_rows)))
    # round to a multiple of 256 for clean tiling
    return max(256, (c // 256) * 256)


def _pad_rows(arrs, chunk: int, pad_values):
    n = arrs[0].shape[0]
    rem = (-n) % chunk
    if rem == 0:
        return arrs
    out = []
    for a, pv in zip(arrs, pad_values):
        pad_width = [(0, rem)] + [(0, 0)] * (a.ndim - 1)
        out.append(jnp.pad(a, pad_width, constant_values=pv))
    return out


@functools.partial(jax.jit, static_argnames=("num_slots", "num_bins", "impl"))
def build_histograms(bins: jax.Array, gh: jax.Array, row_slot: jax.Array,
                     *, num_slots: int, num_bins: int,
                     impl: str = "auto") -> jax.Array:
    """Histograms for a batch of target leaves.

    Args:
      bins: ``[R, F]`` uint8/uint16 binned features.
      gh: ``[R, 3]`` float32 (grad, hess, count-weight); rows excluded by
        bagging carry zeros.
      row_slot: ``[R]`` int32 — target slot of each row, or -1 to ignore.
        (Computed by the caller as ``leaf_to_slot[row_leaf]``.)
      num_slots: static number of target leaves.
      num_bins: static padded bin count per feature.

    Returns: ``[num_slots, F, num_bins, 3]`` float32.
    """
    R, F = bins.shape
    if impl == "auto":
        impl = "onehot" if num_slots <= 2 else "segment"
    chunk = _choose_chunk(R, F, num_bins)
    bins_p, gh_p, slot_p = _pad_rows(
        [bins, gh, row_slot], chunk, [0, 0.0, -1])
    n_chunks = bins_p.shape[0] // chunk
    bins_c = bins_p.reshape(n_chunks, chunk, F)
    gh_c = gh_p.reshape(n_chunks, chunk, NUM_CH)
    slot_c = slot_p.reshape(n_chunks, chunk)

    if impl == "segment":
        fb = F * num_bins
        f_off = (jnp.arange(F, dtype=jnp.int32) * num_bins)[None, :]

        def body(hist, xs):
            b, g, s = xs
            idx = jnp.where(s[:, None] >= 0,
                            s[:, None] * fb + f_off + b.astype(jnp.int32),
                            num_slots * fb)  # dump bucket
            data = jnp.broadcast_to(g[:, None, :], (chunk, F, NUM_CH))
            seg = jax.ops.segment_sum(data.reshape(-1, NUM_CH),
                                      idx.reshape(-1),
                                      num_segments=num_slots * fb + 1)
            return hist + seg[:num_slots * fb], None

        init = jnp.zeros((num_slots * fb, NUM_CH), jnp.float32)
        hist, _ = jax.lax.scan(body, init, (bins_c, gh_c, slot_c))
        return hist.reshape(num_slots, F, num_bins, NUM_CH)

    # one-hot matmul formulation: contraction over rows rides the MXU
    iota_b = jnp.arange(num_bins, dtype=jnp.int32)

    def body(hist, xs):
        b, g, s = xs
        onehot = (b.astype(jnp.int32)[:, :, None] == iota_b).astype(jnp.float32)
        if num_slots == 1:
            ghm = jnp.where(s[:, None] == 0, g, 0.0)
            h = jnp.einsum("rfb,rc->fbc", onehot, ghm,
                           preferred_element_type=jnp.float32)
            return hist + h[None], None
        slot_oh = (s[:, None] == jnp.arange(num_slots, dtype=jnp.int32)
                   ).astype(jnp.float32)  # [C, S]
        ghs = slot_oh[:, :, None] * g[:, None, :]  # [C, S, 3]
        h = jnp.einsum("rfb,rsc->sfbc", onehot, ghs,
                       preferred_element_type=jnp.float32)
        return hist + h, None

    init = jnp.zeros((num_slots, F, num_bins, NUM_CH), jnp.float32)
    hist, _ = jax.lax.scan(body, init, (bins_c, gh_c, slot_c))
    return hist


def histogram_subtract(parent: jax.Array, child: jax.Array) -> jax.Array:
    """Sibling histogram via subtraction (ref: feature_histogram.hpp Subtract,
    serial_tree_learner.cpp:423-425 smaller/larger-leaf trick)."""
    return parent - child
