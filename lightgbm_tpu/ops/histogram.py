"""On-device gradient/hessian histogram construction.

TPU-native replacement for the reference's histogram kernels — the hottest
loop of GBDT training (ref: src/io/dense_bin.hpp ConstructHistogram,
src/treelearner/ocl/histogram{16,64,256}.cl, src/treelearner/kernels/
histogram_16_64_256.cu).  The reference uses per-thread/per-workgroup
scatter-adds with atomics; TPUs have no fast atomics, so the formulations here
are dense-array programs XLA can tile:

- ``segment``: one ``jax.ops.segment_sum`` over a joint (slot, feature, bin)
  index per row-chunk, scanned over chunks.  Works for any number of target
  leaves (depth-wise frontier batches).
- ``onehot``: builds a ``[chunk, F, B]`` one-hot of the bin indices and
  contracts it with (grad, hess, count) on the MXU.  Fastest when targeting a
  single leaf (leaf-wise growth; the smaller-child + subtraction trick,
  ref: serial_tree_learner.cpp:423-425).
- a Pallas kernel (ops/pallas_histogram.py) specializes the onehot formulation
  with VMEM-resident accumulators to avoid materializing the one-hot in HBM.

Histograms are ``float32 [num_slots, F, B, 3]`` with channels (sum_grad,
sum_hess, count); the reference accumulates float64 on CPU and float32 on GPU
with acceptable AUC drift (ref: docs/GPU-Performance.rst:130-160) — we match
the GPU precision contract by default.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

# channels: grad, hess, count
NUM_CH = 3

# the one-hot / scatter-chunk byte budget the row-chunk size derives
# from (was a bare ``1 << 26`` literal; named so the telemetry the
# driver emits — hist.bytes_per_level — and this bound share a source)
HIST_CHUNK_BUDGET_BYTES = 1 << 26


def _choose_chunk(num_rows: int, num_features: int, num_bins: int,
                  elem_bytes: int = 4,
                  budget_bytes: int = HIST_CHUNK_BUDGET_BYTES) -> int:
    """Row-chunk size keeping the materialized one-hot under
    ``budget_bytes``.  ``elem_bytes`` is the accumulated element width —
    4 for the f32 default, 1/2 for the quantized int8/int16 grids
    (ops/quantize.quant_elem_bytes), so quantization buys
    proportionally larger chunks under the same budget."""
    c = budget_bytes // max(1, num_features * num_bins * elem_bytes)
    c = max(256, min(int(c), 1 << 15, max(256, num_rows)))
    # round to a multiple of 256 for clean tiling
    return max(256, (c // 256) * 256)


def _pad_rows(arrs, chunk: int, pad_values):
    n = arrs[0].shape[0]
    rem = (-n) % chunk
    if rem == 0:
        return arrs
    out = []
    for a, pv in zip(arrs, pad_values):
        pad_width = [(0, rem)] + [(0, 0)] * (a.ndim - 1)
        out.append(jnp.pad(a, pad_width, constant_values=pv))
    return out


@functools.partial(jax.jit, static_argnames=("num_slots", "num_bins", "impl",
                                             "quant_bits"))
def build_histograms(bins: jax.Array, gh: jax.Array, row_slot: jax.Array,
                     *, num_slots: int, num_bins: int,
                     impl: str = "auto", quant_bits: int = 0,
                     seed=0) -> jax.Array:
    """Histograms for a batch of target leaves.

    Args:
      bins: ``[R, F]`` uint8/uint16 binned features.
      gh: ``[R, 3]`` float32 (grad, hess, count-weight); rows excluded by
        bagging carry zeros (and rows with slot -1 contribute nothing
        regardless of their gh values — the dump-bucket route).
      row_slot: ``[R]`` int32 — target slot of each row, or -1 to ignore.
        (Computed by the caller as ``leaf_to_slot[row_leaf]``.)
      num_slots: static number of target leaves.
      num_bins: static padded bin count per feature.
      quant_bits: 0 (f32 accumulation, default), 8 or 16 — grad/hess
        stochastically rounded onto the fixed-point grid
        (ops/quantize.py) and accumulated EXACTLY in int32 via the
        segment formulation, rescaled to f32 here before return.

    Returns: ``[num_slots, F, num_bins, 3]`` float32.
    """
    from . import quantize
    R, F = bins.shape
    if quant_bits:
        scales = quantize.quant_scales(gh[:, 0], gh[:, 1], quant_bits)
        qg, qh = quantize.quantize_gh(gh[:, 0], gh[:, 1], scales,
                                      quant_bits, seed)
        qw = (gh[:, 2] > 0).astype(jnp.int32)
        gh = jnp.stack([qg, qh, qw], axis=1)        # int32 grid values
        impl = "segment"                            # int32 segment sums
    if impl == "auto":
        impl = "onehot" if num_slots <= 2 else "segment"
    chunk = _choose_chunk(R, F, num_bins,
                          elem_bytes=quantize.quant_elem_bytes(quant_bits))
    bins_p, gh_p, slot_p = _pad_rows(
        [bins, gh, row_slot], chunk, [0, 0.0, -1])
    n_chunks = bins_p.shape[0] // chunk
    bins_c = bins_p.reshape(n_chunks, chunk, F)
    gh_c = gh_p.reshape(n_chunks, chunk, NUM_CH)
    slot_c = slot_p.reshape(n_chunks, chunk)

    if impl == "segment":
        fb = F * num_bins
        f_off = (jnp.arange(F, dtype=jnp.int32) * num_bins)[None, :]

        def body(hist, xs):
            b, g, s = xs
            idx = jnp.where(s[:, None] >= 0,
                            s[:, None] * fb + f_off + b.astype(jnp.int32),
                            num_slots * fb)  # dump bucket
            data = jnp.broadcast_to(g[:, None, :], (chunk, F, NUM_CH))
            seg = jax.ops.segment_sum(data.reshape(-1, NUM_CH),
                                      idx.reshape(-1),
                                      num_segments=num_slots * fb + 1)
            return hist + seg[:num_slots * fb], None

        acc_dt = jnp.int32 if quant_bits else jnp.float32
        init = jnp.zeros((num_slots * fb, NUM_CH), acc_dt)
        hist, _ = jax.lax.scan(body, init, (bins_c, gh_c, slot_c))
        hist = hist.reshape(num_slots, F, num_bins, NUM_CH)
        if quant_bits:
            # the ONE f32 rescale boundary — everything downstream
            # (split search) is unchanged above it
            hist = jnp.stack(
                [hist[..., 0].astype(jnp.float32) * scales[0],
                 hist[..., 1].astype(jnp.float32) * scales[1],
                 hist[..., 2].astype(jnp.float32)], axis=-1)
        return hist

    # one-hot matmul formulation: contraction over rows rides the MXU
    iota_b = jnp.arange(num_bins, dtype=jnp.int32)

    def body(hist, xs):
        b, g, s = xs
        onehot = (b.astype(jnp.int32)[:, :, None] == iota_b).astype(jnp.float32)
        if num_slots == 1:
            ghm = jnp.where(s[:, None] == 0, g, 0.0)
            h = jnp.einsum("rfb,rc->fbc", onehot, ghm,
                           preferred_element_type=jnp.float32)
            return hist + h[None], None
        slot_oh = (s[:, None] == jnp.arange(num_slots, dtype=jnp.int32)
                   ).astype(jnp.float32)  # [C, S]
        ghs = slot_oh[:, :, None] * g[:, None, :]  # [C, S, 3]
        h = jnp.einsum("rfb,rsc->sfbc", onehot, ghs,
                       preferred_element_type=jnp.float32)
        return hist + h, None

    init = jnp.zeros((num_slots, F, num_bins, NUM_CH), jnp.float32)
    hist, _ = jax.lax.scan(body, init, (bins_c, gh_c, slot_c))
    return hist


def histogram_subtract(parent: jax.Array, child: jax.Array) -> jax.Array:
    """Sibling histogram via subtraction (ref: feature_histogram.hpp Subtract,
    serial_tree_learner.cpp:423-425 smaller/larger-leaf trick)."""
    return parent - child
