"""Exclusive Feature Bundling — bundling algorithm + encoding.

Analog of the reference's EFB (ref: src/io/dataset.cpp FindGroups /
FastFeatureBundling: sparse, mutually-exclusive features share one stored
column so histogram work scales with bundles, not features). This module
provides the standalone pieces — greedy conflict-bounded bundling, the
bundle-column encoding, and the logical-view reconstruction that turns a
bundle histogram back into per-feature histograms (the FixHistogram
default-bin trick, dataset.cpp:1265). Grower integration is planned for
round 3 (the fused kernel's W route tables already express arbitrary
per-bin masks, so routing on bundle columns needs no kernel change).

Encoding (our own, simpler than the reference's offset scheme):
- bundle bin 0 = the row is default (most-frequent bin) in EVERY bundled
  feature;
- feature j owns the window [offset_j, offset_j + num_bin_j): a row
  non-default in j stores offset_j + bin_j(row);
- conflicts (non-default in several features) keep the first feature's
  encoding — allowed up to ``max_conflict_rate`` like the reference.

Reconstruction: the window copy recovers every non-default bin; the
feature's default bin gets ``total - sum(window)`` so masses are exact
for conflict-free rows.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

if hasattr(np, "bitwise_count"):
    _popcount = np.bitwise_count          # NumPy >= 2.0
else:
    # NumPy 1.x fallback: byte-view + unpackbits popcount
    def _popcount(a: np.ndarray) -> np.ndarray:
        return np.unpackbits(a.view(np.uint8)).reshape(a.shape + (64,)) \
            .sum(axis=-1, dtype=np.int64)


def find_bundles(nondefault_masks: Sequence[np.ndarray], num_rows: int,
                 max_conflict_rate: float = 0.0001,
                 max_bundle_bins: int = 65535,
                 num_bin_per_feat: Sequence[int] = None,
                 max_search_bundles: int = 64) -> List[List[int]]:
    """Greedy conflict-bounded bundling (ref: dataset.cpp:108-176
    FindGroups).

    Args:
      nondefault_masks: per-feature boolean [R] arrays (True where the row
        is NOT in the feature's most-frequent bin).
      max_conflict_rate: allowed fraction of rows in conflict per bundle
        (the reference's single_val_max_conflict_cnt is
        total_sample_cnt/10000 — rate 1e-4, the default here).
      max_search_bundles: candidate bundles tried per feature before a new
        one opens (the reference's FindGroups bounds its search the same
        way, max_find_group cap) — keeps the greedy near-linear on
        many-thousand-feature sparse data.

    A feature joins a bundle only when its conflict count also stays
    under HALF its own non-zero count (ref: dataset.cpp:155
    ``cnt <= cur_non_zero_cnt / 2``) — a feature that collides on most
    of its mass would lose its signal to the first-writer-wins encode.

    Returns a list of bundles (lists of feature indices). Dense features
    end up in singleton bundles. Conflict masks are packed uint64 bitsets
    so each probe is a popcount over R/64 words, not R bools.
    """
    F = len(nondefault_masks)
    counts = [int(m.sum()) for m in nondefault_masks]
    order = sorted(range(F), key=lambda f: counts[f], reverse=True)
    budget = int(max_conflict_rate * num_rows)
    words = (num_rows + 63) // 64

    def pack(m):
        return np.packbits(m, bitorder="little")[: words * 8] \
            .copy().view(np.uint64) if len(m) else np.zeros(0, np.uint64)

    bundle_masks: List[np.ndarray] = []
    bundle_conflicts: List[int] = []
    bundle_bins: List[int] = []
    bundles: List[List[int]] = []
    nb = num_bin_per_feat
    for f in order:
        nnz = counts[f]
        f_bins = int(nb[f]) if nb is not None else 1
        placed = False
        packed = None
        # skip bundling for dense features (no savings, conflicts certain)
        if nnz * 2 < num_rows:
            packed = pack(np.pad(nondefault_masks[f],
                                 (0, words * 64 - num_rows)))
            # most-recent bundles first: they are the least full
            cand = range(len(bundles) - 1,
                         max(-1, len(bundles) - 1 - max_search_bundles), -1)
            for bi in cand:
                if bundle_bins[bi] + f_bins > max_bundle_bins:
                    continue  # keep the encoded bin range in dtype bounds
                conflicts = int(_popcount(
                    bundle_masks[bi] & packed).sum())
                if bundle_conflicts[bi] + conflicts <= budget \
                        and conflicts * 2 <= nnz:
                    bundles[bi].append(f)
                    bundle_masks[bi] |= packed
                    bundle_conflicts[bi] += conflicts
                    bundle_bins[bi] += f_bins
                    placed = True
                    break
        if not placed:
            if packed is None:
                packed = pack(np.pad(nondefault_masks[f],
                                     (0, words * 64 - num_rows)))
            bundles.append([f])
            bundle_masks.append(packed.copy())
            bundle_conflicts.append(0)
            bundle_bins.append(1 + f_bins)
    return bundles


class BundleLayout:
    """Column layout for one bundling of F logical features.

    Attributes:
      bundles: list of feature-index lists.
      col_of_feat / offset_of_feat: [F] arrays mapping each logical
        feature to its physical column and bin offset.
      col_num_bin: bins per physical column (1 shared default bin +
        each member's window).
    """

    def __init__(self, bundles: List[List[int]],
                 num_bin_per_feat: Sequence[int]):
        F = len(num_bin_per_feat)
        self.bundles = bundles
        self.col_of_feat = np.full(F, -1, np.int32)
        self.offset_of_feat = np.zeros(F, np.int32)
        self.col_num_bin: List[int] = []
        for ci, b in enumerate(bundles):
            off = 1  # bin 0 = default-in-all
            for f in b:
                self.col_of_feat[f] = ci
                self.offset_of_feat[f] = off
                off += int(num_bin_per_feat[f])
            self.col_num_bin.append(off)

    @property
    def num_columns(self) -> int:
        return len(self.bundles)


def encode_bundles(bins: np.ndarray, default_bins: Sequence[int],
                   layout: BundleLayout) -> np.ndarray:
    """[R, F] logical bins -> [R, C] bundle-column bins."""
    R = bins.shape[0]
    C = layout.num_columns
    dtype = np.uint16 if max(layout.col_num_bin) > 255 else np.uint8
    out = np.zeros((R, C), dtype)
    for ci, bundle in enumerate(layout.bundles):
        col = np.zeros(R, np.int64)
        taken = np.zeros(R, bool)
        for f in bundle:
            b = bins[:, f].astype(np.int64)
            nd = (b != default_bins[f]) & ~taken
            col[nd] = layout.offset_of_feat[f] + b[nd]
            taken |= nd
        out[:, ci] = col.astype(dtype)
    return out


def logical_histograms(bundle_hist: np.ndarray, totals: np.ndarray,
                       layout: BundleLayout,
                       num_bin_per_feat: Sequence[int],
                       default_bins: Sequence[int],
                       max_bin: int) -> np.ndarray:
    """[S, C, B_col, ch] bundle histograms -> [S, F, max_bin, ch] logical
    views. Each feature's window is copied and its default bin receives
    ``totals - sum(window)`` (FixHistogram, ref: dataset.cpp:1265).

    totals: [S, ch] per-slot leaf sums.
    """
    S = bundle_hist.shape[0]
    ch = bundle_hist.shape[-1]
    F = len(num_bin_per_feat)
    out = np.zeros((S, F, max_bin, ch), bundle_hist.dtype)
    for f in range(F):
        ci = layout.col_of_feat[f]
        off = layout.offset_of_feat[f]
        nb = int(num_bin_per_feat[f])
        win = bundle_hist[:, ci, off:off + nb, :]
        out[:, f, :nb, :] = win
        missing = totals - win.sum(axis=1)
        out[:, f, default_bins[f], :] += missing
    return out
