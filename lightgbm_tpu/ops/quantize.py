"""Fixed-point gradient/hessian quantization for histogram accumulation.

Grounding: arxiv 2011.02022 (Booster's fixed-point gradient
accumulators).  Gradients and hessians are stochastically rounded onto a
signed ``2^(bits-1)-1`` grid under a per-iteration GLOBAL scale (one
traced max-abs reduction per channel), histograms accumulate the integer
grid values EXACTLY (int32 — integer addition is associative, so the
quantized histograms are also bit-identical across shard/psum orders),
and one f32 rescale per level happens at the decode boundary, BEFORE the
split search (``ops/split.py`` is unchanged above that boundary).

TPU shape of the design: the MXU's native integer path is s8 x s8 -> s32,
so the 16-bit grid is carried as two int8 channels per value (hi/lo split
— the integer analog of the bf16 hi/lo trick ``ops/fused_level.pack_gh``
already uses for f32-grade sums):

    q = 256 * hi + lo' + 128 * w      with lo' in [-128, 127], w in {0, 1}

The ``128 * w`` recentering keeps lo' signed while zero-weight rows
(padding, out-of-bag) contribute exactly zero; the count channel ``w``
the histograms already carry supplies the recentering sum for free.

Stochastic rounding is hash-based and fully traced: the dither for a
value is derived from its own bits, its row index and a per-iteration
seed through a murmur-style integer mix — deterministic given
(values, seed), so the quantized paths keep the repo's bit-reproducible
A/B contracts (fast path vs sync driver, resume-from-checkpoint).

Error model (docs/Performance.md "Histogram plane"): each row's
grad/hess carries uniform quantization noise with zero mean (stochastic
rounding is unbiased) and magnitude <= scale = max|g| / (2^(bits-1)-1);
a bin summing n rows accumulates noise O(scale * sqrt(n)).  int32
accumulators are exact for |sum q| < 2^31: worst case n_bin * 2^(bits-1)
— safe to ~16M rows per bin at 8 bits, and the 16-bit hi channel's
|hi| <= 128 gives the same ~16M bound per channel; the hi/lo
RECOMBINATION (256x) therefore happens in f32 at the decode boundary,
never in int32 (see decode_sums).
"""
from __future__ import annotations

from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

QMAX = {8: 127, 16: 32767}
# channel count per bit width: 8 -> (g, h, w); 16 -> (g_hi, g_lo, h_hi,
# h_lo, w) — mirrors NCH_FAST / NCH_PRECISE of the f32 kernel path
QNCH = {8: 3, 16: 5}


def quant_elem_bytes(quant_bits: int) -> int:
    """Element width of the quantized grid (4 = the f32 default) — what
    the one-hot chunk budget derives from (``histogram._choose_chunk``)."""
    return {0: 4, 8: 1, 16: 2}[int(quant_bits)]


def _mix(h: jax.Array) -> jax.Array:
    """murmur3-style avalanche over uint32."""
    h = h ^ (h >> np.uint32(16))
    h = h * np.uint32(0x85EBCA6B)
    h = h ^ (h >> np.uint32(13))
    h = h * np.uint32(0xC2B2AE35)
    h = h ^ (h >> np.uint32(16))
    return h


def stochastic_round(x: jax.Array, seed) -> jax.Array:
    """floor(x) + (u < frac(x)) as int32, u in [0, 1) hashed from
    (row index, seed).  Unbiased: u is independent of x and uniform, so
    P(round up) = frac(x) and E[result] = x.  Deterministic given
    (shape, seed).

    Two deliberate properties:
    - the dither compares against the FRACTIONAL part instead of being
      added to x — adding u to a large-magnitude x rounds in f32 and
      would mis-round exact integers (|x| ~ 2^15 has f32 spacing larger
      than small dithers), breaking the integer-grid bit-comparability
      contract;
    - the hash takes ONLY (index, seed), never the value bits: hashing
      the value would turn any ulp-level difference between two traced
      programs (XLA fma/fusion choices differ between the pipelined
      fast path and the sync driver) into a completely different dither
      for that row, amplifying one-ulp drift into visible model
      divergence.  With a value-independent dither, an ulp of drift
      flips a rounding only when it straddles the u threshold — the
      same robustness class as the f32 path's A/B contracts."""
    n = x.shape[-1]
    idx = jax.lax.iota(jnp.uint32, n)
    if x.ndim > 1:
        idx = jnp.broadcast_to(idx, x.shape)
    seed = jnp.asarray(seed, jnp.uint32)
    x = x.astype(jnp.float32)
    h = _mix((idx * np.uint32(2654435761)) ^ (seed * np.uint32(0x27D4EB2F)))
    u = (h >> np.uint32(8)).astype(jnp.float32) * np.float32(1.0 / (1 << 24))
    lo = jnp.floor(x)
    frac = x - lo          # exact: lo is within one ulp-neighborhood of x
    return (lo.astype(jnp.int32)
            + (u < frac).astype(jnp.int32))


def quant_scales(grad: jax.Array, hess: jax.Array, bits: int) -> jax.Array:
    """[2] f32 per-iteration global scales (grad, hess) from traced
    max-abs reductions; a GSPMD-sharded operand reduces globally, so
    every shard quantizes on the identical grid."""
    qmax = np.float32(QMAX[bits])
    tiny = np.float32(1e-30)
    sg = jnp.maximum(jnp.max(jnp.abs(grad)), tiny) / qmax
    sh = jnp.maximum(jnp.max(jnp.abs(hess)), tiny) / qmax
    return jnp.stack([sg, sh]).astype(jnp.float32)


def quantize_gh(grad: jax.Array, hess: jax.Array, scales: jax.Array,
                bits: int, seed) -> Tuple[jax.Array, jax.Array]:
    """(q_grad, q_hess) int32 on the signed grid, clipped to +-QMAX.
    Distinct dither streams per channel (seed offsets)."""
    qmax = QMAX[bits]
    seed = jnp.asarray(seed, jnp.uint32)
    qg = stochastic_round(grad / scales[0], seed)
    qh = stochastic_round(hess / scales[1], seed ^ np.uint32(0x9E3779B9))
    return (jnp.clip(qg, -qmax, qmax), jnp.clip(qh, -qmax, qmax))


def encode_channels(qg: jax.Array, qh: jax.Array, w01: jax.Array,
                    bits: int) -> List[jax.Array]:
    """int8 channel rows for the kernels' packed gh block.

    bits=8:  [g, h, w]
    bits=16: [g_hi, g_lo', h_hi, h_lo', w] with the 128*w recentering
             (module docstring); zero-weight rows encode exactly zero.
    """
    w8 = (w01 > 0).astype(jnp.int8)
    if bits == 8:
        return [qg.astype(jnp.int8), qh.astype(jnp.int8), w8]
    w32 = w8.astype(jnp.int32)

    def split(q):
        hi = jnp.floor_divide(q, 256)
        lo = q - 256 * hi - 128 * w32
        return hi.astype(jnp.int8), lo.astype(jnp.int8)
    g_hi, g_lo = split(qg)
    h_hi, h_lo = split(qh)
    return [g_hi, g_lo, h_hi, h_lo, w8]


def decode_sums(planes: List[jax.Array], scales: jax.Array, bits: int
                ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(grad, hess, count) f32 sums from the int32 accumulator planes —
    the ONE f32 rescale boundary before the split search.

    The 16-bit hi/lo recombination happens in f32: ``256 * hi_sum``
    would re-bind the int32 overflow limit at ~65K non-canceling rows
    per bin (the ACCUMULATOR channels are safe to ~16M — |hi| <= 128 —
    but the recombined magnitude is 256x larger). The f32 product
    ``hi_sum * 256`` is exact (pow2 scaling of an exactly-represented
    int < 2^24) and the two adds round once each — within the f32
    rescale rounding the error model already accepts."""
    if bits == 8:
        g = planes[0].astype(jnp.float32) * scales[0]
        h = planes[1].astype(jnp.float32) * scales[1]
        c = planes[2].astype(jnp.float32)
        return g, h, c
    w = planes[4].astype(jnp.float32)
    g = (planes[0].astype(jnp.float32) * 256.0
         + planes[1].astype(jnp.float32) + 128.0 * w) * scales[0]
    h = (planes[2].astype(jnp.float32) * 256.0
         + planes[3].astype(jnp.float32) + 128.0 * w) * scales[1]
    return g, h, w
