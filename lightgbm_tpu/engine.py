"""Training engine: train() and cv().

Behavioral analog of ref: python-package/lightgbm/engine.py (train :25,
cv :399, CVBooster :285, _make_n_folds :323).
"""
from __future__ import annotations

import collections
import copy
from typing import Any, Dict, List, Optional, Union

import numpy as np

from . import callback as callback_mod
from .basic import Booster, Dataset
from .config import Config
from .utils import log

__all__ = ["train", "cv", "CVBooster"]

_ROUND_ALIASES = ("num_iterations", "num_iteration", "n_iter", "num_tree",
                  "num_trees", "num_round", "num_rounds", "nrounds",
                  "num_boost_round", "n_estimators", "max_iter")
_ES_ALIASES = ("early_stopping_round", "early_stopping_rounds",
               "early_stopping", "n_iter_no_change")


def train(params: Dict[str, Any], train_set: Dataset,
          num_boost_round: int = 100,
          valid_sets: Optional[List[Dataset]] = None,
          valid_names: Optional[List[str]] = None,
          fobj=None, feval=None,
          init_model: Optional[Union[str, Booster]] = None,
          feature_name="auto", categorical_feature="auto",
          keep_training_booster: bool = False,
          callbacks: Optional[List] = None,
          resume_from: Optional[str] = None) -> Booster:
    """Train a booster (ref: engine.py:25).

    ``resume_from`` restores a run from a resilience checkpoint (a
    ``ckpt_<iteration>`` directory or a ``checkpoint_dir`` root — the
    newest complete one is selected) and continues it bit-identically
    to an uninterrupted run with the same params/seed; pass the same
    dataset, valid sets and callbacks the interrupted run used
    (docs/Reliability.md). The ``resume`` params key is equivalent."""
    params = dict(params) if params else {}
    # pop BOTH keys unconditionally: a resume path left in params would
    # echo into the serialized model's parameters block and break the
    # bit-identical-serialization contract below
    _p_resume = params.pop("resume", "") or params.pop("resume_from", "")
    params.pop("resume_from", None)
    if resume_from and _p_resume and str(_p_resume) != str(resume_from):
        log.warning("resume_from=%s overrides params resume=%s",
                    resume_from, _p_resume)
    resume_from = resume_from or _p_resume or None
    if train_set is not None and isinstance(getattr(train_set, "params",
                                                    None), dict):
        # the resume path is a per-invocation instruction, not a model
        # property: scrub it from the dataset params too so the
        # resumed model's echoed parameters block (and hence its
        # serialization) stays identical to an uninterrupted run's
        for key in ("resume", "resume_from"):
            train_set.params.pop(key, None)
    if resume_from and init_model is not None:
        log.warning("resume_from and init_model both given; resume wins "
                    "(the checkpoint already contains the full model)")
        init_model = None
    # resolve num_boost_round / early stopping aliases (params win)
    for alias in _ROUND_ALIASES:
        if alias in params:
            num_boost_round = int(params.pop(alias))
    params["num_iterations"] = num_boost_round
    snapshot_freq = int(params.get("snapshot_freq",
                                   params.get("save_period", -1) or -1))
    snapshot_base = str(params.get("output_model", "LightGBM_model.txt"))
    first_metric_only = bool(params.get("first_metric_only", False))
    early_stopping_round = None
    for alias in _ES_ALIASES:
        if alias in params:
            early_stopping_round = int(params[alias])

    if feature_name != "auto":
        train_set.feature_name = feature_name
    if categorical_feature != "auto":
        train_set.categorical_feature = categorical_feature

    # continued training: init model's raw predictions become init scores
    predictor = None
    if isinstance(init_model, str):
        predictor = Booster(model_file=init_model)
    elif isinstance(init_model, Booster):
        # num_iteration=-1: continuation must see EVERY tree, including
        # the post-best overrun of an early-stopped init_model (the
        # default would truncate to best_iteration)
        predictor = Booster(model_str=init_model.model_to_string(
            num_iteration=-1))
    if predictor is not None and train_set.init_score is None:
        raw = predictor.predict(train_set.data, raw_score=True)
        train_set.set_init_score(np.asarray(raw).reshape(-1, order="F"))

    # train_set appearing in valid_sets enables training metrics
    # (ref: engine.py train_data_name handling)
    if valid_sets is not None:
        vs_list = valid_sets if isinstance(valid_sets, list) else [valid_sets]
        if any(vs is train_set for vs in vs_list):
            params.setdefault("is_provide_training_metric", True)

    booster = Booster(params=params, train_set=train_set)
    if valid_sets is not None:
        if not isinstance(valid_sets, list):
            valid_sets = [valid_sets]
        for i, vs in enumerate(valid_sets):
            if vs is train_set:
                name = "training"
            elif valid_names is not None and i < len(valid_names):
                name = valid_names[i]
            else:
                name = f"valid_{i}"
            if vs is not train_set:
                if predictor is not None and vs.init_score is None:
                    raw = predictor.predict(vs.data, raw_score=True)
                    vs.set_init_score(np.asarray(raw).reshape(-1, order="F"))
                booster.add_valid(vs, name)
    train_in_valid = valid_sets is not None and any(
        vs is train_set for vs in valid_sets)

    callbacks = list(callbacks) if callbacks else []
    if early_stopping_round is not None and early_stopping_round > 0:
        callbacks.append(callback_mod.early_stopping(
            early_stopping_round, first_metric_only, verbose=True))
    callbacks_before = [cb for cb in callbacks
                        if getattr(cb, "before_iteration", False)]
    callbacks_after = [cb for cb in callbacks
                       if not getattr(cb, "before_iteration", False)]
    callbacks_before.sort(key=lambda cb: getattr(cb, "order", 0))
    callbacks_after.sort(key=lambda cb: getattr(cb, "order", 0))

    # main loop (ref: engine.py:260-283)
    # Megastep arming: this loop may consume multi-iteration steps (one
    # jit fusing up to tpu_megastep_iters iterations) because it breaks
    # on `finished` and nothing here needs per-iteration observation.
    #
    # Per-iteration consumers no longer force the synchronous path when
    # they are the BUILT-IN set (early_stopping / log_evaluation /
    # record_evaluation / record_telemetry, plus snapshot_freq): the
    # megastep evaluates every configured metric ON DEVICE inside the
    # scan (metric/traced.py) and the drain replays these callbacks in
    # iteration order against the stacked metric matrix
    # (callback.DrainEvalReplay) — no score fetch, no re-predict, and a
    # scan-carried early-stop flag keeps the drained model bit-identical
    # to this loop's synchronous early-stopped model. Anything the drain
    # cannot replay (user callbacks, reset_parameter, feval, fobj, an
    # untraceable metric) falls back to the classic inline loop below,
    # with a structured megastep_evicted event naming the blocker.
    gbdt = booster._gbdt
    consumer = None
    want_replay = bool(callbacks) or snapshot_freq > 0
    if want_replay and feval is None and fobj is None:
        blocker = callback_mod.drain_replay_blocker(
            callbacks_before + callbacks_after)
        if blocker is None:
            ok, blocker = gbdt.megastep_eval_precheck(
                include_training=train_in_valid,
                es_spec=callback_mod.find_es_spec(callbacks_after))
            if ok:
                consumer = callback_mod.DrainEvalReplay(
                    booster=booster, params=params,
                    callbacks_before=callbacks_before,
                    callbacks_after=callbacks_after,
                    end_iteration=num_boost_round,
                    snapshot_freq=snapshot_freq,
                    snapshot_base=snapshot_base,
                    include_training=train_in_valid)
                gbdt.arm_megastep(True, eval_consumer=consumer)
        if consumer is None:
            gbdt._report_eviction(blocker, stage="engine")
    elif want_replay or feval is not None or fobj is not None:
        gbdt._report_eviction("feval" if feval is not None else "fobj",
                              stage="engine")
    if consumer is None and not callbacks and feval is None \
            and fobj is None and snapshot_freq <= 0:
        gbdt.arm_megastep(True)
    evaluation_result_list: List = []
    start_iteration = 0
    if resume_from:
        # restore AFTER valid sets were added and the megastep consumer
        # was armed: the score-carry shapes and the traced eval plan are
        # settled, so the checkpoint slots can be matched against them
        from .resilience import state as rstate
        payload = rstate.restore_into_booster(booster, str(resume_from))
        start_iteration = gbdt.iter
        saved_eval = rstate.eval_list_from_payload(payload)
        env = callback_mod.CallbackEnv(
            model=booster, params=params,
            iteration=max(0, start_iteration - 1), begin_iteration=0,
            end_iteration=num_boost_round,
            evaluation_result_list=saved_eval)
        es_state = rstate.restore_callback_states(
            callbacks_before + callbacks_after,
            (payload.get("engine_extra") or {}).get("callbacks") or [],
            env)
        evaluation_result_list = list(saved_eval)
        if consumer is not None:
            consumer.last_eval = list(saved_eval)
            if es_state is not None:
                # rebuild the scan's device early-stop carry from the
                # restored callback state (same f32 values + compares)
                rstate.synthesize_es_carry(gbdt, es_state)
    if gbdt._ckpt is not None:
        # checkpoint extra-state hook: the callback closures' early-stop
        # lists and the last eval list ride every checkpoint so the
        # restore above has them on the other side
        def _engine_ckpt_extra():
            from .resilience import state as rstate
            ev = (list(consumer.last_eval) if consumer is not None
                  else list(evaluation_result_list))
            return {"callbacks": rstate.callback_states(
                        callbacks_before + callbacks_after),
                    "eval_list": [list(t) for t in ev]}
        gbdt.set_checkpoint_extra(_engine_ckpt_extra)
    i = -1
    try:
      for i in range(start_iteration, num_boost_round):
        try:
            if consumer is not None:
                finished = booster.update()
                if gbdt._eval_consumer is None and consumer.stop is None:
                    # defensive fallback (see GBDT.train_one_iter):
                    # resume classic inline evaluation from here on
                    consumer = None
                    continue
                if consumer.stop is not None:
                    booster.best_iteration = consumer.stop[0] + 1
                    evaluation_result_list = consumer.stop[1]
                    break
                evaluation_result_list = list(consumer.last_eval)
                if finished:
                    break
                continue
            for cb in callbacks_before:
                cb(callback_mod.CallbackEnv(
                    model=booster, params=params, iteration=i,
                    begin_iteration=0, end_iteration=num_boost_round,
                    evaluation_result_list=None))
            finished = booster.update(fobj=fobj)
            if snapshot_freq > 0 and (i + 1) % snapshot_freq == 0:
                # periodic checkpoint (ref: gbdt.cpp:279-283
                # SaveModelToFile snapshot_out); the text model is the
                # checkpoint format — snapshots are resume checkpoints:
                # keep the full model
                booster.save_model(
                    f"{snapshot_base}.snapshot_iter_{i + 1}",
                    num_iteration=-1)

            evaluation_result_list = []
            if valid_sets is not None or feval is not None:
                if train_in_valid or (feval is not None
                                      and booster._gbdt.training_metrics):
                    evaluation_result_list.extend(
                        booster.eval_train(feval))
                evaluation_result_list.extend(booster.eval_valid(feval))
            try:
                for cb in callbacks_after:
                    cb(callback_mod.CallbackEnv(
                        model=booster, params=params, iteration=i,
                        begin_iteration=0, end_iteration=num_boost_round,
                        evaluation_result_list=evaluation_result_list))
            except callback_mod.EarlyStopException as es:
                booster.best_iteration = es.best_iteration + 1
                evaluation_result_list = es.best_score
                break
            # sync-driver checkpoint cadence: the iteration is fully
            # settled here (update + snapshot + eval + callbacks), so
            # the captured callback state matches the captured model
            gbdt.maybe_checkpoint()
            if finished:
                break
        except callback_mod.EarlyStopException:
            raise   # control flow, not a crash
        except BaseException as exc:
            # crash flight recorder: anything unwinding out of the train
            # loop — the update itself, a callback, eval, or a snapshot
            # write — lands the ring buffer + section stack + config in
            # <telemetry_out>.crash.json before reaching the caller.
            # BaseException, not Exception: Ctrl-C on a wedged run is
            # the flight recorder's primary "where was it stuck" case
            booster._dump_crash(exc)
            raise
    finally:
        # a kept booster must return to the one-iteration-per-update
        # contract once this loop stops consuming multi-iteration steps
        # (disarming with a consumer bound drains + replays the tail
        # first, so no queued metric rows are dropped)
        booster._gbdt.arm_megastep(False)
        booster._gbdt.set_checkpoint_extra(None)

    if consumer is not None:
        # the tail drain above may have replayed the final iterations —
        # pick up a late early-stop verdict or the last eval list
        if consumer.stop is not None and booster.best_iteration <= 0:
            booster.best_iteration = consumer.stop[0] + 1
            evaluation_result_list = consumer.stop[1]
        elif consumer.last_eval and not evaluation_result_list:
            evaluation_result_list = list(consumer.last_eval)

    booster.best_score = collections.defaultdict(collections.OrderedDict)
    for name, metric, value, _ in (evaluation_result_list or []):
        booster.best_score[name][metric] = value
    # observability epilogue: stop an open profiler trace, write the
    # telemetry summary + flush the JSONL sink, then let callbacks with a
    # finalize hook (record_telemetry) drain the completed records
    booster._finalize_telemetry()
    for cb in callbacks_before + callbacks_after:
        fin = getattr(cb, "finalize", None)
        if fin is not None:
            fin(callback_mod.CallbackEnv(
                model=booster, params=params, iteration=i,
                begin_iteration=0, end_iteration=num_boost_round,
                evaluation_result_list=evaluation_result_list))
    return booster


class CVBooster:
    """Container of per-fold boosters (ref: engine.py:285)."""

    def __init__(self):
        self.boosters: List[Booster] = []
        self.best_iteration = -1

    def _append(self, booster: Booster) -> None:
        self.boosters.append(booster)

    def __getattr__(self, name):
        def handler_function(*args, **kwargs):
            return [getattr(b, name)(*args, **kwargs) for b in self.boosters]
        return handler_function


def _make_n_folds(full_data: Dataset, folds, nfold: int, params: Dict,
                  seed: int, stratified: bool, shuffle: bool):
    """(ref: engine.py:323)"""
    full_data = full_data.construct()
    num_data = full_data.num_data()
    if folds is not None:
        if not hasattr(folds, "__iter__") and not hasattr(folds, "split"):
            raise AttributeError(
                "folds should be a generator or iterator of (train_idx, "
                "test_idx) tuples or scikit-learn splitter object")
        if hasattr(folds, "split"):
            group_info = full_data.get_field("group")
            if group_info is not None:
                group_sizes = np.diff(group_info)
                flattened = np.repeat(np.arange(len(group_sizes)),
                                      group_sizes)
            else:
                flattened = None
            folds = folds.split(X=np.empty(num_data), y=full_data.get_label(),
                                groups=flattened)
        return list(folds)
    rng = np.random.RandomState(seed)
    if stratified:
        label = np.asarray(full_data.get_label())
        classes = np.unique(label)
        test_folds = np.zeros(num_data, np.int32)
        for c in classes:
            idx = np.nonzero(label == c)[0]
            if shuffle:
                rng.shuffle(idx)
            test_folds[idx] = np.arange(len(idx)) % nfold
        return [(np.nonzero(test_folds != f)[0], np.nonzero(test_folds == f)[0])
                for f in range(nfold)]
    group_info = full_data.get_field("group")
    if group_info is not None:
        # fold by whole queries (ref: engine.py group-aware kfold)
        num_groups = len(group_info) - 1
        gidx = np.arange(num_groups)
        if shuffle:
            rng.shuffle(gidx)
        splits = np.array_split(gidx, nfold)
        boundaries = np.asarray(group_info)
        out = []
        for f in range(nfold):
            test_groups = set(splits[f].tolist())
            test_mask = np.zeros(num_data, bool)
            for g in test_groups:
                test_mask[boundaries[g]:boundaries[g + 1]] = True
            out.append((np.nonzero(~test_mask)[0], np.nonzero(test_mask)[0]))
        return out
    idx = np.arange(num_data)
    if shuffle:
        rng.shuffle(idx)
    splits = np.array_split(idx, nfold)
    return [(np.concatenate([splits[j] for j in range(nfold) if j != f]),
             splits[f]) for f in range(nfold)]


def cv(params: Dict[str, Any], train_set: Dataset, num_boost_round: int = 100,
       folds=None, nfold: int = 5, stratified: bool = True,
       shuffle: bool = True, metrics=None, feval=None, init_model=None,
       feature_name="auto", categorical_feature="auto",
       fpreproc=None, seed: int = 0, callbacks=None,
       eval_train_metric: bool = False,
       return_cvbooster: bool = False) -> Dict[str, List[float]]:
    """Cross-validation (ref: engine.py:399)."""
    params = dict(params) if params else {}
    for alias in _ROUND_ALIASES:
        if alias in params:
            num_boost_round = int(params.pop(alias))
    if metrics is not None:
        params["metric"] = metrics
    obj = str(params.get("objective", "regression"))
    if stratified and not obj.startswith(("binary", "multiclass")):
        stratified = False

    train_set.construct()
    fold_splits = _make_n_folds(train_set, folds, nfold, params, seed,
                                stratified, shuffle)
    cvbooster = CVBooster()
    fold_data = []
    for train_idx, test_idx in fold_splits:
        tr = train_set.subset(train_idx)
        te = train_set.subset(test_idx, )
        if fpreproc is not None:
            tr, te, params = fpreproc(tr, te, dict(params))
        booster = Booster(params=dict(params), train_set=tr)
        booster.add_valid(te, "valid")
        if eval_train_metric:
            booster._gbdt.training_metrics = booster._make_metrics(tr._inner)
        cvbooster._append(booster)
        fold_data.append((tr, te))

    callbacks = list(callbacks) if callbacks else []
    es_round = None
    for alias in _ES_ALIASES:
        if alias in params:
            es_round = int(params[alias])
    if es_round is not None and es_round > 0:
        callbacks.append(callback_mod.early_stopping(
            es_round, bool(params.get("first_metric_only", False)),
            verbose=False))
    callbacks_before = [cb for cb in callbacks
                        if getattr(cb, "before_iteration", False)]
    callbacks_after = [cb for cb in callbacks
                       if not getattr(cb, "before_iteration", False)]

    results = collections.defaultdict(list)
    for i in range(num_boost_round):
        for cb in callbacks_before:
            cb(callback_mod.CallbackEnv(
                model=cvbooster, params=params, iteration=i,
                begin_iteration=0, end_iteration=num_boost_round,
                evaluation_result_list=None))
        agg: Dict[str, List[float]] = collections.defaultdict(list)
        bigger: Dict[str, bool] = {}
        for booster in cvbooster.boosters:
            booster.update()
            for name, metric, value, hb in (booster.eval_train(feval)
                                            if eval_train_metric else []) \
                    + booster.eval_valid(feval):
                agg[f"{name} {metric}"].append(value)
                bigger[f"{name} {metric}"] = hb
        res_list = []
        for key, vals in agg.items():
            mean, std = float(np.mean(vals)), float(np.std(vals))
            results[key + "-mean"].append(mean)
            results[key + "-stdv"].append(std)
            res_list.append(("cv_agg", key, mean, bigger[key]))
        try:
            for cb in callbacks_after:
                cb(callback_mod.CallbackEnv(
                    model=cvbooster, params=params, iteration=i,
                    begin_iteration=0, end_iteration=num_boost_round,
                    evaluation_result_list=res_list))
        except callback_mod.EarlyStopException as es:
            cvbooster.best_iteration = es.best_iteration + 1
            for key in list(results):
                results[key] = results[key][:cvbooster.best_iteration]
            break
    out = dict(results)
    if return_cvbooster:
        out["cvbooster"] = cvbooster
    return out
