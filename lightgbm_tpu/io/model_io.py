"""Model text / JSON serialization in the LightGBM format.

Behavioral analog of ref: src/boosting/gbdt_model_text.cpp (SaveModelToString
:311, LoadModelFromString :421, DumpModel).  The text format is kept
compatible with the reference so models interoperate: a model saved here loads
in stock LightGBM and vice versa (numerical splits; categorical bitsets follow
the same cat_boundaries/cat_threshold encoding).
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..models.tree import HostTree
from ..utils import log

MODEL_VERSION = "v3"


def _fmt(x: float) -> str:
    """Shortest round-trip float formatting (the reference uses
    Common::DoubleToStr with %.17g semantics)."""
    return np.format_float_positional(
        x, unique=True, trim="0") if np.isfinite(x) else repr(float(x))


def _fmt_arr(arr, high_precision=False) -> str:
    out = []
    for v in arr:
        if isinstance(v, (int, np.integer)):
            out.append(str(int(v)))
        elif high_precision:
            out.append(f"{float(v):.17g}")
        else:
            out.append(f"{float(v):g}")
    return " ".join(out)


def tree_to_string(tree: HostTree) -> str:
    """(ref: src/io/tree.cpp:336 Tree::ToString)"""
    nl = tree.num_leaves
    ni = max(0, nl - 1)
    num_cat = len(tree.cat_boundaries) - 1 if tree.cat_threshold else 0
    lines = [
        f"num_leaves={nl}",
        f"num_cat={num_cat}",
        "split_feature=" + _fmt_arr(tree.split_feature[:ni]),
        "split_gain=" + _fmt_arr(tree.split_gain[:ni]),
        "threshold=" + _fmt_arr(tree.threshold[:ni], high_precision=True),
        "decision_type=" + _fmt_arr(tree.decision_type[:ni]),
        "left_child=" + _fmt_arr(tree.left_child[:ni]),
        "right_child=" + _fmt_arr(tree.right_child[:ni]),
        "leaf_value=" + _fmt_arr(tree.leaf_value[:nl], high_precision=True),
        "leaf_weight=" + _fmt_arr(tree.leaf_weight[:nl],
                                  high_precision=True),
        "leaf_count=" + _fmt_arr(tree.leaf_count[:nl]),
        "internal_value=" + _fmt_arr(tree.internal_value[:ni]),
        "internal_weight=" + _fmt_arr(tree.internal_weight[:ni]),
        "internal_count=" + _fmt_arr(tree.internal_count[:ni]),
    ]
    if num_cat > 0:
        lines.append("cat_boundaries=" + _fmt_arr(tree.cat_boundaries))
        lines.append("cat_threshold=" + _fmt_arr(tree.cat_threshold))
    lines.append(f"is_linear={1 if tree.is_linear else 0}")
    if tree.is_linear:
        # ref: tree.cpp ToString is_linear block
        lines.append("leaf_const=" + _fmt_arr(tree.leaf_const,
                                              high_precision=True))
        nf = [len(c) for c in tree.leaf_coeff]
        lines.append("num_features=" + _fmt_arr(nf))
        flat_f = [f for fs in tree.leaf_features for f in fs]
        flat_c = [c for cs in tree.leaf_coeff for c in cs]
        lines.append("leaf_features=" + _fmt_arr(flat_f))
        lines.append("leaf_coeff=" + _fmt_arr(flat_c, high_precision=True))
    lines.append(f"shrinkage={tree.shrinkage:g}")
    return "\n".join(lines) + "\n"


def tree_from_block(kv: Dict[str, str]) -> HostTree:
    """(ref: src/io/tree.cpp Tree::Tree(const char*, size_t*))"""
    nl = int(kv["num_leaves"])
    tree = HostTree(nl, shrinkage=float(kv.get("shrinkage", 1.0)))
    ni = max(0, nl - 1)

    def arr(key, dtype, n):
        if n == 0 or key not in kv or not kv[key].strip():
            return np.zeros(n, dtype)
        return np.asarray(kv[key].split(), dtype=dtype)

    tree.split_feature = arr("split_feature", np.int32, ni)
    tree.split_gain = arr("split_gain", np.float64, ni)
    tree.threshold = arr("threshold", np.float64, ni)
    tree.decision_type = arr("decision_type", np.int32, ni)
    tree.left_child = arr("left_child", np.int32, ni)
    tree.right_child = arr("right_child", np.int32, ni)
    tree.leaf_value = arr("leaf_value", np.float64, nl)
    tree.leaf_weight = arr("leaf_weight", np.float64, nl)
    tree.leaf_count = arr("leaf_count", np.int64, nl)
    tree.internal_value = arr("internal_value", np.float64, ni)
    tree.internal_weight = arr("internal_weight", np.float64, ni)
    tree.internal_count = arr("internal_count", np.int64, ni)
    num_cat = int(kv.get("num_cat", 0))
    if num_cat > 0:
        tree.cat_boundaries = [int(x) for x in kv["cat_boundaries"].split()]
        tree.cat_threshold = [int(x) for x in kv["cat_threshold"].split()]
    tree.is_linear = bool(int(kv.get("is_linear", 0)))
    if tree.is_linear:
        import numpy as _np
        tree.leaf_const = _np.array(
            [float(x) for x in kv.get("leaf_const", "").split()] or
            [0.0] * nl, _np.float64)
        nf = [int(x) for x in kv.get("num_features", "").split()] or \
            [0] * nl
        flat_f = [int(x) for x in kv.get("leaf_features", "").split()]
        flat_c = [float(x) for x in kv.get("leaf_coeff", "").split()]
        tree.leaf_features, tree.leaf_coeff = [], []
        pos = 0
        for n in nf:
            tree.leaf_features.append(flat_f[pos:pos + n])
            tree.leaf_coeff.append(flat_c[pos:pos + n])
            pos += n
    return tree


def feature_importance(models: List[HostTree], num_features: int,
                       importance_type: int = 0) -> np.ndarray:
    """(ref: gbdt.cpp FeatureImportance — 0=split count, 1=total gain)"""
    imp = np.zeros(num_features, np.float64)
    for t in models:
        ni = max(0, t.num_leaves - 1)
        for i in range(ni):
            if t.split_gain[i] <= 0:
                continue
            f = int(t.split_feature[i])
            if importance_type == 0:
                imp[f] += 1.0
            else:
                imp[f] += t.split_gain[i]
    return imp


def save_model_to_string(booster, start_iteration: int = 0,
                         num_iteration: int = -1,
                         importance_type: int = 0) -> str:
    """(ref: gbdt_model_text.cpp:311 SaveModelToString).

    ``booster`` duck-types: models, num_tree_per_iteration, objective,
    feature_names, feature_infos, max_feature_idx, num_class,
    average_output, config (optional).
    """
    ss = ["tree", f"version={MODEL_VERSION}",
          f"num_class={booster.num_class}",
          f"num_tree_per_iteration={booster.num_tree_per_iteration}",
          f"label_index={getattr(booster, 'label_index', 0)}",
          f"max_feature_idx={booster.max_feature_idx}"]
    if booster.objective is not None:
        ss.append(f"objective={booster.objective.to_string()}")
    if getattr(booster, "average_output", False):
        ss.append("average_output")
    ss.append("feature_names=" + " ".join(booster.feature_names))
    if getattr(booster, "monotone_constraints", None) is not None:
        ss.append("monotone_constraints="
                  + " ".join(str(int(m))
                             for m in booster.monotone_constraints))
    ss.append("feature_infos=" + " ".join(booster.feature_infos))

    models = booster.models
    k = booster.num_tree_per_iteration
    total_iteration = len(models) // k
    start_iteration = min(max(start_iteration, 0), total_iteration)
    num_used_model = len(models)
    if num_iteration > 0:
        num_used_model = min((start_iteration + num_iteration) * k,
                             num_used_model)
    start_model = start_iteration * k

    tree_strs = []
    for i in range(start_model, num_used_model):
        s = f"Tree={i - start_model}\n" + tree_to_string(models[i]) + "\n"
        tree_strs.append(s)
    ss.append("tree_sizes=" + " ".join(str(len(s)) for s in tree_strs))
    ss.append("")
    body = "\n".join(ss) + "\n" + "".join(tree_strs) + "end of trees\n"

    imp = feature_importance(models[start_model:num_used_model],
                             booster.max_feature_idx + 1, importance_type)
    pairs = sorted([(int(imp[i]), booster.feature_names[i])
                    for i in range(len(imp)) if imp[i] > 0],
                   key=lambda p: -p[0])
    body += "\nfeature_importances:\n"
    for cnt, name in pairs:
        body += f"{name}={cnt}\n"
    if getattr(booster, "loaded_parameter", ""):
        body += "\nparameters:\n" + booster.loaded_parameter \
                + "\nend of parameters\n"
    elif getattr(booster, "config", None) is not None:
        body += "\nparameters:\n"
        for kk, vv in booster.config.to_dict().items():
            if kk in _INGEST_TRANSPORT_KEYS:
                # data-loading transport knobs (chunked ingest, binary
                # cache maintenance) select HOW the shard reached the
                # device, never what was learned: the streamed/cached
                # paths' bit-identical-serialization contract
                # (docs/Data.md) requires they not echo, like `resume`
                continue
            if isinstance(vv, list):
                vv = ",".join(str(x) for x in vv)
            body += f"[{kk}: {vv}]\n"
        body += "end of parameters\n"
    # drift/lineage plane (obs/drift.py): the training DataProfile and
    # the provenance record ride the artifact as trailing blocks AFTER
    # "end of parameters" — the header loop stops at the first Tree=
    # and the parameter extraction uses explicit start/end markers, so
    # stock-LightGBM interoperability and the existing parser are both
    # untouched. canonical_json makes the round trip byte-stable:
    # saving a loaded model re-emits the identical block.
    profile = getattr(booster, "data_profile", None)
    if profile:
        from ..obs.drift import canonical_json
        body += "\ndata_profile:\n" + canonical_json(profile) \
                + "\nend of data_profile\n"
    prov = getattr(booster, "provenance", None)
    if prov:
        from ..obs.drift import canonical_json
        # parent_checkpoint is RUN metadata, not model identity: a
        # resumed run must serialize byte-identically to the straight
        # run it resumes (the resume-identity contract), so the chained
        # checkpoint hash stays in-memory and in checkpoint manifests
        # but out of the artifact
        prov = dict(prov, parent_checkpoint="")
        body += "\nprovenance:\n" + canonical_json(prov) \
                + "\nend of provenance\n"
    return body


# scrubbed from the serialized parameters block — see above
_INGEST_TRANSPORT_KEYS = frozenset(
    ("two_round", "ingest_chunk_rows", "ingest_prefetch", "save_binary"))


def parse_model_string(model_str: str) -> Tuple[Dict[str, str],
                                                List[HostTree], str]:
    """Parse the text format into (header key/values, trees, parameter blob)
    (ref: gbdt_model_text.cpp:421 LoadModelFromString)."""
    header: Dict[str, str] = {}
    lines = model_str.split("\n")
    i = 0
    # header until first Tree= or tree_sizes consumed
    while i < len(lines):
        line = lines[i].strip()
        if line.startswith("Tree="):
            break
        if line == "end of trees":
            break
        if "=" in line:
            key, v = line.split("=", 1)
            header[key.strip()] = v.strip()
        elif line == "average_output":
            header["average_output"] = "1"
        i += 1

    trees: List[HostTree] = []
    cur: Optional[Dict[str, str]] = None
    while i < len(lines):
        line = lines[i].strip()
        if line.startswith("Tree="):
            if cur is not None:
                trees.append(tree_from_block(cur))
            cur = {}
        elif line == "end of trees":
            if cur is not None:
                trees.append(tree_from_block(cur))
                cur = None
            break
        elif "=" in line and cur is not None:
            key, v = line.split("=", 1)
            cur[key.strip()] = v.strip()
        i += 1

    # parameters blob
    params = ""
    if "\nparameters:" in model_str:
        start = model_str.index("\nparameters:") + len("\nparameters:\n")
        end = model_str.find("\nend of parameters", start)
        if end > 0:
            params = model_str[start:end]
    return header, trees, params


def _extract_json_block(model_str: str, name: str) -> Optional[dict]:
    """Parse one trailing ``<name>:`` ... ``end of <name>`` JSON block
    (the drift/lineage plane's artifact channel).  Absent or corrupt
    blocks return ``None`` — a model file without a profile must load
    exactly as before, never raise."""
    marker = f"\n{name}:\n"
    if marker not in model_str:
        return None
    start = model_str.index(marker) + len(marker)
    end = model_str.find(f"\nend of {name}", start)
    if end < 0:
        return None
    try:
        blob = json.loads(model_str[start:end])
    except (json.JSONDecodeError, ValueError):
        return None
    return blob if isinstance(blob, dict) else None


def extract_data_profile(model_str: str) -> Optional[dict]:
    """The embedded training DataProfile, or ``None`` (back-compat with
    every pre-profile artifact)."""
    return _extract_json_block(model_str, "data_profile")


def extract_provenance(model_str: str) -> Optional[dict]:
    """The embedded provenance/lineage record, or ``None``."""
    return _extract_json_block(model_str, "provenance")


def dump_model_json(booster, start_iteration: int = 0,
                    num_iteration: int = -1,
                    importance_type: int = 0) -> str:
    """JSON dump (ref: gbdt_model_text.cpp DumpModel)."""
    models = booster.models
    k = booster.num_tree_per_iteration
    num_used = len(models)
    if num_iteration > 0:
        num_used = min((start_iteration + num_iteration) * k, num_used)

    def node_json(tree: HostTree, node: int):
        if node < 0:
            leaf = ~node
            return {
                "leaf_index": int(leaf),
                "leaf_value": float(tree.leaf_value[leaf]),
                "leaf_weight": float(tree.leaf_weight[leaf])
                if len(tree.leaf_weight) > leaf else 0.0,
                "leaf_count": int(tree.leaf_count[leaf])
                if len(tree.leaf_count) > leaf else 0,
            }
        d = int(tree.decision_type[node])
        cat = bool(d & 1)
        return {
            "split_index": int(node),
            "split_feature": int(tree.split_feature[node]),
            "split_gain": float(tree.split_gain[node]),
            "threshold": float(tree.threshold[node]),
            "decision_type": "==" if cat else "<=",
            "default_left": bool(d & 2),
            "missing_type": ["None", "Zero", "NaN"][(d >> 2) & 3],
            "internal_value": float(tree.internal_value[node]),
            "internal_weight": float(tree.internal_weight[node]),
            "internal_count": int(tree.internal_count[node]),
            "left_child": node_json(tree, int(tree.left_child[node])),
            "right_child": node_json(tree, int(tree.right_child[node])),
        }

    tree_infos = []
    for i in range(start_iteration * k, num_used):
        t = models[i]
        tree_infos.append({
            "tree_index": i,
            "num_leaves": t.num_leaves,
            "num_cat": len(t.cat_boundaries) - 1 if t.cat_threshold else 0,
            "shrinkage": t.shrinkage,
            "tree_structure": node_json(t, 0 if t.num_leaves > 1 else -1),
        })
    out = {
        "name": "tree",
        "version": MODEL_VERSION,
        "num_class": booster.num_class,
        "num_tree_per_iteration": booster.num_tree_per_iteration,
        "label_index": getattr(booster, "label_index", 0),
        "max_feature_idx": booster.max_feature_idx,
        "objective": (booster.objective.to_string()
                      if booster.objective is not None else "none"),
        "average_output": bool(getattr(booster, "average_output", False)),
        "feature_names": booster.feature_names,
        "monotone_constraints": [],
        "feature_infos": {},
        "tree_info": tree_infos,
    }
    # nonzero importances keyed by feature name; the int truncation and
    # the >0 drop are the REFERENCE's own behavior (gbdt_model_text.cpp
    # :105-107 static_cast<size_t> + `if (feature_importances_int > 0)`)
    imp = feature_importance(models[start_iteration * k:num_used],
                             booster.max_feature_idx + 1, importance_type)
    names = booster.feature_names or [
        f"Column_{i}" for i in range(booster.max_feature_idx + 1)]
    out["feature_importances"] = {
        names[i]: int(v) for i, v in enumerate(imp) if int(v) > 0}
    return json.dumps(out, indent=2)


# ---------------------------------------------------------------------
# if-else C code generation (ref: src/io/tree.cpp:562 Tree::ToIfElse +
# application.cpp task=convert_model): a standalone C++ translation unit
# with one PredictTree function per tree, PredictRaw summing them, and
# Predict applying the objective's output transform.
def _tree_to_if_else(ht, idx: int) -> str:
    """One tree as ``double PredictTree<idx>(const double* arr)``."""
    lines = []
    cat_words = []

    def cat_bitset(nd):
        ci = int(ht.threshold[nd])
        lo, hi = ht.cat_boundaries[ci], ht.cat_boundaries[ci + 1]
        words = [int(w) for w in ht.cat_threshold[lo:hi]]
        off = len(cat_words)
        cat_words.extend(words)
        return off, len(words)

    def emit(node, ind):
        pad = "  " * ind
        if node < 0:
            lines.append(f"{pad}return {float(ht.leaf_value[~node])!r};")
            return
        f = int(ht.split_feature[node])
        d = int(ht.decision_type[node])
        cat, dl, mt = bool(d & 1), bool(d & 2), (d >> 2) & 3
        v = f"arr[{f}]"
        if cat:
            off, nw = cat_bitset(node)
            # unseen/NaN categories go RIGHT (ref: tree.h
            # CategoricalDecision)
            cond = (f"(!std::isnan({v}) && (int){v} >= 0 && "
                    f"(int){v} < {nw * 32} && "
                    f"((CatBitset{idx}[{off} + ((int){v} / 32)] >> "
                    f"((int){v} % 32)) & 1))")
        else:
            thr = repr(float(ht.threshold[node]))
            if mt == 2:      # NaN-missing rides default_left
                miss = f"std::isnan({v})"
                val = v
            elif mt == 1:    # zero (and NaN-as-zero) rides default_left
                miss = (f"(std::isnan({v}) || std::fabs({v}) <= "
                        f"kZeroThreshold)")
                val = v
            else:            # none: NaN is treated as 0.0
                miss = "false"
                val = f"(std::isnan({v}) ? 0.0 : {v})"
            branch = f"{val} <= {thr}"
            cond = (f"({miss} ? {str(dl).lower()} : ({branch}))"
                    if mt else f"({branch})")
        lines.append(f'{"  " * ind}if ({cond}) {{')
        emit(int(ht.left_child[node]), ind + 1)
        lines.append(f'{"  " * ind}}} else {{')
        emit(int(ht.right_child[node]), ind + 1)
        lines.append(f'{"  " * ind}}}')

    if ht.num_leaves <= 1:
        body = f"  return {float(ht.leaf_value[0])!r};"
        return (f"double PredictTree{idx}(const double* arr) {{\n"
                f"{body}\n}}\n")
    emit(0, 1)
    out = ""
    if cat_words:
        words = ", ".join(f"{w}u" for w in cat_words)
        out += (f"static const uint32_t CatBitset{idx}[] = "
                f"{{{words}}};\n")
    out += (f"double PredictTree{idx}(const double* arr) {{\n"
            + "\n".join(lines) + "\n}\n")
    return out


def model_to_if_else(booster) -> str:
    """Full model as compilable C++ (ref: gbdt_model_text.cpp SaveModelToIfElse
    — the convert_model task's output). ``Predict`` fills
    ``num_tree_per_iteration`` outputs per row; sigmoid/exp transforms
    follow the model's objective."""
    models = booster.models
    k = max(1, booster.num_tree_per_iteration)
    obj = getattr(booster, "objective", None)
    obj_name = getattr(obj, "name", "") if obj is not None else ""
    parts = [
        "// generated by lightgbm_tpu convert_model "
        "(ref: src/io/tree.cpp:562 ToIfElse)",
        "#include <cmath>",
        "#include <cstdint>",
        "static const double kZeroThreshold = 1e-35;",
        "",
    ]
    for i, ht in enumerate(models):
        parts.append(_tree_to_if_else(ht, i))
    per_class = [[] for _ in range(k)]
    for i in range(len(models)):
        per_class[i % k].append(i)
    sums = []
    for c, idxs in enumerate(per_class):
        terms = " + ".join(f"PredictTree{i}(arr)" for i in idxs) or "0.0"
        sums.append(f"  out[{c}] = {terms};")
    parts.append("void PredictRaw(const double* arr, double* out) {\n"
                 + "\n".join(sums) + "\n}\n")
    if obj_name == "binary":
        sig = getattr(obj, "sigmoid", 1.0)
        conv = (f"  out[0] = 1.0 / (1.0 + std::exp(-{float(sig)!r} "
                f"* out[0]));")
    elif obj_name in ("poisson", "gamma", "tweedie",
                      "cross_entropy_lambda"):
        conv = "\n".join(f"  out[{c}] = std::exp(out[{c}]);"
                         for c in range(k))
    elif obj_name == "multiclass":
        conv = ("  double m = out[0], s = 0.0;\n"
                + "".join(f"  if (out[{c}] > m) m = out[{c}];\n"
                          for c in range(k))
                + "".join(f"  out[{c}] = std::exp(out[{c}] - m); "
                          f"s += out[{c}];\n" for c in range(k))
                + "".join(f"  out[{c}] /= s;\n" for c in range(k)))
    else:
        conv = "  // identity output transform"
    parts.append("void Predict(const double* arr, double* out) {\n"
                 "  PredictRaw(arr, out);\n" + conv + "\n}\n")
    return "\n".join(parts)
