"""File-based dataset ingestion.

Behavioral analog of the reference DatasetLoader text pipeline (ref:
src/io/dataset_loader.cpp:203 LoadFromFile, parser.cpp format
auto-detection): CSV/TSV/LibSVM auto-detected, a label column extracted
(``label_column`` param: index, ``name:<col>``, or LibSVM's implicit first
column), and the reference's sidecar conventions honored (``<file>.weight``
one weight per row, ``<file>.query``/``.group`` query sizes,
``<file>.init`` init scores — ref: src/io/metadata.cpp loaders).

Distributed loading (ref: dataset_loader.cpp:1015 rank partitioning) maps
to ``rank``/``num_machines``: each host parses only its contiguous row
slice; bin mappers must then be built from a shared sample or a reference
dataset so shards agree (TpuDataset(reference=...)).

Parsing is bounded: whole-file loads go through the native parser's
streaming line reader (or the preallocated numpy fallback — no per-line
Python list accumulation), and rank-sharded multi-process loads parse
ONLY the rank's row slice via the resumable chunk iterator
(ingest/chunker.py) instead of materializing the full file on every
rank.  The fully streaming O(chunk)-RSS path is ingest/pipeline.py;
this module remains the monolithic "give me the shard as one array"
surface.
"""
from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

from ..native import loader as native
from ..utils import log


def _label_spec(label_column, header_names):
    """-> column index or None (ref: config.h label_column semantics)."""
    if label_column in (None, ""):
        return 0
    if isinstance(label_column, int):
        return label_column
    s = str(label_column)
    if s.startswith("name:"):
        name = s[5:]
        if header_names and name in header_names:
            return header_names.index(name)
        raise ValueError(f"label column name '{name}' not in header")
    return int(s)


def query_sidecar_path(path: str) -> Optional[str]:
    return next((path + sfx for sfx in (".query", ".group")
                 if os.path.exists(path + sfx)), None)


# last-parsed query sidecar, keyed by (path, mtime_ns, size): the rank
# slice computation AND the sidecar loader both need the sizes, and a
# ranking file can carry millions of queries — parse once per file state
_QUERY_SIZES_CACHE: dict = {}


def _query_sizes(path: str) -> np.ndarray:
    st = os.stat(path)
    key = (st.st_mtime_ns, st.st_size)
    cached = _QUERY_SIZES_CACHE.get(path)
    if cached is not None and cached[0] == key:
        return cached[1]
    vals = np.loadtxt(path, dtype=np.float64, ndmin=1)
    _QUERY_SIZES_CACHE.clear()      # keep exactly one entry live
    _QUERY_SIZES_CACHE[path] = (key, vals)
    return vals


def compute_rank_slice(path: str, n_rows: int, rank: int,
                       num_machines: int) -> slice:
    """This rank's contiguous row slice of an ``n_rows``-row file
    (reference pre_partition-style).  Ranking data: slice boundaries
    ALIGN to query boundaries so every rank holds whole queries (ref:
    metadata.cpp:141 CheckOrPartition — "Data partition error, data
    didn't match queries" is a hard error there; here the partition is
    computed query-aligned up front).  Shared by the monolithic loader
    and the streaming ingest pipeline so both shard identically."""
    if num_machines <= 1:
        return slice(0, n_rows)
    qside = query_sidecar_path(path)
    if qside is not None:
        sizes = _query_sizes(qside).astype(np.int64)
        ends = np.cumsum(sizes)
        if int(ends[-1]) != n_rows:
            raise ValueError(
                f"query sizes sum to {int(ends[-1])} but the file has "
                f"{n_rows} rows")
        cuts = [0]
        for r in range(1, num_machines):
            target = (r * n_rows) // num_machines
            qi = int(np.searchsorted(ends, target, side="left"))
            cuts.append(int(ends[min(qi, len(ends) - 1)]))
        cuts.append(n_rows)
        return slice(cuts[rank], cuts[rank + 1])
    per = (n_rows + num_machines - 1) // num_machines
    # clamp BOTH bounds: with more machines than rows the ceil division
    # overshoots and an unclamped start would make the slice length
    # negative (the downstream np.empty allocations need >= 0; the
    # overflow ranks legitimately hold an empty shard)
    return slice(min(n_rows, rank * per), min(n_rows, (rank + 1) * per))


def load_sidecars(path: str, sl: slice, rank: int,
                  num_machines: int) -> dict:
    """Load ``<file>.weight``/``.query``/``.group``/``.init`` sidecars
    sliced to this rank's rows (ref: src/io/metadata.cpp loaders +
    CheckOrPartition group sharding)."""
    side = {}
    for suffix, key in ((".weight", "weight"), (".query", "group"),
                        (".group", "group"), (".init", "init_score")):
        sp = path + suffix
        if not os.path.exists(sp):
            continue
        vals = (_query_sizes(sp) if key == "group"
                else np.loadtxt(sp, dtype=np.float64, ndmin=1))
        if key == "group":
            if num_machines > 1:
                # shard whole queries: keep those whose rows fall in
                # this rank's slice (ref: metadata.cpp CheckOrPartition)
                ends = np.cumsum(vals.astype(np.int64))
                starts = ends - vals.astype(np.int64)
                keep = (starts >= sl.start) & (ends <= sl.stop)
                if not keep.any() or \
                        int(vals[keep].sum()) != sl.stop - sl.start:
                    log.warning(
                        "rank %d row slice cuts through query "
                        "boundaries; group sizes clipped to the slice",
                        rank)
                    clipped = (np.minimum(ends, sl.stop)
                               - np.maximum(starts, sl.start))
                    side[key] = clipped[clipped > 0]
                else:
                    side[key] = vals[keep].astype(np.int64)
            else:
                side[key] = vals.astype(np.int64)
        else:
            side[key] = vals[sl]
        log.info("Loaded %s from %s", key, sp)
    return side


def split_label_column(data: np.ndarray, li: Optional[int],
                       n_cols: int, path: str):
    """Extract the label column from parsed dense rows -> (X, y)."""
    if li is None or li < 0:
        return data, None        # label_column < 0: no label column
    if li >= n_cols:
        raise ValueError(
            f"label_column={li} out of range for {n_cols}-column file "
            f"{path}")
    y = data[:, li].copy()
    X = np.delete(data, li, axis=1)
    return X, y


def load_text_file(path: str, label_column=None, rank: int = 0,
                   num_machines: int = 1, force_header: bool = None
                   ) -> Tuple[np.ndarray, Optional[np.ndarray], dict]:
    """Parse a CSV/TSV/LibSVM file -> (X, label, sidecars).

    sidecars: {"weight": arr?, "group": arr?, "init_score": arr?}
    ``force_header`` overrides the auto-detection (the reference's
    ``has_header`` flag — an all-numeric header line would otherwise be
    misread as a data row).
    """
    from ..ingest.chunker import iter_chunks, scan_layout
    layout = scan_layout(path, force_header=force_header)
    n_rows, n_cols = layout.n_rows, layout.n_cols
    if n_rows == 0:
        raise ValueError(f"no data rows in {path}")
    sl = compute_rank_slice(path, n_rows, rank, num_machines)

    if num_machines > 1:
        # rank-sharded load: parse ONLY this rank's slice via the
        # resumable chunk iterator (same native field parser) — a rank
        # never materializes the rows it is about to throw away
        n_local = sl.stop - sl.start
        if layout.is_libsvm:
            X = np.empty((n_local, n_cols - 1), np.float32)
            y = np.empty((n_local,), np.float32)
            for row0, Xc, yc in iter_chunks(layout, 1 << 18,
                                            sl.start, sl.stop):
                X[row0:row0 + len(Xc)] = Xc
                y[row0:row0 + len(Xc)] = yc
        else:
            data = np.empty((n_local, n_cols), np.float32)
            for row0, Xc, _ in iter_chunks(layout, 1 << 18,
                                           sl.start, sl.stop):
                data[row0:row0 + len(Xc)] = Xc
            li = _label_spec(label_column, layout.header_names)
            X, y = split_label_column(data, li, n_cols, path)
    elif layout.is_libsvm:
        X, y = native.parse_libsvm(path, n_rows, n_cols)
    else:
        data = native.parse_dense(path, layout.sep, layout.has_header,
                                  n_rows, n_cols)
        li = _label_spec(label_column, layout.header_names)
        X, y = split_label_column(data, li, n_cols, path)

    side = load_sidecars(path, sl, rank, num_machines)
    return X, y, side
