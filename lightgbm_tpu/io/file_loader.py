"""File-based dataset ingestion.

Behavioral analog of the reference DatasetLoader text pipeline (ref:
src/io/dataset_loader.cpp:203 LoadFromFile, parser.cpp format
auto-detection): CSV/TSV/LibSVM auto-detected, a label column extracted
(``label_column`` param: index, ``name:<col>``, or LibSVM's implicit first
column), and the reference's sidecar conventions honored (``<file>.weight``
one weight per row, ``<file>.query``/``.group`` query sizes,
``<file>.init`` init scores — ref: src/io/metadata.cpp loaders).

Distributed loading (ref: dataset_loader.cpp:1015 rank partitioning) maps
to ``rank``/``num_machines``: each host parses only its contiguous row
slice; bin mappers must then be built from a shared sample or a reference
dataset so shards agree (TpuDataset(reference=...)).
"""
from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

from ..native import loader as native
from ..utils import log


def _label_spec(label_column, header_names):
    """-> column index or None (ref: config.h label_column semantics)."""
    if label_column in (None, ""):
        return 0
    if isinstance(label_column, int):
        return label_column
    s = str(label_column)
    if s.startswith("name:"):
        name = s[5:]
        if header_names and name in header_names:
            return header_names.index(name)
        raise ValueError(f"label column name '{name}' not in header")
    return int(s)


def load_text_file(path: str, label_column=None, rank: int = 0,
                   num_machines: int = 1, force_header: bool = None
                   ) -> Tuple[np.ndarray, Optional[np.ndarray], dict]:
    """Parse a CSV/TSV/LibSVM file -> (X, label, sidecars).

    sidecars: {"weight": arr?, "group": arr?, "init_score": arr?}
    ``force_header`` overrides the auto-detection (the reference's
    ``has_header`` flag — an all-numeric header line would otherwise be
    misread as a data row).
    """
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    sep, n_rows, n_cols, is_libsvm, has_header = native.scan(path)
    if force_header is not None and bool(force_header) != bool(has_header):
        if force_header and not has_header:
            n_rows -= 1   # the scan counted the numeric header as data
        elif has_header and not force_header:
            n_rows += 1
        has_header = bool(force_header)
    if n_rows == 0:
        raise ValueError(f"no data rows in {path}")

    header_names = None
    if has_header:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line and not line.startswith("#"):
                    header_names = [t.strip() for t in line.split(sep)]
                    break

    if is_libsvm:
        X, y = native.parse_libsvm(path, n_rows, n_cols)
    else:
        data = native.parse_dense(path, sep, has_header, n_rows, n_cols)
        li = _label_spec(label_column, header_names)
        if li is None or li < 0:
            X, y = data, None        # label_column < 0: no label column
        elif li >= n_cols:
            raise ValueError(
                f"label_column={li} out of range for {n_cols}-column file "
                f"{path}")
        else:
            y = data[:, li].copy()
            X = np.delete(data, li, axis=1)

    # rank-sharded slice (contiguous, reference pre_partition-style).
    # Ranking data: slice boundaries ALIGN to query boundaries so every
    # rank holds whole queries (ref: metadata.cpp:141 CheckOrPartition —
    # "Data partition error, data didn't match queries" is a hard error
    # there; here the partition is computed query-aligned up front)
    if num_machines > 1:
        qside = next((path + sfx for sfx in (".query", ".group")
                      if os.path.exists(path + sfx)), None)
        if qside is not None:
            sizes = np.loadtxt(qside, dtype=np.float64,
                               ndmin=1).astype(np.int64)
            ends = np.cumsum(sizes)
            if int(ends[-1]) != n_rows:
                raise ValueError(
                    f"query sizes sum to {int(ends[-1])} but the file has "
                    f"{n_rows} rows")
            cuts = [0]
            for r in range(1, num_machines):
                target = (r * n_rows) // num_machines
                qi = int(np.searchsorted(ends, target, side="left"))
                cuts.append(int(ends[min(qi, len(ends) - 1)]))
            cuts.append(n_rows)
            sl = slice(cuts[rank], cuts[rank + 1])
        else:
            per = (n_rows + num_machines - 1) // num_machines
            sl = slice(rank * per, min(n_rows, (rank + 1) * per))
        X = X[sl]
        y = None if y is None else y[sl]
    else:
        sl = slice(0, n_rows)

    side = {}
    for suffix, key in ((".weight", "weight"), (".query", "group"),
                        (".group", "group"), (".init", "init_score")):
        sp = path + suffix
        if os.path.exists(sp):
            vals = np.loadtxt(sp, dtype=np.float64, ndmin=1)
            if key == "group":
                if num_machines > 1:
                    # shard whole queries: keep those whose rows fall in
                    # this rank's slice (ref: metadata.cpp CheckOrPartition)
                    ends = np.cumsum(vals.astype(np.int64))
                    starts = ends - vals.astype(np.int64)
                    keep = (starts >= sl.start) & (ends <= sl.stop)
                    if not keep.any() or                             int(vals[keep].sum()) != sl.stop - sl.start:
                        log.warning(
                            "rank %d row slice cuts through query "
                            "boundaries; group sizes clipped to the slice",
                            rank)
                        clipped = (np.minimum(ends, sl.stop)
                                   - np.maximum(starts, sl.start))
                        side[key] = clipped[clipped > 0]
                    else:
                        side[key] = vals[keep].astype(np.int64)
                else:
                    side[key] = vals.astype(np.int64)
            else:
                side[key] = vals[sl]
            log.info("Loaded %s from %s", key, sp)
    return X, y, side
