"""TreeSHAP feature contributions.

Analog of ref: include/LightGBM/tree.h:437 PredictContrib (PathElement
recursion from the TreeSHAP paper).  Exact polynomial-time algorithm over the
host trees.
"""
from __future__ import annotations

from typing import List

import numpy as np


def _tree_shap_row(tree, x: np.ndarray, phi: np.ndarray) -> None:
    """Exact TreeSHAP for one row of one tree (ref: tree.cpp TreeSHAP)."""
    # unique path entries: (feature_index, zero_fraction, one_fraction, pweight)
    def decision(node: int) -> bool:
        f = int(tree.split_feature[node])
        v = x[f]
        d = int(tree.decision_type[node])
        cat = bool(d & 1)
        dl = bool(d & 2)
        mt = (d >> 2) & 3
        if np.isnan(v):
            if mt == 2:
                return dl
            v = 0.0
        if cat:
            iv = int(v) if v >= 0 else -1
            if iv < 0:
                return False
            cat_idx = int(tree.threshold[node])
            lo = tree.cat_boundaries[cat_idx]
            hi = tree.cat_boundaries[cat_idx + 1]
            word, bit = divmod(iv, 32)
            return (word < hi - lo
                    and (tree.cat_threshold[lo + word] >> bit) & 1 == 1)
        if mt == 1 and abs(v) <= 1e-35:
            return dl
        return v <= tree.threshold[node]

    def node_count(node: int) -> float:
        if node < 0:
            return max(float(tree.leaf_count[~node]), 1.0)
        return max(float(tree.internal_count[node]), 1.0)

    def extend(path, zero_fraction, one_fraction, feature_index):
        # deep-copy rows: sibling recursions must not see our pweight edits
        path = [row[:] for row in path] \
            + [[feature_index, zero_fraction, one_fraction,
                1.0 if len(path) == 0 else 0.0]]
        n = len(path) - 1
        for i in range(n - 1, -1, -1):
            path[i + 1][3] += one_fraction * path[i][3] * (i + 1) / (n + 1)
            path[i][3] = zero_fraction * path[i][3] * (n - i) / (n + 1)
        return path

    def unwind(path, i):
        n = len(path) - 1
        one_fraction = path[i][2]
        zero_fraction = path[i][1]
        next_one_portion = path[n][3]
        out = [row[:] for row in path]
        for j in range(n - 1, -1, -1):
            if one_fraction != 0:
                tmp = out[j][3]
                out[j][3] = next_one_portion * (n + 1) / ((j + 1)
                                                          * one_fraction)
                next_one_portion = tmp - out[j][3] * zero_fraction \
                    * (n - j) / (n + 1)
            else:
                out[j][3] = out[j][3] * (n + 1) / (zero_fraction * (n - j))
        for j in range(i, n):
            out[j][0] = out[j + 1][0]
            out[j][1] = out[j + 1][1]
            out[j][2] = out[j + 1][2]
        return out[:n]

    def unwound_sum(path, i):
        n = len(path) - 1
        one_fraction = path[i][2]
        zero_fraction = path[i][1]
        next_one_portion = path[n][3]
        total = 0.0
        for j in range(n - 1, -1, -1):
            if one_fraction != 0:
                tmp = next_one_portion * (n + 1) / ((j + 1) * one_fraction)
                total += tmp
                next_one_portion = path[j][3] - tmp * zero_fraction \
                    * (n - j) / (n + 1)
            else:
                total += path[j][3] / (zero_fraction * (n - j) / (n + 1))
        return total

    def recurse(node, path, zero_fraction, one_fraction, feature_index):
        path = extend(path, zero_fraction, one_fraction, feature_index)
        if node < 0:
            leaf = ~node
            for i in range(1, len(path)):
                w = unwound_sum(path, i)
                phi[path[i][0]] += w * (path[i][2] - path[i][1]) \
                    * tree.leaf_value[leaf]
            return
        f = int(tree.split_feature[node])
        go_left = decision(node)
        hot = int(tree.left_child[node]) if go_left \
            else int(tree.right_child[node])
        cold = int(tree.right_child[node]) if go_left \
            else int(tree.left_child[node])
        w = node_count(node)
        hot_frac = node_count(hot) / w
        cold_frac = node_count(cold) / w
        incoming_zero = 1.0
        incoming_one = 1.0
        # undo previous split on the same feature
        for i in range(1, len(path)):
            if path[i][0] == f:
                incoming_zero = path[i][1]
                incoming_one = path[i][2]
                path = unwind(path, i)
                break
        recurse(hot, path, hot_frac * incoming_zero, incoming_one, f)
        recurse(cold, path, cold_frac * incoming_zero, 0.0, f)

    if tree.num_leaves <= 1:
        return
    recurse(0, [], 1.0, 1.0, -1)


def _expected_value(tree) -> float:
    if tree.num_leaves <= 1:
        return float(tree.leaf_value[0])
    total = max(float(tree.internal_count[0]), 1.0)
    ev = 0.0
    for leaf in range(tree.num_leaves):
        ev += float(tree.leaf_value[leaf]) \
            * max(float(tree.leaf_count[leaf]), 1.0) / total
    return ev


def predict_contrib(booster, X: np.ndarray, lo: int, hi: int) -> np.ndarray:
    """Per-feature SHAP contributions + expected value in the last column
    (ref: c_api predict contrib; output shape [n, (F+1)*k])."""
    n, _ = X.shape
    F = booster.max_feature_idx + 1
    k = booster.num_tree_per_iteration
    out = np.zeros((n, (F + 1) * k))
    for i, tree in enumerate(booster.models[lo:hi]):
        tid = (lo + i) % k
        base = tid * (F + 1)
        ev = _expected_value(tree)
        out[:, base + F] += ev
        if tree.num_leaves <= 1:
            continue
        for r in range(n):
            phi = np.zeros(F + 1)
            _tree_shap_row(tree, X[r], phi)
            out[r, base:base + F] += phi[:F]
    return out
