"""Feature binning: value -> bin mapping construction.

TPU-native analog of the reference BinMapper (ref: include/LightGBM/bin.h:61-218,
src/io/bin.cpp:78-520).  Behavior-equivalent re-implementation in vectorized
numpy: greedy equal-count bin finding honoring ``min_data_in_bin``, the
zero-as-one-bin partition around ``kZeroThreshold``, NaN handling as an extra
last bin, categorical vocabularies sorted by count, forced bin bounds, trivial
feature pre-filtering, and the default/most-frequent-bin bookkeeping used by
the histogram FixHistogram trick.

Binning runs on host (numpy) — the reference also does this on CPU during
dataset loading — while the resulting ``[num_rows, num_features]`` bin matrix
is what lives in TPU HBM.
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

import numpy as np

from .utils import log

# ref: include/LightGBM/meta.h:54
K_ZERO_THRESHOLD = 1e-35
# ref: include/LightGBM/bin.h:39
K_SPARSE_THRESHOLD = 0.7

MISSING_NONE = 0
MISSING_ZERO = 1
MISSING_NAN = 2

BIN_NUMERICAL = 0
BIN_CATEGORICAL = 1

_MISSING_TYPE_STR = {MISSING_NONE: "None", MISSING_ZERO: "Zero", MISSING_NAN: "NaN"}
_MISSING_TYPE_FROM_STR = {v: k for k, v in _MISSING_TYPE_STR.items()}


def _next_after(a: float) -> float:
    # ref: utils/common.h:855 GetDoubleUpperBound
    return math.nextafter(a, math.inf)


def _double_equal_ordered(a: float, b: float) -> bool:
    # ref: utils/common.h:850 CheckDoubleEqualOrdered
    return b <= math.nextafter(a, math.inf)


def greedy_find_bin(distinct_values: Sequence[float], counts: Sequence[int],
                    max_bin: int, total_cnt: int, min_data_in_bin: int) -> List[float]:
    """Equal-count greedy bin boundary search over a sorted distinct-value
    histogram (behavioral analog of ref: src/io/bin.cpp:78 GreedyFindBin).

    Values with count >= mean bin size get dedicated bins; the rest are packed
    greedily to roughly equal counts.  Returns bin upper bounds ending in +inf.
    """
    n = len(distinct_values)
    bounds: List[float] = []
    if max_bin <= 0:
        log.fatal("max_bin must be positive")
    if n <= max_bin:
        cur_cnt = 0
        for i in range(n - 1):
            cur_cnt += counts[i]
            if cur_cnt >= min_data_in_bin:
                val = _next_after((distinct_values[i] + distinct_values[i + 1]) / 2.0)
                if not bounds or not _double_equal_ordered(bounds[-1], val):
                    bounds.append(val)
                    cur_cnt = 0
        bounds.append(math.inf)
        return bounds

    if min_data_in_bin > 0:
        max_bin = max(1, min(max_bin, total_cnt // min_data_in_bin))
    mean_bin_size = total_cnt / max_bin

    # Event-driven form of the sequential greedy packer: a bin closes at
    # the first index hitting one of three events (a big value, the
    # running count reaching the mean, or the count reaching half the
    # mean right before a big value), so each closure is found with a
    # prefix-sum search instead of walking every distinct value. The
    # comparisons are re-checked exactly at the landing index (the
    # searchsorted threshold base+mean can round) so the boundaries are
    # bit-identical to the sequential walk.
    dv = np.asarray(distinct_values, np.float64)
    cnts = np.asarray(counts, np.int64)
    big = cnts >= mean_bin_size
    rest_bins = max_bin - int(np.sum(big))
    rest_cnt0 = total_cnt - int(np.sum(cnts[big]))
    rest_cnt = rest_cnt0
    mean_bin_size = rest_cnt / rest_bins if rest_bins > 0 else math.inf

    cum = np.cumsum(cnts)                       # inclusive prefix counts
    cum_rest = np.cumsum(np.where(big, 0, cnts))
    big_idx = np.nonzero(big)[0]

    def first_cum_at_least(s, base, thr):
        """Smallest i >= s with cum[i] - base >= thr (exact), or n."""
        if math.isinf(thr):
            return n
        i = int(np.searchsorted(cum, base + thr, side="left"))
        while i > s and cum[i - 1] - base >= thr:
            i -= 1
        while i < n and cum[i] - base < thr:
            i += 1
        return max(i, s)

    uppers: List[float] = []
    lowers: List[float] = [float(dv[0])]
    s = 0
    while s <= n - 2 and len(uppers) < max_bin - 1:
        base = int(cum[s - 1]) if s > 0 else 0
        bp = int(np.searchsorted(big_idx, s))
        c_big = int(big_idx[bp]) if bp < len(big_idx) else n
        c_mean = first_cum_at_least(s, base, mean_bin_size)
        half = max(1.0, mean_bin_size * 0.5)
        # the only half-mean candidate that can precede c_big is the index
        # right before the first big value (later bigs are dominated)
        c_half = n
        if s + 1 <= c_big < n:
            ch = first_cum_at_least(s, base, half)
            if ch <= c_big - 1:
                c_half = c_big - 1
        closure = min(c_big, c_mean, c_half)
        if closure > n - 2:
            break
        uppers.append(float(dv[closure]))
        lowers.append(float(dv[closure + 1]))
        if len(uppers) >= max_bin - 1:
            break
        if not big[closure]:
            rest_bins -= 1
            rest_cnt = rest_cnt0 - int(cum_rest[closure])
            mean_bin_size = rest_cnt / rest_bins if rest_bins > 0 \
                else math.inf
        s = closure + 1

    for i in range(len(uppers)):
        val = _next_after((uppers[i] + lowers[i + 1]) / 2.0)
        if not bounds or not _double_equal_ordered(bounds[-1], val):
            bounds.append(val)
    bounds.append(math.inf)
    return bounds


def _split_zero_counts(distinct_values, counts):
    dv = np.asarray(distinct_values, np.float64)
    c = np.asarray(counts, np.int64)
    left = dv <= -K_ZERO_THRESHOLD
    right = dv > K_ZERO_THRESHOLD
    left_cnt_data = int(c[left].sum())
    right_cnt_data = int(c[right].sum())
    cnt_zero = int(c.sum()) - left_cnt_data - right_cnt_data
    return left_cnt_data, cnt_zero, right_cnt_data


def find_bin_zero_as_one(distinct_values: List[float], counts: List[int],
                         max_bin: int, total_cnt: int,
                         min_data_in_bin: int) -> List[float]:
    """Numerical bin bounds with a dedicated zero bin (ref: bin.cpp:256)."""
    n = len(distinct_values)
    dv = np.asarray(distinct_values, np.float64)
    left_cnt_data, cnt_zero, right_cnt_data = _split_zero_counts(
        distinct_values, counts)

    # first index with value > -K_ZERO_THRESHOLD (distinct is sorted)
    left_cnt = int(np.searchsorted(dv, -K_ZERO_THRESHOLD, side="right"))

    bounds: List[float] = []
    if left_cnt > 0 and max_bin > 1:
        denom = total_cnt - cnt_zero
        left_max_bin = max(1, int(left_cnt_data / denom * (max_bin - 1))) \
            if denom > 0 else 1
        bounds = greedy_find_bin(distinct_values[:left_cnt], counts[:left_cnt],
                                 left_max_bin, left_cnt_data, min_data_in_bin)
        if bounds:
            bounds[-1] = -K_ZERO_THRESHOLD

    right_start = int(np.searchsorted(dv, K_ZERO_THRESHOLD, side="right"))
    if right_start >= n:
        right_start = -1
    right_max_bin = max_bin - 1 - len(bounds)
    if right_start >= 0 and right_max_bin > 0:
        right = greedy_find_bin(distinct_values[right_start:],
                                counts[right_start:], right_max_bin,
                                right_cnt_data, min_data_in_bin)
        bounds.append(K_ZERO_THRESHOLD)
        bounds.extend(right)
    else:
        bounds.append(math.inf)
    return bounds


def find_bin_with_forced(distinct_values: List[float], counts: List[int],
                         max_bin: int, total_cnt: int, min_data_in_bin: int,
                         forced_bounds: List[float]) -> List[float]:
    """Numerical bin bounds honoring user-forced boundaries
    (ref: bin.cpp:157 FindBinWithPredefinedBin)."""
    n = len(distinct_values)
    dv = np.asarray(distinct_values, np.float64)
    left_cnt = int(np.searchsorted(dv, -K_ZERO_THRESHOLD, side="right"))
    right_start = int(np.searchsorted(dv, K_ZERO_THRESHOLD, side="right"))
    if right_start >= n:
        right_start = -1

    bounds: List[float] = []
    if max_bin == 2:
        bounds.append(K_ZERO_THRESHOLD if left_cnt == 0 else -K_ZERO_THRESHOLD)
    elif max_bin >= 3:
        if left_cnt > 0:
            bounds.append(-K_ZERO_THRESHOLD)
        if right_start >= 0:
            bounds.append(K_ZERO_THRESHOLD)
    bounds.append(math.inf)

    max_to_insert = max_bin - len(bounds)
    inserted = 0
    for fb in forced_bounds:
        if inserted >= max_to_insert:
            break
        if abs(fb) > K_ZERO_THRESHOLD:
            bounds.append(fb)
            inserted += 1
    bounds.sort()

    free_bins = max_bin - len(bounds)
    to_add: List[float] = []
    value_ind = 0
    for i, ub in enumerate(bounds):
        cnt_in_bin = 0
        bin_start = value_ind
        while value_ind < n and distinct_values[value_ind] < ub:
            cnt_in_bin += counts[value_ind]
            value_ind += 1
        bins_remaining = max_bin - len(bounds) - len(to_add)
        num_sub = min(round(cnt_in_bin * free_bins / total_cnt), bins_remaining) + 1
        if i == len(bounds) - 1:
            num_sub = bins_remaining + 1
        sub = greedy_find_bin(distinct_values[bin_start:value_ind],
                              counts[bin_start:value_ind], num_sub,
                              cnt_in_bin, min_data_in_bin)
        to_add.extend(sub[:-1])  # last bound is inf
    bounds.extend(to_add)
    bounds.sort()
    if len(bounds) > max_bin:
        log.fatal("forced bins produced more than max_bin bounds")
    return bounds


def effective_bin_counts(mappers: Sequence["BinMapper"]) -> np.ndarray:
    """Per-feature EFFECTIVE bin counts (NaN/zero bins included) — what
    the adaptive per-feature kernel layout
    (``ops/layout.packed_feature_layout``, ``tpu_adaptive_bins``) sizes
    each feature's slab from, instead of padding every feature to the
    global pow2 ``max_bin``.  The single emission point: dataset
    finalization routes through here, so the layout and the split-scan
    ``num_bin_per_feat`` can never disagree."""
    return np.array([max(1, int(m.num_bin)) for m in mappers], np.int32)


class BinMapper:
    """Per-feature value→bin mapping (ref: include/LightGBM/bin.h:61)."""

    def __init__(self):
        self.num_bin: int = 1
        self.missing_type: int = MISSING_NONE
        self.is_trivial: bool = True
        self.sparse_rate: float = 1.0
        self.bin_type: int = BIN_NUMERICAL
        self.min_val: float = 0.0
        self.max_val: float = 0.0
        self.default_bin: int = 0
        self.most_freq_bin: int = 0
        self.bin_upper_bound: np.ndarray = np.array([np.inf])
        self.bin_2_categorical: List[int] = []
        self.categorical_2_bin: Dict[int, int] = {}

    # ------------------------------------------------------------------
    def find_bin(self, values: np.ndarray, total_sample_cnt: int, max_bin: int,
                 min_data_in_bin: int, min_split_data: int, pre_filter: bool,
                 bin_type: int, use_missing: bool, zero_as_missing: bool,
                 forced_bounds: Optional[List[float]] = None) -> None:
        """Construct the mapping from non-zero sampled ``values``
        (behavioral analog of ref: src/io/bin.cpp:325 BinMapper::FindBin).

        ``total_sample_cnt`` includes implicit zeros not present in ``values``.
        """
        forced_bounds = forced_bounds or []
        values = np.asarray(values, dtype=np.float64)
        finite = values[~np.isnan(values)]
        na_cnt = 0
        if not use_missing:
            self.missing_type = MISSING_NONE
        elif zero_as_missing:
            self.missing_type = MISSING_ZERO
        else:
            if finite.size == values.size:
                self.missing_type = MISSING_NONE
            else:
                self.missing_type = MISSING_NAN
                na_cnt = values.size - finite.size

        self.bin_type = bin_type
        self.default_bin = 0
        zero_cnt = int(total_sample_cnt - finite.size - na_cnt)

        # distinct values with zero inserted at its sorted position, merging
        # float-equal neighbors (keeping the larger; ref: bin.cpp:357-389).
        # Vectorized: a group BREAK happens exactly where the next raw value
        # exceeds nextafter(previous raw value), and each group keeps its
        # last (largest) member — identical to the sequential chain-merge.
        sv = np.sort(finite, kind="stable")
        if sv.size == 0:
            distinct = np.array([0.0])
            counts = np.array([zero_cnt], dtype=np.int64)
        else:
            brk = sv[1:] > np.nextafter(sv[:-1], np.inf)
            starts = np.concatenate(([0], np.nonzero(brk)[0] + 1))
            ends = np.concatenate((starts[1:], [sv.size]))  # exclusive
            distinct = sv[ends - 1]
            counts = (ends - starts).astype(np.int64)
            zero_at = -1
            if sv[0] > 0.0:
                if zero_cnt > 0:     # leading zero group is gated
                    zero_at = 0
            elif sv[-1] < 0.0:
                if zero_cnt > 0:     # trailing zero group is gated
                    zero_at = len(distinct)
            else:
                # the break where the previous group ends negative and the
                # next starts positive — inserted UNCONDITIONALLY like the
                # sequential walk (a zero entry with count 0 still lands
                # in the distinct list and can shift forced/categorical
                # binning)
                first_vals = sv[starts]
                hits = np.nonzero((distinct[:-1] < 0.0)
                                  & (first_vals[1:] > 0.0))[0]
                if hits.size:
                    zero_at = int(hits[0]) + 1
            if zero_at >= 0:
                distinct = np.insert(distinct, zero_at, 0.0)
                counts = np.insert(counts, zero_at, zero_cnt)

        self.min_val = float(distinct[0]) if len(distinct) else 0.0
        self.max_val = float(distinct[-1]) if len(distinct) else 0.0

        cnt_in_bin: List[int] = []
        if bin_type == BIN_NUMERICAL:
            if self.missing_type == MISSING_NAN:
                eff_max_bin, eff_total = max_bin - 1, total_sample_cnt - na_cnt
            else:
                eff_max_bin, eff_total = max_bin, total_sample_cnt
            if forced_bounds:
                bounds = find_bin_with_forced(distinct, counts, eff_max_bin,
                                              eff_total, min_data_in_bin,
                                              forced_bounds)
            else:
                bounds = find_bin_zero_as_one(distinct, counts, eff_max_bin,
                                              eff_total, min_data_in_bin)
            if self.missing_type == MISSING_ZERO and len(bounds) == 2:
                self.missing_type = MISSING_NONE
            if self.missing_type == MISSING_NAN:
                bounds.append(math.nan)
            self.bin_upper_bound = np.asarray(bounds)
            self.num_bin = len(bounds)
            # bin of each distinct value = first bound >= value (the NaN
            # sentinel bound, when present, is last and never reached
            # since the numeric bounds end at +inf)
            numeric_bounds = np.asarray(bounds[:self.num_bin - 1],
                                        np.float64)
            dbin = np.searchsorted(numeric_bounds, np.asarray(distinct),
                                   side="left")
            cnt_in_bin = np.bincount(
                dbin, weights=np.asarray(counts, np.float64),
                minlength=self.num_bin).astype(np.int64).tolist()
            if self.missing_type == MISSING_NAN:
                cnt_in_bin[-1] = na_cnt
        else:
            # categorical: count-sorted vocabulary, bin 0 = NaN/other
            # (ref: bin.cpp:424-491)
            dvi = np.asarray(distinct, np.float64).astype(np.int64)
            ci = np.asarray(counts, np.int64)
            neg = dvi < 0
            if np.any(neg):
                na_cnt += int(ci[neg].sum())
                log.warning("Met negative value in categorical features, "
                            "will convert it to NaN")
            # aggregate per integer value (distinct floats can alias the
            # same int); unique is sorted, so a stable argsort by -count
            # keeps ascending-value order among ties like the dict walk
            vals, inv = np.unique(dvi[~neg], return_inverse=True)
            agg = np.bincount(inv, weights=ci[~neg].astype(np.float64)) \
                .astype(np.int64) if vals.size else np.zeros(0, np.int64)
            rest_cnt = total_sample_cnt - na_cnt
            self.categorical_2_bin = {-1: 0}
            self.bin_2_categorical = [-1]
            cnt_in_bin = [0]
            self.num_bin = 1
            if rest_cnt > 0:
                perm = np.argsort(-agg, kind="stable")
                order = [(int(vals[p]), int(agg[p])) for p in perm]
                cut_cnt = int(round(rest_cnt * 0.99))
                distinct_cnt = len(order) + (1 if na_cnt > 0 else 0)
                eff_max_bin = min(distinct_cnt, max_bin)
                used_cnt = 0
                for idx, (cat, c) in enumerate(order):
                    if not (used_cnt < cut_cnt or self.num_bin < eff_max_bin):
                        break
                    if c < min_data_in_bin and idx > 1:
                        break
                    self.bin_2_categorical.append(cat)
                    self.categorical_2_bin[cat] = self.num_bin
                    used_cnt += c
                    cnt_in_bin.append(c)
                    self.num_bin += 1
                if len(self.bin_2_categorical) - 1 == len(order) and na_cnt == 0:
                    self.missing_type = MISSING_NONE
                else:
                    self.missing_type = MISSING_NAN
                cnt_in_bin[0] = total_sample_cnt - used_cnt

        self.is_trivial = self.num_bin <= 1
        if not self.is_trivial and pre_filter and self._need_filter(
                cnt_in_bin, total_sample_cnt, min_split_data):
            self.is_trivial = True

        if not self.is_trivial:
            self.default_bin = int(self.value_to_bin(0.0))
            self.most_freq_bin = int(np.argmax(cnt_in_bin))
            max_sparse_rate = cnt_in_bin[self.most_freq_bin] / total_sample_cnt
            if (self.most_freq_bin != self.default_bin
                    and max_sparse_rate < K_SPARSE_THRESHOLD):
                self.most_freq_bin = self.default_bin
            self.sparse_rate = cnt_in_bin[self.most_freq_bin] / total_sample_cnt
        else:
            self.sparse_rate = 1.0

    def _need_filter(self, cnt_in_bin: List[int], total_cnt: int,
                     filter_cnt: int) -> bool:
        """True if no split on this feature could satisfy min_data
        (ref: bin.h:87-120 NeedFilter analog: cumulative count check)."""
        if self.bin_type == BIN_NUMERICAL:
            sum_left = 0
            for i in range(len(cnt_in_bin) - 1):
                sum_left += cnt_in_bin[i]
                if sum_left >= filter_cnt and total_cnt - sum_left >= filter_cnt:
                    return False
            return True
        else:
            if len(cnt_in_bin) <= 2:
                for c in cnt_in_bin:
                    if c >= filter_cnt and total_cnt - c >= filter_cnt:
                        return False
                return True
            return False

    # ------------------------------------------------------------------
    def _bounds_f32(self, n_numeric: int) -> np.ndarray:
        """Largest-float32-not-above each f64 bound: for float32 inputs v,
        v <= bound_f64 iff v <= bound_f32, so binning float32 data against
        these is bit-identical to the f64 comparison without upcasting the
        whole column."""
        cached = getattr(self, "_bounds_f32_cache", None)
        if cached is not None and len(cached) == n_numeric:
            return cached
        b = np.asarray(self.bin_upper_bound[:n_numeric], np.float64)
        b32 = b.astype(np.float32)
        over = b32.astype(np.float64) > b
        b32[over] = np.nextafter(b32[over], np.float32(-np.inf))
        self._bounds_f32_cache = b32
        return b32

    def value_to_bin(self, value):
        """Vectorized value→bin (ref: bin.h:457-495 ValueToBin)."""
        scalar = np.isscalar(value)
        arr = np.atleast_1d(np.asarray(value))
        if self.bin_type == BIN_CATEGORICAL:
            v = arr.astype(np.float64, copy=False)
            iv = np.where(np.isnan(v), -1, v).astype(np.int64)
            cached = getattr(self, "_cat_lookup_cache", None)
            if cached is None or len(cached[0]) != len(
                    self.categorical_2_bin):
                cats = np.array(sorted(self.categorical_2_bin), np.int64)
                cbins = np.array([self.categorical_2_bin[c] for c in cats],
                                 np.int32)
                cached = self._cat_lookup_cache = (cats, cbins)
            cats, cbins = cached
            pos = np.clip(np.searchsorted(cats, iv), 0, len(cats) - 1)
            out = np.where(cats[pos] == iv, cbins[pos], 0).astype(np.int32)
            return out[0] if scalar else out
        # float32 columns bin against pre-rounded f32 bounds (exact; see
        # _bounds_f32) — no 2x column upcast copy on the hot ingest path
        n_numeric = self.num_bin - (1 if self.missing_type == MISSING_NAN
                                    else 0)
        if arr.dtype == np.float32:
            v = arr
            bounds = self._bounds_f32(n_numeric)
            zero = np.float32(0.0)
        else:
            v = arr.astype(np.float64, copy=False)
            bounds = self.bin_upper_bound[:n_numeric]
            zero = 0.0
        nan_mask = np.isnan(v)
        # bin = smallest i with value <= bin_upper_bound[i]; searchsorted
        # side='left' returns exactly the first index whose bound >= value
        safe_v = np.where(nan_mask, zero, v)
        out = np.searchsorted(bounds, safe_v, side="left").astype(np.int32)
        out = np.minimum(out, n_numeric - 1)
        if self.missing_type == MISSING_NAN:
            out = np.where(nan_mask, self.num_bin - 1, out)
        return out[0] if scalar else out

    def bin_to_value(self, bin_idx: int) -> float:
        """Representative threshold for a bin (used in model text output —
        ref: tree.cpp RealThreshold uses the bin upper bound)."""
        if self.bin_type == BIN_CATEGORICAL:
            return float(self.bin_2_categorical[bin_idx])
        return float(self.bin_upper_bound[bin_idx])

    # ------------------------------------------------------------------
    def to_dict(self) -> dict:
        return {
            "num_bin": self.num_bin,
            "missing_type": self.missing_type,
            "is_trivial": self.is_trivial,
            "sparse_rate": self.sparse_rate,
            "bin_type": self.bin_type,
            "min_val": self.min_val,
            "max_val": self.max_val,
            "default_bin": self.default_bin,
            "most_freq_bin": self.most_freq_bin,
            "bin_upper_bound": self.bin_upper_bound.tolist(),
            "bin_2_categorical": list(self.bin_2_categorical),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "BinMapper":
        m = cls()
        m.num_bin = d["num_bin"]
        m.missing_type = d["missing_type"]
        m.is_trivial = d["is_trivial"]
        m.sparse_rate = d["sparse_rate"]
        m.bin_type = d["bin_type"]
        m.min_val = d["min_val"]
        m.max_val = d["max_val"]
        m.default_bin = d["default_bin"]
        m.most_freq_bin = d["most_freq_bin"]
        m.bin_upper_bound = np.asarray(d["bin_upper_bound"], dtype=np.float64)
        m.bin_2_categorical = list(d.get("bin_2_categorical", []))
        m.categorical_2_bin = {c: i for i, c in enumerate(m.bin_2_categorical)}
        return m

    def missing_type_str(self) -> str:
        return _MISSING_TYPE_STR[self.missing_type]


def mappers_digest(mappers: Sequence["BinMapper"]) -> str:
    """Stable SHA-256 over every mapper's defining state (bounds at full
    float64 precision via repr, vocabularies, missing semantics).  Two
    datasets whose mappers share a digest bin any value identically —
    the ingest binary-cache manifest records it so a cache hit can
    assert bit-compatibility instead of assuming it, and a reference-
    aligned validation cache can be checked against its training
    dataset."""
    import hashlib
    import json
    h = hashlib.sha256()
    for m in mappers:
        d = m.to_dict()
        d["bin_upper_bound"] = [repr(float(b)) for b in d["bin_upper_bound"]]
        h.update(json.dumps(d, sort_keys=True, default=str).encode())
        h.update(b"\x00")
    return h.hexdigest()


def mapper_drift_counts(mapper: "BinMapper", col) -> tuple:
    """Diff one raw column chunk against a frozen mapper (the ingest
    drift monitor's per-chunk primitive — obs/drift.py).

    Returns ``(out_of_range, new_categories, n_finite)``: for numeric
    mappers, how many finite values fall outside the [min_val, max_val]
    range the bins were fit on (the out-of-range quantile mass); for
    categorical mappers, how many values name a category absent from
    the training vocabulary.  NaNs are missing, not drift — the
    mapper already has a missing bin for them."""
    v = np.asarray(col, np.float64).ravel()
    v = v[np.isfinite(v)]
    n = int(v.size)
    if n == 0 or mapper.is_trivial:
        return 0, 0, n
    if mapper.bin_type == BIN_CATEGORICAL:
        if not mapper.categorical_2_bin:
            return 0, n, n
        known = np.array(sorted(mapper.categorical_2_bin), np.int64)
        iv = v.astype(np.int64)
        pos = np.clip(np.searchsorted(known, iv), 0, known.size - 1)
        return 0, int(np.count_nonzero(known[pos] != iv)), n
    out = int(np.count_nonzero((v < mapper.min_val)
                               | (v > mapper.max_val)))
    return out, 0, n
