"""Python side of the C ABI (native/capi.cpp).

The C layer passes raw buffer addresses and scalar metadata; this module
wraps them with numpy (zero-copy via ctypes) and drives the normal
package objects. Handles crossing the ABI are ordinary Python objects
whose references the C layer owns (Py_DECREF on *Free).

Field/data type codes follow the reference C API
(ref: include/LightGBM/c_api.h: C_API_DTYPE_FLOAT32=0, FLOAT64=1,
INT32=2, INT64=3; predict types: NORMAL=0, RAW_SCORE=1, LEAF_INDEX=2,
CONTRIB=3).
"""
from __future__ import annotations

import ctypes
import os

import numpy as np

# Honor JAX_PLATFORMS deterministically BEFORE anything can touch a jax
# backend: a pure-C host embedding the interpreter gets no other chance
# to pin it, and an unreachable TPU would otherwise hang backend
# bring-up forever.
from .utils.platform import pin_jax_platforms

pin_jax_platforms()

from .basic import Booster, Dataset

_BACKEND_READY = False


def _ensure_backend():
    """Bound jax backend bring-up so an unreachable device yields an
    LGBM_GetLastError message instead of an infinite hang (the axon
    tunnel's "device grant stuck" state blocks jax.devices() forever).
    Runs device discovery in a daemon thread with a deadline; on timeout
    the thread is abandoned and the caller gets a C API error (-1)."""
    global _BACKEND_READY
    if _BACKEND_READY:
        return
    import threading

    import jax

    timeout = float(os.environ.get("LGBM_TPU_BACKEND_TIMEOUT", "120"))
    box = {}

    def _probe():
        try:
            box["devices"] = jax.devices()
        except Exception as e:  # surfaced below on the calling thread
            box["error"] = e

    t = threading.Thread(target=_probe, daemon=True)
    t.start()
    t.join(timeout)
    if t.is_alive():
        raise RuntimeError(
            f"JAX backend initialization did not complete within "
            f"{timeout:.0f}s — the accelerator is unreachable. Set "
            f"JAX_PLATFORMS=cpu (honored at capi init) or raise "
            f"LGBM_TPU_BACKEND_TIMEOUT.")
    if "error" in box:
        raise box["error"]
    _BACKEND_READY = True

_DTYPES = {0: np.float32, 1: np.float64, 2: np.int32, 3: np.int64}


def _wrap(ptr: int, count: int, type_code: int) -> np.ndarray:
    dt = np.dtype(_DTYPES[type_code])
    buf = (ctypes.c_char * (count * dt.itemsize)).from_address(ptr)
    return np.frombuffer(buf, dtype=dt)


def _parse_params(parameters: str) -> dict:
    out = {}
    for tok in parameters.replace("\t", " ").split():
        if "=" in tok:
            k, v = tok.split("=", 1)
            out[k] = v
    return out


# ---------------------------------------------------------------- dataset
def dataset_create_from_mat(ptr, data_type, nrow, ncol, is_row_major,
                            parameters, reference):
    _ensure_backend()
    if not ptr or nrow <= 0 or ncol <= 0:
        raise ValueError("DatasetCreateFromMat: data pointer is null or "
                         f"shape ({nrow}, {ncol}) is empty")
    arr = _wrap(ptr, nrow * ncol, data_type)
    X = arr.reshape(nrow, ncol) if is_row_major else \
        arr.reshape(ncol, nrow).T
    # COPY before returning: the reference's CreateFromMat owns its data
    # from this point on, and Dataset.construct() runs lazily — a view
    # would read caller memory that may already be freed
    ds = Dataset(np.array(X, copy=True),
                 params=_parse_params(parameters),
                 reference=reference if isinstance(reference, Dataset)
                 else None)
    return ds


def dataset_set_field(ds, name, ptr, num_element, type_code):
    if isinstance(ds, _PushBuild) and ds.ds is None:
        # SetField during a streaming build is legal (the reference's
        # push-rows protocol); it is applied at finalize
        ds.fields[name] = _wrap(ptr, num_element, type_code).copy()
        return True
    ds = _resolve_ds(ds)
    vals = _wrap(ptr, num_element, type_code).copy()
    if name == "label":
        ds.set_label(vals)
    elif name == "weight":
        ds.set_weight(vals)
    elif name in ("group", "query"):
        ds.set_group(vals.astype(np.int64))
    elif name == "init_score":
        ds.init_score = vals
        if ds._inner is not None:
            ds._inner.metadata.set_init_score(vals)
    else:
        raise ValueError(f"unknown field name {name!r}")
    return True


def dataset_num_data(ds):
    if isinstance(ds, _PushBuild) and ds.ds is None:
        return ds.n            # declared size; keeps the build pushable
    ds = _resolve_ds(ds)
    ds.construct()
    return int(ds._inner.num_data)


def dataset_num_feature(ds):
    if isinstance(ds, _PushBuild) and ds.ds is None:
        return ds.ncol
    ds = _resolve_ds(ds)
    ds.construct()
    return int(ds._inner.num_total_features)


# ---------------------------------------------------------------- booster
def booster_create(train_ds, parameters):
    _ensure_backend()
    train_ds = _resolve_ds(train_ds)
    params = _parse_params(parameters)
    # the reference C API evaluates the training data unconditionally
    # (c_api.cpp Booster constructor builds train metrics), so GetEval(0)
    # must work without the Python-facade opt-in flag
    params.setdefault("is_provide_training_metric", "true")
    return Booster(params=params, train_set=train_ds)


def booster_from_modelfile(filename):
    _ensure_backend()
    bst = Booster(model_file=filename)
    return bst, bst.current_iteration()


def booster_add_valid(bst, valid_ds):
    bst.add_valid(_resolve_ds(valid_ds), f"valid_{len(bst.valid_sets)}")
    return True


def booster_update(bst):
    return int(bool(bst.update()))


def booster_current_iteration(bst):
    return int(bst.current_iteration())


def booster_num_classes(bst):
    return int(bst.num_class)


def booster_calc_num_predict(bst, num_row, predict_type, start_iteration,
                             num_iteration):
    """(ref: c_api.cpp LGBM_BoosterCalcNumPredict semantics)"""
    k = max(1, bst.num_tree_per_iteration)
    total_iter = bst.num_trees() // k
    if num_iteration <= 0:
        num_iteration = total_iter - start_iteration
    num_iteration = max(0, min(num_iteration, total_iter - start_iteration))
    if predict_type == 2:      # leaf index: one value per tree
        return int(num_row * num_iteration * k)
    if predict_type == 3:      # contrib: per feature + bias, per class
        return int(num_row * k * (bst.num_feature() + 1))
    return int(num_row * max(1, bst.num_class))


def booster_predict_for_mat(bst, ptr, data_type, nrow, ncol, is_row_major,
                            predict_type, start_iteration, num_iteration,
                            parameter, out_ptr):
    arr = _wrap(ptr, nrow * ncol, data_type)
    X = arr.reshape(nrow, ncol) if is_row_major else \
        arr.reshape(ncol, nrow).T
    return _predict_to_buffer(bst, X, predict_type, start_iteration,
                              num_iteration, out_ptr)


def booster_save_model(bst, start_iteration, num_iteration,
                       feature_importance_type, filename):
    bst.save_model(filename, start_iteration=start_iteration,
                   num_iteration=num_iteration,
                   importance_type=("gain" if feature_importance_type == 1
                                    else "split"))
    return True


# ------------------------------------------------- round-3 surface growth
# (VERDICT r2 missing #4: CSR/CSC/file dataset creation, file/CSR predict,
# GetEval, leaf accessors, NetworkInit, FastInit single-row paths —
# ref: src/c_api.cpp:398-520, :939-1156, c_api.h:1317)
def _ref(ds_or_none):
    from .basic import Dataset as _DS
    if isinstance(ds_or_none, _PushBuild):
        return ds_or_none.finalize()
    return ds_or_none if isinstance(ds_or_none, _DS) else None


def dataset_create_from_file(filename, parameters, reference):
    _ensure_backend()
    return Dataset(filename, params=_parse_params(parameters),
                   reference=_ref(reference))


def _sparse_from_ptrs(fmt, ptr_arr, ptr_type, indices_ptr, data_ptr,
                      data_type, nptr, nelem, other_dim):
    """Shared CSR/CSC constructor from raw C pointers (indptr/colptr
    type codes: 2 = int32, 3 = int64, C_API_DTYPE)."""
    import scipy.sparse as sp
    ptrs = _wrap(ptr_arr, nptr, ptr_type).copy()
    indices = _wrap(indices_ptr, nelem, 2).copy()
    vals = _wrap(data_ptr, nelem, data_type).copy().astype(np.float64)
    if fmt == "csr":
        return sp.csr_matrix((vals, indices, ptrs),
                             shape=(nptr - 1, other_dim))
    return sp.csc_matrix((vals, indices, ptrs),
                         shape=(other_dim, nptr - 1))


def _csr_from_ptrs(indptr_ptr, indptr_type, indices_ptr, data_ptr,
                   data_type, nindptr, nelem, num_col):
    return _sparse_from_ptrs("csr", indptr_ptr, indptr_type, indices_ptr,
                             data_ptr, data_type, nindptr, nelem, num_col)


def dataset_create_from_csr(indptr_ptr, indptr_type, indices_ptr, data_ptr,
                            data_type, nindptr, nelem, num_col,
                            parameters, reference):
    _ensure_backend()
    X = _csr_from_ptrs(indptr_ptr, indptr_type, indices_ptr, data_ptr,
                       data_type, nindptr, nelem, num_col)
    return Dataset(X, params=_parse_params(parameters),
                   reference=_ref(reference))


def dataset_create_from_csc(colptr_ptr, colptr_type, indices_ptr, data_ptr,
                            data_type, ncolptr, nelem, num_row,
                            parameters, reference):
    _ensure_backend()
    X = _sparse_from_ptrs("csc", colptr_ptr, colptr_type, indices_ptr,
                          data_ptr, data_type, ncolptr, nelem, num_row)
    return Dataset(X, params=_parse_params(parameters),
                   reference=_ref(reference))


def dataset_save_binary(ds, filename):
    ds = _resolve_ds(ds)
    ds.construct()
    ds._inner.save_binary(filename)
    return True


def booster_num_feature(bst):
    return int(bst.num_feature())


def _run_predict(bst, X, predict_type, start_iteration, num_iteration):
    """Shared predict-type dispatch for every LGBM_*Predict* entry
    (predict_type codes: 0 normal, 1 raw_score, 2 leaf_index, 3
    contrib — ref: c_api.h C_API_PREDICT_*)."""
    kwargs = dict(start_iteration=start_iteration,
                  num_iteration=(num_iteration if num_iteration > 0
                                 else None))
    if predict_type == 1:
        return bst.predict(X, raw_score=True, **kwargs)
    if predict_type == 2:
        return bst.predict(X, pred_leaf=True, **kwargs)
    if predict_type == 3:
        return bst.predict(X, pred_contrib=True, **kwargs)
    return bst.predict(X, **kwargs)


def _predict_to_buffer(bst, X, predict_type, start_iteration,
                       num_iteration, out_ptr):
    flat = np.asarray(_run_predict(bst, X, predict_type, start_iteration,
                                   num_iteration), np.float64).reshape(-1)
    out = _wrap(out_ptr, flat.size, 1)
    out[:] = flat
    return int(flat.size)


def booster_predict_for_file(bst, data_filename, data_has_header,
                             predict_type, start_iteration, num_iteration,
                             parameter, result_filename):
    """(ref: Application::Predict -> Predictor::Predict(file),
    predictor.hpp:164 — parse rows, predict, one line per row)"""
    from .io.file_loader import load_text_file
    # the caller's explicit flag wins over auto-detection (an all-numeric
    # header would otherwise pass as a data row)
    X, _, _ = load_text_file(data_filename, label_column=None,
                             force_header=bool(data_has_header))
    pred = np.asarray(_run_predict(bst, X, predict_type, start_iteration,
                                   num_iteration))
    with open(result_filename, "w") as fh:
        for row in (pred if pred.ndim > 1 else pred[:, None]):
            fh.write("\t".join(repr(float(v)) for v in row) + "\n")
    return True


def booster_predict_for_csr(bst, indptr_ptr, indptr_type, indices_ptr,
                            data_ptr, data_type, nindptr, nelem, num_col,
                            predict_type, start_iteration, num_iteration,
                            parameter, out_ptr):
    X = _csr_from_ptrs(indptr_ptr, indptr_type, indices_ptr, data_ptr,
                       data_type, nindptr, nelem, num_col)
    return _predict_to_buffer(bst, X, predict_type, start_iteration,
                              num_iteration, out_ptr)


def booster_get_eval_counts(bst):
    bst._drain()
    g = bst._gbdt
    # every dataset shares the config's metric list, so any one set's
    # width is THE width (ref: c_api.cpp LGBM_BoosterGetEvalCounts)
    for ms in ([g.training_metrics] if g.training_metrics
               else []) + list(g.valid_metrics):
        return sum(len(m.names) for m in ms)
    return 0


def booster_get_eval_names(bst):
    bst._drain()
    g = bst._gbdt
    for ms in ([g.training_metrics] if g.training_metrics
               else []) + list(g.valid_metrics):
        return [n for m in ms for n in m.names]
    return []


def booster_get_eval(bst, data_idx):
    """data_idx 0 = training data, i+1 = i-th validation set
    (ref: c_api.cpp LGBM_BoosterGetEval)."""
    bst._drain()
    import jax
    g = bst._gbdt
    if data_idx == 0:
        metrics, score = g.training_metrics, g.scores
        if not metrics:
            raise ValueError("no training metrics were configured")
    else:
        vi = data_idx - 1
        if vi >= len(g.valid_metrics):
            raise IndexError(f"no validation set {vi}")
        metrics, score = g.valid_metrics[vi], g.valid_scores[vi]
    vals = g.eval_metric_set("", metrics, score)
    return [float(v) for v in jax.device_get([v for (_, _, v, _)
                                              in vals])]


def _checked_tree_leaf(g, tree_idx, leaf_idx):
    # the reference returns -1 for invalid indices; Python negative
    # indexing would silently read/mutate the LAST tree instead
    if not (0 <= tree_idx < len(g.models)):
        raise IndexError(f"tree index {tree_idx} out of range "
                         f"[0, {len(g.models)})")
    ht = g.models[tree_idx]
    if not (0 <= leaf_idx < ht.num_leaves):
        raise IndexError(f"leaf index {leaf_idx} out of range "
                         f"[0, {ht.num_leaves})")
    return ht


def booster_get_leaf_value(bst, tree_idx, leaf_idx):
    bst._drain()
    ht = _checked_tree_leaf(bst._gbdt, tree_idx, leaf_idx)
    return float(ht.leaf_value[leaf_idx])


def booster_set_leaf_value(bst, tree_idx, leaf_idx, value):
    """(ref: c_api.cpp LGBM_BoosterSetLeafValue -> Tree::SetLeafOutput)"""
    bst._drain()
    g = bst._gbdt
    ht = _checked_tree_leaf(g, tree_idx, leaf_idx)
    ht.leaf_value[leaf_idx] = float(value)
    dt = g.device_trees[tree_idx]
    import jax.numpy as jnp
    dt.leaf_value = jnp.asarray(ht.leaf_value, jnp.float32)
    bst._model_version += 1   # invalidate the cached device predictor
    return True


def booster_rollback_one_iter(bst):
    bst.rollback_one_iter()
    return True


def network_init(machines, local_listen_port, listen_time_out,
                 num_machines):
    from .parallel.distributed import set_network
    set_network(machines, local_listen_port=local_listen_port,
                num_machines=num_machines, time_out=listen_time_out)
    return True


def network_free():
    from .parallel import extnet
    from .parallel.distributed import free_network
    extnet.free()
    free_network()
    return True


# ------------------------------------------------- round-4 surface growth
# (VERDICT r3 missing #2 tranche 3: custom-gradient training, JSON dump,
# field/feature-name access, CSC predict, sparse contribs, streaming
# dataset push — ref: src/c_api.cpp:430-845, c_api.h)
class _PushBuild:
    """Streaming dataset under construction (ref: c_api.cpp:430-520
    LGBM_DatasetCreateByReference + LGBM_DatasetPushRows*): rows arrive
    in chunks; binning reuses the reference dataset's mappers. The
    handle behaves as a Dataset lazily — _resolve_ds finalizes on first
    use by a consumer (booster creation, field access...)."""

    def __init__(self, reference, num_total_row):
        if not isinstance(reference, Dataset):
            raise ValueError("DatasetCreateByReference needs a constructed "
                             "reference dataset")
        reference.construct()
        self.reference = reference
        self.n = int(num_total_row)
        self.ncol = int(reference._inner.num_total_features)
        self.buf = np.zeros((self.n, self.ncol), np.float64)
        self.pushed = np.zeros(self.n, bool)   # declared-row coverage
        self.fields = {}          # SetField before finalize is legal
        self.ds: Dataset = None

    def push(self, X, start_row):
        if self.ds is not None:
            raise ValueError("cannot push rows after the dataset was used")
        end = start_row + X.shape[0]
        if end > self.n or X.shape[1] != self.ncol:
            raise ValueError(
                f"push of rows [{start_row}, {end}) x {X.shape[1]} cols "
                f"exceeds the declared [{self.n}, {self.ncol}] dataset")
        self.buf[start_row:end] = X
        self.pushed[start_row:end] = True

    def finalize(self) -> Dataset:
        if self.ds is None:
            # the reference finishes the dataset only when the final chunk
            # arrives; silently training on never-pushed all-zero rows
            # would be corrupt data
            if not self.pushed.all():
                missing = int((~self.pushed).sum())
                first = int(np.argmin(self.pushed))
                raise ValueError(
                    f"dataset declared {self.n} rows but {missing} were "
                    f"never pushed (first missing row: {first})")
            # inherit the reference's params: binning already comes from
            # its mappers, but the booster's resolved config (and hence
            # the serialized parameters block) must see the same
            # dataset-defining keys, or a pushed-rows model's
            # serialization differs from the monolithic one by its echo
            self.ds = Dataset(self.buf, reference=self.reference,
                              params=dict(self.reference.params))
            for name, vals in self.fields.items():
                self.ds.set_field(name, vals)
            self.ds.construct()
        return self.ds


def _resolve_ds(h):
    """Dataset handles may be streaming builders; consumers get the
    finalized Dataset."""
    return h.finalize() if isinstance(h, _PushBuild) else h


def dataset_create_by_reference(reference, num_total_row):
    return _PushBuild(_ref(reference), num_total_row)


def dataset_push_rows(h, ptr, data_type, nrow, ncol, start_row):
    X = _wrap(ptr, nrow * ncol, data_type).reshape(nrow, ncol)
    h.push(np.asarray(X, np.float64), start_row)
    return True


def dataset_push_rows_by_csr(h, indptr_ptr, indptr_type, indices_ptr,
                             data_ptr, data_type, nindptr, nelem, num_col,
                             start_row):
    X = _csr_from_ptrs(indptr_ptr, indptr_type, indices_ptr, data_ptr,
                       data_type, nindptr, nelem, num_col)
    h.push(np.asarray(X.todense(), np.float64), start_row)
    return True


def booster_update_one_iter_custom(bst, grad_ptr, hess_ptr):
    """(ref: c_api.cpp:581 LGBM_BoosterUpdateOneIterCustom — the custom-
    objective path every binding's fobj support crosses)."""
    g = bst._gbdt
    k = max(1, bst.num_tree_per_iteration)
    n = int(g.num_data)
    grad = _wrap(grad_ptr, k * n, 0).copy()
    hess = _wrap(hess_ptr, k * n, 0).copy()
    bst._model_version += 1   # cached device predictors must re-stack
    return int(bool(bst._Booster__boost(grad, hess)))


def booster_dump_model(bst, start_iteration, num_iteration,
                       feature_importance_type):
    from .io import model_io
    bst._drain()
    return model_io.dump_model_json(bst, start_iteration,
                                    num_iteration if num_iteration != 0
                                    else -1,
                                    importance_type=feature_importance_type)


_FIELD_TYPE = {"label": 0, "weight": 0, "group": 2, "init_score": 1}
_FIELD_NP = {0: np.float32, 1: np.float64, 2: np.int32, 3: np.int64}


def dataset_get_field(ds, name):
    """Returns (ptr, num_element, type_code); the array is pinned on the
    handle so the pointer stays valid until DatasetFree (the reference
    returns pointers into Metadata the same way)."""
    ds = _resolve_ds(ds)
    vals = ds.get_field(name)
    if vals is None:
        return 0, 0, _FIELD_TYPE.get(name, 0)
    tc = _FIELD_TYPE[name]
    arr = np.ascontiguousarray(np.asarray(vals), dtype=_FIELD_NP[tc])
    if not hasattr(ds, "_capi_field_pins"):
        ds._capi_field_pins = {}
    ds._capi_field_pins[name] = arr
    return int(arr.ctypes.data), int(arr.size), tc


def dataset_get_feature_names(ds):
    ds = _resolve_ds(ds)
    ds.construct()
    names = ds._inner.feature_names
    if not names:
        names = [f"Column_{i}"
                 for i in range(ds._inner.num_total_features)]
    return list(names)


def dataset_set_feature_names(ds, names):
    ds = _resolve_ds(ds)
    ds.construct()
    names = list(names)
    if len(names) != ds._inner.num_total_features:
        raise ValueError(
            f"got {len(names)} feature names for "
            f"{ds._inner.num_total_features} features")
    ds._inner.feature_names = names
    ds.feature_name = names
    return True


def booster_predict_for_csc(bst, colptr_ptr, colptr_type, indices_ptr,
                            data_ptr, data_type, ncolptr, nelem, num_row,
                            predict_type, start_iteration, num_iteration,
                            parameter, out_ptr):
    X = _sparse_from_ptrs("csc", colptr_ptr, colptr_type, indices_ptr,
                          data_ptr, data_type, ncolptr, nelem, num_row)
    return _predict_to_buffer(bst, X.tocsr(), predict_type,
                              start_iteration, num_iteration, out_ptr)


# sparse prediction results pinned until LGBM_BoosterFreePredictSparse
# (keyed by the indptr address the C caller hands back)
_SPARSE_PINS = {}


def booster_predict_sparse_contribs(bst, indptr_ptr, indptr_type,
                                    indices_ptr, data_ptr, data_type,
                                    nindptr, nelem, num_col,
                                    start_iteration, num_iteration):
    """CSR-input SHAP contributions with CSR OUTPUT (ref: c_api.cpp:845
    LGBM_BoosterPredictSparseOutput, matrix_type=CSR). Returns
    (nindptr_out, nnz, indptr_addr, indices_addr, data_addr), pinned
    until freed. Per the reference contract, the OUTPUT indptr/data
    buffers use the caller's indptr_type/data_type (multiclass output
    is one concatenated [n, k*(F+1)] CSR)."""
    import scipy.sparse as sp
    X = _csr_from_ptrs(indptr_ptr, indptr_type, indices_ptr, data_ptr,
                       data_type, nindptr, nelem, num_col)
    dense = np.asarray(_run_predict(bst, X, 3, start_iteration,
                                    num_iteration), np.float64)
    dense = dense.reshape(X.shape[0], -1)   # [n, k*(F+1)]
    out = sp.csr_matrix(dense)
    indptr = np.ascontiguousarray(out.indptr, _FIELD_NP[indptr_type]
                                  if indptr_type in (2, 3) else np.int64)
    indices = np.ascontiguousarray(out.indices, np.int32)
    data = np.ascontiguousarray(out.data, _FIELD_NP[data_type]
                                if data_type in (0, 1) else np.float64)
    key = int(indptr.ctypes.data)
    _SPARSE_PINS[key] = (indptr, indices, data)
    return (int(indptr.size), int(data.size), key,
            int(indices.ctypes.data), int(data.ctypes.data))


def booster_free_predict_sparse(indptr_addr):
    _SPARSE_PINS.pop(int(indptr_addr), None)
    return True


def booster_merge(bst, other):
    """(ref: gbdt.h:63 MergeFrom — other's trees are PREPENDED and become
    the init segment; training scores are not replayed, matching the
    reference, so merge is a prediction-surface operation)."""
    bst._drain()
    other._drain()
    if getattr(bst, "_gbdt", None) is not None:
        # string-loaded trees carry raw-value thresholds only; the live
        # driver's device bookkeeping (score replay, rollback indexing)
        # needs binned thresholds per tree — refuse rather than corrupt
        raise ValueError(
            "BoosterMerge into a booster with live training state is not "
            "supported; merge into a model-file/string booster")
    from .io import model_io
    cloned = model_io.parse_model_string(
        other.model_to_string(num_iteration=-1))[1]
    bst.models[:0] = cloned
    bst._model_version += 1
    return True


# ------------------------------------------------- round-4 tranche 4
# (booster lifecycle/string IO breadth — ref: c_api.h:313-1310)
def booster_save_model_to_string(bst, start_iteration, num_iteration,
                                 feature_importance_type):
    bst._drain()
    return bst.model_to_string(
        start_iteration=start_iteration,
        num_iteration=(num_iteration if num_iteration != 0 else -1),
        importance_type=("gain" if feature_importance_type == 1
                         else "split"))


def booster_load_model_from_string(model_str):
    _ensure_backend()
    bst = Booster(model_str=model_str)
    return bst, bst.current_iteration()


def booster_get_feature_names(bst):
    return list(bst.feature_name())


def booster_num_model_per_iteration(bst):
    return int(max(1, bst.num_tree_per_iteration))


def booster_number_of_total_model(bst):
    return int(bst.num_trees())


def booster_get_lower_bound_value(bst):
    """(ref: gbdt.cpp:678 GetLowerBoundValue — sum of per-tree minima)"""
    bst._drain()
    return float(sum(float(np.min(ht.leaf_value)) for ht in bst.models))


def booster_get_upper_bound_value(bst):
    bst._drain()
    return float(sum(float(np.max(ht.leaf_value)) for ht in bst.models))


def booster_reset_parameter(bst, parameters):
    bst.reset_parameter(_parse_params(parameters))
    return True


def booster_shuffle_models(bst, start_iter, end_iter):
    """(ref: gbdt.h:82 ShuffleModels — Fisher-Yates over iteration blocks
    with the reference's Random(17) stream; a live booster's device-tree
    list rides the same permutation so score replay stays aligned)"""
    from .utils import random as ref_random
    bst._drain()
    k = max(1, bst.num_tree_per_iteration)
    total_iter = len(bst.models) // k
    start_iter = max(0, start_iter)
    end_iter = total_iter if end_iter <= 0 else min(total_iter, end_iter)
    idx = list(range(total_iter))
    rand = ref_random.Random(17)
    for i in range(start_iter, end_iter - 1):
        j = rand.next_short(i + 1, end_iter)
        idx[i], idx[j] = idx[j], idx[i]
    perm = [it * k + j for it in idx for j in range(k)]
    bst.models[:] = [bst.models[i] for i in perm]
    g = getattr(bst, "_gbdt", None)
    if g is not None and len(g.device_trees) == len(perm):
        g.device_trees[:] = [g.device_trees[i] for i in perm]
    bst._model_version += 1
    return True


def booster_predict_for_mats(bst, row_ptrs_addr, data_type, nrow, ncol,
                             predict_type, start_iteration, num_iteration,
                             parameter, out_ptr):
    """(ref: c_api.h:1185 LGBM_BoosterPredictForMats — one pointer per
    row)"""
    ptrs = _wrap(row_ptrs_addr, nrow, 3)   # void* array as int64
    X = np.empty((nrow, ncol), np.float64)
    for i in range(nrow):
        X[i] = _wrap(int(ptrs[i]), ncol, data_type)
    return _predict_to_buffer(bst, X, predict_type, start_iteration,
                              num_iteration, out_ptr)


def dataset_get_subset(ds, indices_ptr, num_indices, parameters):
    ds = _resolve_ds(ds)
    idx = _wrap(indices_ptr, num_indices, 2).copy()
    # reference CHECKs: indices in range and sorted (c_api.cpp
    # LGBM_DatasetGetSubset); numpy would wrap a -1 to the LAST row and
    # silently train on corrupt data otherwise
    n = dataset_num_data(ds)
    if idx.size == 0:
        raise ValueError("used_row_indices is empty")
    if int(idx.min()) < 0 or int(idx.max()) >= n:
        raise ValueError(
            f"used_row_indices out of range [0, {n})")
    if np.any(np.diff(idx) < 0):
        raise ValueError("used_row_indices must be sorted")
    sub = ds.subset(idx, params=_parse_params(parameters))
    sub.construct()
    return sub


# dataset-defining params that cannot change between construction and a
# later consumer (ref: c_api.cpp LGBM_DatasetUpdateParamChecking ->
# Dataset::ValidateParams-class checks)
_DS_PARAMS = ("max_bin", "max_bin_by_feature", "bin_construct_sample_cnt",
              "min_data_in_bin", "use_missing", "zero_as_missing",
              "enable_bundle", "data_random_seed", "min_data_in_leaf",
              "linear_tree")


def dataset_update_param_checking(old_parameters, new_parameters):
    """Error iff a dataset-defining param RESOLVES differently under the
    new string (the reference builds Configs from both strings so
    defaults, aliases, and value normalization are applied before the
    compare — a new param explicitly set to the old/default value is
    fine)."""
    from .config import Config
    old_cfg = Config(_parse_params(old_parameters))
    new_cfg = Config(_parse_params(new_parameters))
    changed = getattr(new_cfg, "_user_set", set())
    for key in _DS_PARAMS:
        if key in changed and getattr(old_cfg, key, None) \
                != getattr(new_cfg, key, None):
            raise ValueError(
                f"Cannot change {key} after constructed Dataset handle")
    return True


class _FastConfig:
    """Preallocated single-row predict state (ref: c_api.cpp:939-1156
    FastConfigHandle — parse params/alloc once, then per-call predicts
    touch only the row buffer)."""

    def __init__(self, bst, predict_type, start_iteration, num_iteration,
                 data_type, ncol):
        self.bst = bst
        self.predict_type = predict_type
        self.start_iteration = start_iteration
        self.num_iteration = num_iteration
        self.data_type = data_type
        self.ncol = ncol
        self.row = np.zeros((1, ncol), np.float64)


def fast_config_create(bst, predict_type, start_iteration, num_iteration,
                       data_type, ncol, parameter):
    return _FastConfig(bst, predict_type, start_iteration, num_iteration,
                       data_type, ncol)


def predict_single_row_fast(cfg, data_ptr, out_ptr):
    cfg.row[0, :] = _wrap(data_ptr, cfg.ncol, cfg.data_type)
    return _predict_to_buffer(cfg.bst, cfg.row, cfg.predict_type,
                              cfg.start_iteration, cfg.num_iteration,
                              out_ptr)


# ------------------------------------------------- round-5 tranche 5
# (final 20 symbols to 78/78 — VERDICT r4 missing #1: booster lifecycle
# over the ABI, sampling helpers, multi-mat/sampled-column dataset
# creation, CSR single-row fast paths, log/network injection hooks —
# ref: include/LightGBM/c_api.h, src/c_api.cpp)
def get_sample_count(num_total_row, parameters):
    """(ref: c_api.cpp LGBM_GetSampleCount — min(bin_construct_sample_cnt,
    num_total_row))"""
    from .config import Config
    c = Config(_parse_params(parameters))
    return int(min(int(c.bin_construct_sample_cnt), int(num_total_row)))


def sample_indices(num_total_row, parameters, out_ptr):
    """(ref: c_api.cpp LGBM_SampleIndices ->
    Random(data_random_seed).Sample — the same LCG stream
    utils/random.py reproduces bit-for-bit)"""
    from .config import Config
    from .utils import random as ref_random
    c = Config(_parse_params(parameters))
    k = min(int(c.bin_construct_sample_cnt), int(num_total_row))
    idx = ref_random.Random(int(c.data_random_seed)).sample(
        int(num_total_row), k)
    arr = np.asarray(idx, np.int32)
    out = _wrap(out_ptr, arr.size, 2)
    out[:] = arr
    return int(arr.size)


def dump_param_aliases():
    """JSON {param: [aliases...]} from the config registry
    (ref: c_api.cpp:62 LGBM_DumpParamAliases -> Config::DumpAliases)."""
    import json
    from .config import _PARAMS
    out = {p.name: list(p.aliases) for p in _PARAMS}
    return json.dumps(out, indent=1)


def register_log_callback(cb_addr):
    """(ref: c_api.cpp:903 LGBM_RegisterLogCallback) Route every log line
    through a C ``void(const char*)`` callback."""
    from .utils import log as _log
    if not cb_addr:
        _log.register_logger(None)
        _CALLBACK_PINS.pop("log", None)
        return True
    cfn = ctypes.CFUNCTYPE(None, ctypes.c_char_p)(cb_addr)
    _CALLBACK_PINS["log"] = cfn     # keep the ctypes thunk alive

    def _redirect(msg):
        cfn(str(msg).encode("utf-8", "replace"))
    _log.register_logger(_redirect)
    return True


_CALLBACK_PINS = {}


def booster_get_linear(bst):
    if getattr(bst, "config", None) is not None:
        return int(bool(bst.config.linear_tree))
    bst._drain()
    return int(any(getattr(t, "is_linear", False) for t in bst.models))


def booster_feature_importance(bst, num_iteration, importance_type,
                               out_ptr):
    """(ref: c_api.cpp:2289 — caller allocates num_feature doubles)"""
    vals = np.asarray(bst.feature_importance(
        "split" if importance_type == 0 else "gain",
        iteration=(num_iteration if num_iteration > 0 else None)),
        np.float64)
    out = _wrap(out_ptr, vals.size, 1)
    out[:] = vals
    return int(vals.size)


def booster_get_num_predict(bst, data_idx):
    """(ref: gbdt.h:200 GetNumPredictAt — num_data * num_class of the
    indexed dataset)"""
    g = getattr(bst, "_gbdt", None)
    if g is None:
        raise ValueError("booster has no training data attached")
    if data_idx == 0:
        n = int(g.num_data)
    else:
        vi = data_idx - 1
        if vi >= len(g.valid_data):
            raise IndexError(f"no validation set {vi}")
        n = int(g.valid_data[vi].num_data)
    return n * max(1, bst.num_class)


def booster_get_predict(bst, data_idx, out_ptr):
    """Inner (transformed) predictions for train/valid data
    (ref: gbdt.cpp:633 GetPredictAt — raw scores through the objective's
    ConvertOutput, [class, row] layout)."""
    bst._drain()
    g = bst._gbdt
    if data_idx == 0:
        score = g.scores
    else:
        vi = data_idx - 1
        if vi >= len(g.valid_scores):
            raise IndexError(f"no validation set {vi}")
        score = g.valid_scores[vi]
    raw = np.asarray(score, np.float64)          # [k, n]
    if g.objective is not None:
        if bst.num_class > 1:
            vals = np.asarray(g.objective.convert_output(raw.T),
                              np.float64).T      # softmax over classes
        else:
            vals = np.asarray(g.objective.convert_output(raw[0]),
                              np.float64).reshape(1, -1)
    else:
        vals = raw
    flat = vals.reshape(-1)
    out = _wrap(out_ptr, flat.size, 1)
    out[:] = flat
    return int(flat.size)


def booster_refit(bst, leaf_preds_ptr, nrow, ncol):
    lp = _wrap(leaf_preds_ptr, nrow * ncol, 2).reshape(nrow, ncol)
    bst.refit_by_leaf_preds(lp)
    return True


def booster_reset_training_data(bst, train_ds):
    bst.reset_training_data(_resolve_ds(train_ds))
    return True


def dataset_add_features_from(target, source):
    """(ref: c_api.cpp:1553 LGBM_DatasetAddFeaturesFrom)"""
    _resolve_ds(target).add_features_from(_resolve_ds(source))
    return True


def dataset_dump_text(ds, filename):
    """(ref: c_api.cpp LGBM_DatasetDumpText -> dataset.cpp:1063
    DumpTextFile — header then per-row BINNED values, the debugging
    surface)."""
    ds = _resolve_ds(ds)
    ds.construct()
    inner = ds._inner
    bins = np.asarray(inner.bins)
    # sparse-built datasets store EFB BUNDLE columns, not per-feature
    # bins — say so in the header instead of dumping rows that contradict
    # the feature count
    bundled = getattr(inner, "prebundled", None) is not None
    with open(filename, "w") as fh:
        fh.write(f"num_features: {inner.num_features}\n")
        fh.write(f"num_total_features: {inner.num_total_features}\n")
        fh.write(f"num_data: {inner.num_data}\n")
        names = inner.feature_names or [
            f"Column_{i}" for i in range(inner.num_total_features)]
        fh.write("feature_names: " + ", ".join(names) + "\n")
        if bundled:
            fh.write(f"storage: EFB bundle columns "
                     f"(num_bundles: {bins.shape[1]}; rows below are "
                     f"bundle-offset-encoded, not per-feature bins)\n")
        for r in range(inner.num_data):
            fh.write(" ".join(str(int(b)) for b in bins[r]) + "\n")
    return True


def dataset_create_from_mats(nmat, ptrs_addr, data_type, nrows_ptr, ncol,
                             is_row_major, parameters, reference):
    """(ref: c_api.cpp:1090 LGBM_DatasetCreateFromMats — vertically
    stacked matrices, one pointer + row count each)"""
    _ensure_backend()
    ptrs = _wrap(ptrs_addr, nmat, 3)            # void* array as int64
    nrows = _wrap(nrows_ptr, nmat, 2)
    parts = []
    for i in range(nmat):
        arr = _wrap(int(ptrs[i]), int(nrows[i]) * ncol, data_type)
        X = arr.reshape(int(nrows[i]), ncol) if is_row_major else \
            arr.reshape(ncol, int(nrows[i])).T
        parts.append(np.array(X, np.float64))
    return Dataset(np.concatenate(parts, axis=0),
                   params=_parse_params(parameters),
                   reference=_ref(reference))


def dataset_create_from_sampled_column(sample_data_addr, sample_idx_addr,
                                       ncol, num_per_col_ptr,
                                       num_sample_row, num_total_row,
                                       parameters):
    """(ref: c_api.cpp LGBM_DatasetCreateFromSampledColumn ->
    DatasetLoader::ConstructFromSampleData): bin mappers are built from
    the per-column samples; the returned handle is an empty
    ``num_total_row``-row dataset to be filled by LGBM_DatasetPushRows*.
    The sample matrix is reconstructed dense (absent entries are 0 — the
    reference's sparse sample semantics) and binned by the same
    GreedyFindBin the reference applies to the sample."""
    _ensure_backend()
    data_ptrs = _wrap(sample_data_addr, ncol, 3)     # double* per column
    idx_ptrs = _wrap(sample_idx_addr, ncol, 3)       # int* per column
    per_col = _wrap(num_per_col_ptr, ncol, 2)
    sample = np.zeros((num_sample_row, ncol), np.float64)
    for j in range(ncol):
        cnt = int(per_col[j])
        if cnt == 0:
            continue
        vals = _wrap(int(data_ptrs[j]), cnt, 1)
        rows = _wrap(int(idx_ptrs[j]), cnt, 2)
        sample[rows, j] = vals
    params = _parse_params(parameters)
    # pre-binned mapper source: the sample dataset IS the reference whose
    # mappers the pushed rows are binned with
    mapper_src = Dataset(sample, params=params)
    mapper_src.construct()
    return _PushBuild(mapper_src, num_total_row)


def fast_config_create_csr(bst, predict_type, start_iteration,
                           num_iteration, data_type, num_col, parameter):
    """CSR single-row fast state reuses _FastConfig (same fields; the
    row width is the declared num_col) — ref: c_api.cpp:939
    LGBM_BoosterPredictForCSRSingleRowFastInit."""
    return _FastConfig(bst, predict_type, start_iteration, num_iteration,
                       data_type, int(num_col))


def predict_single_row_fast_csr(cfg, indptr_ptr, indptr_type, indices_ptr,
                                data_ptr, nindptr, nelem, out_ptr):
    indptr = _wrap(indptr_ptr, nindptr, indptr_type)
    # honor the row's slice [indptr[0], indptr[1]) — a caller may pass a
    # view into a larger CSR matrix (the reference's RowFunctionFromCSR
    # iterates exactly this window)
    lo, hi = int(indptr[0]), int(indptr[1])
    cfg.row[:] = 0.0
    if hi > lo:
        idx = _wrap(indices_ptr, nelem, 2)[lo:hi]
        vals = _wrap(data_ptr, nelem, cfg.data_type)[lo:hi]
        cfg.row[0, idx] = vals
    return _predict_to_buffer(cfg.bst, cfg.row, cfg.predict_type,
                              cfg.start_iteration, cfg.num_iteration,
                              out_ptr)


def booster_predict_for_csr_single_row(bst, indptr_ptr, indptr_type,
                                       indices_ptr, data_ptr, data_type,
                                       nindptr, nelem, num_col,
                                       predict_type, start_iteration,
                                       num_iteration, parameter, out_ptr):
    cfg = _FastConfig(bst, predict_type, start_iteration, num_iteration,
                      data_type, int(num_col))
    return predict_single_row_fast_csr(cfg, indptr_ptr, indptr_type,
                                       indices_ptr, data_ptr, nindptr,
                                       nelem, out_ptr)


def network_init_with_functions(num_machines, rank, reduce_scatter_addr,
                                allgather_addr):
    """(ref: c_api.h:1336 LGBM_NetworkInitWithFunctions — the external
    collective-injection hook SynapseML-style embedders use)."""
    from .parallel import extnet
    extnet.init_with_functions(int(num_machines), int(rank),
                               int(reduce_scatter_addr),
                               int(allgather_addr))
    return True
