"""Python side of the C ABI (native/capi.cpp).

The C layer passes raw buffer addresses and scalar metadata; this module
wraps them with numpy (zero-copy via ctypes) and drives the normal
package objects. Handles crossing the ABI are ordinary Python objects
whose references the C layer owns (Py_DECREF on *Free).

Field/data type codes follow the reference C API
(ref: include/LightGBM/c_api.h: C_API_DTYPE_FLOAT32=0, FLOAT64=1,
INT32=2, INT64=3; predict types: NORMAL=0, RAW_SCORE=1, LEAF_INDEX=2,
CONTRIB=3).
"""
from __future__ import annotations

import ctypes

import numpy as np

from .basic import Booster, Dataset

_DTYPES = {0: np.float32, 1: np.float64, 2: np.int32, 3: np.int64}


def _wrap(ptr: int, count: int, type_code: int) -> np.ndarray:
    dt = np.dtype(_DTYPES[type_code])
    buf = (ctypes.c_char * (count * dt.itemsize)).from_address(ptr)
    return np.frombuffer(buf, dtype=dt)


def _parse_params(parameters: str) -> dict:
    out = {}
    for tok in parameters.replace("\t", " ").split():
        if "=" in tok:
            k, v = tok.split("=", 1)
            out[k] = v
    return out


# ---------------------------------------------------------------- dataset
def dataset_create_from_mat(ptr, data_type, nrow, ncol, is_row_major,
                            parameters, reference):
    if not ptr or nrow <= 0 or ncol <= 0:
        raise ValueError("DatasetCreateFromMat: data pointer is null or "
                         f"shape ({nrow}, {ncol}) is empty")
    arr = _wrap(ptr, nrow * ncol, data_type)
    X = arr.reshape(nrow, ncol) if is_row_major else \
        arr.reshape(ncol, nrow).T
    # COPY before returning: the reference's CreateFromMat owns its data
    # from this point on, and Dataset.construct() runs lazily — a view
    # would read caller memory that may already be freed
    ds = Dataset(np.array(X, copy=True),
                 params=_parse_params(parameters),
                 reference=reference if isinstance(reference, Dataset)
                 else None)
    return ds


def dataset_set_field(ds, name, ptr, num_element, type_code):
    vals = _wrap(ptr, num_element, type_code).copy()
    if name == "label":
        ds.set_label(vals)
    elif name == "weight":
        ds.set_weight(vals)
    elif name in ("group", "query"):
        ds.set_group(vals.astype(np.int64))
    elif name == "init_score":
        ds.init_score = vals
        if ds._inner is not None:
            ds._inner.metadata.set_init_score(vals)
    else:
        raise ValueError(f"unknown field name {name!r}")
    return True


def dataset_num_data(ds):
    ds.construct()
    return int(ds._inner.num_data)


def dataset_num_feature(ds):
    ds.construct()
    return int(ds._inner.num_total_features)


# ---------------------------------------------------------------- booster
def booster_create(train_ds, parameters):
    return Booster(params=_parse_params(parameters), train_set=train_ds)


def booster_from_modelfile(filename):
    bst = Booster(model_file=filename)
    return bst, bst.current_iteration()


def booster_add_valid(bst, valid_ds):
    bst.add_valid(valid_ds, f"valid_{len(bst.valid_sets)}")
    return True


def booster_update(bst):
    return int(bool(bst.update()))


def booster_current_iteration(bst):
    return int(bst.current_iteration())


def booster_num_classes(bst):
    return int(bst.num_class)


def booster_calc_num_predict(bst, num_row, predict_type, start_iteration,
                             num_iteration):
    """(ref: c_api.cpp LGBM_BoosterCalcNumPredict semantics)"""
    k = max(1, bst.num_tree_per_iteration)
    total_iter = bst.num_trees() // k
    if num_iteration <= 0:
        num_iteration = total_iter - start_iteration
    num_iteration = max(0, min(num_iteration, total_iter - start_iteration))
    if predict_type == 2:      # leaf index: one value per tree
        return int(num_row * num_iteration * k)
    if predict_type == 3:      # contrib: per feature + bias, per class
        return int(num_row * k * (bst.num_feature() + 1))
    return int(num_row * max(1, bst.num_class))


def booster_predict_for_mat(bst, ptr, data_type, nrow, ncol, is_row_major,
                            predict_type, start_iteration, num_iteration,
                            parameter, out_ptr):
    arr = _wrap(ptr, nrow * ncol, data_type)
    X = arr.reshape(nrow, ncol) if is_row_major else \
        arr.reshape(ncol, nrow).T
    kwargs = dict(start_iteration=start_iteration,
                  num_iteration=(num_iteration if num_iteration > 0
                                 else None))
    if predict_type == 1:
        pred = bst.predict(X, raw_score=True, **kwargs)
    elif predict_type == 2:
        pred = bst.predict(X, pred_leaf=True, **kwargs)
    elif predict_type == 3:
        pred = bst.predict(X, pred_contrib=True, **kwargs)
    else:
        pred = bst.predict(X, **kwargs)
    flat = np.asarray(pred, np.float64).reshape(-1)
    out = _wrap(out_ptr, flat.size, 1)
    out[:] = flat
    return int(flat.size)


def booster_save_model(bst, start_iteration, num_iteration,
                       feature_importance_type, filename):
    bst.save_model(filename, start_iteration=start_iteration,
                   num_iteration=num_iteration,
                   importance_type=("gain" if feature_importance_type == 1
                                    else "split"))
    return True
