"""lightgbm_tpu: a TPU-native gradient boosting framework.

A from-scratch re-design of the LightGBM feature surface
(ref: /root/reference, keisho-oh/LightGBM v3.3.1.99) for TPU hardware:
jit-compiled JAX/XLA histogram + split kernels, tree growth without host
round trips, and XLA collectives over a device mesh in place of the
socket/MPI network layer.
"""
from .basic import Booster, Dataset, Sequence
from .callback import (EarlyStopException, early_stopping, log_evaluation,
                       record_evaluation, record_telemetry, reset_parameter)
from .config import Config
from .engine import CVBooster, cv, train
from .sklearn import LGBMClassifier, LGBMModel, LGBMRanker, LGBMRegressor
from .utils.log import LightGBMError, register_logger
from . import ingest, serve
from .serve import PredictionService

try:  # plotting needs matplotlib (optional)
    from .plotting import (create_tree_digraph, plot_importance, plot_metric,
                           plot_split_value_histogram, plot_tree)
    _PLOT = ["plot_importance", "plot_metric", "plot_split_value_histogram",
             "plot_tree", "create_tree_digraph"]
except ImportError:  # pragma: no cover
    _PLOT = []

__version__ = "0.1.0"

__all__ = [
    "Dataset", "Booster", "Sequence", "Config", "CVBooster",
    "train", "cv",
    "LGBMModel", "LGBMRegressor", "LGBMClassifier", "LGBMRanker",
    "early_stopping", "log_evaluation", "record_evaluation",
    "record_telemetry", "reset_parameter", "EarlyStopException",
    "register_logger", "LightGBMError", "serve", "PredictionService",
    "ingest",
] + _PLOT
