"""Sharded binary dataset cache — the v2 ``LGBMTPU2`` artifact.

Single-file-per-rank layout, written streaming (O(chunk) writer RSS) and
atomically (resilience/atomicio.py write-then-rename), mmap-able on
reload:

    [8 B magic "LGBMTPU2"]
    [packed bin matrix, C-order uint8/uint16  [num_data, num_used]]
    [metadata pickle (mappers, label, weight, queries, init_score, ...)]
    [manifest JSON]
    [8 B little-endian uint64: manifest length][8 B magic "LGBMTPU2"]

The manifest travels at the TAIL so the whole artifact is produced in
one forward streaming pass (bins are hashed as they are appended — no
seek-back), yet a reader finds it in one 16-byte footer read.  It
records the format version, region offsets/sizes, SHA-256 of the bins
and metadata regions, the mapper digest, the producing rank/world, and
an optional source-file fingerprint — so corruption, truncation,
version skew, and rank-layout mismatches are all REFUSED with a
structured :class:`CacheError` instead of silently training on bad
bins.  Reloading mmaps the bins region read-only: a cache-hit startup
does zero text parsing, zero binning, and zero bulk host allocation
(the OS pages bins in as the device prefetcher streams them up).

Analog of ref: src/io/dataset_loader.cpp:336 LoadFromBinFile /
Dataset::SaveBinaryFile, extended with the hash manifest and per-rank
sharding (``cache_shard_path``) the multiproc launcher routes through.
"""
from __future__ import annotations

import hashlib
import json
import os
import pickle
import struct
import time
from typing import Any, Dict, Optional

import numpy as np

from ..resilience.atomicio import atomic_stream
from ..utils import log

CACHE_MAGIC = b"LGBMTPU2"
CACHE_FORMAT_VERSION = 2
CACHE_SCHEMA = "lightgbm_tpu.dataset_cache"
_FOOTER = struct.Struct("<Q8s")
_HASH_BLOCK = 1 << 22          # 4 MB streaming-hash read block


class CacheError(Exception):
    """A binary dataset cache that must not be used: corrupt, truncated,
    version-mismatched, or written for a different rank layout."""


def cache_shard_path(path: str, rank: int = 0, world: int = 1) -> str:
    """Per-rank shard file name: the bare path for single-process, a
    ``.rank<r>of<w>`` suffix under a multi-process layout (each rank
    caches only its contiguous row slice)."""
    if world <= 1:
        return str(path)
    return f"{path}.rank{int(rank)}of{int(world)}"


def source_fingerprint(path: str, params_digest: str = "") -> Dict[str, Any]:
    """Identity of the text file a cache was built from: size + mtime +
    the dataset-defining-params digest.  An auto-maintained sidecar
    cache (``save_binary=true``) is a HIT only when all three match."""
    st = os.stat(path)
    return {"path": os.path.abspath(str(path)), "size": int(st.st_size),
            "mtime_ns": int(st.st_mtime_ns),
            "params_digest": params_digest}


class CacheWriter:
    """Streaming cache writer: ``append_rows`` packed-bin chunks in row
    order, then ``finalize`` with the metadata dict.  Everything lands
    in an atomic temp sibling; a crash (or ``abort``) before finalize
    leaves the destination untouched."""

    def __init__(self, path: str, num_data: int, num_total_features: int,
                 used_features, bin_dtype, rank: int = 0, world: int = 1,
                 source: Optional[Dict[str, Any]] = None,
                 fsync: bool = True):
        self.path = str(path)
        self.num_data = int(num_data)
        self.num_total_features = int(num_total_features)
        self.used_features = list(used_features)
        self.dtype = np.dtype(bin_dtype)
        self.rank, self.world = int(rank), int(world)
        self.source = source
        self.rows_written = 0
        self.chunks_written = 0
        self._bins_hash = hashlib.sha256()
        self._cm = atomic_stream(self.path, fsync=fsync)
        self._fh = self._cm.__enter__()
        self._fh.write(CACHE_MAGIC)
        self._done = False

    def append_rows(self, packed: np.ndarray) -> None:
        if self._done:
            raise CacheError("cache writer already finalized")
        if packed.dtype != self.dtype or packed.ndim != 2 \
                or packed.shape[1] != len(self.used_features):
            raise CacheError(
                f"chunk shape/dtype {packed.shape}/{packed.dtype} does "
                f"not match the declared "
                f"[*, {len(self.used_features)}] {self.dtype}")
        if self.rows_written + packed.shape[0] > self.num_data:
            raise CacheError(
                f"cache overflow: {self.rows_written + packed.shape[0]} "
                f"rows pushed into a {self.num_data}-row artifact")
        buf = np.ascontiguousarray(packed).tobytes()
        self._bins_hash.update(buf)
        self._fh.write(buf)
        self.rows_written += packed.shape[0]
        self.chunks_written += 1

    def finalize(self, meta: Dict[str, Any], mappers_digest: str = "",
                 extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Write metadata + manifest + footer, fsync, rename into place.
        ``extra`` merges additional manifest fields (e.g. the
        reference-binned provenance flag). Returns the manifest."""
        if self._done:
            raise CacheError("cache writer already finalized")
        if self.rows_written != self.num_data:
            raise CacheError(
                f"cache underflow: {self.rows_written} of "
                f"{self.num_data} rows written")
        meta_bytes = pickle.dumps(meta, protocol=4)
        bins_nbytes = self.num_data * len(self.used_features) \
            * self.dtype.itemsize
        manifest = {
            "format_version": CACHE_FORMAT_VERSION,
            "schema": CACHE_SCHEMA,
            "num_data": self.num_data,
            "num_used_features": len(self.used_features),
            "num_total_features": self.num_total_features,
            "bin_dtype": self.dtype.name,
            "bins_offset": len(CACHE_MAGIC),
            "bins_nbytes": bins_nbytes,
            "meta_offset": len(CACHE_MAGIC) + bins_nbytes,
            "meta_nbytes": len(meta_bytes),
            "bins_sha256": self._bins_hash.hexdigest(),
            "meta_sha256": hashlib.sha256(meta_bytes).hexdigest(),
            "mappers_digest": mappers_digest,
            "rank": self.rank, "world": self.world,
            "chunks": self.chunks_written,
            "source": self.source,
            "created": round(time.time(), 3),
        }
        manifest.update(extra or {})
        mf = json.dumps(manifest, sort_keys=True).encode("utf-8")
        self._fh.write(meta_bytes)
        self._fh.write(mf)
        self._fh.write(_FOOTER.pack(len(mf), CACHE_MAGIC))
        self._cm.__exit__(None, None, None)      # fsync + rename
        self._done = True
        return manifest

    def abort(self) -> None:
        """Discard the temp artifact (destination stays untouched)."""
        if self._done:
            return
        self._done = True
        exc = CacheError("cache write aborted")
        self._cm.__exit__(CacheError, exc, None)


# --------------------------------------------------------------- reading
def read_manifest(path: str) -> Dict[str, Any]:
    """Footer -> manifest dict; raises CacheError on any structural
    problem (short file, bad magic, unparseable manifest, version or
    schema skew)."""
    try:
        size = os.path.getsize(path)
    except OSError as e:
        raise CacheError(f"cannot stat cache {path}: {e}")
    if size < len(CACHE_MAGIC) + _FOOTER.size:
        raise CacheError(f"{path}: too short to be a dataset cache "
                         f"({size} bytes)")
    with open(path, "rb") as fh:
        if fh.read(8) != CACHE_MAGIC:
            raise CacheError(f"{path}: bad cache magic")
        fh.seek(size - _FOOTER.size)
        mf_len, tail_magic = _FOOTER.unpack(fh.read(_FOOTER.size))
        if tail_magic != CACHE_MAGIC:
            raise CacheError(f"{path}: truncated cache (footer magic "
                             "missing — the write never finalized)")
        if mf_len <= 0 or mf_len > size - _FOOTER.size - len(CACHE_MAGIC):
            raise CacheError(f"{path}: corrupt manifest length {mf_len}")
        fh.seek(size - _FOOTER.size - mf_len)
        raw = fh.read(mf_len)
    try:
        manifest = json.loads(raw.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise CacheError(f"{path}: corrupt manifest JSON: {e}")
    ver = manifest.get("format_version")
    if ver != CACHE_FORMAT_VERSION:
        raise CacheError(
            f"{path}: cache format version {ver} != supported "
            f"{CACHE_FORMAT_VERSION} — rebuild the cache from the text "
            "source (task=save_binary)")
    if manifest.get("schema") != CACHE_SCHEMA:
        raise CacheError(f"{path}: unknown cache schema "
                         f"{manifest.get('schema')!r}")
    expect_end = manifest["meta_offset"] + manifest["meta_nbytes"] \
        + mf_len + _FOOTER.size
    if expect_end != size:
        raise CacheError(
            f"{path}: size {size} does not match manifest layout "
            f"({expect_end}) — truncated or corrupt")
    return manifest


def _verify_region(path: str, offset: int, nbytes: int, expect: str,
                   what: str) -> None:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        fh.seek(offset)
        left = nbytes
        while left > 0:
            block = fh.read(min(_HASH_BLOCK, left))
            if not block:
                raise CacheError(f"{path}: {what} region truncated")
            h.update(block)
            left -= len(block)
    if h.hexdigest() != expect:
        raise CacheError(
            f"{path}: {what} hash mismatch (expected {expect[:12]}…, "
            f"got {h.hexdigest()[:12]}…) — the cache is corrupt; delete "
            "it and rebuild from the text source")


def load_dataset_cache(path: str, verify: bool = True, mmap: bool = True,
                       expect_rank: Optional[int] = None,
                       expect_world: Optional[int] = None):
    """Cache file -> TpuDataset.  ``verify`` streams the SHA-256 of both
    regions against the manifest (bounded memory); ``mmap`` maps the
    bins region read-only instead of reading it into RAM.  The returned
    dataset is flagged ``streamed`` so the training driver routes its
    host->device transfer through the double-buffered prefetcher."""
    from ..dataset import Metadata, TpuDataset
    from ..binning import BinMapper

    manifest = read_manifest(path)
    if expect_world is not None and int(manifest.get("world", 1)) \
            != int(expect_world):
        raise CacheError(
            f"{path}: cache was written for world={manifest.get('world')}"
            f" but this run has world={expect_world} — rebuild per-rank "
            "caches (save_binary under the current launcher layout)")
    if expect_rank is not None and int(manifest.get("rank", 0)) \
            != int(expect_rank):
        raise CacheError(
            f"{path}: cache shard belongs to rank {manifest.get('rank')} "
            f"but rank {expect_rank} tried to load it")
    if verify:
        _verify_region(path, manifest["bins_offset"],
                       manifest["bins_nbytes"],
                       manifest["bins_sha256"], "bins")
        _verify_region(path, manifest["meta_offset"],
                       manifest["meta_nbytes"],
                       manifest["meta_sha256"], "metadata")
    with open(path, "rb") as fh:
        fh.seek(manifest["meta_offset"])
        meta = pickle.loads(fh.read(manifest["meta_nbytes"]))

    n = int(manifest["num_data"])
    n_used = int(manifest["num_used_features"])
    dtype = np.dtype(manifest["bin_dtype"])
    if mmap and n * n_used > 0:
        bins = np.memmap(path, dtype=dtype, mode="r",
                         offset=int(manifest["bins_offset"]),
                         shape=(n, n_used))
    else:
        with open(path, "rb") as fh:
            fh.seek(manifest["bins_offset"])
            bins = np.frombuffer(
                fh.read(manifest["bins_nbytes"]),
                dtype=dtype).reshape(n, n_used).copy()

    ds = TpuDataset()
    ds.bins = bins
    ds.mappers = [BinMapper.from_dict(d) for d in meta["mappers"]]
    ds.used_features = list(meta["used_features"])
    ds.num_data = n
    ds.num_total_features = int(manifest["num_total_features"])
    ds.feature_names = list(meta.get("feature_names") or [])
    ds.metadata = Metadata(n)
    if meta.get("label") is not None:
        ds.metadata.set_label(meta["label"])
    ds.metadata.weight = meta.get("weight")
    ds.metadata.query_boundaries = meta.get("query_boundaries")
    ds.metadata.init_score = meta.get("init_score")
    ds.monotone_constraints = meta.get("monotone_constraints")
    ds.dataset_params = dict(meta.get("dataset_params") or {})
    ds.reference_binned = bool(manifest.get("reference_binned", False))
    if meta.get("mp_sample_bins") is not None:
        ds.mp_sample_bins = meta["mp_sample_bins"]
    ds._finalize_feature_arrays()
    ds.streamed = True
    ds.ingest_stats = {"source": "cache", "cache_hit": 1,
                       "cache_path": str(path),
                       "chunks": int(manifest.get("chunks", 1)),
                       "rows": n, "max_live_chunks": 0,
                       "verified": bool(verify), "mmap": bool(mmap)}
    return ds


def dataset_meta(ds) -> Dict[str, Any]:
    """The picklable metadata region for a built TpuDataset."""
    md = ds.metadata
    return {
        "mappers": [m.to_dict() for m in ds.mappers],
        "used_features": list(ds.used_features),
        "feature_names": list(ds.feature_names or []),
        "label": None if md is None else md.label,
        "weight": None if md is None else md.weight,
        "query_boundaries": None if md is None else md.query_boundaries,
        "init_score": None if md is None else md.init_score,
        "monotone_constraints": ds.monotone_constraints,
        "dataset_params": dict(getattr(ds, "dataset_params", {}) or {}),
        # multi-process builds retain the allgathered binning sample
        # (BINNED, uint16) for EFB conflict masks — without it a
        # cache-hit rank would skip bundling and diverge from a
        # cache-miss rank's layout
        "mp_sample_bins": getattr(ds, "mp_sample_bins", None),
    }


def save_dataset_cache(ds, path: str, rank: int = 0, world: int = 1,
                       source: Optional[Dict[str, Any]] = None,
                       chunk_rows: int = 65536) -> Dict[str, Any]:
    """Write a constructed TpuDataset as a v2 cache artifact, streaming
    its bin matrix in ``chunk_rows`` blocks.  Returns the manifest."""
    from ..binning import mappers_digest
    if getattr(ds, "prebundled", None) is not None:
        raise CacheError(
            "sparse EFB-bundled datasets store bundle columns, not "
            "per-feature bins, and are not cacheable — construct from "
            "dense/text input to use the binary cache")
    if getattr(ds, "raw_data", None) is not None:
        raise CacheError(
            "linear_tree datasets retain raw feature values, which the "
            "binary cache does not carry — train linear_tree from the "
            "text/array source")
    bins = np.asarray(ds.bins)
    w = CacheWriter(path, ds.num_data, ds.num_total_features,
                    ds.used_features, bins.dtype, rank=rank, world=world,
                    source=source)
    try:
        for lo in range(0, ds.num_data, max(1, int(chunk_rows))):
            w.append_rows(bins[lo:lo + int(chunk_rows)])
        manifest = w.finalize(
            dataset_meta(ds), mappers_digest=mappers_digest(ds.mappers),
            extra={"reference_binned": bool(getattr(ds, "reference_binned",
                                                    False))})
    except BaseException:
        w.abort()
        raise
    log.info("Saved binary dataset cache: %s (%d rows x %d features, "
             "%d chunks)", path, ds.num_data, len(ds.used_features),
             manifest["chunks"])
    return manifest
