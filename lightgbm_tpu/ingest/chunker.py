"""Bounded resumable text chunk iteration.

One layout scan (the native ``lgbt_scan`` — identical separator/header/
LibSVM decisions to the monolithic load), then row chunks parsed through
the native range parsers (``lgbt_parse_dense_range`` /
``lgbt_parse_libsvm_range``), which share the field parser with the
monolithic entry points — so every float a chunk yields is bit-identical
to what ``load_text_file`` would have produced for the same row.

The iterator tracks byte offsets across calls: streaming a whole file is
O(bytes) total, and skipping to a rank's row slice never materializes the
rows before it.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Iterator, List, Optional, Tuple

import numpy as np

from ..native import loader as native


@dataclasses.dataclass
class TextLayout:
    """One scan's worth of file facts (ref: parser.cpp lgbt_scan)."""
    path: str
    sep: str
    n_rows: int
    n_cols: int
    is_libsvm: bool
    has_header: bool
    header_names: Optional[List[str]] = None


def scan_layout(path: str, force_header: Optional[bool] = None
                ) -> TextLayout:
    """Scan ``path`` once -> TextLayout (the same auto-detection +
    ``force_header`` override semantics as io.file_loader.load_text_file,
    so layout decisions cannot differ between the monolithic and the
    chunked path)."""
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    sep, n_rows, n_cols, is_libsvm, has_header = native.scan(path)
    if force_header is not None and bool(force_header) != bool(has_header):
        if force_header and not has_header:
            n_rows -= 1   # the scan counted the numeric header as data
        elif has_header and not force_header:
            n_rows += 1
        has_header = bool(force_header)
    header_names = None
    if has_header:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line and not line.startswith("#"):
                    header_names = [t.strip() for t in line.split(sep)]
                    break
    return TextLayout(path=path, sep=sep, n_rows=n_rows, n_cols=n_cols,
                      is_libsvm=bool(is_libsvm),
                      has_header=bool(has_header),
                      header_names=header_names)


def _skip_data_rows(layout: TextLayout, n_skip: int) -> int:
    """Byte offset just past the first ``n_skip`` data rows (header,
    blank and ``#`` comment lines excluded), without parsing a single
    float.  Line classification MUST mirror the parsers' (empty after
    CR/LF strip, or first char ``#`` — never a whole-line strip): a
    whitespace-only line is a DATA row to the scan and both parsers, so
    skipping it uncounted here would shift every later rank's slice."""
    if n_skip <= 0:
        return 0
    skipped = 0
    with open(layout.path, "rb") as f:
        first = True
        offset = 0
        while skipped < n_skip:
            raw = f.readline()
            if not raw:
                break
            line = raw.rstrip(b"\r\n")
            if not line or line.startswith(b"#"):
                offset = f.tell()
                continue
            if first and layout.has_header:
                first = False
                offset = f.tell()
                continue
            first = False
            skipped += 1
            offset = f.tell()
    return offset


def slice_start_offset(layout: TextLayout, start_row: int) -> int:
    """Byte offset of data row ``start_row`` — computed once and passed
    to repeated ``iter_chunks`` calls over the same slice (two-pass
    builds), so the pure-Python skip walk over the rows before a rank's
    slice is not paid per pass."""
    return _skip_data_rows(layout, start_row)


def iter_chunks(layout: TextLayout, chunk_rows: int, start_row: int = 0,
                stop_row: Optional[int] = None,
                start_offset: Optional[int] = None
                ) -> Iterator[Tuple[int, np.ndarray,
                                    Optional[np.ndarray]]]:
    """Yield ``(row0, X, label_or_None)`` chunks of at most
    ``chunk_rows`` rows covering data rows ``[start_row, stop_row)``.

    ``row0`` is relative to ``start_row`` (chunk placement index for the
    caller's slice). Dense chunks carry the FULL parsed row (label
    column included — extraction is the pipeline's job); LibSVM chunks
    carry features + the separated label. Exactly one chunk is live per
    iteration step; holding more is the caller's (instrumented)
    choice."""
    if chunk_rows <= 0:
        raise ValueError(f"chunk_rows must be positive, got {chunk_rows}")
    stop = layout.n_rows if stop_row is None else min(stop_row,
                                                      layout.n_rows)
    if start_row >= stop:
        return
    offset = (start_offset if start_offset is not None
              else _skip_data_rows(layout, start_row))
    # offset 0 means "file head" to the range parsers (header skipped
    # there); a positive offset is already past it
    row = start_row
    while row < stop:
        want = min(chunk_rows, stop - row)
        if layout.is_libsvm:
            X, y, offset = native.parse_libsvm_range(
                layout.path, offset, want, layout.n_cols)
        else:
            X, offset = native.parse_dense_range(
                layout.path, layout.sep, layout.has_header, offset,
                want, layout.n_cols)
            y = None
        if X.shape[0] == 0:
            raise IOError(
                f"{layout.path}: expected data rows up to {stop}, file "
                f"ended at row {row}")
        yield row - start_row, X, y
        row += X.shape[0]
