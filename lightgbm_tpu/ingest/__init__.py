"""Streaming out-of-core ingest: chunked bin-and-pack, sharded binary
dataset cache, and double-buffered host->device prefetch.

The monolithic text pipeline (io/file_loader.py -> dataset.py) holds a
rank's ENTIRE parsed float shard in host RAM before binning; this
package is the beyond-RAM path (ROADMAP item 3, ref: LightGBM's
streaming ``LGBM_DatasetPushRows`` build + ``save_binary`` cache;
arxiv 1706.08359 / 2011.02022 on keeping the boosting loop fed):

- chunker.py — bounded resumable text chunk iteration over the SAME
  native field parser as the monolithic load (bit-identical values);
- pipeline.py — two-pass chunked build: pass 1 streams the binning
  sample (exactly the rows the monolithic build would sample), pass 2
  parses -> bins -> packs per chunk, so peak host RSS is O(chunk), not
  O(shard);
- cache.py — the sharded v2 binary dataset artifact (``LGBMTPU2``):
  versioned, SHA-256-manifested, written streaming + atomically
  (resilience/atomicio.py write-then-rename), mmap-able on reload —
  cache-hit startup skips text parsing AND binning entirely;
- prefetch.py — double-buffered host->device chunk transfer feeding the
  training driver's device bin matrix, with at most two chunks live on
  host and ``ingest.*``/``prefetch.*`` telemetry counters.

Contract: a model trained from the streamed/cached path serializes
byte-equal to one trained from the monolithic text load (the pipeline
shares the mapper construction, sampling, and row binning code with the
monolithic path — see tests/test_ingest.py). Knobs and the artifact
format are documented in docs/Data.md.
"""
from .cache import (CACHE_FORMAT_VERSION, CACHE_MAGIC, CacheError,
                    CacheWriter, cache_shard_path, load_dataset_cache,
                    read_manifest, save_dataset_cache)
from .pipeline import ingest_text_streamed, streaming_eligible
from .prefetch import IngestStats, publish_ingest_stats, stream_to_device

__all__ = ["CACHE_FORMAT_VERSION", "CACHE_MAGIC", "CacheError",
           "CacheWriter", "cache_shard_path", "load_dataset_cache",
           "read_manifest", "save_dataset_cache", "ingest_text_streamed",
           "streaming_eligible", "IngestStats", "publish_ingest_stats",
           "stream_to_device"]
