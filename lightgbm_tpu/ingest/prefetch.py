"""Double-buffered host->device prefetch for streamed/cached bin data.

The training driver holds the binned matrix in device HBM; for a
streamed or mmap-backed cache dataset the one-shot ``jnp.asarray(bins)``
would fault the whole artifact into host RAM at once and serialize
read -> transfer.  ``stream_to_device`` instead walks the matrix in row
chunks with a two-deep buffer: while chunk *k*'s host->device copy is in
flight, chunk *k+1*'s pages are being read/faulted on host — and since
every step is an async dispatch, the caller's first training step
queues behind the tail of the assembly without the host ever blocking
on the full matrix.  At most TWO chunks are live host-side at any
moment (the acceptance invariant ``ingest.max_live_chunks <= 2``);
``prefetch.host_wait_ms`` counts the time the host spent waiting for a
transfer slot to free up.

On TPU/GPU the chunk is folded into the destination buffer in place
(``donate_argnums``); the CPU backend (no real donation, no real
transfer) keeps identical semantics for the deterministic counter
tests.  The assembled buffer is elementwise-identical to
``jnp.asarray(bins)`` — prefetch is a transfer schedule, not a data
transform.
"""
from __future__ import annotations

import time
from typing import Any, Dict, Optional

import numpy as np


class IngestStats:
    """Host-side chunk-residency and throughput accounting for one
    ingest (parse->bin->pack) or prefetch (host->device) pass.  The
    ``max_live_chunks`` watermark is the bounded-RSS proof the tests
    and bench assert on."""

    def __init__(self, source: str = "text"):
        self.source = source
        self.chunks = 0
        self.rows = 0
        self.live_chunks = 0
        self.max_live_chunks = 0
        self.cache_hit = 0
        self.host_wait_ms = 0.0
        self.sample_rows = 0
        # per-chunk mapper-drift aggregate (obs/drift.py): set by the
        # pipeline's pass 2 when drift_profile is on
        self.mapper_drift: Optional[Dict[str, Any]] = None

    def chunk_opened(self, rows: int = 0) -> None:
        self.chunks += 1
        self.rows += int(rows)
        self.live_chunks += 1
        self.max_live_chunks = max(self.max_live_chunks, self.live_chunks)

    def chunk_closed(self) -> None:
        self.live_chunks = max(0, self.live_chunks - 1)

    def to_dict(self) -> Dict[str, Any]:
        out = {"source": self.source, "chunks": self.chunks,
               "rows": self.rows,
               "max_live_chunks": self.max_live_chunks,
               "cache_hit": self.cache_hit,
               "host_wait_ms": round(self.host_wait_ms, 3),
               "sample_rows": self.sample_rows}
        if self.mapper_drift is not None:
            out["mapper_drift"] = dict(self.mapper_drift)
        return out


def publish_ingest_stats(tel, stats: Dict[str, Any]) -> None:
    """Fold a dataset's ingest stats into the training telemetry
    registry (counters ``ingest.chunks``/``ingest.rows``/
    ``ingest.cache_hits``, gauge ``ingest.max_live_chunks``, one
    structured ``ingest`` event).  Ingest runs before the booster owns a
    registry, so the stats ride the dataset and land here at init."""
    if tel is None or not getattr(tel, "enabled", False) or not stats:
        return
    tel.inc("ingest.chunks", float(stats.get("chunks", 0)))
    tel.inc("ingest.rows", float(stats.get("rows", 0)))
    if stats.get("cache_hit"):
        tel.inc("ingest.cache_hits", 1)
    tel.gauge_max("ingest.max_live_chunks",
                  float(stats.get("max_live_chunks", 0)))
    if stats.get("host_wait_ms"):
        tel.inc("prefetch.host_wait_ms", float(stats["host_wait_ms"]))
    tel.event("ingest", **{k: v for k, v in stats.items()
                           if k not in ("event", "mapper_drift")})
    md = stats.get("mapper_drift")
    if md:
        # ingest runs before the booster owns a registry, so the
        # per-chunk mapper diff rides the dataset's stats and its
        # structured event lands here — the rebuild-vs-append trigger
        # (ROADMAP item 2, docs/Data.md)
        tel.inc("ingest.drift_chunks", float(md.get("flagged_chunks", 0)))
        tel.inc("ingest.out_of_range_values",
                float(md.get("out_of_range", 0)))
        tel.inc("ingest.new_category_values",
                float(md.get("new_categories", 0)))
        if md.get("flagged_chunks", 0) > 0:
            tel.event("mapper_drift", **md)


def stream_to_device(bins: np.ndarray, chunk_rows: int, tel=None,
                     stats: Optional[IngestStats] = None):
    """Assemble the device-resident bin matrix from host ``bins`` in
    double-buffered row chunks -> jnp array (bit-identical to
    ``jnp.asarray(bins)``).  Small matrices (<= one chunk) take the
    one-shot path."""
    import jax
    import jax.numpy as jnp

    from ..parallel.mesh import donate_argnums

    n = int(bins.shape[0])
    if stats is None:
        stats = IngestStats(source="prefetch")
    if chunk_rows <= 0 or n <= chunk_rows:
        stats.chunk_opened(n)
        out = jnp.asarray(np.ascontiguousarray(bins))
        stats.chunk_closed()
        if tel is not None and getattr(tel, "enabled", False):
            tel.inc("prefetch.chunks", 1)
        return out

    # fold each chunk into the destination in place (donated on
    # TPU/GPU); start row rides as an operand so every full-size chunk
    # shares ONE executable
    upd = jax.jit(
        lambda buf, chunk, row0: jax.lax.dynamic_update_slice(
            buf, chunk, (row0, jnp.int32(0))),
        donate_argnums=donate_argnums(0))

    buf = jnp.zeros(bins.shape, dtype=bins.dtype)
    inflight = []          # [(device_chunk, host_chunk)] — bounds host RSS
    n_chunks = 0
    for lo in range(0, n, chunk_rows):
        hi = min(n, lo + chunk_rows)
        # double buffer: before faulting the NEXT chunk's pages in,
        # retire transfers beyond the two-deep window
        while len(inflight) >= 2:
            dev, _host = inflight.pop(0)
            t0 = time.perf_counter()
            dev.block_until_ready()
            stats.host_wait_ms += (time.perf_counter() - t0) * 1000.0
            stats.chunk_closed()
        stats.chunk_opened(hi - lo)
        host_chunk = np.ascontiguousarray(bins[lo:hi])
        dev_chunk = jax.device_put(host_chunk)
        buf = upd(buf, dev_chunk, jnp.int32(lo))
        inflight.append((dev_chunk, host_chunk))
        n_chunks += 1
    while inflight:
        dev, _host = inflight.pop(0)
        t0 = time.perf_counter()
        dev.block_until_ready()
        stats.host_wait_ms += (time.perf_counter() - t0) * 1000.0
        stats.chunk_closed()
    if tel is not None and getattr(tel, "enabled", False):
        tel.inc("prefetch.chunks", n_chunks)
        tel.inc("prefetch.host_wait_ms", stats.host_wait_ms)
        tel.observe("prefetch.host_wait", stats.host_wait_ms / 1000.0)
        # max-merge: the gauge is the HIGH WATERMARK across the ingest
        # pipeline AND every prefetch assembly — a plain set() here
        # would mask a pipeline residency regression with the transfer
        # window's own <=2
        tel.gauge_max("ingest.max_live_chunks",
                      float(stats.max_live_chunks))
    return buf
