"""Two-pass chunked bin-and-pack: text shard -> binned TpuDataset with
O(chunk) peak host residency.

Pass 1 streams the file once collecting EXACTLY the rows the monolithic
build would sample (``dataset._sample_rows`` over the rank's slice — the
same RandomState stream, so the BinMappers come out bit-identical) plus
the label column.  Pass 2 streams again, binning each chunk through
``TpuDataset.bin_rows`` (the same code the monolithic ``_push_data``
uses) and packing it either into the preallocated bin matrix (1 B/elem)
or straight into a :class:`~.cache.CacheWriter` — in which case the
finished artifact is mmapped back and the parsed float rows NEVER exist
as one array (the reference's ``two_round`` semantics, ref:
dataset_loader.cpp two-round loading + PushRows streaming build).

Eligibility: dense/LibSVM text input; ``linear_tree`` needs retained raw
values and falls back to the monolithic load (reported via the
dataset's ingest stats / a ``megastep``-style structured event at
booster init).
"""
from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

from ..io.file_loader import (_label_spec, compute_rank_slice,
                              load_sidecars, split_label_column)
from ..utils import log
from .chunker import iter_chunks, scan_layout, slice_start_offset
from .prefetch import IngestStats


def streaming_eligible(config, data) -> Tuple[bool, str]:
    """(eligible, reason) — may this construct take the chunked ingest
    path?  Engages when the user opted in (``two_round=true``, the
    reference's memory-saving switch, or an explicit
    ``ingest_chunk_rows``) and nothing requires retained raw values."""
    if not isinstance(data, (str, os.PathLike)):
        return False, "not_a_file"
    if not (bool(config.two_round) or config.was_set("ingest_chunk_rows")):
        return False, "not_requested"
    if bool(config.linear_tree):
        return False, "linear_tree_needs_raw_data"
    return True, "ok"


def ingest_text_streamed(path: str, config, label_column=None,
                         rank: int = 0, num_machines: int = 1,
                         categorical_feature=(), feature_names=None,
                         reference=None,
                         cache_out: Optional[str] = None,
                         world: int = 1):
    """Chunked two-pass build -> (TpuDataset, label, sidecars).

    ``reference`` (a constructed TpuDataset) skips pass 1 entirely and
    bins against its mappers (validation files).  ``cache_out`` streams
    the packed chunks into a v2 cache artifact and mmaps it back instead
    of materializing the bin matrix in RAM."""
    from ..dataset import Metadata, TpuDataset, _sample_rows

    chunk_rows = max(1, int(config.ingest_chunk_rows))
    layout = scan_layout(str(path))
    if layout.n_rows == 0:
        raise ValueError(f"no data rows in {path}")
    sl = compute_rank_slice(str(path), layout.n_rows, rank, num_machines)
    n = sl.stop - sl.start
    li = None if layout.is_libsvm else _label_spec(label_column,
                                                  layout.header_names)
    n_feat = layout.n_cols - 1 if layout.is_libsvm else (
        layout.n_cols - 1 if li is not None and 0 <= li < layout.n_cols
        else layout.n_cols)
    if not layout.is_libsvm and li is not None and li >= layout.n_cols:
        raise ValueError(
            f"label_column={li} out of range for {layout.n_cols}-column "
            f"file {path}")

    stats = IngestStats(source="text")
    # the byte offset of this rank's first row is walked ONCE; both
    # streaming passes resume from it
    off0 = slice_start_offset(layout, sl.start)
    ds = TpuDataset()
    ds.num_data = n
    ds.num_total_features = n_feat
    ds.feature_names = (list(feature_names) if feature_names
                        else [f"Column_{i}" for i in range(n_feat)])
    ds.metadata = Metadata(n)

    label = np.empty((n,), np.float32) if (layout.is_libsvm or
                                           (li is not None and li >= 0)) \
        else None

    def _features_of(Xc, yc, row0):
        """Chunk -> (feature rows float32, rows consumed); stashes the
        label slice."""
        if layout.is_libsvm:
            if label is not None:
                label[row0:row0 + len(Xc)] = yc
            return Xc
        Xf, yl = split_label_column(Xc, li, layout.n_cols, str(path))
        if yl is not None and label is not None:
            label[row0:row0 + len(Xc)] = yl
        return Xf

    if reference is not None:
        ds.mappers = reference.mappers
        ds.used_features = reference.used_features
        ds.dataset_params = dict(
            getattr(reference, "dataset_params", {}) or {})
        ds.reference_binned = True
        ds._finalize_feature_arrays()
    else:
        # ---- pass 1: stream the binning sample (the SAME rows the
        # monolithic build samples: _sample_rows over this rank's slice)
        sample_idx = _sample_rows(n, config.bin_construct_sample_cnt,
                                  config.data_random_seed)
        sample = np.empty((len(sample_idx), n_feat), np.float64)
        filled = 0
        for row0, Xc, yc in iter_chunks(layout, chunk_rows, sl.start,
                                        sl.stop, start_offset=off0):
            stats.chunk_opened(len(Xc))
            lo_i = int(np.searchsorted(sample_idx, row0))
            hi_i = int(np.searchsorted(sample_idx, row0 + len(Xc)))
            if hi_i > lo_i:
                # work only on the SAMPLED rows of this chunk: the
                # label-column delete commutes with row selection, so
                # slicing first keeps pass 1 at O(sample) copies while
                # binning off values bit-identical to the monolithic
                # np.asarray(X[sample_idx], np.float64)
                rows = sample_idx[lo_i:hi_i] - row0
                sub = Xc[rows]
                if not layout.is_libsvm:
                    sub, _ = split_label_column(sub, li, layout.n_cols,
                                                str(path))
                sample[lo_i:hi_i] = np.asarray(sub, np.float64)
                filled += hi_i - lo_i
            stats.chunk_closed()
        log.check(filled == len(sample_idx),
                  f"ingest sample collected {filled} of "
                  f"{len(sample_idx)} rows")
        stats.sample_rows = len(sample_idx)
        cat_set = set(int(c) for c in categorical_feature)
        ds.build_mappers_from_sample(sample, config, cat_set)
        del sample

    # ---- pass 2: parse -> bin -> pack per chunk
    writer = None
    bins_out = None
    if cache_out is not None:
        from .cache import CacheWriter
        writer = CacheWriter(cache_out, n, n_feat, ds.used_features,
                             ds.bin_dtype(), rank=rank, world=world,
                             source=None)
    else:
        bins_out = np.empty((n, len(ds.used_features)), ds.bin_dtype())
    # per-chunk mapper-drift diff against the frozen mappers (fresh or
    # reference-borrowed): pure numpy on the chunk pass 2 already holds
    drift_on = bool(getattr(config, "drift_profile", True))
    drift_thresh = float(getattr(config, "drift_mapper_threshold", 0.02))
    drift_agg: Optional[dict] = None
    if drift_on:
        drift_agg = {"chunks": 0, "flagged_chunks": 0, "rows": 0,
                     "out_of_range": 0, "new_categories": 0, "values": 0,
                     "worst_rate": 0.0, "worst_feature": -1,
                     "threshold": drift_thresh}
    try:
        for row0, Xc, yc in iter_chunks(layout, chunk_rows, sl.start,
                                        sl.stop, start_offset=off0):
            stats.chunk_opened(len(Xc))
            Xf = _features_of(Xc, yc, row0)
            packed = ds.bin_rows(Xf)
            if drift_agg is not None:
                from ..obs.drift import chunk_mapper_drift
                d = chunk_mapper_drift(ds.mappers, ds.used_features, Xf)
                drift_agg["chunks"] += 1
                drift_agg["rows"] += d["rows"]
                drift_agg["out_of_range"] += d["out_of_range"]
                drift_agg["new_categories"] += d["new_categories"]
                drift_agg["values"] += d["values"]
                rate = d["out_of_range_rate"] + d["new_category_rate"]
                if rate > drift_thresh:
                    drift_agg["flagged_chunks"] += 1
                if d["worst_rate"] > drift_agg["worst_rate"]:
                    drift_agg["worst_rate"] = d["worst_rate"]
                    drift_agg["worst_feature"] = d["worst_feature"]
            if writer is not None:
                writer.append_rows(packed)
            else:
                bins_out[row0:row0 + len(packed)] = packed
            stats.chunk_closed()
    except BaseException:
        if writer is not None:
            writer.abort()
        raise
    if drift_agg is not None:
        vals = drift_agg["values"]
        drift_agg["out_of_range_rate"] = round(
            drift_agg["out_of_range"] / vals, 6) if vals else 0.0
        drift_agg["new_category_rate"] = round(
            drift_agg["new_categories"] / vals, 6) if vals else 0.0
        stats.mapper_drift = drift_agg

    side = load_sidecars(str(path), sl, rank, num_machines)
    if label is not None:
        ds.metadata.set_label(label)
    if "weight" in side:
        ds.metadata.set_weight(side["weight"])
    if "group" in side:
        ds.metadata.set_group(side["group"])
    if "init_score" in side:
        ds.metadata.set_init_score(side["init_score"])
    if config.monotone_constraints:
        mc = np.asarray(config.monotone_constraints, dtype=np.int32)
        log.check(mc.size == n_feat, "monotone_constraints length mismatch")
        ds.monotone_constraints = mc

    if writer is not None:
        from ..binning import mappers_digest
        from .cache import (dataset_meta, load_dataset_cache,
                            source_fingerprint)
        writer.source = source_fingerprint(
            str(path),
            dataset_params_digest(config, categorical_feature))
        writer.finalize(
            dataset_meta(ds), mappers_digest=mappers_digest(ds.mappers),
            extra={"reference_binned": bool(ds.reference_binned)})
        cached = load_dataset_cache(cache_out, verify=False, mmap=True,
                                    expect_rank=rank, expect_world=world)
        cached.ingest_stats = dict(stats.to_dict(), source="text+cache",
                                   cache_path=str(cache_out), cache_hit=0)
        cached.streamed = True
        log.info("Streamed ingest wrote cache %s (%d rows, %d chunks)",
                 cache_out, n, stats.chunks)
        return cached, label, side

    ds.bins = bins_out
    ds.streamed = True
    ds.ingest_stats = stats.to_dict()
    log.info("Streamed ingest: %s -> %d rows x %d features in %d chunks "
             "(max %d live)", path, n, len(ds.used_features),
             stats.chunks, stats.max_live_chunks)
    return ds, label, side


def dataset_params_digest(config, categorical_feature=()) -> str:
    """Digest over the dataset-defining parameters: a sidecar cache
    built under different binning params must MISS, not silently serve
    stale bins.  Keys derive from dataset._DATASET_DEFINING_KEYS (the
    ONE binning-defining list, also round-tripped in the cache meta)
    plus the load-shaping extras the cache cannot represent.
    ``categorical_feature`` takes the RESOLVED index list — the Python
    API passes categoricals via the Dataset constructor, which the
    config key never sees, and a categorical change rebinbs every
    affected feature."""
    import hashlib
    import json

    from ..dataset import _DATASET_DEFINING_KEYS
    keys = _DATASET_DEFINING_KEYS + (
        "label_column", "categorical_feature", "monotone_constraints",
        "linear_tree")
    d = {k: getattr(config, k, None) for k in keys}
    d["resolved_categorical_feature"] = sorted(
        int(c) for c in (categorical_feature or ()))
    return hashlib.sha256(
        json.dumps(d, sort_keys=True, default=str).encode()).hexdigest()
